"""Simultaneous multi-link failure what-if: exactness of the set form.

The repair kernel's warm start generalizes link-by-link: a snapshot's
affected region is the UNION of its failed links' affected bitsets (a
vertex outside the union has a base shortest path avoiding every failed
link — any crossing path would make it a DAG-descendant of a failed
edge's head).  These tests pin that argument against two independent
oracles: the native C++ set solver (spf_scalar_solve_set) and the pure
-Python Dijkstra with links_to_ignore.
"""

import numpy as np
import pytest

from openr_tpu.decision.link_state import LinkState
from openr_tpu.emulation.topology import (
    build_adj_dbs,
    grid_edges,
    random_connected_edges,
)
from openr_tpu.ops.csr import encode_link_state
from openr_tpu.ops.native_spf import NativeSpf
from openr_tpu.ops.sweep_select import SweepCandidates, SweepRouteSelector
from openr_tpu.ops.whatif import LinkFailureSweep


def build_world(seed=4, n_nodes=48, n_links=96):
    edges = random_connected_edges(n_nodes, n_links, seed=seed)
    ls = LinkState("0")
    for db in build_adj_dbs(edges).values():
        ls.update_adjacency_database(db)
    return ls, encode_link_state(ls)


def random_sets(topo, rng, B, kmax):
    return [
        tuple(
            int(x)
            for x in rng.choice(
                len(topo.links), size=int(rng.integers(1, kmax + 1)),
                replace=False,
            )
        )
        for _ in range(B)
    ]


def test_native_solve_set_matches_python_oracle():
    ls, topo = build_world(seed=9, n_nodes=40, n_links=80)
    nat = NativeSpf(topo, "node0")
    rng = np.random.default_rng(1)
    for lids in random_sets(topo, rng, 12, 3):
        dist, _ = nat.solve_set(lids)
        links = frozenset(topo.links[l] for l in lids)
        res = ls.run_spf("node0", links_to_ignore=links)
        for name, nid in topo.node_ids.items():
            want = res[name].metric if name in res else np.inf
            got = dist[nid]
            assert (np.isinf(want) and np.isinf(got)) or want == got, (
                lids,
                name,
            )


def test_run_sets_tables_match_native_set_solver():
    """Engine path: dedup + pure-off-DAG base aliasing + depth sort +
    chunking, table parity (distances AND first-hop lane sets) vs the
    native set solver."""
    _ls, topo = build_world()
    eng = LinkFailureSweep(topo, "node0")
    nat = NativeSpf(topo, "node0")
    rng = np.random.default_rng(2)
    sets = random_sets(topo, rng, 48, 3)
    sets += [sets[0], ()]  # duplicate + empty (base alias)
    res = eng.run_sets(sets)
    V = topo.num_nodes
    for b, lids in enumerate(sets):
        nd, _mask = nat.solve_set(list(lids))
        lanes = nat.lanes_dense(eng.D)
        dist_b = res.dist_of(b)
        nh_b = res.nh_of(b)
        finite = np.isfinite(nd[:V])
        assert np.array_equal(nd[:V][finite], dist_b[:V][finite]), b
        assert np.all(~finite == (dist_b[:V] >= 3.0e38)), b
        assert np.array_equal(lanes[:V][finite], nh_b[:V][finite]), b
    # the empty set aliases the base row, the duplicate solves once
    assert res.snap_row[-1] == 0
    assert res.snap_row[-2] == res.snap_row[0]
    assert res.num_device_solves <= len(set(s for s in sets if s))


def test_run_sets_pure_off_dag_aliases_base():
    """A set with NO on-DAG member provably aliases the base (no base
    shortest path crossed any of its links; removals can't shorten)."""
    _ls, topo = build_world(seed=11)
    eng = LinkFailureSweep(topo, "node0")
    off = np.nonzero(~eng.on_dag_links())[0]
    if len(off) == 0:
        pytest.skip("every link on the DAG for this seed")
    res = eng.run_sets([tuple(int(l) for l in off[:3])])
    assert res.snap_row[0] == 0
    assert np.array_equal(res.dist_of(0), res.base[0])


def test_run_sets_mixed_off_dag_member_still_removed():
    """A link OFF the base DAG can carry the reroute once an on-DAG
    member fails — mixed sets must remove it too (code-review r4
    counterexample: triangle a-b w1 on-DAG, a-c w1, c-b w5 off-DAG;
    failing {a-b, c-b} must leave b UNREACHABLE, not rerouted at 6 via
    the failed c-b link)."""
    from openr_tpu.types import AdjacencyDatabase, Adjacency

    def adj(me, other, metric):
        return Adjacency(
            other_node_name=other,
            if_name=f"if_{me}_{other}",
            metric=metric,
            other_if_name=f"if_{other}_{me}",
        )

    ls = LinkState("0")
    for me, nbrs in {
        "a": [("b", 1), ("c", 1)],
        "b": [("a", 1), ("c", 5)],
        "c": [("a", 1), ("b", 5)],
    }.items():
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name=me,
                adjacencies=[adj(me, o, m) for o, m in nbrs],
            )
        )
    topo = encode_link_state(ls)
    eng = LinkFailureSweep(topo, "a")
    on_dag = eng.on_dag_links()
    lid_ab = next(
        i for i, l in enumerate(topo.links) if {"a", "b"} == {l.n1, l.n2}
    )
    lid_cb = next(
        i for i, l in enumerate(topo.links) if {"c", "b"} == {l.n1, l.n2}
    )
    assert on_dag[lid_ab] and not on_dag[lid_cb]
    res = eng.run_sets([(lid_ab, lid_cb)])
    b_id = topo.node_id("b")
    assert res.dist_of(0)[b_id] >= 3.0e38, (
        "b must be unreachable when BOTH links fail"
    )
    # native oracle agrees
    nat = NativeSpf(topo, "a")
    nd, _ = nat.solve_set([lid_ab, lid_cb])
    assert not np.isfinite(nd[b_id])
    # sanity: failing only a-b reroutes b via c at metric 6
    single = eng.run_sets([(lid_ab,)])
    assert single.dist_of(0)[b_id] == 6.0


def test_run_sets_through_selector_routes():
    """Full pipeline: set sweep -> on-device selection -> route deltas,
    vs a from-scratch python selection over the native set solve."""
    _ls, topo = build_world(seed=7)
    eng = LinkFailureSweep(topo, "node0")
    V = topo.num_nodes
    cands = SweepCandidates.single_advertiser(np.arange(V))
    sel = SweepRouteSelector(topo, "node0", cands, max_degree=eng.D)
    nat = NativeSpf(topo, "node0")
    rng = np.random.default_rng(3)
    sets = random_sets(topo, rng, 16, 3)
    deltas = sel.run(eng.run_sets(sets, fetch=False))
    root_id = topo.node_id("node0")
    for b, lids in enumerate(sets):
        nd, _ = nat.solve_set(list(lids))
        lanes = nat.lanes_dense(eng.D)
        valid, metric, nh = deltas.routes_of(b)
        for p in range(V):
            reach = np.isfinite(nd[p]) and lanes[p].any()
            want_valid = bool(reach) and p != root_id
            assert valid[p] == want_valid, (b, p)
            if want_valid:
                assert metric[p] == nd[p], (b, p)
                assert np.array_equal(nh[p], lanes[p]), (b, p)


def test_run_sets_sharded_parity():
    """Set sweeps shard over the mesh bit-identically (same shard_map
    path as single-link)."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    from openr_tpu.parallel.mesh import make_mesh, shard_map_supported

    if not shard_map_supported():
        # version-gated: this jax predates the stable jax.shard_map the
        # sharded kernels target (see parallel/mesh.py) — skip, don't red
        pytest.skip("this jax has no stable jax.shard_map")

    _ls, topo = build_world(seed=13)
    rng = np.random.default_rng(5)
    sets = None
    eng1 = LinkFailureSweep(topo, "node0")
    sets = random_sets(topo, rng, 40, 3)
    r1 = eng1.run_sets(sets)
    engN = LinkFailureSweep(topo, "node0", mesh=make_mesh())
    rN = engN.run_sets(sets)
    assert np.array_equal(r1.snap_row, rN.snap_row)
    assert np.array_equal(r1.dist, rN.dist)
    assert np.array_equal(r1.nh, rN.nh)
