"""Aux services: Monitor (event-log ring), Watchdog (stall/queue/memory),
PersistentStore (journal + snapshot recovery).

Reference test models: openr/watchdog/ (no OSS test — behavior from
Watchdog.cpp:71-174), openr/config-store/tests/PersistentStoreTest.cpp,
openr/monitor/tests/.
"""

import asyncio
import json

import pytest

from openr_tpu.common.runtime import Actor, CounterMap, SimClock
from openr_tpu.config_store.persistent_store import (
    SNAPSHOT_EVERY,
    PersistentStore,
)
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.monitor.monitor import Monitor, SystemMetrics
from openr_tpu.types import LogSample
from openr_tpu.watchdog.watchdog import Watchdog


def run(coro):
    return asyncio.get_event_loop_policy().new_event_loop().run_until_complete(
        coro
    )


# ---------------------------------------------------------------------------
# Monitor
# ---------------------------------------------------------------------------


def test_monitor_event_log_ring_and_counters():
    async def main():
        clock = SimClock()
        q = ReplicateQueue("logSamples")
        counters = CounterMap()
        mon = Monitor(
            "node1", clock, q.get_reader(), counters, max_event_log_size=3
        )
        mon.start()
        for i in range(5):
            q.push(LogSample(event=f"EV{i}", attributes={"i": i}))
        await clock.run_for(1)
        logs = mon.get_event_logs()
        # ring keeps only the newest 3
        assert len(logs) == 3
        events = [json.loads(rec)["event"] for rec in logs]
        assert events == ["EV2", "EV3", "EV4"]
        rec = json.loads(logs[-1])
        assert rec["node_name"] == "node1" and rec["i"] == 4
        assert counters.get("monitor.log.sample_received") == 5
        await mon.stop()

    run(main())


def test_monitor_submission_disabled_drops():
    async def main():
        clock = SimClock()
        q = ReplicateQueue("logSamples")
        counters = CounterMap()
        mon = Monitor(
            "node1",
            clock,
            q.get_reader(),
            counters,
            enable_event_log_submission=False,
        )
        mon.start()
        q.push(LogSample(event="X"))
        await clock.run_for(1)
        assert mon.get_event_logs() == []
        assert counters.get("monitor.log.sample_dropped") == 1
        await mon.stop()

    run(main())


def test_monitor_forward_fn_receives_records():
    async def main():
        clock = SimClock()
        q = ReplicateQueue("logSamples")
        seen = []
        mon = Monitor(
            "node1", clock, q.get_reader(), forward_fn=seen.append
        )
        mon.start()
        q.push(LogSample(event="NEIGHBOR_UP", attributes={"nbr": "node2"}))
        await clock.run_for(1)
        assert seen and seen[0]["event"] == "NEIGHBOR_UP"
        await mon.stop()

    run(main())


def test_system_metrics_rss_and_cpu():
    m = SystemMetrics()
    rss = m.rss_bytes()
    assert rss is not None and rss > 1024 * 1024  # python process > 1MB
    assert m.cpu_pct() is None  # first sample has no delta
    for _ in range(10000):
        pass
    pct = m.cpu_pct()
    assert pct is None or pct >= 0.0


def test_monitor_periodic_metrics_sampling():
    async def main():
        clock = SimClock()
        q = ReplicateQueue("logSamples")
        counters = CounterMap()
        mon = Monitor(
            "node1", clock, q.get_reader(), counters, metrics_interval_s=60
        )
        mon.start()
        await clock.run_for(130)  # 3 samples: t=0, 60, 120
        assert counters.get("process.memory.rss") > 0
        await mon.stop()

    run(main())


# ---------------------------------------------------------------------------
# Watchdog
# ---------------------------------------------------------------------------


class _CrashingActor(Actor):
    """Actor whose main fiber dies right after start."""

    async def run(self):
        raise RuntimeError("boom")


class _IdleActor(Actor):
    """Healthy actor with a parked main fiber (idle network)."""

    async def run(self):
        await asyncio.get_running_loop().create_future()  # park forever


def test_watchdog_detects_crashed_actor_but_not_idle():
    async def main():
        clock = SimClock()
        crashes = []
        wd = Watchdog(
            "node1",
            clock,
            interval_s=20,
            thread_timeout_s=100,
            fire_crash=crashes.append,
        )
        crashed = _CrashingActor("crashed_mod", clock)
        idle = _IdleActor("idle_mod", clock)
        wd.add_actor(crashed)
        wd.add_actor(idle)
        crashed.start()
        idle.start()
        wd.start()
        await clock.run_for(150)
        # crashed module stops being refreshed -> stall fires after timeout;
        # an idle-but-alive module must never trip the check
        assert crashes and "crashed_mod" in crashes[0]
        assert all("idle_mod" not in c for c in crashes)
        assert wd.crashed is not None
        await idle.stop()
        await crashed.stop()

    run(main())


def test_watchdog_detects_queue_backlog():
    async def main():
        clock = SimClock()
        crashes = []
        wd = Watchdog(
            "node1",
            clock,
            interval_s=20,
            max_queue_size=1000,  # config knob (OpenrConfig.thrift:209-221)
            fire_crash=crashes.append,
        )
        q = ReplicateQueue("bigQueue")
        q.get_reader()  # reader that never drains
        wd.add_queue(q)
        wd.start()
        for i in range(1001):
            q.push(i)
        await clock.run_for(25)
        assert crashes and "bigQueue" in crashes[0]

    run(main())


def test_watchdog_memory_limit():
    async def main():
        clock = SimClock()
        crashes = []
        wd = Watchdog(
            "node1",
            clock,
            interval_s=20,
            max_memory_mb=1,  # any python process exceeds 1MB RSS
            fire_crash=crashes.append,
        )
        wd.start()
        await clock.run_for(25)
        assert crashes and "Memory" in crashes[0]

    run(main())


def test_watchdog_quiet_when_healthy():
    async def main():
        clock = SimClock()
        crashes = []
        counters = CounterMap()
        wd = Watchdog(
            "node1", clock, counters, interval_s=20, fire_crash=crashes.append
        )
        q = ReplicateQueue("ok")
        q.get_reader()
        wd.add_queue(q)
        wd.start()
        await clock.run_for(100)
        assert crashes == []
        assert counters.get("watchdog.checks") == 5

    run(main())


# ---------------------------------------------------------------------------
# PersistentStore
# ---------------------------------------------------------------------------


def test_persistent_store_roundtrip(tmp_path):
    path = str(tmp_path / "store.bin")
    s = PersistentStore(path)
    s.store("k1", {"a": 1})
    s.store("k2", [1, 2, 3])
    s.store("k1", {"a": 2})  # overwrite
    assert s.load("k1") == {"a": 2}
    assert s.load("missing", "dflt") == "dflt"

    # recovery from journal replay
    s2 = PersistentStore(path)
    assert s2.load("k1") == {"a": 2}
    assert s2.load("k2") == [1, 2, 3]


def test_persistent_store_erase(tmp_path):
    path = str(tmp_path / "store.bin")
    s = PersistentStore(path)
    s.store("k", 1)
    assert s.erase("k") is True
    assert s.erase("k") is False
    s2 = PersistentStore(path)
    assert s2.load("k") is None


def test_persistent_store_compaction(tmp_path):
    path = str(tmp_path / "store.bin")
    s = PersistentStore(path)
    for i in range(SNAPSHOT_EVERY + 10):
        s.store(f"k{i % 7}", i)
    # after compaction the file is a single snapshot + small journal tail
    with open(path) as f:
        lines = [json.loads(x) for x in f if x.strip()]
    assert lines[0]["op"] == "snapshot"
    assert len(lines) <= SNAPSHOT_EVERY
    s2 = PersistentStore(path)
    assert sorted(s2.keys()) == sorted({f"k{i % 7}" for i in range(7)})
    assert s2.load(f"k{(SNAPSHOT_EVERY + 9) % 7}") == SNAPSHOT_EVERY + 9


def test_persistent_store_torn_tail_is_ignored(tmp_path):
    path = str(tmp_path / "store.bin")
    s = PersistentStore(path)
    s.store("good", 1)
    with open(path, "a") as f:
        f.write('{"op": "save", "key": "bad", "val')  # torn write
    s2 = PersistentStore(path)
    assert s2.load("good") == 1
    assert s2.load("bad") is None


def test_persistent_store_dryrun_no_file(tmp_path):
    path = str(tmp_path / "store.bin")
    s = PersistentStore(path, dryrun=True)
    s.store("k", 1)
    s.flush()
    assert s.load("k") == 1
    import os

    assert not os.path.exists(path)


def test_node_drain_state_survives_restart(tmp_path):
    """End-to-end: OpenrNode persists drain ops; a new node with the same
    store path comes up drained (reference: LinkMonitor + PersistentStore)."""
    from openr_tpu.config import OpenrConfig
    from openr_tpu.emulation.network import EmulatedNetwork

    path = str(tmp_path / "node1_store.bin")

    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock)

        cfg = OpenrConfig(node_name="node1", dryrun=True)
        node = net.add_node("node1", cfg)
        node.config.persistent_store_path = path  # emulation blanks it
        node.persistent_store = PersistentStore(path)
        net.start()
        await clock.run_for(1)
        node.set_node_overload(True)
        node.set_link_metric("if_a", 5000)
        await net.stop()

        # "restart": fresh store from same path
        restored = PersistentStore(path)
        state = restored.load("link-monitor-config:node1")  # node-scoped key
        assert state["node_overloaded"] is True
        assert state["link_metric_overrides"] == {"if_a": 5000}

    run(main())
