"""End-to-end multi-node system tests (reference:
openr/tests/OpenrSystemTest.cpp) — full stacks, simulated network,
dryrun-backed mock FIB, virtual time.

The full pipeline under test:
Spark discovery → LinkMonitor adj advertisement → KvStore flooding →
Dispatcher → Decision (SPF) → Fib programming → PrefixManager feedback.
"""

import asyncio

import pytest

from openr_tpu.common.runtime import SimClock
from openr_tpu.emulation.network import EmulatedNetwork
from openr_tpu.emulation.topology import grid_edges, line_edges, ring_edges
from openr_tpu.types import InitializationEvent, PrefixEntry


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


CONVERGE_S = 12.0  # virtual seconds for cold-start full-mesh convergence


def test_two_node_end_to_end():
    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock)
        net.build(line_edges(2))
        net.start()
        await clock.run_for(CONVERGE_S)
        ok, why = net.converged_full_mesh()
        assert ok, why
        assert net.all_initialized()
        # initialization sequence order sanity on one node
        evs = net.nodes["node0"].init_tracker.events
        assert evs.index(InitializationEvent.KVSTORE_SYNCED) < evs.index(
            InitializationEvent.RIB_COMPUTED
        )
        assert evs[-1] == InitializationEvent.INITIALIZED
        # route details: node0 reaches node1's loopback via node1
        routes = net.fib_routes("node0")
        assert routes[net.loopback("node1")] == ["node1"]
        await net.stop()

    run(main())


def test_line_of_four_transit_routing():
    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock)
        net.build(line_edges(4))
        net.start()
        await clock.run_for(CONVERGE_S)
        ok, why = net.converged_full_mesh()
        assert ok, why
        # transit: node0 reaches node3 via node1
        assert net.fib_routes("node0")[net.loopback("node3")] == ["node1"]
        assert net.fib_routes("node3")[net.loopback("node0")] == ["node2"]
        await net.stop()

    run(main())


def test_ring_reconvergence_after_link_failure():
    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock)
        net.build(ring_edges(4))
        net.start()
        await clock.run_for(CONVERGE_S)
        ok, why = net.converged_full_mesh()
        assert ok, why
        # node0 -> node1 direct
        assert net.fib_routes("node0")[net.loopback("node1")] == ["node1"]
        # fail node0-node1: traffic must reroute the long way (via node3)
        net.fail_link("node0", "node1")
        await clock.run_for(8.0)
        routes = net.fib_routes("node0")
        assert routes[net.loopback("node1")] == ["node3"]
        # restore: back to direct (within flap backoff + hello interval)
        net.restore_link("node0", "node1")
        await clock.run_for(70.0)  # linkflap initial backoff is 60s
        assert net.fib_routes("node0")[net.loopback("node1")] == ["node1"]
        await net.stop()

    run(main())


def test_grid_ecmp_and_convergence():
    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock)
        net.build(grid_edges(3))  # 9 nodes
        net.start()
        await clock.run_for(CONVERGE_S + 6.0)
        ok, why = net.converged_full_mesh()
        assert ok, why
        # corner-to-corner ECMP: node0 -> node8 via node1 and node3
        assert net.fib_routes("node0")[net.loopback("node8")] == [
            "node1",
            "node3",
        ]
        await net.stop()

    run(main())


def test_node_drain_end_to_end():
    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock)
        net.build(ring_edges(4))
        net.start()
        await clock.run_for(CONVERGE_S)
        # node0 -> node2 has two equal paths (via node1 or node3)
        assert net.fib_routes("node0")[net.loopback("node2")] == [
            "node1",
            "node3",
        ]
        # operator hard-drains node1 network-wide
        net.nodes["node1"].link_monitor.set_node_overload(True)
        await clock.run_for(5.0)
        # transit through node1 avoided everywhere
        assert net.fib_routes("node0")[net.loopback("node2")] == ["node3"]
        # node1 itself still reachable as a destination
        assert net.loopback("node1") in net.fib_routes("node0")
        # undrain restores ECMP
        net.nodes["node1"].link_monitor.set_node_overload(False)
        await clock.run_for(5.0)
        assert net.fib_routes("node0")[net.loopback("node2")] == [
            "node1",
            "node3",
        ]
        await net.stop()

    run(main())


def test_prefix_withdraw_propagates():
    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock)
        net.build(line_edges(3))
        net.start()
        await clock.run_for(CONVERGE_S)
        extra = PrefixEntry("192.0.2.0/24")
        net.nodes["node2"].advertise_prefixes([extra])
        await clock.run_for(4.0)
        assert "192.0.2.0/24" in net.fib_routes("node0")
        net.nodes["node2"].withdraw_prefixes([extra])
        await clock.run_for(20.0)  # clear = stop refresh + ttl expiry
        assert "192.0.2.0/24" not in net.fib_routes("node0")
        await net.stop()

    run(main())


def test_node_death_routes_expire():
    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock)
        net.build(ring_edges(4))
        net.start()
        await clock.run_for(CONVERGE_S)
        ok, why = net.converged_full_mesh()
        assert ok, why
        # node2 dies hard (no graceful restart)
        await net.nodes["node2"].stop()
        net.kv_transport.unregister("node2")
        await clock.run_for(30.0)
        # hold timers fire, adjacencies drop, routes to node2 vanish
        routes = net.fib_routes("node0")
        assert net.loopback("node2") not in routes
        # ring is cut: node0 reaches node1/node3 directly still
        assert net.loopback("node1") in routes
        assert net.loopback("node3") in routes
        await net.stop()

    run(main())


def test_convergence_wall_clock_budget():
    """The reference asserts ≤3s wall convergence for 2-4 nodes
    (kMaxOpenrSyncTime); our virtual-time equivalent: the whole 4-node
    cold start must complete within the discovery+debounce budget."""

    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock)
        net.build(ring_edges(4))
        net.start()
        # discovery min window 0.5s + handshake + kvstore sync + debounce:
        # must converge well within 10 virtual seconds
        await clock.run_for(10.0)
        ok, why = net.converged_full_mesh()
        assert ok, why
        await net.stop()

    run(main())


def test_flood_optimization_grid_end_to_end():
    """DUAL SPT over the full stack: handshake-advertised capability,
    tree formation, spanning-tree flooding, reconvergence after losing a
    tree edge (the verify-drive scenario, kept as regression)."""

    def overrides(cfg):
        cfg.kvstore_config.enable_flood_optimization = True
        cfg.kvstore_config.is_flood_root = cfg.node_name == "node0"

    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock, config_overrides=overrides)
        net.build(grid_edges(3))  # 9 nodes, 12 links
        net.start()
        await clock.run_for(30.0)
        ok, why = net.converged_full_mesh()
        assert ok, why
        tree_edges = 0
        for name, node in net.nodes.items():
            topo = node.kv_store.get_flood_topo("0")
            assert topo is not None and topo["node0"]["is_chosen"], name
            assert topo["node0"]["passive"], name
            tree_edges += len(topo["node0"]["children"])
        assert tree_edges == 8  # spanning tree: V-1 edges
        # cut node1's tree uplink; SPT + routes must reconverge
        victim = net.nodes["node1"].kv_store.get_flood_topo("0")["node0"][
            "nexthop"
        ]
        net.fail_link("node1", victim)
        await clock.run_for(30.0)
        topo = net.nodes["node1"].kv_store.get_flood_topo("0")["node0"]
        assert topo["passive"] and topo["nexthop"] not in (None, victim)
        ok, why = net.converged_full_mesh()
        assert ok, why
        await net.stop()

    run(main())


def test_large_grid_emulation_scale():
    """64 in-process nodes (8x8 grid) — the reference's internal practice
    is large-emulation testing (DeveloperGuide.md:51); this is the
    standing mid-scale point (100+ nodes verified manually; kept at 64
    for CI wall time).  Cold-start full-mesh convergence, then
    reconvergence after failing a central link."""

    async def await_converged(net, clock, rounds, step_s):
        for _ in range(rounds):
            await clock.run_for(step_s)
            ok, why = net.converged_full_mesh()
            if ok:
                return
        raise AssertionError(why)

    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock)
        net.build(grid_edges(8))
        net.start()
        await await_converged(net, clock, rounds=6, step_s=10.0)
        # central link failure: every pair must still converge (grid has
        # alternate paths around any single link)
        net.fail_link("node27", "node28")
        await await_converged(net, clock, rounds=8, step_s=5.0)
        # the direct neighbor pair now routes around the failed link
        nhs = net.fib_routes("node27")[net.loopback("node28")]
        assert nhs and "node28" not in nhs, nhs
        await net.stop()

    run(main())


def test_chaos_random_link_churn_reconverges():
    """Randomized fault schedule (SURVEY §5 failure injection at the
    system level): 14 rounds of random link fails/heals on a 4x4 grid
    in virtual time, then heal everything and require (a) full-mesh
    reconvergence, (b) identical LSDB content on every node, and
    (c) FIB == Decision on every node — the openr-validate invariants
    after sustained churn, not just a single staged failure."""
    import random

    async def main():
        rng = random.Random(1234)
        clock = SimClock()
        net = EmulatedNetwork(clock)
        edges = grid_edges(4)
        net.build(edges)
        net.start()
        await clock.run_for(CONVERGE_S)
        ok, why = net.converged_full_mesh()
        assert ok, why

        pairs = [(a, b) for a, b, _m in edges]
        failed: set = set()
        for _round in range(14):
            if failed and rng.random() < 0.4:
                pair = rng.choice(sorted(failed))
                failed.discard(pair)
                net.restore_link(*pair)
            else:
                up = [p for p in pairs if p not in failed]
                pair = rng.choice(up)
                failed.add(pair)
                net.fail_link(*pair)
            await clock.run_for(rng.uniform(1.0, 6.0))

        for pair in sorted(failed):
            net.restore_link(*pair)
        # worst-case linkflap backoff (300s max) + convergence slack —
        # virtual seconds, so this costs milliseconds of wall clock
        await clock.run_for(330.0)

        ok, why = net.converged_full_mesh()
        assert ok, why

        # (b) LSDB agreement: same keys at same versions everywhere
        def lsdb_view(node):
            # value bytes included: the merge tie-break admits equal
            # (version, originator) with DIFFERENT payloads — exactly
            # the divergence a flooding bug would leave behind
            return {
                k: (v.version, v.originator_id, v.value)
                for k, v in node.kv_store.dump_all("0").items()
            }

        views = {n: lsdb_view(node) for n, node in net.nodes.items()}
        ref_name = next(iter(views))
        for n, view in views.items():
            assert view == views[ref_name], (
                f"LSDB divergence between {ref_name} and {n}"
            )

        # (c) FIB == Decision per node
        for n, node in net.nodes.items():
            rib = {
                p: sorted(nh.neighbor_node_name for nh in e.nexthops)
                for p, e in node.decision.get_route_db()
                .unicast_routes.items()
            }
            fib = {
                p: sorted(nh.neighbor_node_name for nh in e.nexthops)
                for p, e in node.fib.get_route_db().items()
            }
            assert rib == fib, f"FIB/Decision divergence on {n}"
        await net.stop()

    run(main())


def test_very_large_grid_256_nodes_slo():
    """256 in-process nodes (16x16 grid) — an order of magnitude over
    the 64-node standing point, toward the reference's 1000-node
    emulation practice (DeveloperGuide.md:51).  SLO-asserted: the COLD
    START of the whole fabric must reach full-mesh convergence within
    10 s of VIRTUAL time (the reference's system tests assert <=3 s on
    2-4 nodes, OpenrSystemTest.cpp:38; discovery staggering dominates
    at this scale), and a central link failure must reconverge within a
    further 5 s virtual.  Wall-clock is budgeted so a CI regression in
    emulation throughput fails loudly instead of timing out the suite."""
    import time as _time

    async def main():
        t0 = _time.perf_counter()
        clock = SimClock()
        net = EmulatedNetwork(clock)
        net.build(grid_edges(16))
        net.start()
        await clock.run_for(10.0)  # the SLO window
        ok, why = net.converged_full_mesh()
        assert ok, f"256-node cold start missed the 10s-virtual SLO: {why}"
        # central link failure: reroute within 5s virtual
        net.fail_link("node119", "node120")
        await clock.run_for(5.0)
        ok, why = net.converged_full_mesh()
        assert ok, f"reconvergence missed the 5s-virtual SLO: {why}"
        nhs = net.fib_routes("node119")[net.loopback("node120")]
        assert nhs and "node120" not in nhs, nhs
        await net.stop()
        wall = _time.perf_counter() - t0
        # generous for a loaded single-core CI host; catches order-of-
        # magnitude emulation-throughput regressions
        assert wall < 600, f"256-node emulation took {wall:.0f}s wall"

    run(main())
