"""Fleet compute fabric (ISSUE 19) — cross-node sweep sharding + the
consistent-hash feed directory.

The contracts under test (docs/Fleet.md):

* assignment is a PURE FUNCTION of (content key, live-node set):
  content-derived, arrival-order independent, minimal reshuffle on
  membership change (a dead node's keys move, nobody else's);
* a fleet sweep's merged summary digest is byte-equal to a single-node
  run of the same scenario set; a mid-sweep node kill re-packs ONLY the
  victim's worlds onto survivors and the final fleet manifest stays
  byte-identical to an uninterrupted run's;
* a node kill mid-stream migrates exactly its watchers to their hash
  successors with ZERO monotone-generation violations and no
  pre-migration generation re-emitted; a drain hands off cleanly while
  the daemon stays up; seeded replays are byte-identical;
* membership transitions feed the health plane: node loss PAGES,
  drain migration TICKETS, restoration resolves both.

Small scale runs in tier-1; the fleet-scale variant is ``-m slow``.
"""

import asyncio
import json

import pytest

from openr_tpu.common.runtime import CounterMap, SimClock
from openr_tpu.emulation.fabric import FleetFabric
from openr_tpu.fleet import (
    FeedDirectory,
    FleetMembership,
    assign_worlds,
    owner_of,
    rank_members,
)
from openr_tpu.health.alerts import AlertSink, alert_counter_key
from openr_tpu.parallel.nodes import NodeSet, node_shard_counts

pytestmark = [pytest.mark.fleet]


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        pending = asyncio.all_tasks(loop)
        for t in pending:
            t.cancel()
        if pending:
            loop.run_until_complete(
                asyncio.gather(*pending, return_exceptions=True)
            )
        loop.close()


SWEEP_PARAMS = {
    "drain_node_sets": [[], ["node5"], ["node7"], ["node3"]],
    "metric_perturbations": [{"pattern": "node.*", "factor": 2.0}],
}


def make_fabric(clock, tmp_path, **kwargs):
    kwargs.setdefault("n_side", 3)
    kwargs.setdefault(
        "sweep_overrides",
        {"shard_scenarios": 2, "inter_shard_pause_s": 0.2},
    )
    return FleetFabric(clock, spill_root=str(tmp_path), **kwargs)


# ---------------------------------------------------------------------------
# assignment: pure, content-derived, minimal reshuffle
# ---------------------------------------------------------------------------


def test_rendezvous_owner_is_pure_and_arrival_order_independent():
    nodes = ["fab2", "fab0", "fab1"]
    for key in ("drain[]|metric[]", "drain[node5]|metric[]", "x"):
        a = owner_of("salt", key, nodes)
        b = owner_of("salt", key, list(reversed(nodes)))
        assert a == b
        assert a in nodes
    # ranking is a permutation of the members and salt-sensitive
    r = rank_members("salt", "k", nodes)
    assert sorted(r) == sorted(nodes)
    assert any(
        rank_members("other-salt", k, nodes) != rank_members("salt", k, nodes)
        for k in ("k1", "k2", "k3", "k4", "k5")
    )


def test_assignment_reshuffle_is_minimal_on_node_loss():
    worlds = [f"drain[node{i}]|metric[]" for i in range(40)]
    live = ("fab0", "fab1", "fab2", "fab3")
    before = assign_worlds("hash", worlds, live)
    assert sorted(w for ws in before.values() for w in ws) == sorted(worlds)
    dead = "fab1"
    after = assign_worlds(
        "hash", worlds, tuple(n for n in live if n != dead)
    )
    # every world the dead node did NOT own stays put; only its worlds
    # moved (each to its second-ranked member)
    for node, ws in before.items():
        if node == dead:
            continue
        assert set(ws) <= set(after.get(node, ()))
    for w in before.get(dead, ()):
        new_owner = owner_of(
            "hash", w, tuple(n for n in live if n != dead)
        )
        assert new_owner == rank_members("hash", w, live)[1]
    # a different set hash shuffles independently (content-derived)
    assert assign_worlds("other", worlds, live) != before


# ---------------------------------------------------------------------------
# node-level health: NodeSet + FleetMembership
# ---------------------------------------------------------------------------


def test_nodeset_transitions_bump_membership_seq():
    ns = NodeSet(["b", "a", "c"])
    assert ns.names == ("a", "b", "c")  # sorted, never arrival order
    assert ns.live_nodes() == ("a", "b", "c")
    assert ns.mark_down("b") and not ns.mark_down("b")
    assert ns.live_nodes() == ("a", "c")
    assert ns.down_nodes() == ("b",)
    seq = ns.membership_seq
    assert ns.mark_drained("c") and ns.membership_seq == seq + 1
    assert ns.live_nodes() == ("a",)
    assert ns.drained_nodes() == ("c",)
    assert not ns.mark_drained("b")  # down nodes can't drain
    assert ns.mark_up("b") and ns.clear_drained("c")
    assert ns.live_nodes() == ("a", "b", "c")
    with pytest.raises(ValueError):
        NodeSet(["a", "a"])
    assert node_shard_counts(7, ["a", "b", "c"]) == [3, 2, 2]


def test_membership_listeners_and_health_firing():
    counters = CounterMap()
    m = FleetMembership(["fab0", "fab1", "fab2"], counters=counters)
    events = []
    m.add_listener(events.append)
    assert m.health_firing() == {}
    assert m.node_down("fab1", reason="chaos")
    assert m.drain_node("fab2")
    assert [e["event"] for e in events] == ["node_down", "node_drained"]
    assert events[0]["live"] == ["fab0", "fab2"]
    firing = m.health_firing()
    assert firing["fleet_node_loss"]["nodes"] == ["fab1"]
    assert firing["fleet_drain_migration"]["nodes"] == ["fab2"]
    assert m.node_up("fab1") and m.undrain_node("fab2")
    assert m.health_firing() == {}
    assert counters.get("fleet.membership.node_down") == 1


def test_fleet_alerts_fire_and_resolve_through_the_sink():
    """The health satellite: node loss PAGES, a drain TICKETS, and the
    expected migration resolves quietly once membership heals."""
    clock = SimClock(1.0)
    m = FleetMembership(["fab0", "fab1"])
    sink = AlertSink("agg", clock, CounterMap())
    m.node_down("fab1")
    sink.report(m.health_firing())
    assert [a["name"] for a in sink.active_alerts()] == ["fleet_node_loss"]
    assert sink.counters.get(alert_counter_key("fleet_node_loss")) == 1.0
    m.node_up("fab1")
    sink.report(m.health_firing())
    assert sink.active_alerts() == []
    m.drain_node("fab0")
    sink.report(m.health_firing())
    assert [a["name"] for a in sink.active_alerts()] == [
        "fleet_drain_migration"
    ]
    m.undrain_node("fab0")
    sink.report(m.health_firing())
    assert sink.active_alerts() == []
    events = [json.loads(line) for line in sink.log]
    assert [e["event"] for e in events] == [
        "fired", "resolved", "fired", "resolved",
    ]
    assert events[0]["severity"] == "page"
    assert events[2]["severity"] == "ticket"


def test_feed_directory_tracks_live_set():
    m = FleetMembership(["fab0", "fab1", "fab2"])
    d = FeedDirectory(m)
    params = {"node": "node3"}
    first, successor = d.owners("route_db", params, k=2)
    assert d.owner("route_db", params) == first
    m.node_down(first)
    assert d.owner("route_db", params) == successor
    m.node_down(successor)
    last = d.owner("route_db", params)
    assert last is not None and last not in (first, successor)
    m.node_down(last)
    assert d.owner("route_db", params) is None
    assert d.owners("route_db", params) == ()


# ---------------------------------------------------------------------------
# cross-node sweep: digest parity, node-kill repack, manifest identity
# ---------------------------------------------------------------------------


async def _drive_fleet_sweep(fab, clock, kill=None):
    """Run one fleet sweep to completion; optionally kill a node the
    moment it has a running sub-sweep.  Returns (digest, manifest
    bytes, status)."""
    fab.coordinator.prepare(SWEEP_PARAMS)
    fab.coordinator.start()
    hit = False
    for _ in range(5000):
        await clock.run_for(0.05)
        st = fab.coordinator.status()
        if kill and not hit and any(
            t["node"] == kill and t["state"] == "running"
            for t in st["assignments"]
        ):
            await fab.kill_node(kill)
            hit = True
        if fab.coordinator.state != "running":
            break
    assert fab.coordinator.state == "done", fab.coordinator.state
    s = fab.coordinator.summary()
    assert s["complete"] and s["summary"]["scenarios"] > 0
    return s["summary_digest"], fab.coordinator.manifest_bytes(), (
        fab.coordinator.status()
    )


def test_fleet_sweep_digest_matches_single_node_run(tmp_path):
    async def main():
        clock = SimClock()
        fab = make_fabric(clock, tmp_path / "fleet")
        fab.start()
        await clock.run_for(2.0)
        digest, _man, st = await _drive_fleet_sweep(fab, clock)
        assert st["worlds_merged"] == st["worlds_total"] == 8
        assert st["nodes_live"] == 3 and st["rounds"] == 1
        # the single-node reference: same grammar, one executor
        from openr_tpu.sweep import SweepExecutor
        from openr_tpu.sweep.scenario import ScenarioSpec

        svc = fab.nodes["fab0"].sweep
        spec = ScenarioSpec.from_params(svc.config, SWEEP_PARAMS)
        ex = SweepExecutor(
            svc._inputs, str(tmp_path / "single"), clock=clock,
            shard_scenarios=8,
        )
        ex.prepare(spec, resume=False)
        ex.run()
        assert ex.reducer.summary_digest() == digest
        # every member's status carries the fleet rows
        for fnode in fab.nodes.values():
            fleet_st = fnode.sweep.get_sweep_status()["fleet"]
            assert fleet_st["state"] == "done"
            rows = fleet_st["assignments"]
            assert rows and {r["node"] for r in rows} <= set(fab.nodes)
        await fab.stop()

    run(main())


@pytest.mark.chaos
def test_node_kill_mid_sweep_repacks_only_its_worlds(tmp_path):
    async def run_one(root, kill=None):
        clock = SimClock()
        fab = make_fabric(clock, root)
        fab.start()
        await clock.run_for(2.0)
        out = await _drive_fleet_sweep(fab, clock, kill=kill)
        await fab.stop()
        return out

    async def main():
        d0, m0, st0 = await run_one(tmp_path / "clean")
        d1, m1, st1 = await run_one(tmp_path / "killed", kill="fab1")
        # the victim's running worlds re-packed onto survivors as a new
        # round; nobody else's work moved
        lost = [t for t in st1["assignments"] if t["state"] == "lost"]
        assert lost and all(t["node"] == "fab1" for t in lost)
        assert st1["repacked_worlds"] == sum(t["worlds"] for t in lost)
        assert st1["rounds"] == 2 and st0["rounds"] == 1
        assert {
            t["node"] for t in st1["assignments"] if t["round"] == 1
        } <= {"fab0", "fab2"}
        # merged digest AND fleet manifest byte-identical to the
        # uninterrupted run
        assert d1 == d0
        assert m1 == m0
        assert json.loads(m0)["completed_worlds"] == sorted(
            json.loads(m0)["completed_worlds"]
        )

    run(main())


def test_fleet_manifest_resumes_merged_worlds(tmp_path):
    """A coordinator restart against the same spill root replays merged
    worlds from their recorded spills instead of re-solving them."""

    async def main():
        clock = SimClock()
        fab = make_fabric(clock, tmp_path)
        fab.start()
        await clock.run_for(2.0)
        digest, _man, _st = await _drive_fleet_sweep(fab, clock)
        # a fresh coordinator over the same members + spill root
        from openr_tpu.fleet import FleetSweepCoordinator

        c2 = FleetSweepCoordinator(
            clock,
            fab.membership,
            {n: f.sweep for n, f in fab.nodes.items()},
            spill_root=str(tmp_path) + "/fleet",
        )
        rep = c2.prepare(SWEEP_PARAMS)
        assert rep["resumed_worlds"] == rep["worlds"] == 8
        assert rep["state"] == "done"
        assert c2.summary()["summary_digest"] == digest
        assert c2.manifest_bytes() == fab.coordinator.manifest_bytes()
        await fab.stop()

    run(main())


# ---------------------------------------------------------------------------
# feed directory: migration on kill/drain, invariants, seeded replay
# ---------------------------------------------------------------------------


async def _stream_scenario(root, drain_instead=False):
    """Six watchers over the fleet; churn, kill (or drain) the busiest
    serving node, churn again.  Returns the fabric + watchers + victim
    for assertions, after stopping everything."""
    clock = SimClock()
    fab = make_fabric(clock, root)
    fab.start()
    await clock.run_for(2.0)
    watchers = [
        fab.router.watch("route_db", {"node": f"node{i}"})
        for i in range(6)
    ]
    await clock.run_for(1.0)
    fab.announce_prefix("node2", "10.99.0.0/24")
    await clock.run_for(2.0)
    placement = {}
    for w in watchers:
        placement.setdefault(w.serving_node, []).append(w)
    victim = max(placement, key=lambda n: len(placement[n]))
    pre_cursor = {w.watcher_id: w.cursor_seq for w in watchers}
    if drain_instead:
        fab.drain_node(victim)
    else:
        await fab.kill_node(victim)
    await clock.run_for(1.0)
    fab.announce_prefix("node0", "10.98.0.0/24")
    await clock.run_for(2.0)
    logs = b"\x00".join(w.log_bytes() for w in watchers)
    await fab.stop()
    return fab, watchers, victim, placement, pre_cursor, logs


@pytest.mark.chaos
def test_node_kill_migrates_watchers_with_zero_violations(tmp_path):
    async def main():
        fab, ws, victim, placement, pre, _logs = await _stream_scenario(
            tmp_path
        )
        # exactly the victim's watchers migrated, to their successors
        for w in ws:
            if w in placement[victim]:
                assert w.migrations == 1
                assert w.serving_node is not None
                assert w.serving_node != victim
                assert w.serving_node == fab.directory.owner(
                    w.kind, w.params
                )
            else:
                assert w.migrations == 0
        # the fleet invariants: zero monotone violations, nothing from
        # before the migration re-emitted, every cursor still advanced
        assert fab.router.invariant_violations() == 0
        assert fab.router.pre_migration_re_emissions() == 0
        for w in ws:
            assert w.cursor_seq >= pre[w.watcher_id]
            assert w.emissions[0]["type"] == "snapshot"
        # the per-node StreamingServices agree
        for fnode in fab.nodes.values():
            assert fnode.streaming.num_invariant_violations == 0
        assert fab.router.status()["migrations"] == len(
            placement[victim]
        )

    run(main())


@pytest.mark.chaos
def test_node_drain_hands_off_cleanly_and_kill_replays_identically(
    tmp_path,
):
    async def main():
        # drain: daemon stays up, hand-off unsubscribes the old node
        fab, ws, victim, placement, _pre, _logs = await _stream_scenario(
            tmp_path / "drain", drain_instead=True
        )
        assert fab.router.invariant_violations() == 0
        assert fab.router.pre_migration_re_emissions() == 0
        assert all(
            w.serving_node != victim for w in placement[victim]
        )
        # the drained daemon carries no fleet subscribers anymore
        stats = fab.nodes[victim].streaming.stats()
        assert sum(f["subscribers"] for f in stats["feeds"]) == 0
        # seeded replay: the whole kill scenario twice, byte-identical
        _f1, _w1, v1, _p1, _c1, log_a = await _stream_scenario(
            tmp_path / "replay_a"
        )
        _f2, _w2, v2, _p2, _c2, log_b = await _stream_scenario(
            tmp_path / "replay_b"
        )
        assert v1 == v2
        assert log_a == log_b

    run(main())


# ---------------------------------------------------------------------------
# fleet scale (slow tier)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.chaos
def test_fleet_scale_sweep_with_kill(tmp_path):
    """Five members, a bigger grammar, a mid-sweep kill — the same
    byte-identity law at fleet scale."""

    async def run_one(root, kill=None):
        clock = SimClock()
        fab = FleetFabric(
            clock,
            spill_root=str(root),
            node_names=tuple(f"fab{i}" for i in range(5)),
            n_side=4,
            sweep_overrides={
                "shard_scenarios": 8, "inter_shard_pause_s": 0.05,
            },
        )
        fab.start()
        await clock.run_for(2.0)
        out = await _drive_fleet_sweep(fab, clock, kill=kill)
        await fab.stop()
        return out

    async def main():
        d0, m0, _s0 = await run_one(tmp_path / "clean")
        d1, m1, st1 = await run_one(tmp_path / "killed", kill="fab2")
        assert d1 == d0 and m1 == m0
        assert st1["repacked_worlds"] > 0

    run(main())
