"""Decision actor tests (patterns from decision/tests/DecisionTest.cpp) +
TPU-backend vs scalar-backend differential parity."""

import asyncio
import json

import pytest

from openr_tpu.common.runtime import SimClock
from openr_tpu.config import DecisionConfig
from openr_tpu.decision.backend import ScalarBackend, TpuBackend
from openr_tpu.decision.decision import Decision
from openr_tpu.decision.rib import DecisionRouteUpdate, DecisionRouteUpdateType
from openr_tpu.decision.rib_policy import (
    RibPolicy,
    RibPolicyStatement,
    RibRouteActionWeight,
)
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.emulation.topology import build_adj_dbs, grid_edges, line_edges
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.types import (
    InitializationEvent,
    PrefixDatabase,
    PrefixEntry,
    Publication,
    Value,
    adj_key,
    prefix_key,
)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def adj_value(db, version=1):
    return Value(
        version=version,
        originator_id=db.this_node_name,
        value=json.dumps(db.to_wire()).encode(),
        ttl=300000,
    )


def prefix_value(node, entry, version=1, area="0"):
    db = PrefixDatabase(this_node_name=node, prefix_entries=[entry], area=area)
    return Value(
        version=version,
        originator_id=node,
        value=json.dumps(db.to_wire()).encode(),
        ttl=300000,
    )


def topology_publication(edges, area="0", **kwargs):
    dbs = build_adj_dbs(edges, area=area, **kwargs)
    return Publication(
        key_vals={adj_key(n): adj_value(db) for n, db in dbs.items()},
        area=area,
    )


class Rig:
    def __init__(self, clock, node="node0", config=None, backend=None):
        self.routes_q = ReplicateQueue("routeUpdates")
        self.routes_r = self.routes_q.get_reader()
        self.kv_q = ReplicateQueue("kvpubs")
        self.static_q = ReplicateQueue("static")
        self.init_events = []
        solver = SpfSolver(node)
        self.decision = Decision(
            node_name=node,
            clock=clock,
            config=config or DecisionConfig(unblock_initial_routes_ms=120000),
            route_updates_queue=self.routes_q,
            kv_store_updates_reader=self.kv_q.get_reader(),
            static_routes_reader=self.static_q.get_reader(),
            solver=solver,
            backend=backend,
            initialization_cb=self.init_events.append,
        )
        self.decision.start()

    def drain(self):
        out = []
        while (u := self.routes_r.try_get()) is not None:
            out.append(u)
        return out


def test_initial_build_gated_on_kvstore_sync():
    async def main():
        clock = SimClock()
        rig = Rig(clock)
        rig.kv_q.push(topology_publication(line_edges(3)))
        rig.kv_q.push(
            Publication(
                key_vals={
                    prefix_key("node2", "10.0.0.0/24"): prefix_value(
                        "node2", PrefixEntry("10.0.0.0/24")
                    )
                }
            )
        )
        await clock.run_for(5.0)
        assert rig.drain() == []  # gated: no KVSTORE_SYNCED yet
        rig.decision.on_initialization_event(InitializationEvent.KVSTORE_SYNCED)
        await clock.run_for(1.0)
        updates = rig.drain()
        assert len(updates) == 1
        assert updates[0].type == DecisionRouteUpdateType.FULL_SYNC
        assert "10.0.0.0/24" in updates[0].unicast_routes_to_update
        assert InitializationEvent.RIB_COMPUTED in rig.init_events
        assert updates[0].perf_events is not None
        await rig.decision.stop()

    run(main())


def test_forced_unblock_after_timeout():
    async def main():
        clock = SimClock()
        rig = Rig(clock, config=DecisionConfig(unblock_initial_routes_ms=2000))
        rig.kv_q.push(topology_publication(line_edges(2)))
        await clock.run_for(1.0)
        assert rig.drain() == []
        await clock.run_for(2.0)  # forced unblock at 2s
        updates = rig.drain()
        assert updates and updates[0].type == DecisionRouteUpdateType.FULL_SYNC
        assert rig.decision.counters.get("decision.forced_initial_unblock") == 1
        await rig.decision.stop()

    run(main())


def test_incremental_updates_after_full_sync():
    async def main():
        clock = SimClock()
        rig = Rig(clock)
        rig.decision.on_initialization_event(InitializationEvent.KVSTORE_SYNCED)
        rig.kv_q.push(topology_publication(line_edges(3)))
        rig.kv_q.push(
            Publication(
                key_vals={
                    prefix_key("node2", "10.0.0.0/24"): prefix_value(
                        "node2", PrefixEntry("10.0.0.0/24")
                    )
                }
            )
        )
        await clock.run_for(2.0)
        assert rig.drain()[0].type == DecisionRouteUpdateType.FULL_SYNC
        # new prefix appears -> one INCREMENTAL update with only that route
        rig.kv_q.push(
            Publication(
                key_vals={
                    prefix_key("node1", "10.9.0.0/24"): prefix_value(
                        "node1", PrefixEntry("10.9.0.0/24")
                    )
                }
            )
        )
        await clock.run_for(2.0)
        updates = rig.drain()
        assert len(updates) == 1
        assert updates[0].type == DecisionRouteUpdateType.INCREMENTAL
        assert list(updates[0].unicast_routes_to_update) == ["10.9.0.0/24"]
        # no-op publication (ttl refresh) -> no rebuild output
        rig.kv_q.push(
            Publication(
                key_vals={
                    adj_key("node1"): Value(
                        version=1, originator_id="node1", value=None, ttl=60000,
                        ttl_version=1,
                    )
                }
            )
        )
        await clock.run_for(2.0)
        assert rig.drain() == []
        await rig.decision.stop()

    run(main())


def test_publication_storm_debounced_into_one_build():
    async def main():
        clock = SimClock()
        rig = Rig(clock)
        rig.decision.on_initialization_event(InitializationEvent.KVSTORE_SYNCED)
        rig.kv_q.push(topology_publication(line_edges(4)))
        await clock.run_for(2.0)
        rig.drain()
        builds_before = rig.decision.counters.get("decision.route_build_runs")
        # 20 rapid metric changes, 2ms apart
        dbs = build_adj_dbs(line_edges(4))
        for i in range(20):
            for adj in dbs["node1"].adjacencies:
                adj.metric = 2 + i
            rig.kv_q.push(
                Publication(
                    key_vals={adj_key("node1"): adj_value(dbs["node1"], version=2 + i)}
                )
            )
            await clock.run_for(0.002)
        await clock.run_for(1.0)
        builds = rig.decision.counters.get("decision.route_build_runs") - builds_before
        assert builds <= 3  # debounce max 250ms coalesces the storm
        await rig.decision.stop()

    run(main())


def test_expired_adj_key_removes_node():
    async def main():
        clock = SimClock()
        rig = Rig(clock)
        rig.decision.on_initialization_event(InitializationEvent.KVSTORE_SYNCED)
        rig.kv_q.push(topology_publication(line_edges(3)))
        rig.kv_q.push(
            Publication(
                key_vals={
                    prefix_key("node2", "10.0.0.0/24"): prefix_value(
                        "node2", PrefixEntry("10.0.0.0/24")
                    )
                }
            )
        )
        await clock.run_for(2.0)
        assert "10.0.0.0/24" in rig.drain()[0].unicast_routes_to_update
        # node2's adjacency expires -> route withdrawn
        rig.kv_q.push(Publication(expired_keys=[adj_key("node2")]))
        await clock.run_for(2.0)
        updates = rig.drain()
        assert updates and updates[0].unicast_routes_to_delete == ["10.0.0.0/24"]
        await rig.decision.stop()

    run(main())


def test_rib_policy_apply_and_persist(tmp_path):
    async def main():
        clock = SimClock()
        policy_file = str(tmp_path / "rib_policy.json")
        rig = Rig(clock)
        rig.decision.rib_policy_file = policy_file
        rig.decision.on_initialization_event(InitializationEvent.KVSTORE_SYNCED)
        # diamond: two nexthops to node3's prefix
        edges = [
            ("node0", "node1", 1),
            ("node0", "node2", 1),
            ("node1", "node3", 1),
            ("node2", "node3", 1),
        ]
        rig.kv_q.push(topology_publication(edges))
        rig.kv_q.push(
            Publication(
                key_vals={
                    prefix_key("node3", "10.0.0.0/24"): prefix_value(
                        "node3", PrefixEntry("10.0.0.0/24")
                    )
                }
            )
        )
        await clock.run_for(2.0)
        route = rig.drain()[0].unicast_routes_to_update["10.0.0.0/24"]
        assert len(route.nexthops) == 2
        # policy: drop nexthops via node1, weight 3 elsewhere
        policy = RibPolicy(
            statements=[
                RibPolicyStatement(
                    name="drain-node1",
                    prefixes=["10.0.0.0/24"],
                    action=RibRouteActionWeight(
                        default_weight=3, neighbor_to_weight={"node1": 0}
                    ),
                )
            ],
            valid_until=clock.now() + 60.0,
        )
        rig.decision.set_rib_policy(policy)
        await clock.run_for(1.0)
        updates = rig.drain()
        assert updates
        route = updates[-1].unicast_routes_to_update["10.0.0.0/24"]
        assert {nh.neighbor_node_name for nh in route.nexthops} == {"node2"}
        assert next(iter(route.nexthops)).weight == 3
        # persisted with remaining ttl
        saved = RibPolicy.from_json(open(policy_file).read(), clock)
        assert saved is not None and saved.statements[0].name == "drain-node1"
        await rig.decision.stop()

    run(main())


def test_compute_route_db_for_other_node():
    async def main():
        clock = SimClock()
        rig = Rig(clock)
        rig.decision.on_initialization_event(InitializationEvent.KVSTORE_SYNCED)
        rig.kv_q.push(topology_publication(line_edges(3)))
        rig.kv_q.push(
            Publication(
                key_vals={
                    prefix_key("node0", "10.0.0.0/24"): prefix_value(
                        "node0", PrefixEntry("10.0.0.0/24")
                    )
                }
            )
        )
        await clock.run_for(2.0)
        # from node2's perspective the route points toward node1
        db = rig.decision.compute_route_db_for_node("node2")
        nh = next(iter(db.unicast_routes["10.0.0.0/24"].nexthops))
        assert nh.neighbor_node_name == "node1"
        await rig.decision.stop()

    run(main())


def _routes_summary(db):
    return {
        p: (
            round(e.igp_cost, 1),
            sorted(nh.neighbor_node_name for nh in e.nexthops),
            e.best_area,
            e.best_prefix_entry.metrics.drain_metric,
        )
        for p, e in db.unicast_routes.items()
    }


def test_tpu_backend_matches_scalar_backend():
    """The flagship seam: TpuBackend must produce the identical RouteDb."""
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState

    edges = grid_edges(4)
    dbs = build_adj_dbs(
        edges, overloaded=["node5"], soft_drained={"node10": 60}
    )
    ls = LinkState("0", "node0")
    for db in dbs.values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    ps.update_prefix("node15", "0", PrefixEntry("10.0.0.0/24"))
    ps.update_prefix("node12", "0", PrefixEntry("10.0.0.0/24"))
    ps.update_prefix("node3", "0", PrefixEntry("2001:db8::/64"))
    ps.update_prefix("node5", "0", PrefixEntry("10.7.0.0/24"))  # hard-drained
    ps.update_prefix("node10", "0", PrefixEntry("10.8.0.0/24"))  # soft-drained
    ps.update_prefix("node0", "0", PrefixEntry("10.9.0.0/24"))  # self
    ps.update_prefix(
        "node9", "0", PrefixEntry("10.3.0.0/24", min_nexthop=5)
    )  # gated

    scalar_db = ScalarBackend(SpfSolver("node0")).build_route_db({"0": ls}, ps)
    tpu_db = TpuBackend(SpfSolver("node0")).build_route_db({"0": ls}, ps)
    assert _routes_summary(tpu_db) == _routes_summary(scalar_db)
    # nexthop details too (addresses, interfaces)
    for p in scalar_db.unicast_routes:
        assert (
            tpu_db.unicast_routes[p].nexthops
            == scalar_db.unicast_routes[p].nexthops
        ), p


def test_tpu_backend_in_decision_actor():
    async def main():
        clock = SimClock()
        solver = SpfSolver("node0")
        rig = Rig(clock, backend=TpuBackend(solver))
        rig.decision.solver = solver
        rig.decision.on_initialization_event(InitializationEvent.KVSTORE_SYNCED)
        rig.kv_q.push(topology_publication(grid_edges(3)))
        rig.kv_q.push(
            Publication(
                key_vals={
                    prefix_key("node8", "10.0.0.0/24"): prefix_value(
                        "node8", PrefixEntry("10.0.0.0/24")
                    )
                }
            )
        )
        await clock.run_for(2.0)
        updates = rig.drain()
        assert updates and "10.0.0.0/24" in updates[0].unicast_routes_to_update
        route = updates[0].unicast_routes_to_update["10.0.0.0/24"]
        assert {nh.neighbor_node_name for nh in route.nexthops} == {
            "node1",
            "node3",
        }
        await rig.decision.stop()

    run(main())


def test_tpu_backend_wide_anycast_uses_bigger_candidate_bucket():
    """10 candidates exceed the smallest bucket (8): the encoder widens to
    the 16 bucket and the device path still runs (VERDICT r1 weak #8)."""
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.emulation.topology import ring_edges

    edges = ring_edges(12)
    dbs = build_adj_dbs(edges)
    ls = LinkState("0", "node0")
    for db in dbs.values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(1, 11):
        ps.update_prefix(f"node{i}", "0", PrefixEntry("10.0.0.0/24"))
    backend = TpuBackend(SpfSolver("node0"))
    db = backend.build_route_db({"0": ls}, ps)
    assert backend.num_scalar_builds == 0
    assert backend.num_device_builds == 1
    scalar = ScalarBackend(SpfSolver("node0")).build_route_db({"0": ls}, ps)
    assert _routes_summary(db) == _routes_summary(scalar)


def test_tpu_backend_falls_back_past_largest_candidate_bucket():
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.emulation.topology import ring_edges

    n = 70  # > largest candidate bucket (64)
    edges = ring_edges(n)
    dbs = build_adj_dbs(edges)
    ls = LinkState("0", "node0")
    for db in dbs.values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(1, n):
        ps.update_prefix(f"node{i}", "0", PrefixEntry("10.0.0.0/24"))
    backend = TpuBackend(SpfSolver("node0"))
    db = backend.build_route_db({"0": ls}, ps)
    assert backend.num_scalar_builds == 1
    assert backend.num_fallback_cand_overflow == 1
    scalar = ScalarBackend(SpfSolver("node0")).build_route_db({"0": ls}, ps)
    assert _routes_summary(db) == _routes_summary(scalar)


def test_auto_cutover_picks_scalar_on_small_worlds():
    """min_device_prefixes=None (the daemon default) auto-calibrates:
    an expensive dispatch round trip routes small builds to the scalar
    path; a free one keeps the device path — no operator tuning
    (VERDICT r3 weak #4)."""
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.rib import route_db_summary

    ls = LinkState("0")
    for db in build_adj_dbs(grid_edges(3)).values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(9):
        ps.update_prefix(f"node{i}", "0", PrefixEntry(f"10.{i}.0.0/24"))

    expensive = TpuBackend(SpfSolver("node0"), min_device_prefixes=None)
    expensive.auto_dispatch_rt_ms = 1000.0  # tunnel-like
    db = expensive.build_route_db({"0": ls}, ps)
    assert expensive.num_small_scalar_builds == 1
    assert expensive.num_device_builds == 0

    free = TpuBackend(SpfSolver("node0"), min_device_prefixes=None)
    free.auto_dispatch_rt_ms = 0.0001  # collocated device
    db2 = free.build_route_db({"0": ls}, ps)
    assert free.num_device_builds == 1
    assert route_db_summary(db) == route_db_summary(db2)


def test_backend_selection_survives_jit_cache_corruption(monkeypatch):
    """The jax-0.9 executable-cache corruption ("Execution supplied N
    buffers but compiled program expected M") can strike the backend's
    multi_area_select_from_tables / multi_area_spf_tables calls when
    OTHER kernel families compiled first in the same process (observed:
    CLI-golden + ctrl test kernels, then a small build).  The backend
    must heal through ops.jit_guard (clear caches + retry), not fall
    back to scalar.  Simulates the corruption deterministically by
    failing the first call with the exact jaxlib signature."""
    from openr_tpu.decision.backend import TpuBackend
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.ops import route_select

    ls = LinkState("0")
    for db in build_adj_dbs(grid_edges(3)).values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(9):
        ps.update_prefix(f"node{i}", "0", PrefixEntry(f"10.{i}.0.0/24"))

    real = route_select.multi_area_select_from_tables
    calls = {"n": 0}

    def corrupt_once(*args, **kwargs):
        calls["n"] += 1
        if calls["n"] == 1:
            raise ValueError(
                "INVALID_ARGUMENT: Execution supplied 12 buffers but "
                "compiled program expected 14 buffers"
            )
        return real(*args, **kwargs)

    monkeypatch.setattr(
        route_select, "multi_area_select_from_tables", corrupt_once
    )
    backend = TpuBackend(SpfSolver("node0"), min_device_prefixes=0)
    db = backend.build_route_db({"0": ls}, ps)
    assert backend.num_device_builds == 1, "guard must heal, not fall back"
    assert calls["n"] == 2  # failed once, retried once
    assert db.unicast_routes
