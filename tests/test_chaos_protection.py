"""Chaos × fast-reroute protection tier (ISSUE 16).

Acceptance, on a seeded 9-node grid with a TPU-backed vantage whose
protection tier is live:

* a protected single-link flap converges from the minted table —
  ``decision.frr_applied`` fires, the confirming warm solve agrees
  (zero mismatches), and the vantage's RIB has scalar-oracle parity;
* a flap landing on a STALE table (the LSDB moved, no re-mint yet)
  falls back warm — counted, never applied — and the RIB still has
  parity;
* a seeded ``tpu_corrupt(device_index=3)`` landing MID-MINT purges the
  table (purge-on-suspicion via the governor's quarantine listener),
  quarantines exactly chip 3, and the next mint completes on the 7
  survivors with a READY table;
* every scenario's end state is byte-identical across two replays of
  the same virtual-time schedule (route summary + table hash +
  protection counters), because patch identity is content-addressed
  and minting follows the sweep's deterministic shard order.

The 64-node grid8 variant of the protected flap runs the same
assertions at fabric scale (slow tier).
"""

import asyncio
import hashlib

import pytest

from openr_tpu.chaos import ChaosController, FaultPlan, InvariantChecker
from openr_tpu.common.runtime import SimClock
from openr_tpu.config import ParallelConfig, ProtectionConfig, ResilienceConfig
from openr_tpu.decision.backend import ScalarBackend
from openr_tpu.decision.rib import route_db_summary
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.emulation.network import EmulatedNetwork
from openr_tpu.emulation.topology import grid_edges
from openr_tpu.sweep.scenario import canonical_json

pytestmark = [pytest.mark.chaos, pytest.mark.protection, pytest.mark.multichip]

SEED = 7
CONVERGE_S = 18.0
VANTAGE = "node4"
BAD_CHIP = 3


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        pending = asyncio.all_tasks(loop)
        for t in pending:
            t.cancel()
        if pending:
            loop.run_until_complete(
                asyncio.gather(*pending, return_exceptions=True)
            )
        loop.close()


def overrides(tmp_path, vantage=VANTAGE, slow_mint=False):
    def apply(cfg):
        cfg.tpu_compute_config.min_device_prefixes = 0  # always device
        cfg.parallel_config = ParallelConfig(min_shard_rows=0)
        cfg.resilience_config = ResilienceConfig(
            shadow_sample_every=2,
            failure_threshold=2,
            probe_backoff_initial_s=0.5,
            probe_backoff_max_s=4.0,
            jitter_pct=0.1,
            seed=SEED,
        )
        if cfg.node_name == vantage:
            cfg.protection_config = ProtectionConfig(
                enabled=True,
                store_dir=str(tmp_path / f"prot.{cfg.node_name}"),
                mint_debounce_s=0.2,
                # slow_mint stretches a 12-link mint over ~10 virtual
                # seconds so the chaos corruption + quarantine land
                # MID-mint
                shard_scenarios=1 if slow_mint else 4,
                inter_shard_pause_s=0.8 if slow_mint else 0.01,
            )

    return apply


async def booted_grid(tmp_path, n=3, slow_mint=False):
    clock = SimClock()
    net = EmulatedNetwork(
        clock,
        use_tpu_backend=True,
        config_overrides=overrides(tmp_path, slow_mint=slow_mint),
    )
    net.build(grid_edges(n))
    net.start()
    await clock.run_for(CONVERGE_S)
    ok, why = net.converged_full_mesh()
    assert ok, why
    return clock, net


async def wait_table_ready(clock, svc, budget_s=60.0):
    for _ in range(int(budget_s / 0.5)):
        if svc.table.state == "ready":
            return
        await clock.run_for(0.5)
    raise AssertionError(
        f"table never went ready: {svc.table.state} {svc.error!r}"
    )


def vantage_parity(net):
    d = net.nodes[VANTAGE].decision
    oracle = ScalarBackend(SpfSolver(VANTAGE)).build_route_db(
        d.area_link_states, d.prefix_state
    )
    assert route_db_summary(d.route_db) == route_db_summary(oracle)


def end_state_digest(net) -> str:
    """Everything the scenario is allowed to vary: the vantage RIB, the
    minted table identity and the protection counter ledger."""
    d = net.nodes[VANTAGE].decision
    svc = net.nodes[VANTAGE].protection
    doc = {
        "routes": route_db_summary(d.route_db),
        "table": svc.table.status(),
        "counters": {
            k: v
            for k, v in sorted(d.counters.dump().items())
            if k.startswith(("protection.", "decision.frr"))
        },
    }
    return hashlib.sha256(canonical_json(doc).encode()).hexdigest()


# ---------------------------------------------------------------------------
# scenario (a): protected flap converges from the table, with parity
# ---------------------------------------------------------------------------


async def _protected_flap(tmp_path, n=3) -> str:
    clock, net = await booted_grid(tmp_path, n=n)
    svc = net.nodes[VANTAGE].protection
    assert svc is not None, "vantage must boot the protection tier"
    checker = InvariantChecker(net)
    await wait_table_ready(clock, svc)
    assert svc.table.eligible > 0

    d = net.nodes[VANTAGE].decision
    # a REMOTE link (the vantage keeps its own lanes): protected flap
    net.fail_link("node0", "node1")
    await clock.run_for(4.0)
    assert d.counters.get("decision.frr_applied") == 1
    assert d.counters.get("decision.frr_mismatches") == 0
    assert d.counters.get("protection.confirms") >= 1
    assert net.nodes[VANTAGE].fib.counters.get("fib.frr_patches_applied") == 1
    vantage_parity(net)

    # the tier re-mints for the new topology and keeps protecting
    await wait_table_ready(clock, svc)
    checker.check_change_seq_monotonic()
    checker.check_no_blackholes()
    digest = end_state_digest(net)
    await net.stop()
    return digest


def test_protected_flap_converges_from_table_with_parity(tmp_path):
    a = run(_protected_flap(tmp_path / "a"))
    b = run(_protected_flap(tmp_path / "b"))
    assert a == b, "seeded replays must be byte-identical"


@pytest.mark.slow
def test_protected_flap_at_grid8_scale(tmp_path):
    a = run(_protected_flap(tmp_path / "a", n=8))
    b = run(_protected_flap(tmp_path / "b", n=8))
    assert a == b, "seeded replays must be byte-identical"


# ---------------------------------------------------------------------------
# scenario (b): stale table falls back warm
# ---------------------------------------------------------------------------


async def _stale_fallback(tmp_path) -> str:
    clock, net = await booted_grid(tmp_path)
    svc = net.nodes[VANTAGE].protection
    await wait_table_ready(clock, svc)
    d = net.nodes[VANTAGE].decision

    # two failures inside ONE debounce/mint window: the first applies
    # from the table; the second arrives while the table is stale for
    # its (new) previous generation and must fall back warm.  The
    # window is long in virtual time (mint wall >> flap spacing), so
    # the race is deterministic.
    net.fail_link("node0", "node1")
    await clock.run_for(0.05)
    net.fail_link("node2", "node5")
    await clock.run_for(6.0)
    assert d.counters.get("protection.fallbacks") >= 1, (
        "the second flap must refuse the stale table"
    )
    assert (
        d.counters.get("protection.fallback.stale")
        + d.counters.get("protection.fallback.minting")
        + d.counters.get("protection.fallback.miss")
        >= 1
    )
    assert d.counters.get("decision.frr_mismatches") == 0
    vantage_parity(net)
    await wait_table_ready(clock, svc)
    digest = end_state_digest(net)
    await net.stop()
    return digest


def test_stale_table_falls_back_warm(tmp_path):
    a = run(_stale_fallback(tmp_path / "a"))
    b = run(_stale_fallback(tmp_path / "b"))
    assert a == b, "seeded replays must be byte-identical"


# ---------------------------------------------------------------------------
# scenario (c): tpu_corrupt mid-mint — purge, quarantine chip 3, re-mint
# ---------------------------------------------------------------------------


async def _corrupt_mid_mint(tmp_path) -> str:
    clock, net = await booted_grid(tmp_path, slow_mint=True)
    svc = net.nodes[VANTAGE].protection
    d = net.nodes[VANTAGE].decision
    await wait_table_ready(clock, svc)

    # arm the chaos: chip 3 starts lying 1s from now, for long enough
    # to span the whole scenario
    plan = FaultPlan().tpu_corrupt(
        VANTAGE, at=1.0, duration=200.0, device_index=BAD_CHIP
    )
    controller = ChaosController(net, plan, seed=SEED)
    controller.start()

    # dirty the table: the re-mint (1 scenario/shard, 0.8s pauses)
    # stretches over ~10 virtual seconds
    net.fail_link("node0", "node1")
    await clock.run_for(2.0)
    # shadow-checked full rebuilds catch the lying chip while the mint
    # is still walking its shards
    net.fail_link("node1", "node2")
    await clock.run_for(2.0)
    net.restore_link("node1", "node2")
    await clock.run_for(6.0)

    gov = net.nodes[VANTAGE].decision.backend.governor
    assert gov.num_chip_quarantines >= 1, "chip 3 must quarantine"
    pool = net.nodes[VANTAGE].decision.backend.dispatch_pool()
    assert pool.quarantined_indices() == [BAD_CHIP], (
        "exactly the corrupted chip quarantines"
    )
    assert d.counters.get("protection.purge.quarantine") >= 1, (
        "quarantine must purge the table (purge-on-suspicion)"
    )

    # the next mint completes on the 7 survivors
    await wait_table_ready(clock, svc)
    assert svc.table.eligible > 0
    vantage_parity(net)
    await controller.stop()
    digest = end_state_digest(net)
    await net.stop()
    return digest


def test_tpu_corrupt_mid_mint_purges_quarantines_and_reminnts(tmp_path):
    a = run(_corrupt_mid_mint(tmp_path / "a"))
    b = run(_corrupt_mid_mint(tmp_path / "b"))
    assert a == b, "seeded replays must be byte-identical"
