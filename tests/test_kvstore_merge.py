"""mergeKeyValues conflict-resolution tests (semantics from
openr/kvstore/tests/KvStoreUtilTest.cpp) + convergence property test."""

import random

from openr_tpu import constants as C
from openr_tpu.kvstore.merge import (
    ComparisonResult,
    MergeResult,
    compare_values,
    dump_hashes,
    generate_hash,
    merge_key_values,
)
from openr_tpu.types import KvStoreNoMergeReason, Value


def v(version=1, originator="node1", value=b"data", ttl=300000, ttl_version=0):
    val = Value(
        version=version,
        originator_id=originator,
        value=value,
        ttl=ttl,
        ttl_version=ttl_version,
    )
    val.hash = generate_hash(val)
    return val


def test_fresh_key_accepted():
    store = {}
    r = merge_key_values(store, {"k": v()})
    assert "k" in r.key_vals and store["k"].value == b"data"
    assert store["k"].hash is not None


def test_invalid_ttl_rejected():
    store = {}
    r = merge_key_values(store, {"k": v(ttl=0), "j": v(ttl=-5)})
    assert store == {}
    assert r.no_merge_reasons["k"] == KvStoreNoMergeReason.INVALID_TTL
    assert r.no_merge_reasons["j"] == KvStoreNoMergeReason.INVALID_TTL
    # infinity is valid
    r2 = merge_key_values(store, {"k": v(ttl=C.TTL_INFINITY)})
    assert "k" in r2.key_vals


def test_old_version_rejected():
    store = {"k": v(version=5)}
    r = merge_key_values(store, {"k": v(version=4, value=b"other")})
    assert r.key_vals == {}
    assert r.no_merge_reasons["k"] == KvStoreNoMergeReason.OLD_VERSION
    assert store["k"].version == 5
    # version 0 is undefined -> rejected even on empty store
    r2 = merge_key_values({}, {"k": v(version=0)})
    assert r2.no_merge_reasons["k"] == KvStoreNoMergeReason.OLD_VERSION


def test_higher_version_wins():
    store = {"k": v(version=1, value=b"old")}
    r = merge_key_values(store, {"k": v(version=2, value=b"new")})
    assert store["k"].value == b"new"
    assert "k" in r.key_vals


def test_same_version_higher_originator_wins():
    store = {"k": v(originator="nodeA", value=b"a")}
    r = merge_key_values(store, {"k": v(originator="nodeB", value=b"b")})
    assert store["k"].originator_id == "nodeB"
    assert "k" in r.key_vals
    # lower originator loses
    r2 = merge_key_values(store, {"k": v(originator="nodeA", value=b"zzz")})
    assert store["k"].originator_id == "nodeB"
    assert r2.no_merge_reasons["k"] == KvStoreNoMergeReason.NO_NEED_TO_UPDATE


def test_same_version_originator_larger_value_wins():
    store = {"k": v(value=b"aaa")}
    r = merge_key_values(store, {"k": v(value=b"bbb")})
    assert store["k"].value == b"bbb"
    assert "k" in r.key_vals
    r2 = merge_key_values(store, {"k": v(value=b"abc")})
    assert store["k"].value == b"bbb"
    assert r2.key_vals == {}


def test_equal_value_higher_ttl_version_refreshes():
    store = {"k": v(ttl_version=1)}
    stored_obj = store["k"]
    r = merge_key_values(store, {"k": v(ttl_version=3, ttl=60000)})
    assert "k" in r.key_vals
    assert store["k"] is stored_obj  # ttl-update mutates, not replaces
    assert store["k"].ttl_version == 3
    assert store["k"].ttl == 60000
    # equal ttl_version: no-op
    r2 = merge_key_values(store, {"k": v(ttl_version=3)})
    assert r2.key_vals == {}


def test_ttl_update_without_value():
    store = {"k": v(ttl_version=0)}
    ttl_up = Value(version=1, originator_id="node1", value=None, ttl=90000, ttl_version=2)
    r = merge_key_values(store, {"k": ttl_up})
    assert "k" in r.key_vals
    assert store["k"].ttl == 90000 and store["k"].ttl_version == 2
    assert store["k"].value == b"data"  # data preserved


def test_ttl_update_missing_key_inconsistency():
    ttl_up = Value(version=1, originator_id="node1", value=None, ttl=90000, ttl_version=2)
    # sender is NOT originator: dropped quietly
    r = merge_key_values({}, {"k": ttl_up}, sender="node9")
    assert not r.inconsistency_detected_with_originator
    assert r.no_merge_reasons["k"] == KvStoreNoMergeReason.NO_MATCHED_KEY
    # sender IS originator: resync flag raised
    r2 = merge_key_values({}, {"k": ttl_up}, sender="node1")
    assert r2.inconsistency_detected_with_originator
    assert r2.no_merge_reasons["k"] == KvStoreNoMergeReason.INCONSISTENCY_DETECTED


def test_ttl_update_version_mismatch_inconsistency():
    store = {"k": v(version=3)}
    ttl_up = Value(version=2, originator_id="node1", value=None, ttl=90000, ttl_version=9)
    r = merge_key_values(store, {"k": ttl_up}, sender="node1")
    assert r.inconsistency_detected_with_originator


def test_key_filter():
    store = {}
    r = merge_key_values(
        store,
        {"adj:x": v(), "prefix:y": v()},
        key_filter=lambda k, _v: k.startswith("adj:"),
    )
    assert set(store) == {"adj:x"}
    assert r.no_merge_reasons["prefix:y"] == KvStoreNoMergeReason.NO_MATCHED_KEY


def test_compare_values():
    assert compare_values(v(version=2), v(version=1)) == ComparisonResult.FIRST
    assert (
        compare_values(v(originator="a"), v(originator="b"))
        == ComparisonResult.SECOND
    )
    a, b = v(ttl_version=5), v(ttl_version=2)
    assert compare_values(a, b) == ComparisonResult.FIRST
    assert compare_values(v(), v()) == ComparisonResult.TIED
    assert (
        compare_values(v(value=b"zz"), v(value=b"aa")) == ComparisonResult.FIRST
    )


def test_dump_hashes():
    store = {"a": v(), "b": v(version=2)}
    h = dump_hashes(store)
    assert set(h) == {"a", "b"}
    assert h["b"][0] == 2
    assert dump_hashes(store, ["b", "missing"]) == {"b": h["b"]}


def test_merge_convergence_property():
    """Any interleaving of the same update set converges to one state."""
    rng = random.Random(7)
    updates = []
    for i in range(200):
        updates.append(
            (
                f"key{rng.randrange(12)}",
                v(
                    version=rng.randrange(1, 5),
                    originator=f"node{rng.randrange(4)}",
                    value=bytes([rng.randrange(256)]) * 3,
                    ttl_version=rng.randrange(3),
                ),
            )
        )
    stores = [{} for _ in range(4)]
    for store in stores:
        order = updates[:]
        rng.shuffle(order)
        for key, value in order:
            merge_key_values(store, {key: value})
    # pairwise cross-merge (simulates anti-entropy sync)
    for a in stores:
        for b in stores:
            merge_key_values(a, dict(b))
    base = {
        k: (val.version, val.originator_id, val.value, val.ttl_version)
        for k, val in stores[0].items()
    }
    for store in stores[1:]:
        got = {
            k: (val.version, val.originator_id, val.value, val.ttl_version)
            for k, val in store.items()
        }
        assert got == base


def test_merge_order_is_canonical_not_arrival_order():
    """ISSUE-15 regression (orlint unordered-emission): the accepted
    delta's iteration order becomes the flooded publication's wire
    order, and before the fix it was the INCOMING dict's insertion
    order — stable across seeded replays only by the accident that both
    replays reconstruct identical arrival order.  Two stores merging
    the same facts delivered in different orders emitted different
    bytes.  Now the merge iterates sorted keys, so the accepted delta
    (and anything serialized from it) is content-ordered."""
    vals = {f"k{i:02d}": v(version=i + 1, value=b"x%d" % i) for i in range(8)}
    forward = dict(sorted(vals.items()))
    backward = dict(sorted(vals.items(), reverse=True))
    assert list(forward) != list(backward)  # genuinely different arrival

    s1, s2 = {}, {}
    r1 = merge_key_values(s1, forward)
    r2 = merge_key_values(s2, backward)
    # identical accepted content AND identical iteration order
    assert list(r1.key_vals) == list(r2.key_vals) == sorted(vals)
    # the stores converge byte-identically too (same insertion order)
    assert list(s1) == list(s2)
    assert {k: val.hash for k, val in s1.items()} == {
        k: val.hash for k, val in s2.items()
    }
