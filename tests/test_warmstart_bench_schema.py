"""Tier-1 smoke: the checked-in BENCH_WARMSTART artifact obeys the
schema the bench emits (shared validator — bench.validate_warmstart_bench)
and holds the ISSUE-9 acceptance shape: warm generation-delta rebuild
p50 below BOTH the in-run cold p50 and the round-5 127ms grid4096
reference, device warm sweep beating the cold kernel on the same sweep,
in-bench warm-vs-cold RIB parity asserted, and the warm-hit /
cold-fallback counters recorded.

The validator lives in bench.py so the emitter and this gate can never
drift apart; regenerate with `python bench.py --warm-start`.
"""

import json
import pathlib

import bench

ARTIFACT = pathlib.Path(__file__).resolve().parent.parent / (
    "BENCH_WARMSTART_r01.json"
)


def doc():
    return json.loads(ARTIFACT.read_text())


def test_artifact_exists_and_matches_schema():
    bench.validate_warmstart_bench(doc())


def test_warm_beats_cold_and_the_r05_reference():
    d = doc()
    rb = d["detail"]["rebuild"]
    assert d["value"] < bench.WARMSTART_COLD_P50_REFERENCE_MS
    assert rb["warm_p50_ms"] < rb["cold_p50_ms"]
    assert rb["speedup_vs_cold"] > 1.0


def test_warm_hit_and_fallback_counts_recorded():
    rb = doc()["detail"]["rebuild"]
    assert rb["warm_hits"] == rb["generations"]
    assert rb["cold_fallbacks"] == 0
    assert rb["warm_selective_builds"] == rb["generations"]
    assert rb["encode_patches"] >= 1


def test_parity_was_asserted_in_bench():
    rb = doc()["detail"]["rebuild"]
    assert rb["parity_ok"] is True
    assert rb["parity_checks"] >= 2


def test_sweep_incrementality_and_native_baseline():
    sw = doc()["detail"]["sweep"]
    assert (
        sw["device_warm_solves_per_sec"] > sw["device_cold_solves_per_sec"]
    )
    assert sw["native_warm_solves_per_sec"] > 0
    # the device-beats-native gate binds whenever a real accelerator is
    # attached; on cpu the ratio is still recorded for transparency
    assert "warm_vs_native" in sw


def test_environment_triple_is_recorded():
    env = doc()["detail"]["env"]
    for key in ("platform", "jax", "device_count"):
        assert key in env
