"""Resilient compute plane (ISSUE 5): CircuitBreaker state machine,
BackendHealthGovernor shadow verification / quarantine / probed
recovery, the Fib agent breaker, and the 9-node ``tpu_corrupt`` chaos
acceptance run — silent device corruption is DETECTED (RIB diff against
the scalar oracle), the device is QUARANTINED (route builds, serving and
what-if degrade coherently), routes keep flowing from the scalar engine
with invariants green, and a half-open probe RESTORES the device after
heal — all deterministic from one seed.
"""

import asyncio
import dataclasses
import math

import pytest

from openr_tpu.common.runtime import SimClock
from openr_tpu.config import ResilienceConfig
from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.emulation.topology import build_adj_dbs, ring_edges
from openr_tpu.resilience import (
    STATE_CLOSED,
    STATE_HALF_OPEN,
    STATE_OPEN,
    CircuitBreaker,
)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# CircuitBreaker: SimClock-deterministic state machine
# ---------------------------------------------------------------------------


def make_breaker(clock, **kw):
    kw.setdefault("failure_threshold", 3)
    kw.setdefault("backoff_initial_s", 1.0)
    kw.setdefault("backoff_max_s", 8.0)
    kw.setdefault("jitter_pct", 0.0)
    return CircuitBreaker("test", clock, **kw)


def test_breaker_closed_to_open_to_half_open_to_closed():
    clock = SimClock()
    br = make_breaker(clock)
    assert br.state == STATE_CLOSED and br.allow_request()
    br.record_failure()
    br.record_failure()
    assert br.state == STATE_CLOSED  # below threshold
    br.record_failure()
    assert br.state == STATE_OPEN and br.num_opens == 1
    assert not br.allow_request()  # hold not elapsed -> short-circuit
    assert br.num_short_circuits == 1
    clock._now += 1.5  # past the 1s hold
    assert br.allow_request()  # THE probe
    assert br.state == STATE_HALF_OPEN and br.num_probes == 1
    br.record_success()
    assert br.state == STATE_CLOSED and br.num_closes == 1
    # the ladder reset: a fresh failure run re-opens at the initial hold
    for _ in range(3):
        br.record_failure()
    assert br.state == STATE_OPEN and br.current_hold_s() == 1.0


def test_breaker_failed_probe_doubles_the_hold():
    clock = SimClock()
    br = make_breaker(clock)
    for _ in range(3):
        br.record_failure()
    assert br.current_hold_s() == 1.0
    clock._now += 2.0
    assert br.allow_request()
    br.record_failure()  # probe failed
    assert br.state == STATE_OPEN
    assert br.num_probe_failures == 1
    assert br.current_hold_s() == 2.0  # doubled
    clock._now += 3.0
    assert br.allow_request()
    br.record_failure()
    assert br.current_hold_s() == 4.0
    # ...capped at the max
    for _ in range(4):
        clock._now += 100.0
        assert br.allow_request()
        br.record_failure()
    assert br.current_hold_s() == 8.0


def test_breaker_concurrent_probe_exclusion():
    clock = SimClock()
    br = make_breaker(clock)
    for _ in range(3):
        br.record_failure()
    clock._now += 2.0
    assert br.allow_request()  # probe owner
    # everyone else is short-circuited until the probe resolves
    assert not br.allow_request()
    assert not br.allow_request()
    br.record_success()
    assert br.allow_request()  # closed again


def test_breaker_probe_exclusion_under_concurrent_callers():
    """Two ACTORS racing a half-open breaker (satellite, ISSUE 6): both
    wake at the same virtual instant once the hold elapses; exactly one
    wins the probe slot, the loser short-circuits — deterministically
    under SimClock (wake order is the deterministic sleep-registration
    order, so replays are byte-identical)."""

    async def main():
        clock = SimClock()
        br = make_breaker(clock)
        for _ in range(3):
            br.record_failure()
        assert br.state == STATE_OPEN
        outcomes = {}

        async def caller(name):
            await clock.sleep(2.0)  # both due at the same virtual time
            outcomes[name] = br.allow_request()

        t1 = asyncio.ensure_future(caller("a"))
        t2 = asyncio.ensure_future(caller("b"))
        await clock.run_for(3.0)
        await asyncio.gather(t1, t2)
        # exactly ONE probe admitted; the loser short-circuited
        assert sorted(outcomes.values()) == [False, True]
        assert br.state == STATE_HALF_OPEN
        assert br.num_probes == 1 and br.num_short_circuits == 1
        # deterministic winner: sleep-registration order
        assert outcomes["a"] is True and outcomes["b"] is False
        # the probe resolves; admission reopens for everyone
        br.record_success()
        assert br.allow_request()

    run(main())


def test_breaker_release_probe_is_unscored():
    clock = SimClock()
    br = make_breaker(clock)
    br.force_open()
    clock._now += 2.0
    assert br.allow_request()
    hold = br.current_hold_s()
    br.release_probe()  # probe never exercised the dependency
    assert br.state == STATE_OPEN
    assert br.num_probe_failures == 0
    assert br.current_hold_s() == hold  # no escalation
    assert br.allow_request()  # immediately re-probeable


def test_breaker_jitter_bounds_and_determinism():
    def holds(seed):
        clock = SimClock()
        br = make_breaker(clock, jitter_pct=0.2, seed=seed)
        out = []
        for _ in range(6):
            br.force_open()
            out.append(br.current_hold_s())
            br.force_close()
        return out

    a = holds(5)
    # every draw within +/- jitter of the 1s base, and actually jittered
    assert all(0.8 <= h <= 1.2 for h in a), a
    assert len(set(a)) > 1, "jitter must vary across draws"
    # deterministic from the seed (the chaos reproducibility contract)
    assert a == holds(5)
    assert a != holds(6)


# ---------------------------------------------------------------------------
# BackendHealthGovernor over a real TpuBackend (small ring LSDB)
# ---------------------------------------------------------------------------


def make_world(n=6):
    edges = ring_edges(n)
    ls = LinkState("0", "node0")
    for db in build_adj_dbs(edges).values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    from openr_tpu.types import PrefixEntry

    for i in range(n):
        ps.update_prefix(f"node{i}", "0", PrefixEntry(f"10.7.{i}.0/24"))
    return {"0": ls}, ps


def make_backend(clock, **resilience_kw):
    from openr_tpu.decision.backend import TpuBackend

    resilience_kw.setdefault("shadow_sample_every", 1)
    resilience_kw.setdefault("failure_threshold", 2)
    resilience_kw.setdefault("probe_backoff_initial_s", 1.0)
    resilience_kw.setdefault("probe_backoff_max_s", 8.0)
    resilience_kw.setdefault("jitter_pct", 0.0)
    return TpuBackend(
        SpfSolver("node0"),
        clock=clock,
        resilience=ResilienceConfig(**resilience_kw),
    )


def norm_db(db):
    return {
        p: (sorted((nh.neighbor_node_name, nh.metric) for nh in e.nexthops),
            float(e.igp_cost))
        for p, e in db.unicast_routes.items()
    }


def test_shadow_verification_passes_on_healthy_device():
    als, ps = make_world()
    backend = make_backend(SimClock())
    db = backend.build_route_db(als, ps)
    gov = backend.governor
    assert gov.num_shadow_checks >= 1
    assert gov.num_shadow_mismatches == 0
    assert not backend.device_failed
    assert norm_db(db) == norm_db(SpfSolver("node0").build_route_db(als, ps))


def test_sdc_detected_quarantined_and_served_from_scalar():
    als, ps = make_world()
    clock = SimClock()
    backend = make_backend(clock)
    backend.build_route_db(als, ps)  # healthy baseline build
    backend.inject_silent_corruption(True)
    db = backend.build_route_db(als, ps, force_full=True)
    gov = backend.governor
    # detected on the sampled build, quarantined, and THE RETURNED DB IS
    # THE SCALAR ORACLE'S — the corrupt answer never leaves the backend
    assert gov.num_shadow_mismatches == 1
    assert gov.num_quarantines == 1
    assert backend.device_failed
    assert norm_db(db) == norm_db(SpfSolver("node0").build_route_db(als, ps))
    # while quarantined: scalar fallbacks, the device is never touched
    before = backend.num_device_builds
    db2 = backend.build_route_db(als, ps)
    assert backend.num_device_builds == before
    assert backend.num_fallback_injected >= 1
    assert norm_db(db2) == norm_db(db)


def test_probed_recovery_after_corruption_heals():
    als, ps = make_world()
    clock = SimClock()
    backend = make_backend(clock)
    gov = backend.governor
    backend.build_route_db(als, ps)
    backend.inject_silent_corruption(True)
    backend.build_route_db(als, ps, force_full=True)
    assert backend.device_failed
    # heal the kernel, but the hold hasn't elapsed: still scalar
    backend.inject_silent_corruption(False)
    backend.build_route_db(als, ps)
    assert backend.device_failed
    # hold elapses -> the next build is the half-open probe; it passes
    # shadow verification and restores the device
    clock._now += 5.0
    db = backend.build_route_db(als, ps, force_full=True)
    assert not backend.device_failed
    assert gov.num_restores == 1
    assert gov.breaker.num_probes >= 1
    assert norm_db(db) == norm_db(SpfSolver("node0").build_route_db(als, ps))


def test_failed_probe_reopens_with_doubled_hold():
    als, ps = make_world()
    clock = SimClock()
    backend = make_backend(clock)
    gov = backend.governor
    backend.build_route_db(als, ps)
    backend.inject_silent_corruption(True)
    backend.build_route_db(als, ps, force_full=True)
    hold0 = gov.breaker.current_hold_s()
    clock._now += hold0 + 0.5
    # still corrupt: the probe build FAILS verification -> re-quarantine
    backend.build_route_db(als, ps, force_full=True)
    assert backend.device_failed
    assert gov.breaker.num_probe_failures == 1
    assert gov.breaker.current_hold_s() == 2 * hold0


def test_dispatch_failures_trip_the_latch_after_threshold():
    als, ps = make_world()
    clock = SimClock()
    backend = make_backend(clock, failure_threshold=2)
    gov = backend.governor
    oracle = norm_db(SpfSolver("node0").build_route_db(als, ps))
    orig = backend._build_device

    def explode(*a, **k):
        raise RuntimeError("chip fell over")

    backend._build_device = explode
    # failure 1: scalar fallback for this build, latch still down
    db1 = backend.build_route_db(als, ps)
    assert norm_db(db1) == oracle
    assert not backend.device_failed and backend.num_dispatch_errors == 1
    # failure 2: threshold reached -> quarantined (no more re-paying the
    # failing device on every rebuild)
    db2 = backend.build_route_db(als, ps)
    assert norm_db(db2) == oracle
    assert backend.device_failed and gov.num_quarantines == 1
    touched = []
    backend._build_device = lambda *a, **k: touched.append(1)
    backend.build_route_db(als, ps)
    assert not touched, "quarantined build must not touch the device"
    # device heals; the hold elapses; the probe restores
    backend._build_device = orig
    clock._now += 10.0
    db3 = backend.build_route_db(als, ps, force_full=True)
    assert not backend.device_failed
    assert norm_db(db3) == oracle


def test_non_finite_guard_trips_shadow_verification():
    als, ps = make_world()
    backend = make_backend(SimClock())
    gov = backend.governor
    db = SpfSolver("node0").build_route_db(als, ps)
    prefix, entry = next(iter(db.unicast_routes.items()))
    db.unicast_routes[prefix] = dataclasses.replace(
        entry, igp_cost=float("nan")
    )
    ok, scalar_db, reason = gov._shadow_verify(db, als, ps)
    assert not ok and reason.startswith("non_finite")
    assert scalar_db is not None
    assert all(
        math.isfinite(e.igp_cost)
        for e in scalar_db.unicast_routes.values()
    )


def test_hard_quarantine_blocks_probes_until_requested():
    als, ps = make_world()
    clock = SimClock()
    backend = make_backend(clock)
    gov = backend.governor
    backend.build_route_db(als, ps)
    gov.force_quarantine(reason="chaos")
    assert backend.device_failed and gov.injected
    # injected outage: NO probes, however long the clock runs — the
    # fault owner declared the device dead
    clock._now += 500.0
    before = backend.num_device_builds
    backend.build_route_db(als, ps)
    assert backend.num_device_builds == before and backend.device_failed
    # the heal is PROBED: request_probe makes the next build a verified
    # probe solve, which restores
    gov.request_probe(reason="chaos_heal")
    assert backend.device_failed  # not restored until the probe passes
    backend.build_route_db(als, ps, force_full=True)
    assert not backend.device_failed and gov.num_restores == 1


def test_forced_probe_mismatch_quarantines_even_from_closed():
    """An operator `force_probe` that catches corruption must quarantine
    outright — even with sampling disabled and the breaker closed
    (probes ALWAYS shadow-verify; proven corruption is never ignored)."""
    als, ps = make_world()
    backend = make_backend(SimClock(), shadow_sample_every=0)
    gov = backend.governor
    backend.build_route_db(als, ps)
    assert gov.num_shadow_checks == 0  # sampling off: no routine checks
    backend.inject_silent_corruption(True)
    backend.build_route_db(als, ps, force_full=True)
    assert not backend.device_failed  # unsampled corruption undetected...
    out = gov.probe_now(als, ps)  # ...until the operator probes
    assert out["probed"] and out["passed"] is False
    assert backend.device_failed and gov.num_quarantines == 1


def test_operator_probe_now_restores_a_quarantined_device():
    als, ps = make_world()
    backend = make_backend(SimClock())
    gov = backend.governor
    backend.build_route_db(als, ps)
    gov.force_quarantine(reason="operator")
    out = gov.probe_now(als, ps)
    assert out["probed"] and out["passed"] and out["restored"]
    assert not backend.device_failed
    # with no LSDB there is nothing to probe against
    assert gov.probe_now({}, PrefixState())["probed"] is False


# ---------------------------------------------------------------------------
# Fib agent breaker: short-circuit while open, probe-close on retry
# ---------------------------------------------------------------------------


def test_fib_breaker_short_circuits_and_recovers():
    from openr_tpu.config import FibConfig
    from openr_tpu.decision.rib import (
        DecisionRouteUpdate,
        DecisionRouteUpdateType,
        RibUnicastEntry,
    )
    from openr_tpu.fib.fib import Fib, MockFibAgent
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.types import NextHop

    def route(prefix):
        return RibUnicastEntry(
            prefix=prefix,
            nexthops={NextHop(address="fe80::1", if_name="if1")},
        )

    async def main():
        clock = SimClock()
        q = ReplicateQueue("routeUpdates")
        agent = MockFibAgent(clock)
        fib = Fib(
            node_name="me",
            clock=clock,
            config=FibConfig(),
            agent=agent,
            route_updates_reader=q.get_reader(),
        )
        fib.start()
        q.push(
            DecisionRouteUpdate(
                type=DecisionRouteUpdateType.FULL_SYNC,
                unicast_routes_to_update={"10.0.0.0/24": route("10.0.0.0/24")},
            )
        )
        await clock.run_for(1.0)
        assert fib.breaker.state == STATE_CLOSED
        agent.fail = True
        q.push(
            DecisionRouteUpdate(
                unicast_routes_to_update={"10.1.0.0/24": route("10.1.0.0/24")}
            )
        )
        await clock.run_for(0.001)
        # first failure opened the breaker (threshold 1)
        assert fib.breaker.state != STATE_CLOSED and fib._dirty
        # further incremental updates SHORT-CIRCUIT: the failing agent is
        # not paid another per-update RPC (adds counter is frozen)
        adds_before = agent.num_add
        q.push(
            DecisionRouteUpdate(
                unicast_routes_to_update={"10.2.0.0/24": route("10.2.0.0/24")}
            )
        )
        await clock.run_for(0.001)
        assert agent.num_add == adds_before
        assert fib.breaker.num_short_circuits >= 1
        # desired state still tracked; agent heals; retry probes close it
        agent.fail = False
        await clock.run_for(30.0)
        assert not fib._dirty and fib.breaker.state == STATE_CLOSED
        assert "10.2.0.0/24" in agent.unicast
        gauges = fib.retry_state()
        assert gauges["resilience.fib_agent.state"] == 0.0
        assert gauges["resilience.fib_agent.opens"] >= 1
        await fib.stop()

    run(main())


# ---------------------------------------------------------------------------
# 9-node tpu_corrupt chaos acceptance: detect -> quarantine -> scalar
# serve -> probed recovery, deterministic from one seed
# ---------------------------------------------------------------------------

VICTIM = "node4"
SAMPLE_EVERY = 2


def corrupt_overrides(cfg):
    cfg.watchdog_config.interval_s = 1.0
    # always-device: the 9-node grid must actually exercise the kernel
    cfg.tpu_compute_config.min_device_prefixes = 0
    cfg.resilience_config = ResilienceConfig(
        shadow_sample_every=SAMPLE_EVERY,
        failure_threshold=2,
        probe_backoff_initial_s=0.5,
        probe_backoff_max_s=4.0,
        jitter_pct=0.1,
        seed=7,
    )


async def _corrupt_run():
    from openr_tpu.chaos import ChaosController, FaultPlan, InvariantChecker
    from openr_tpu.emulation.network import EmulatedNetwork
    from openr_tpu.emulation.topology import grid_edges
    from openr_tpu.types import PrefixEntry

    clock = SimClock()
    net = EmulatedNetwork(
        clock, use_tpu_backend=True, config_overrides=corrupt_overrides
    )
    net.build(grid_edges(3))  # 9 nodes
    net.start()
    checker = InvariantChecker(net)
    plan = FaultPlan().tpu_corrupt(VICTIM, at=2.0, duration=10.0)
    controller = ChaosController(net, plan, seed=7)

    await clock.run_for(18.0)
    ok, why = net.converged_full_mesh()
    assert ok, why
    victim = net.nodes[VICTIM]
    gov = victim.decision.backend.governor
    assert gov is not None and not gov.quarantined

    controller.start()
    await clock.run_for(3.0)  # corruption live at t=2
    # drive rebuilds during the corrupt window: each advertisement floods
    # to every node and triggers a (corrupted, on the victim) device
    # build; detection must land within ONE shadow-sample interval
    for i in range(SAMPLE_EVERY):
        net.nodes["node0"].advertise_prefixes(
            [PrefixEntry(f"10.99.{i}.0/24")]
        )
        await clock.run_for(1.5)
        checker.sample()
    assert gov.num_shadow_mismatches >= 1, (
        "silent corruption escaped shadow verification"
    )
    assert gov.quarantined and victim.decision.backend.device_failed
    # availability degrades COHERENTLY: serving/what-if gate on the same
    # latch route builds do
    assert not victim.decision.device_available()
    # ...and the victim's FIB is still exact (scalar engine serving):
    # its routes match a fresh scalar oracle of its own vantage, and no
    # blackholes anywhere
    checker.check_no_blackholes()
    oracle = SpfSolver(VICTIM).build_route_db(
        victim.decision.area_link_states, victim.decision.prefix_state
    )
    assert norm_db(victim.decision.route_db) == norm_db(oracle)

    # heal fires at t=12 (chaos routes it through the governor: the next
    # build is a probe); drive one more rebuild to carry the probe
    await clock.run_for(8.0)
    net.nodes["node0"].advertise_prefixes([PrefixEntry("10.99.8.0/24")])
    await clock.run_for(4.0)
    assert not gov.quarantined, "device not restored after heal + probe"
    assert victim.decision.device_available()
    assert gov.num_restores >= 1
    assert gov.breaker.num_probes >= 1

    await clock.run_for(8.0)
    checker.check_all()  # LSDB converged, FIBs blackhole-free, full mesh
    assert controller.done

    chaos_dump = controller.counter_dump()
    resilience_dump = victim.counters.dump("resilience.")
    assert resilience_dump.get("resilience.backend.shadow_mismatches", 0) >= 1
    await controller.stop()
    await net.stop()
    return chaos_dump, resilience_dump


@pytest.mark.chaos
def test_tpu_corrupt_detect_quarantine_recover_deterministic():
    a = run(_corrupt_run())
    b = run(_corrupt_run())
    # reproducibility contract: same seed => byte-identical dumps
    assert a == b
    chaos_dump, _ = a
    assert chaos_dump["chaos.injects"] == 1
    assert chaos_dump["chaos.heals"] == 1
    assert "chaos.inject.tpu_corrupt.node4" in chaos_dump


async def _warm_purge_run():
    """ISSUE-9 purge semantics under chaos: ``tpu_corrupt`` landing
    DURING a warm-rebuild regime invalidates the warm context — the
    next device build is cold AND scalar-verified — and warm rebuilds
    resume after probed recovery.  Returns the counters a replay must
    reproduce byte-identically."""
    from openr_tpu.chaos import ChaosController, FaultPlan, InvariantChecker
    from openr_tpu.emulation.network import EmulatedNetwork
    from openr_tpu.emulation.topology import grid_edges
    from openr_tpu.types import PrefixEntry

    clock = SimClock()
    net = EmulatedNetwork(
        clock, use_tpu_backend=True, config_overrides=corrupt_overrides
    )
    net.build(grid_edges(3))
    net.start()
    checker = InvariantChecker(net)
    plan = FaultPlan().tpu_corrupt(VICTIM, at=8.0, duration=10.0)
    controller = ChaosController(net, plan, seed=13)

    await clock.run_for(18.0)
    ok, why = net.converged_full_mesh()
    assert ok, why
    victim = net.nodes[VICTIM]
    backend = victim.decision.backend
    gov = backend.governor
    # a link flap before the fault: a warm-classified perturbation tick
    # — the warm rebuild engages and flows through shadow verification
    # like any other build (sample_every=2 on a warm regime)
    controller.start()  # fault fires at t=+8
    net.fail_link("node0", "node1")
    await clock.run_for(3.0)
    net.restore_link("node0", "node1")
    await clock.run_for(3.0)
    warm_before_fault = backend.num_warm_builds
    assert warm_before_fault >= 1, "perturbation ticks must warm-rebuild"
    assert gov.num_shadow_mismatches == 0
    await clock.run_for(3.0)  # corruption live at t=+8
    # the injection purged the warm context immediately
    assert backend._warm_ctx is None
    assert backend._warm_purge_reasons.get("tpu_corrupt", 0) >= 1
    purges_at_fault = backend.num_warm_purges
    # drive a rebuild during the corrupt window: the purge armed a
    # forced shadow check, so the FIRST corrupt device build is caught
    net.nodes["node0"].advertise_prefixes([PrefixEntry("10.98.0.0/24")])
    await clock.run_for(1.5)
    checker.sample()
    assert gov.num_shadow_mismatches >= 1
    assert gov.quarantined
    checker.check_no_blackholes()
    # heal at t=+18; probe restores; a fresh perturbation warms again
    await clock.run_for(12.0)
    net.nodes["node0"].advertise_prefixes([PrefixEntry("10.98.1.0/24")])
    await clock.run_for(4.0)
    assert not gov.quarantined
    net.fail_link("node1", "node2")
    await clock.run_for(4.0)
    # the first post-purge device build re-solved cold and
    # re-established the context; by now warm rebuilds have resumed
    assert backend.num_warm_builds > warm_before_fault
    assert backend._warm_ctx is not None
    await clock.run_for(6.0)
    checker.check_all()
    stats = (
        backend.num_warm_builds,
        backend.num_warm_purges - purges_at_fault,
        sorted(backend._warm_purge_reasons.items()),
        sorted(backend._warm_fallback_reasons.items()),
        gov.num_shadow_mismatches,
    )
    dumps = (
        controller.counter_dump(),
        victim.counters.dump("resilience."),
        stats,
    )
    await controller.stop()
    await net.stop()
    return dumps


@pytest.mark.chaos
def test_tpu_corrupt_purges_warm_context_deterministic():
    a = run(_warm_purge_run())
    b = run(_warm_purge_run())
    assert a == b  # byte-identical seeded replay (ISSUE-9 acceptance)


@pytest.mark.chaos
def test_tpu_corrupt_on_scalar_backend_is_a_counted_noop():
    from openr_tpu.chaos import ChaosController, FaultPlan
    from openr_tpu.emulation.network import EmulatedNetwork
    from openr_tpu.emulation.topology import line_edges

    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock)  # scalar backends
        net.build(line_edges(2))
        net.start()
        plan = FaultPlan().tpu_corrupt("node0", at=0.0, duration=1.0)
        controller = ChaosController(net, plan, seed=1)
        await clock.run_for(5.0)
        controller.start()
        await clock.run_for(5.0)
        dump = controller.counter_dump()
        assert dump["chaos.tpu_corrupt.noop"] == 2  # inject + heal
        await controller.stop()
        await net.stop()

    run(main())
