"""Native scalar SPF (native/spf_scalar.cc) parity tests: the baseline
denominator must produce exactly the Python oracle's distances and the
device kernel's nexthop lane sets, or the benchmark ratio is meaningless.
"""

import numpy as np
import pytest

from openr_tpu.decision.link_state import LinkState
from openr_tpu.emulation.topology import (
    build_adj_dbs,
    grid_edges,
    random_connected_edges,
)
from openr_tpu.ops.csr import encode_link_state
from openr_tpu.ops.native_spf import NativeSpf


def make_ls(edges, **kwargs) -> LinkState:
    ls = LinkState("0")
    for db in build_adj_dbs(edges, **kwargs).values():
        ls.update_adjacency_database(db)
    return ls


def assert_native_matches_python(ls, topo, root, failed_link=-1):
    eng = NativeSpf(topo, root)
    dist, _ = eng.solve(failed_link=failed_link)
    ignore = (
        frozenset([topo.links[failed_link]])
        if failed_link >= 0
        else frozenset()
    )
    ref = ls.run_spf(root, links_to_ignore=ignore)
    for node, r in ref.items():
        assert dist[topo.node_id(node)] == np.float32(r.metric), node
    reached = {topo.node_id(n) for n in ref}
    for v in range(topo.num_nodes):
        if v not in reached:
            assert not np.isfinite(dist[v])
    return eng


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_native_distances_match_python_oracle(seed):
    edges = random_connected_edges(64, 80, seed=seed)
    ls = make_ls(edges)
    topo = encode_link_state(ls)
    assert_native_matches_python(ls, topo, "node0")


def test_native_distances_with_link_failure():
    edges = random_connected_edges(48, 60, seed=3)
    ls = make_ls(edges)
    topo = encode_link_state(ls)
    for fl in (0, 5, len(topo.links) - 1):
        assert_native_matches_python(ls, topo, "node0", failed_link=fl)


def test_native_overload_semantics():
    edges = grid_edges(4)
    ls = make_ls(edges, overloaded=["node5", "node10"])
    topo = encode_link_state(ls)
    assert_native_matches_python(ls, topo, "node0")
    # overloaded root still transits
    ls2 = make_ls(edges, overloaded=["node0"])
    topo2 = encode_link_state(ls2)
    assert_native_matches_python(ls2, topo2, "node0")


@pytest.mark.parametrize("seed", [4, 5])
def test_native_lanes_match_device_kernel(seed):
    import jax.numpy as jnp

    from openr_tpu.ops.spf import spf_one

    edges = random_connected_edges(40, 50, seed=seed)
    ls = make_ls(edges)
    topo = encode_link_state(ls)
    D = topo.max_out_degree()
    eng = NativeSpf(topo, "node0")
    for fl in (-1, 2):
        eng.solve(failed_link=fl)
        mask = (
            topo.link_index != fl
            if fl >= 0
            else np.ones(topo.padded_edges, bool)
        )
        d_dev, nh_dev = spf_one(
            jnp.asarray(topo.src),
            jnp.asarray(topo.dst),
            jnp.asarray(topo.w),
            jnp.asarray(topo.edge_ok & mask),
            jnp.asarray(topo.overloaded),
            jnp.int32(topo.node_id("node0")),
            D,
        )
        d_dev = np.asarray(d_dev)
        nh_dev = np.asarray(nh_dev)
        finite = np.isfinite(eng.dist)
        assert np.array_equal(eng.dist[finite], d_dev[finite])
        assert (d_dev[~finite] >= 3.0e38).all()
        assert np.array_equal(eng.lanes_dense(D)[finite], nh_dev[finite])


def test_native_sweep_checksum_and_last_solve():
    edges = random_connected_edges(32, 40, seed=7)
    ls = make_ls(edges)
    topo = encode_link_state(ls)
    eng = NativeSpf(topo, "node0")
    fails = np.array([0, 1, 2, 3], np.int32)
    eng.sweep(fails)
    # last solve outputs == solve(failed_link=3)
    dist_last = eng.dist.copy()
    eng.solve(failed_link=3)
    assert np.array_equal(dist_last, eng.dist)


class TestWarmStart:
    """spf_warm_sweep must equal the cold solver for EVERY failed link —
    the warm start is an optimization, not an approximation (the same
    bar ops/repair.py holds on device)."""

    @pytest.mark.parametrize(
        "edges_fn",
        [
            lambda: grid_edges(6),
            lambda: random_connected_edges(80, 160, seed=11),
        ],
    )
    def test_warm_equals_cold_for_every_link(self, edges_fn):
        ls = make_ls(edges_fn())
        topo = encode_link_state(ls)
        root = sorted(topo.node_ids)[0]
        warm = NativeSpf(topo, root)
        warm.warm_prepare()
        cold = NativeSpf(topo, root)
        for li in list(range(len(topo.links))) + [-1]:
            warm.warm_sweep(np.asarray([li], np.int32), keep_last=True)
            wd, wn = warm.dist.copy(), warm.nh_mask.copy()
            cd, cn = cold.solve(failed_link=li)
            assert np.array_equal(wd, cd), li
            assert np.array_equal(wn, cn), li

    def test_warm_sweep_checksum_matches_cold(self):
        ls = make_ls(random_connected_edges(120, 260, seed=3))
        topo = encode_link_state(ls)
        root = sorted(topo.node_ids)[0]
        rng = np.random.default_rng(0)
        fails = rng.integers(
            0, len(topo.links), size=500
        ).astype(np.int32)
        warm = NativeSpf(topo, root)
        c_warm = warm.warm_sweep(fails)
        cold = NativeSpf(topo, root)
        c_cold = cold.sweep(fails)
        assert c_warm == c_cold

    def test_warm_with_overloaded_node(self):
        ls = make_ls(grid_edges(5), overloaded=["node12"])
        topo = encode_link_state(ls)
        warm = NativeSpf(topo, "node0")
        warm.warm_prepare()
        cold = NativeSpf(topo, "node0")
        for li in range(len(topo.links)):
            warm.warm_sweep(np.asarray([li], np.int32), keep_last=True)
            cd, cn = cold.solve(failed_link=li)
            assert np.array_equal(warm.dist, cd), li
            assert np.array_equal(warm.nh_mask, cn), li
