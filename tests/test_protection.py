"""Fast-reroute protection tier (ISSUE 16).

Covers:

* the patch table lifecycle + staleness matrix: generation-exact
  lookups only; MINTING/EMPTY/mismatched-generation lookups refuse with
  the right fallback reason; purge wipes the store;
* the spill-backed store: per-shard durability, the host-memory LRU
  bound (decoded patches beyond ``max_host_patches`` load from disk),
  resume against a matching manifest, the table-hash identity;
* the scenario-grammar satellites: SRLG groups fold into enumeration
  with deterministic content identity, the single-link bound, and the
  regression that pre-existing specs hash EXACTLY as before the new
  fields (content() only grows keys when they're set);
* ``world_deltas`` as the shared one-pass iterator: the builder's
  delta consumer sees the same scenario stream the reducer's spill rows
  record;
* LinkStateChange failure classification: clean up→down flips land in
  ``down_links``; adds/metric/overload/node-leave set
  ``other_topology_change`` (never patch-served);
* the Decision apply path end-to-end on a real mint: protected flap →
  patch published at detection (``decision.frr_applied``, INCREMENTAL
  + frr-stamped) with scalar-oracle RIB parity after the confirming
  warm solve; stale table falls back warm; multi-failure falls back;
  a corrupted patch trips the confirm → FULL_SYNC + mismatch counter +
  table purge; SRLG flap (both members in one publication) applies the
  per-SRLG patch;
* builder discipline: generation move mid-mint refuses to touch the
  device; kill-after-shard-K resume reproduces the clean mint's
  table hash byte-for-byte; global ineligibility (rib policy / node
  segment labels) mints tombstones that fall back at apply.
"""

import asyncio
import json

import pytest

from openr_tpu.common.runtime import SimClock
from openr_tpu.config import DecisionConfig, ProtectionConfig
from openr_tpu.decision.backend import ScalarBackend, TpuBackend
from openr_tpu.decision.decision import Decision
from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.rib import route_db_summary
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.emulation.topology import build_adj_dbs, grid_edges
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.protection import (
    ProtectionBuildError,
    ProtectionBuilder,
    ProtectionService,
    ProtectionStore,
    ProtectionTable,
    link_patch_key,
    make_ineligible_patch,
    make_patch,
)
from openr_tpu.sweep import SweepInputs
from openr_tpu.sweep.scenario import (
    ScenarioSpec,
    enumerate_scenarios,
    normalize_srlg_groups,
    scenario_set_hash,
    srlg_domain,
)
from openr_tpu.types import (
    InitializationEvent,
    PrefixDatabase,
    PrefixEntry,
    PrefixMetrics,
    Publication,
    Value,
    prefix_key,
)

pytestmark = [pytest.mark.protection]

N = 3
EDGES = grid_edges(N)
PAIRS = [
    ("node0", "node1"),
    ("node1", "node2"),
    ("node2", "node3"),
    ("node0", "node3"),
]

GEN = {"change_seq": 5, "areas": [["0", 7]]}
GEN_KEY = (5, (("0", 7),))


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        pending = asyncio.all_tasks(loop)
        for t in pending:
            t.cancel()
        if pending:
            loop.run_until_complete(
                asyncio.gather(*pending, return_exceptions=True)
            )
        loop.close()


# ---------------------------------------------------------------------------
# table lifecycle + staleness matrix
# ---------------------------------------------------------------------------


def make_table(tmp_path, **kw):
    return ProtectionTable(
        ProtectionStore(str(tmp_path / "store"), **kw)
    )


def seed_ready(table, key="a|b"):
    # the table state machine and the store lifecycle are driven
    # side by side, the way the builder drives them
    table.begin_mint(GEN_KEY, "sh")
    table.store.begin(GEN, "sh")
    table.store.put_shard(0, [make_patch(key, [], [])])
    th = table.store.commit_ready()
    table.mark_ready(th, 1, 1)
    return th


def test_table_lookup_is_generation_exact(tmp_path):
    t = make_table(tmp_path)
    # EMPTY refuses as miss
    assert t.lookup(GEN_KEY, "a|b")[0] == "miss"
    t.begin_mint(GEN_KEY, "sh")
    t.store.begin(GEN, "sh")
    assert t.state == "minting"
    assert t.lookup(GEN_KEY, "a|b")[0] == "minting"
    t.store.put_shard(0, [make_patch("a|b", [], [])])
    th = t.store.commit_ready()
    t.mark_ready(th, 1, 1)
    # generation-exact hit — even after the table is marked STALE,
    # because the generation listeners fire BEFORE the apply path runs
    # for the very event the table protects
    t.mark_stale()
    assert t.state == "stale"
    status, doc = t.lookup(GEN_KEY, "a|b")
    assert status == "hit" and doc["eligible"]
    # any other previous-generation key refuses as stale
    assert t.lookup((6, (("0", 8),)), "a|b")[0] == "stale"
    # unknown link refuses as miss
    assert t.lookup(GEN_KEY, "x|y")[0] == "miss"
    # ineligible doc refuses as miss (reason preserved for operators)
    t.begin_mint(GEN_KEY, "sh")
    t.store.begin(GEN, "sh")
    t.store.put_shard(0, [make_ineligible_patch("a|b", "ksp2")])
    t.mark_ready(t.store.commit_ready(), 1, 0)
    assert t.lookup(GEN_KEY, "a|b")[0] == "miss"


def test_purge_and_abort_reset_table_and_store(tmp_path):
    t = make_table(tmp_path)
    seed_ready(t)
    t.purge_table("mismatch")
    assert t.state == "empty" and t.patches == 0
    assert t.store.lookup("a|b") is None, "purge wipes the disk store"
    # abort mid-mint: MINTING -> EMPTY, partial shards stay on disk
    # for a later resume
    t.begin_mint(GEN_KEY, "sh")
    t.store.begin(GEN, "sh")
    t.store.put_shard(0, [make_patch("a|b", [], [])])
    t.abort_mint()
    assert t.state == "empty"
    assert t.store.lookup("a|b") is not None


# ---------------------------------------------------------------------------
# store: durability, LRU bound, resume, identity
# ---------------------------------------------------------------------------


def test_store_lru_bound_and_disk_loads(tmp_path):
    s = ProtectionStore(str(tmp_path), max_host_patches=4)
    s.begin(GEN, "sh")
    docs = [make_patch(f"k{i:02d}|x", [], []) for i in range(16)]
    s.put_shard(0, docs[:8])
    s.put_shard(1, docs[8:])
    assert s.stats()["cached"] == 4, "decoded cache bounded"
    assert len(s.keys()) == 16, "index covers everything on disk"
    for d in docs:
        got = s.lookup(d["key"])
        assert got == d
    st = s.stats()
    assert st["disk_loads"] >= 12, "evicted patches reload from disk"
    assert st["cached"] == 4


def test_store_resume_requires_matching_identity(tmp_path):
    s = ProtectionStore(str(tmp_path))
    s.begin(GEN, "sh")
    s.put_shard(0, [make_patch("a|b", [], [])])
    s2 = ProtectionStore(str(tmp_path))
    assert s2.resume(GEN, "sh", [0])
    assert s2.lookup("a|b") is not None, "index rebuilt from shard files"
    # generation or set-hash drift refuses the resume
    assert not ProtectionStore(str(tmp_path)).resume(
        {"change_seq": 6, "areas": [["0", 7]]}, "sh", [0]
    )
    assert not ProtectionStore(str(tmp_path)).resume(GEN, "other", [0])
    # a shard the checkpoint claims but the store lacks refuses
    assert not ProtectionStore(str(tmp_path)).resume(GEN, "sh", [0, 1])


def test_table_hash_is_content_pure(tmp_path):
    docs = [make_patch("a|b", [], ["10.0.0.0/24"]), make_patch("c|d", [], [])]
    hashes = []
    for sub in ("x", "y"):
        s = ProtectionStore(str(tmp_path / sub))
        s.begin(GEN, "sh")
        s.put_shard(0, docs[:1])
        s.put_shard(1, docs[1:])
        hashes.append(s.commit_ready())
    assert hashes[0] == hashes[1]
    # different content, different identity
    s = ProtectionStore(str(tmp_path / "z"))
    s.begin(GEN, "sh")
    s.put_shard(0, [make_patch("a|b", [], [])])
    s.put_shard(1, docs[1:])
    assert s.commit_ready() != hashes[0]


# ---------------------------------------------------------------------------
# scenario grammar satellites
# ---------------------------------------------------------------------------


def test_pre_existing_specs_hash_exactly_as_before():
    """The new fields only appear in content() when set — every
    checkpoint/plan hash minted before this PR must still match."""
    spec = ScenarioSpec(drain_node_sets=((), ("node2",)))
    doc = spec.content()
    assert "srlg_groups" not in doc
    assert "max_single_link_scenarios" not in doc
    bounded = ScenarioSpec(
        drain_node_sets=((), ("node2",)), max_single_link_scenarios=2
    )
    assert "max_single_link_scenarios" in bounded.content()
    scens = enumerate_scenarios(spec, PAIRS)
    assert scenario_set_hash(spec, scens) == scenario_set_hash(
        ScenarioSpec(drain_node_sets=((), ("node2",))), scens
    )


def test_single_link_bound_truncates_canonically():
    spec = ScenarioSpec(max_single_link_scenarios=2)
    scens = enumerate_scenarios(spec, list(reversed(PAIRS)))
    singles = [s for s in scens if not s.domains]
    assert len(singles) == 2
    # the bound applies to the canonically sorted pair order, not the
    # caller's enumeration order
    assert {s.failed_links[0] for s in singles} == set(
        sorted(tuple(sorted(p)) for p in PAIRS)[:2]
    )


def test_srlg_groups_fold_into_grammar_with_stable_identity():
    groups = normalize_srlg_groups(
        [
            {"name": "conduit7", "links": [PAIRS[1], PAIRS[0]]},
            {"name": "span2", "links": [PAIRS[2]]},
        ]
    )
    spec = ScenarioSpec(srlg_groups=groups)
    a = enumerate_scenarios(spec, PAIRS)
    b = enumerate_scenarios(spec, list(reversed(PAIRS)))
    assert [s.hash for s in a] == [s.hash for s in b]
    srlg = [s for s in a if s.domains]
    assert {s.domains[0] for s in srlg} == {
        "srlg:conduit7",
        "srlg:span2",
    }
    by_dom = {s.domains[0]: s for s in srlg}
    assert set(by_dom["srlg:conduit7"].failed_links) == {
        tuple(sorted(PAIRS[0])),
        tuple(sorted(PAIRS[1])),
    }
    # spelling variations normalize to ONE content identity
    groups2 = normalize_srlg_groups(
        [
            {"name": "span2", "links": [tuple(reversed(PAIRS[2]))]},
            {"name": "conduit7", "links": [PAIRS[0], PAIRS[1], PAIRS[0]]},
        ]
    )
    assert groups2 == groups
    assert scenario_set_hash(spec, a) == scenario_set_hash(
        ScenarioSpec(srlg_groups=groups2), b
    )
    # a group whose members are all absent from the live topology
    # enumerates nothing (dead conduit, no scenario)
    ghost = normalize_srlg_groups(
        [{"name": "ghost", "links": [("nodeX", "nodeY")]}]
    )
    assert not [
        s
        for s in enumerate_scenarios(
            ScenarioSpec(srlg_groups=ghost), PAIRS
        )
        if s.domains
    ]


# ---------------------------------------------------------------------------
# LinkStateChange failure classification
# ---------------------------------------------------------------------------


def make_link_state(n=3):
    ls = LinkState("0", "node0")
    for db in build_adj_dbs(grid_edges(n)).values():
        ls.update_adjacency_database(db)
    return ls


def fresh_db(node, n=3):
    # never mutate the object the LSDB holds by reference
    return build_adj_dbs(grid_edges(n))[node]


def test_clean_link_down_lands_in_down_links():
    ls = make_link_state()
    db = fresh_db("node1")
    db.adjacencies = [
        a for a in db.adjacencies if a.other_node_name != "node2"
    ]
    change = ls.update_adjacency_database(db)
    assert change.topology_changed
    assert [
        tuple(sorted((lk.n1, lk.n2))) for lk in change.down_links
    ] == [("node1", "node2")]
    assert not change.other_topology_change


def test_link_add_metric_and_overload_are_other_changes():
    ls = make_link_state()
    # metric change
    db = fresh_db("node1")
    db.adjacencies[0].metric += 5
    change = ls.update_adjacency_database(db)
    assert change.other_topology_change and not change.down_links
    # node overload flip (operator drain, never patch-served)
    db = fresh_db("node1")
    db.adjacencies[0].metric += 5
    db.is_overloaded = True
    change = ls.update_adjacency_database(db)
    assert change.other_topology_change and not change.down_links
    # node leaving the LSDB fails ALL its links: outside the envelope
    change = ls.delete_adjacency_database("node3")
    assert change.other_topology_change and not change.down_links


# ---------------------------------------------------------------------------
# decision end-to-end harness
# ---------------------------------------------------------------------------


def adj_pub(version=1, drops=()):
    """drops: (a, b) pairs; node a's DB omits its adjacency to b."""
    kvs = {}
    for node, db in build_adj_dbs(EDGES).items():
        gone = {b for a, b in drops if a == node}
        if gone:
            db.adjacencies = [
                a for a in db.adjacencies if a.other_node_name not in gone
            ]
        kvs[f"adj:{node}"] = Value(
            version=version,
            originator_id=node,
            value=json.dumps(db.to_wire()).encode(),
        )
    return Publication(key_vals=kvs)


def prefix_pub(node, prefix, version=1, pp=1000):
    pdb = PrefixDatabase(
        this_node_name=node,
        prefix_entries=[
            PrefixEntry(prefix, metrics=PrefixMetrics(path_preference=pp))
        ],
    )
    return Publication(
        key_vals={
            prefix_key(node, prefix): Value(
                version=version,
                originator_id=node,
                value=json.dumps(pdb.to_wire()).encode(),
            )
        }
    )


async def booted_decision(clock, tmp_path, srlg_groups=(), **pcfg):
    solver = SpfSolver("node0")
    backend = TpuBackend(solver)
    out_q = ReplicateQueue("routes")
    kv_q = ReplicateQueue("kv")
    d = Decision(
        "node0",
        clock,
        DecisionConfig(debounce_min_ms=10, debounce_max_ms=250),
        out_q,
        kv_store_updates_reader=kv_q.get_reader(),
        backend=backend,
        solver=solver,
    )
    d.backend.auto_dispatch_rt_ms = 0.0
    reader = out_q.get_reader()
    d.start()
    d.on_initialization_event(InitializationEvent.KVSTORE_SYNCED)
    kv_q.push(adj_pub())
    for i in range(1, N * N):
        kv_q.push(prefix_pub(f"node{i}", f"10.{i}.0.0/24"))
    await clock.run_for(2.0)
    assert d._first_build_done
    svc = ProtectionService(
        "node0",
        clock,
        ProtectionConfig(
            enabled=True, store_dir=str(tmp_path / "prot"), **pcfg
        ),
        d,
        counters=d.counters,
        srlg_groups=srlg_groups,
    )
    d.protection = svc
    d.add_generation_listener(svc._on_generation, priority=20)
    return d, svc, kv_q, reader


def drain(reader):
    out = []
    while True:
        u = reader.try_get()
        if u is None:
            return out
        out.append(u)


def scalar_oracle(d):
    return ScalarBackend(SpfSolver("node0")).build_route_db(
        d.area_link_states, d.prefix_state
    )


def test_protected_flap_applies_patch_with_scalar_parity(tmp_path):
    async def main():
        clock = SimClock()
        d, svc, kv_q, reader = await booted_decision(clock, tmp_path)
        rep = svc.mint_now()
        assert rep["eligible"] == len(EDGES), "every grid link eligible"
        drain(reader)
        kv_q.push(adj_pub(version=2, drops=[("node1", "node2")]))
        await clock.run_for(2.0)
        updates = drain(reader)
        # the patch published FIRST, at detection, incremental + stamped
        assert updates and updates[0].frr
        assert updates[0].type.name == "INCREMENTAL"
        assert not updates[0].empty()
        assert all(not u.frr for u in updates[1:])
        assert d.counters.get("decision.frr_applied") == 1
        assert d.counters.get("decision.frr_mismatches") == 0
        # the confirming warm solve agreed exactly
        assert d.counters.get("protection.confirms") == 1
        assert route_db_summary(d.route_db) == route_db_summary(
            scalar_oracle(d)
        )
        await d.stop()

    run(main())


def test_stale_table_falls_back_warm_and_still_converges(tmp_path):
    async def main():
        clock = SimClock()
        d, svc, kv_q, reader = await booted_decision(clock, tmp_path)
        svc.mint_now()
        kv_q.push(adj_pub(version=2, drops=[("node1", "node2")]))
        await clock.run_for(2.0)
        assert d.counters.get("decision.frr_applied") == 1
        # NO re-mint: the second flap's previous generation no longer
        # matches the table → refuse stale, converge warm, stay correct
        kv_q.push(
            adj_pub(
                version=3, drops=[("node1", "node2"), ("node3", "node6")]
            )
        )
        await clock.run_for(2.0)
        assert d.counters.get("decision.frr_applied") == 1
        assert d.counters.get("protection.fallback.stale") == 1
        assert route_db_summary(d.route_db) == route_db_summary(
            scalar_oracle(d)
        )
        await d.stop()

    run(main())


def test_multi_failure_and_bounded_miss_fall_back(tmp_path):
    async def main():
        clock = SimClock()
        # bound the table to 2 links: most flaps miss
        d, svc, kv_q, reader = await booted_decision(
            clock, tmp_path, max_links=2
        )
        rep = svc.mint_now()
        assert rep["patches"] == 2
        # two unrelated links in one event: unprotected multi-failure
        kv_q.push(
            adj_pub(
                version=2, drops=[("node1", "node2"), ("node3", "node6")]
            )
        )
        await clock.run_for(2.0)
        assert d.counters.get("protection.fallback.multi_failure") == 1
        svc.mint_now()
        # node5-node8 sorts far past the 2-link bound: miss
        kv_q.push(
            adj_pub(
                version=3,
                drops=[
                    ("node1", "node2"),
                    ("node3", "node6"),
                    ("node5", "node8"),
                ],
            )
        )
        await clock.run_for(2.0)
        assert d.counters.get("protection.fallback.miss") == 1
        assert d.counters.get("decision.frr_applied") == 0
        assert route_db_summary(d.route_db) == route_db_summary(
            scalar_oracle(d)
        )
        await d.stop()

    run(main())


def test_corrupted_patch_trips_confirm_full_sync_and_purge(tmp_path):
    async def main():
        clock = SimClock()
        d, svc, kv_q, reader = await booted_decision(clock, tmp_path)
        svc.mint_now()
        # poison one minted patch: skew every nexthop's metric (the
        # confirm compares nexthop sets via eq_ignoring_cost, so a
        # wrong METRIC inside the nexthop is a real divergence)
        key = link_patch_key(("node1", "node2"))
        doc = svc.table.store.lookup(key)
        assert doc["sets"], "the failure moves routes at the vantage"
        for row in doc["sets"]:
            for nh in row["nexthops"]:
                nh[3] = int(nh[3]) + 1000
        drain(reader)
        kv_q.push(adj_pub(version=2, drops=[("node1", "node2")]))
        await clock.run_for(2.0)
        updates = drain(reader)
        assert updates[0].frr
        assert d.counters.get("decision.frr_mismatches") == 1
        assert d.counters.get("protection.mismatches") == 1
        # the confirm replaced the whole RIB
        assert any(
            u.type.name == "FULL_SYNC" for u in updates[1:]
        ), [u.type.name for u in updates]
        # purge-on-suspicion: the poisoned table is gone
        assert svc.table.state == "empty"
        assert route_db_summary(d.route_db) == route_db_summary(
            scalar_oracle(d)
        )
        await d.stop()

    run(main())


def test_srlg_flap_applies_the_group_patch(tmp_path):
    async def main():
        clock = SimClock()
        groups = normalize_srlg_groups(
            [
                {
                    "name": "conduit7",
                    "links": [("node1", "node2"), ("node4", "node5")],
                }
            ]
        )
        d, svc, kv_q, reader = await booted_decision(
            clock, tmp_path, srlg_groups=groups
        )
        rep = svc.mint_now()
        assert rep["patches"] == len(EDGES) + 1
        assert (
            svc.table.store.lookup(srlg_domain("conduit7")) is not None
        )
        drain(reader)
        # the conduit is cut: BOTH member links fail in one publication
        kv_q.push(
            adj_pub(
                version=2, drops=[("node1", "node2"), ("node4", "node5")]
            )
        )
        await clock.run_for(2.0)
        updates = drain(reader)
        assert updates and updates[0].frr
        assert d.counters.get("decision.frr_applied") == 1
        assert d.counters.get("decision.frr_mismatches") == 0
        assert d.counters.get("protection.confirms") == 1
        assert route_db_summary(d.route_db) == route_db_summary(
            scalar_oracle(d)
        )
        await d.stop()

    run(main())


def test_quarantine_purges_table_and_requests_abort(tmp_path):
    async def main():
        clock = SimClock()
        d, svc, kv_q, reader = await booted_decision(clock, tmp_path)
        svc.mint_now()
        assert svc.table.state == "ready"
        svc._on_quarantine({"device": 3, "reason": "shadow_mismatch"})
        assert svc.table.state == "empty"
        assert svc._abort_requested and svc._dirty
        assert d.counters.get("protection.purge.quarantine") == 1
        # the next flap finds no table and falls back warm
        kv_q.push(adj_pub(version=2, drops=[("node1", "node2")]))
        await clock.run_for(2.0)
        assert d.counters.get("protection.fallback.miss") == 1
        assert route_db_summary(d.route_db) == route_db_summary(
            scalar_oracle(d)
        )
        await d.stop()

    run(main())


def test_service_mint_loop_runs_on_sim_clock(tmp_path):
    async def main():
        clock = SimClock()
        d, svc, kv_q, reader = await booted_decision(clock, tmp_path)
        # undo the manual wiring; start() owns it
        d._generation_listeners = [
            e for e in d._generation_listeners if e[2] is not svc._on_generation
        ]
        svc.start()
        await clock.run_for(5.0)
        assert svc.table.state == "ready", svc.error
        first_hash = svc.table.table_hash
        # a topology change re-mints (debounced) a DIFFERENT table
        kv_q.push(adj_pub(version=2, drops=[("node1", "node2")]))
        await clock.run_for(5.0)
        assert svc.table.state == "ready"
        assert svc.table.table_hash != first_hash
        assert svc.table.num_mints == 2
        await svc.stop()
        await d.stop()

    run(main())


# ---------------------------------------------------------------------------
# builder discipline
# ---------------------------------------------------------------------------


def make_builder(tmp_path, d, sub="b", **kw):
    return ProtectionBuilder(
        lambda: SweepInputs(**d.capacity_sweep_inputs()),
        ProtectionStore(str(tmp_path / sub / "store")),
        d.solver,
        str(tmp_path / sub / "sweep"),
        counters=d.counters,
        **kw,
    )


def test_generation_move_mid_mint_refuses_the_device(tmp_path):
    async def main():
        clock = SimClock()
        d, svc, kv_q, reader = await booted_decision(clock, tmp_path)
        b = make_builder(tmp_path, d, shard_scenarios=4)
        b.prepare(resume=False)
        b.step(1)
        assert not b.finished()
        d._change_seq += 1
        with pytest.raises(ProtectionBuildError):
            b.step(1)
        await d.stop()

    run(main())


def test_kill_after_shard_resume_reproduces_table_hash(tmp_path):
    async def main():
        clock = SimClock()
        d, svc, kv_q, reader = await booted_decision(clock, tmp_path)
        clean = make_builder(tmp_path, d, "clean", shard_scenarios=4)
        clean.prepare(resume=False)
        while not clean.finished():
            clean.step(1)
        clean_hash = clean.finalize()["table_hash"]

        killed = make_builder(tmp_path, d, "killed", shard_scenarios=4)
        rep = killed.prepare(resume=True)
        assert rep["shards"] == 3
        killed.step(1)  # killed after shard 0

        resumed = make_builder(tmp_path, d, "killed", shard_scenarios=4)
        rep = resumed.prepare(resume=True)
        assert rep["resumed"] and rep["resumed_shards"] == 1
        while not resumed.finished():
            resumed.step(1)
        final = resumed.finalize()
        assert final["table_hash"] == clean_hash, (
            "kill+resume must mint byte-identical patch content"
        )
        await d.stop()

    run(main())


def test_global_ineligibility_mints_tombstones(tmp_path):
    async def main():
        clock = SimClock()
        d, svc, kv_q, reader = await booted_decision(clock, tmp_path)
        b = make_builder(
            tmp_path, d, "pol", policy_active_fn=lambda: True
        )
        b.prepare(resume=False)
        while not b.finished():
            b.step(1)
        final = b.finalize()
        assert final["patches"] == len(EDGES) and final["eligible"] == 0
        doc = b.store.lookup(link_patch_key(("node1", "node2")))
        assert not doc["eligible"] and doc["reason"] == "rib_policy"
        await d.stop()

    run(main())


# ---------------------------------------------------------------------------
# world_deltas: one pass, two consumers
# ---------------------------------------------------------------------------


def test_builder_rider_sees_the_reducer_scenario_stream(tmp_path):
    """The delta consumer (builder's mint) and the spill rows (the
    reducer's durable record) come off ONE device pass and must agree
    on the scenario stream, per shard."""
    from openr_tpu.sweep import SpillReader, SweepExecutor

    async def main():
        clock = SimClock()
        d, svc, kv_q, reader = await booted_decision(clock, tmp_path)

        seen = {}

        def consume(ctx, shard_id, group, deltas):
            from openr_tpu.sweep.reduce import world_deltas

            seen.setdefault(shard_id, []).extend(
                scen.hash for scen, _s, _r, _d in world_deltas(group, deltas)
            )

        ex = SweepExecutor(
            lambda: SweepInputs(**d.capacity_sweep_inputs()),
            str(tmp_path / "wd"),
            clock=clock,
            counters=d.counters,
            shard_scenarios=4,
        )
        ex.delta_consumer = consume
        ex.prepare(ScenarioSpec(single_link_failures=True, combo_k=0))
        ex.run()
        rows = list(SpillReader(str(tmp_path / "wd")).rows())
        by_shard = {}
        for r in rows:
            by_shard.setdefault(r["shard"], []).append(r["hash"])
        assert seen == by_shard
        await d.stop()

    run(main())
