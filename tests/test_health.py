"""Fleet health plane units (ISSUE 8 tentpole): SLO burn-rate window
math, cross-node histogram merge through the fleet rollup (incl. the
PR-7 widen-on-merge path with mismatched bucket widths), generation-
skew detection, the alert sink's transition edges / counters /
deterministic JSONL / page-dump dedupe, and the derived fleet signals
from synthetic snapshots."""

import json

import pytest

from openr_tpu.common.runtime import CounterMap, Histogram, SimClock
from openr_tpu.health import (
    ALERTS,
    AlertSink,
    BurnRateEvaluator,
    FleetHealthAggregator,
    SloSpec,
    alert_counter_key,
    default_slos,
    generation_hash,
    histogram_from_snapshot,
    merge_fleet_histograms,
)
from openr_tpu.health.slo import KIND_COUNTER

pytestmark = [pytest.mark.health]


# ---------------------------------------------------------------------------
# snapshot plumbing helpers
# ---------------------------------------------------------------------------


def hist_snap(values, num_buckets=160):
    h = Histogram(num_buckets=num_buckets)
    for v in values:
        h.observe(v)
    d = dict(h.config())
    d.update(
        count=h.count,
        sum=h.total,
        min=h.vmin,
        max=h.vmax,
        buckets=[[e, c] for e, c in h.bucket_items()],
    )
    return d


def snap(node, counters=None, histograms=None, generation=None):
    return {
        "node": node,
        "ts_ms": 0,
        "generation": generation if generation is not None else [0],
        "env": {},
        "counters": counters or {},
        "histograms": histograms or {},
    }


def make_sink(clock=None, recorder=None, **kw):
    return AlertSink(
        "agg0", clock or SimClock(), CounterMap(),
        flight_recorder=recorder, **kw,
    )


def make_agg(clock, source, sink=None, slos=(), **kw):
    sink = sink or make_sink(clock)
    return (
        FleetHealthAggregator(
            node_name="agg0",
            clock=clock,
            source=source,
            sink=sink,
            slos=list(slos),
            **kw,
        ),
        sink,
    )


# ---------------------------------------------------------------------------
# histogram reconstruction + cross-node merge (satellite: mismatched
# bucket widths exercise PR-7 widen-on-merge through the fleet rollup)
# ---------------------------------------------------------------------------


def test_histogram_from_snapshot_round_trips():
    h = Histogram()
    for v in (0.5, 12.0, 480.0, 1e9):  # last lands in overflow
        h.observe(v)
    d = hist_snap((0.5, 12.0, 480.0, 1e9))
    back = histogram_from_snapshot(d)
    assert back.count == h.count and back.total == h.total
    assert back.counts == h.counts
    assert back.percentile(50) == h.percentile(50)


def test_fleet_merge_sums_counts_across_nodes():
    snaps = [
        snap("a", histograms={"x.ms": hist_snap([1.0, 2.0])}),
        snap("b", histograms={"x.ms": hist_snap([1000.0])}),
    ]
    merged = merge_fleet_histograms(snaps)["x.ms"]
    assert merged["count"] == 3
    assert merged["min"] == 1.0 and merged["max"] == 1000.0
    assert merged["p99"] <= 1000.0


def test_fleet_merge_widens_mismatched_bucket_widths():
    """Node A exports the default 160-bucket grid, node B a 200-bucket
    grid (same min_bound/growth): the rollup must widen to 200 and
    place every sample, whichever order the nodes arrive in."""
    wide_val = Histogram().edges[-1] * 2  # beyond the narrow grid
    for order in ((160, 200), (200, 160)):
        snaps = [
            snap("a", histograms={"k": hist_snap([5.0], num_buckets=order[0])}),
            snap(
                "b",
                histograms={"k": hist_snap([wide_val], num_buckets=order[1])},
            ),
        ]
        merged = merge_fleet_histograms(snaps)["k"]
        assert merged["num_buckets"] == 200
        assert merged["count"] == 2
        assert merged["max"] == wide_val
        assert sum(c for _e, c in merged["buckets"]) == 2


def test_fleet_merge_incompatible_grids_raise():
    a = snap("a", histograms={"k": hist_snap([1.0])})
    b = snap("b", histograms={"k": hist_snap([1.0])})
    b["histograms"]["k"]["growth"] = 2.0
    with pytest.raises(ValueError):
        merge_fleet_histograms([a, b])


def test_aggregator_slo_sees_cross_node_widened_merge():
    """The widen path through the WHOLE rollup: two nodes with
    different grid widths feed one SLO whose bad samples live only in
    the wide node's upper buckets."""
    clock = SimClock()
    wide_val = Histogram().edges[-1] * 2
    calls = {"n": 0}

    def source():
        calls["n"] += 1
        # second sweep adds one bad (wide) + many good samples
        if calls["n"] == 1:
            a_vals, b_vals = [1.0], [2.0]
        else:
            a_vals, b_vals = [1.0] * 3, [2.0, wide_val]
        return [
            snap("a", histograms={"m": hist_snap(a_vals, 160)}),
            snap("b", histograms={"m": hist_snap(b_vals, 200)}),
        ]

    spec = SloSpec(
        name="slo_convergence_p99", metric="m", threshold=1e6,
        objective=0.01, fast_window_s=10, slow_window_s=10,
        burn_threshold=1.0,
    )
    agg, sink = make_agg(clock, source, slos=[spec])
    agg.sweep()
    clock._now += 1.0
    agg.sweep()
    assert [a["name"] for a in sink.active_alerts()] == [
        "slo_convergence_p99"
    ]
    detail = sink.active[spec.name]
    assert detail["fast_burn"] >= 1.0


# ---------------------------------------------------------------------------
# burn-rate engine
# ---------------------------------------------------------------------------


def bad_total_samples(evaluator, name):
    return list(evaluator._state[name].samples)


def test_burn_rate_fires_only_when_both_windows_burn():
    clock = SimClock()
    spec = SloSpec(
        name="slo_convergence_p99", metric="m", threshold=10.0,
        objective=0.1, fast_window_s=2.0, slow_window_s=10.0,
        burn_threshold=2.0,
    )
    ev = BurnRateEvaluator(clock, [spec])

    def sweep(values):
        return ev.evaluate({"m": hist_snap(values)}, {})

    # baseline
    assert sweep([1.0]) == {}
    history = [1.0]
    # 8 clean intervals fill the slow window with good samples
    for _ in range(8):
        clock._now += 1.0
        history.append(1.0)
        assert sweep(list(history)) == {}
    # one bad interval: fast window (2s) is now 100% bad -> burn 10,
    # but the slow window is ~1/10 bad -> burn ~1 < 2: no alert
    clock._now += 1.0
    history.append(1000.0)
    assert sweep(list(history)) == {}
    # sustained badness pushes the slow window over too
    for _ in range(3):
        clock._now += 1.0
        history.append(1000.0)
    firing = sweep(list(history))
    assert "slo_convergence_p99" in firing
    assert firing["slo_convergence_p99"]["fast_burn"] >= 2.0
    assert firing["slo_convergence_p99"]["slow_burn"] >= 2.0
    # recovery: clean intervals age the badness out of the fast window
    for _ in range(6):
        clock._now += 1.0
        history.append(1.0)
        out = sweep(list(history))
    assert out == {}


def test_burn_rate_counter_kind_thresholds_deltas():
    clock = SimClock()
    spec = SloSpec(
        name="slo_convergence_p99", metric="c", kind=KIND_COUNTER,
        threshold=0.0, objective=0.5, fast_window_s=5.0,
        slow_window_s=5.0, burn_threshold=1.0,
    )
    ev = BurnRateEvaluator(clock, [spec])
    assert ev.evaluate({}, {"c": 0.0}) == {}  # baseline
    clock._now += 1.0
    assert ev.evaluate({}, {"c": 0.0}) == {}  # no delta
    clock._now += 1.0
    firing = ev.evaluate({}, {"c": 2.0})  # delta 2 > 0
    assert "slo_convergence_p99" in firing


def test_empty_window_burns_zero():
    clock = SimClock()
    spec = SloSpec(
        name="slo_convergence_p99", metric="m", threshold=10.0,
        objective=0.01, fast_window_s=1.0, slow_window_s=2.0,
    )
    ev = BurnRateEvaluator(clock, [spec])
    ev.evaluate({}, {})  # metric never observed anywhere
    clock._now += 1.0
    assert ev.evaluate({}, {}) == {}
    st = ev.status()[0]
    assert st["fast_burn"] == 0.0 and st["firing"] is False


def test_slo_spec_validation():
    with pytest.raises(ValueError, match="registered alert"):
        SloSpec(name="not_an_alert", metric="m")
    with pytest.raises(ValueError, match="kind"):
        SloSpec(name="slo_convergence_p99", metric="m", kind="bogus")
    with pytest.raises(ValueError, match="objective"):
        SloSpec(name="slo_convergence_p99", metric="m", objective=0.0)
    with pytest.raises(ValueError, match="fast_window"):
        SloSpec(
            name="slo_convergence_p99", metric="m",
            fast_window_s=10.0, slow_window_s=5.0,
        )
    for spec in default_slos():
        assert spec.name in ALERTS  # catalog stays registry-pinned


# ---------------------------------------------------------------------------
# generation skew / staleness
# ---------------------------------------------------------------------------


def test_generation_hash_is_stable_and_content_sensitive():
    g = [3, [["0", 7]]]
    assert generation_hash(g) == generation_hash([3, [["0", 7]]])
    assert generation_hash(g) != generation_hash([4, [["0", 7]]])
    assert len(generation_hash(g)) == 12


def test_generation_skew_fires_for_the_lagging_node_only():
    clock = SimClock()
    gens = {"a": 0, "b": 0}

    def source():
        return [
            snap("a", generation=[gens["a"]]),
            snap("b", generation=[gens["b"]]),
        ]

    agg, sink = make_agg(
        clock, source, skew_min_generations=3, skew_hold_s=5.0
    )
    agg.sweep()  # registers both
    for i in range(4):
        clock._now += 2.0
        gens["a"] += 1  # a churns; b frozen
        agg.sweep()
    assert [a["name"] for a in sink.active_alerts()] == ["generation_skew"]
    assert sink.active["generation_skew"]["stale_nodes"] == ["b"]
    rows = {r["node"]: r for r in agg.status()["nodes"]}
    assert rows["b"]["stale"] and not rows["a"]["stale"]
    assert rows["b"]["missed_generations"] >= 3
    # b advancing again resolves the alert
    clock._now += 2.0
    gens["b"] += 1
    agg.sweep()
    assert sink.active_alerts() == []
    assert json.loads(agg.alert_log()[-1])["event"] == "resolved"


def test_generation_skew_needs_both_miss_count_and_hold_time():
    """Three fast misses inside the hold window must NOT page — the
    hold filters sweep-cadence jitter exactly like the slow burn
    window filters blips."""
    clock = SimClock()
    gens = {"a": 0, "b": 0}

    def source():
        return [
            snap("a", generation=[gens["a"]]),
            snap("b", generation=[gens["b"]]),
        ]

    agg, sink = make_agg(
        clock, source, skew_min_generations=3, skew_hold_s=60.0
    )
    agg.sweep()
    for _ in range(4):
        clock._now += 1.0  # only 4s elapse, hold is 60s
        gens["a"] += 1
        agg.sweep()
    assert sink.active_alerts() == []


def test_quiet_fleet_never_reads_as_stale():
    clock = SimClock()
    source = lambda: [snap("a"), snap("b")]  # noqa: E731
    agg, sink = make_agg(
        clock, source, skew_min_generations=1, skew_hold_s=0.0
    )
    for _ in range(5):
        agg.sweep()
        clock._now += 10.0
    assert sink.active_alerts() == []  # nobody advanced, nobody lags


def test_restarted_node_counts_as_advanced_not_stale():
    clock = SimClock()
    gen = {"b": [1, "incarnation1"]}

    def source():
        return [snap("a", generation=[9]), snap("b", generation=gen["b"])]

    agg, sink = make_agg(
        clock, source, skew_min_generations=2, skew_hold_s=1.0
    )
    agg.sweep()
    clock._now += 5.0
    gen["b"] = [0, "incarnation2"]  # restart: counters reset, hash changes
    agg.sweep()
    rows = {r["node"]: r for r in agg.status()["nodes"]}
    assert rows["b"]["missed_generations"] == 0


# ---------------------------------------------------------------------------
# derived fleet signals from synthetic snapshots
# ---------------------------------------------------------------------------


def test_chip_and_backend_quarantine_rollup():
    clock = SimClock()

    def source():
        return [
            snap(
                "a",
                counters={
                    "decision.backend.pool.size": 8.0,
                    "decision.backend.pool.healthy": 7.0,
                },
            ),
            snap(
                "b",
                counters={
                    "decision.backend.pool.size": 8.0,
                    "decision.backend.pool.healthy": 8.0,
                    "resilience.backend.quarantined": 1.0,
                },
            ),
        ]

    agg, sink = make_agg(clock, source)
    status = agg.sweep()
    names = sorted(a["name"] for a in sink.active_alerts())
    assert names == ["backend_quarantine", "chip_quarantine"]
    assert sink.active["chip_quarantine"]["nodes"] == ["a"]
    assert sink.active["backend_quarantine"]["nodes"] == ["b"]
    assert status["chips"] == {
        "total": 16,
        "healthy": 15,
        "quarantined": 1,
        "per_node": {
            "a": {"size": 8, "healthy": 7},
            "b": {"size": 8, "healthy": 8},
        },
    }


def test_breaker_rollup_excludes_backend_and_chip_breakers():
    clock = SimClock()

    def source():
        return [
            snap(
                "a",
                counters={
                    "resilience.fib_agent.state": 1.0,
                    "resilience.kv_peer.node9.state": 2.0,
                    # covered by the dedicated quarantine alerts:
                    "resilience.backend.state": 1.0,
                    "resilience.backend.dev3.state": 1.0,
                    # closed breakers never roll up
                    "resilience.other.state": 0.0,
                },
            )
        ]

    agg, sink = make_agg(clock, source)
    status = agg.sweep()
    assert [a["name"] for a in sink.active_alerts()] == ["breaker_open"]
    edges = sorted(b["edge"] for b in status["breakers"])
    assert edges == ["fib_agent", "kv_peer.node9"]
    assert {b["state"] for b in status["breakers"]} == {
        "open",
        "half_open",
    }


def test_queue_saturation_threshold():
    clock = SimClock()
    depth = {"v": 10.0}

    def source():
        return [
            snap(
                "a",
                counters={"messaging.queue.routeUpdates.depth": depth["v"]},
            )
        ]

    agg, sink = make_agg(clock, source, queue_depth_threshold=100.0)
    agg.sweep()
    assert sink.active_alerts() == []
    depth["v"] = 250.0
    agg.sweep()
    assert [a["name"] for a in sink.active_alerts()] == ["queue_saturation"]
    assert sink.active["queue_saturation"]["queues"] == [
        "a:routeUpdates"
    ]
    depth["v"] = 3.0
    agg.sweep()
    assert sink.active_alerts() == []


def test_utilization_spread_needs_floor_and_spread():
    from openr_tpu.tracing.pipeline import device_utilization_key

    clock = SimClock()
    utils = {"vals": [0.01, 0.02]}

    def source():
        return [
            snap(
                "a",
                counters={
                    device_utilization_key(i): v
                    for i, v in enumerate(utils["vals"])
                },
            )
        ]

    agg, sink = make_agg(
        clock, source,
        utilization_spread_threshold=0.5,
        utilization_spread_floor=0.2,
    )
    agg.sweep()
    assert sink.active_alerts() == []  # idle jitter under the floor
    utils["vals"] = [0.95, 0.1]  # one hot chip, one cold: imbalance
    agg.sweep()
    assert [a["name"] for a in sink.active_alerts()] == [
        "utilization_spread"
    ]
    assert sink.active["utilization_spread"]["nodes"][0]["node"] == "a"


def test_crash_latch_survives_node_counter_reset():
    clock = SimClock()
    crashes = {"v": 0.0}

    def source():
        return [snap("a", counters={"watchdog.crashes": crashes["v"]})]

    agg, sink = make_agg(clock, source)
    agg.sweep()
    assert sink.active_alerts() == []
    crashes["v"] = 1.0
    agg.sweep()
    assert [a["name"] for a in sink.active_alerts()] == ["node_crash"]
    crashes["v"] = 0.0  # the node restarted; its counters reset
    agg.sweep()
    # the fleet still remembers the crash
    assert [a["name"] for a in sink.active_alerts()] == ["node_crash"]
    assert agg.status()["crashes_seen"] == 1.0


# ---------------------------------------------------------------------------
# alert sink: edges, counters, determinism, page-dump dedupe
# ---------------------------------------------------------------------------


def test_sink_edges_counters_and_log():
    clock = SimClock(1.0)
    sink = make_sink(clock)
    sink.report({"breaker_open": {"count": 1}})
    sink.report({"breaker_open": {"count": 1}})
    sink.report({})
    assert sink.num_fired == 1 and sink.num_resolved == 1
    # counter bumps once per FIRING sweep (2), not per edge
    assert sink.counters.get(alert_counter_key("breaker_open")) == 2.0
    events = [json.loads(line) for line in sink.log]
    assert [e["event"] for e in events] == ["fired", "resolved"]
    assert events[0]["severity"] == "ticket"
    assert events[0]["seq"] == 0 and events[1]["seq"] == 1


def test_sink_rejects_unregistered_names():
    sink = make_sink()
    with pytest.raises(ValueError, match="unregistered"):
        sink.report({"definitely_not_an_alert": {}})


def test_sink_log_bytes_deterministic():
    def one():
        clock = SimClock(2.0)
        sink = make_sink(clock)
        sink.report({"node_crash": {"crashes_seen": 1.0}})
        clock._now += 3.0
        sink.report({})
        return sink.log_bytes()

    assert one() == one() and one()


class _FakeRecorder:
    def __init__(self):
        self.reasons = []

    def dump(self, reason, extra=None):
        self.reasons.append((reason, extra))
        return b"{}"


def test_page_alerts_dump_once_per_sweep_and_rate_limit():
    clock = SimClock()
    rec = _FakeRecorder()
    sink = make_sink(clock, recorder=rec, page_dump_min_s=30.0)
    # two page alerts rising in ONE sweep -> one dump naming both
    sink.report(
        {
            "chip_quarantine": {"quarantined": 1},
            "node_crash": {"crashes_seen": 1.0},
            "breaker_open": {"count": 1},  # ticket: never dumps
        }
    )
    assert len(rec.reasons) == 1
    reason, extra = rec.reasons[0]
    assert reason == "health_page_alert"
    assert extra["alerts"] == ["chip_quarantine", "node_crash"]
    # resolve + re-fire inside the rate-limit window: suppressed
    sink.report({})
    clock._now += 5.0
    sink.report({"chip_quarantine": {"quarantined": 1}})
    assert len(rec.reasons) == 1 and sink.num_page_dumps_suppressed == 1
    # past the window a fresh page dumps again
    sink.report({})
    clock._now += 31.0
    sink.report({"node_crash": {"crashes_seen": 2.0}})
    assert len(rec.reasons) == 2


def test_ticket_alerts_never_dump():
    rec = _FakeRecorder()
    sink = make_sink(recorder=rec)
    sink.report({"generation_skew": {"stale_nodes": ["b"]}})
    assert rec.reasons == []


def test_sink_gauges_and_aggregator_gauges():
    clock = SimClock()
    agg, sink = make_agg(clock, lambda: [snap("a")])
    agg.sweep()
    g = agg.gauges()
    assert g["health.sweeps"] == 1.0
    assert g["health.alerts.active"] == 0.0
    assert alert_counter_key("node_crash") == "health.alert.node_crash"


def test_alert_log_is_bounded():
    clock = SimClock()
    sink = make_sink(clock, max_log_entries=4)
    for i in range(6):
        clock._now += 1.0
        sink.report({"breaker_open": {"count": i}})
        sink.report({})
    assert len(sink.log) == 4
    assert sink.num_fired == 6


# ---------------------------------------------------------------------------
# config plumbing
# ---------------------------------------------------------------------------


def test_health_config_validation_and_slo_overrides():
    from openr_tpu.config import HealthConfig, OpenrConfig, SloSpecConfig

    with pytest.raises(ValueError, match="sweep_interval"):
        OpenrConfig(health_config=HealthConfig(sweep_interval_s=0.0))
    with pytest.raises(ValueError, match="name and metric"):
        OpenrConfig(
            health_config=HealthConfig(slos=[SloSpecConfig(name="x")])
        )
    cfg = OpenrConfig(
        health_config=HealthConfig(
            slos=[
                SloSpecConfig(
                    name="slo_convergence_p99",
                    metric="convergence.event_to_fib_ms",
                    threshold=500.0,
                )
            ]
        )
    )
    # round-trips through JSON like every other config block
    back = OpenrConfig.from_json(cfg.to_json())
    assert back.health_config.slos[0].threshold == 500.0
    assert back.health_config.enabled is True


def test_export_health_jsonl(tmp_path, sim_loop):
    """EmulatedNetwork.export_health_jsonl (the --health-export
    surface): the lead node's alert-transition log lands as JSONL."""
    loop, clock = sim_loop
    from openr_tpu.emulation.network import EmulatedNetwork
    from openr_tpu.emulation.topology import line_edges

    async def scenario():
        net = EmulatedNetwork(clock)
        net.build(line_edges(2))
        net.start()
        await clock.run_for(10.0)
        path = str(tmp_path / "alerts.jsonl")
        assert net.export_health_jsonl(path) == 0  # clean run: empty
        assert open(path).read() == ""
        # force one transition through the lead node's sink
        net.nodes["node0"].health.sink.report(
            {"breaker_open": {"count": 1}}
        )
        assert net.export_health_jsonl(path) == 1
        doc = json.loads(open(path).read().strip())
        assert doc["name"] == "breaker_open" and doc["event"] == "fired"
        await net.stop()

    loop.run_until_complete(scenario())


def test_node_health_wiring(sim_loop):
    """OpenrNode builds the aggregator from config; disabled config
    builds none and the ctrl verbs raise."""
    loop, clock = sim_loop
    from openr_tpu.config import OpenrConfig
    from openr_tpu.ctrl.handler import OpenrCtrlHandler
    from openr_tpu.emulation.network import EmulatedNetwork

    async def scenario():
        net = EmulatedNetwork(clock)
        net.add_node("solo")
        net.config_overrides = lambda cfg: setattr(
            cfg.health_config, "enabled", False
        )
        net.add_node("dark")
        net.start()
        await clock.run_for(8.0)
        node = net.nodes["solo"]
        assert node.health is not None
        handler = OpenrCtrlHandler(node)
        status = handler.get_health_status()
        assert status["sweeps"] >= 1
        # the emulation re-pointed the source at the FLEET
        assert {r["node"] for r in status["nodes"]} == {"solo", "dark"}
        alerts = handler.get_active_alerts()
        assert alerts["active"] == [] and alerts["log"] == []
        dark = OpenrCtrlHandler(net.nodes["dark"])
        with pytest.raises(ValueError, match="disabled"):
            dark.get_health_status()
        await net.stop()

    loop.run_until_complete(scenario())
