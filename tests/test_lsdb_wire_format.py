"""LSDB flood-payload format end-to-end: thrift-compact and mixed areas.

With ``OpenrConfig.lsdb_wire_format = "thrift-compact"`` every
``adj:``/``prefix:`` KvStore value carries the reference's
CompactSerializer byte encoding (openr_tpu/interop) instead of wire
JSON; decoding sniffs, so a mixed-format network — half the nodes
flooding compact, half JSON, as in a migration or federation with
reference nodes — must converge identically."""

import asyncio

from openr_tpu.common.runtime import SimClock
from openr_tpu.emulation.network import EmulatedNetwork
from openr_tpu.emulation.topology import line_edges, ring_edges
from openr_tpu.lsdb_codec import (
    deserialize_adj_db,
    deserialize_prefix_db,
    serialize_adj_db,
    serialize_prefix_db,
)
from openr_tpu.types import AdjacencyDatabase, Adjacency, PrefixDatabase
from openr_tpu.types import parse_adj_key, parse_prefix_key

CONVERGE_S = 12.0


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_codec_round_trip_and_sniffing():
    db = AdjacencyDatabase(
        this_node_name="n1",
        adjacencies=[
            Adjacency(other_node_name="n2", if_name="e0", metric=3,
                      next_hop_v6="fe80::2")
        ],
        area="7",
    )
    js = serialize_adj_db(db, "json")
    tc = serialize_adj_db(db, "thrift-compact")
    assert js[:1] == b"{" and tc[:1] != b"{"
    assert deserialize_adj_db(js) == deserialize_adj_db(tc)
    pdb = PrefixDatabase(this_node_name="n1", delete_prefix=True)
    assert (
        deserialize_prefix_db(serialize_prefix_db(pdb, "thrift-compact"))
        == deserialize_prefix_db(serialize_prefix_db(pdb, "json"))
    )


def _flood_values(net, node):
    return net.nodes[node].kv_store.dump_all("0")


def test_thrift_compact_network_converges_and_floods_compact_bytes():
    async def main():
        clock = SimClock()
        net = EmulatedNetwork(
            clock,
            config_overrides=lambda cfg: setattr(
                cfg, "lsdb_wire_format", "thrift-compact"
            ),
        )
        net.build(line_edges(3))
        net.start()
        await clock.run_for(CONVERGE_S)
        ok, why = net.converged_full_mesh()
        assert ok, why
        # every flooded LSDB payload is compact bytes, not JSON
        checked = 0
        for key, v in _flood_values(net, "node0").items():
            if v.value is None:
                continue
            if parse_adj_key(key) or parse_prefix_key(key):
                assert v.value[:1] != b"{", key
                # and it decodes as the reference encoding
                if parse_adj_key(key):
                    db = deserialize_adj_db(v.value)
                    assert db.this_node_name
                checked += 1
        assert checked >= 5  # 3 adj dbs + loopback prefixes
        await net.stop()

    run(main())


def test_mixed_format_network_interoperates():
    """Even-numbered nodes flood thrift-compact, odd flood JSON; the
    ring must converge full-mesh either way (decode always sniffs)."""

    def overrides(cfg):
        idx = int(cfg.node_name.replace("node", ""))
        cfg.lsdb_wire_format = (
            "thrift-compact" if idx % 2 == 0 else "json"
        )

    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock, config_overrides=overrides)
        net.build(ring_edges(4))
        net.start()
        await clock.run_for(CONVERGE_S)
        ok, why = net.converged_full_mesh()
        assert ok, why
        vals = _flood_values(net, "node1")
        fmts = set()
        for key, v in vals.items():
            n = parse_adj_key(key)
            if n and v.value:
                fmts.add("json" if v.value[:1] == b"{" else "compact")
        assert fmts == {"json", "compact"}
        await net.stop()

    run(main())
