"""Self-hosted fleet membership (ISSUE 20) — heartbeat liveness,
epoch-fenced ownership, and the partition/gray-failure-hardened
coordinator.

The contracts under test (docs/Fleet.md, "Liveness" section):

* membership is heartbeat-derived: a TTL-bearing ``fleet:member:*``
  key per member, incarnation-stamped; the tracker's suspicion machine
  walks up -> suspect (``suspect_after_s``) -> down (``heartbeat_ttl_s``),
  rejoin needs a STRICTLY higher incarnation, and a bouncing node is
  flap-damped with a deterministic exponential hold;
* ownership is epoch-fenced: subscriptions and sweep dispatches carry
  the epoch they derived under and receivers reject stale-epoch work —
  counted (``fleet.fenced.stream`` / ``fleet.fenced.sweep``), never
  raised, never double-applied;
* the coordinator trusts no member: every ctrl touch rides a
  per-member breaker, a straggler's worlds re-pack without waiting for
  death (first-committed-wins keeps the digest byte-identical), and a
  heartbeating-but-failing member is demoted to drained with the
  ``fleet_gray_failure`` ticket;
* an UNANNOUNCED kill is detected from heartbeat silence alone with
  zero invariant violations and a merged digest byte-equal to a clean
  run; seeded replays of every chaos scenario are byte-identical.
"""

import asyncio
import dataclasses
import json
from types import SimpleNamespace

import pytest

from openr_tpu.common.runtime import CounterMap, SimClock
from openr_tpu.emulation.fabric import FleetFabric
from openr_tpu.fleet import (
    FleetMembership,
    FleetSweepCoordinator,
    LivenessTracker,
    MemberBeacon,
    MembershipView,
    heartbeat_value,
    parse_heartbeat,
)
from openr_tpu.fleet.coordinator import _CTRL_UNAVAILABLE
from openr_tpu.health.alerts import AlertSink, alert_counter_key
from openr_tpu.types import Publication, Value, fleet_member_key

pytestmark = [pytest.mark.fleet]


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        pending = asyncio.all_tasks(loop)
        for t in pending:
            t.cancel()
        if pending:
            loop.run_until_complete(
                asyncio.gather(*pending, return_exceptions=True)
            )
        loop.close()


SWEEP_PARAMS = {
    "drain_node_sets": [[], ["node5"], ["node7"], ["node3"]],
    "metric_perturbations": [{"pattern": "node.*", "factor": 2.0}],
}

#: liveness timers compressed for virtual-time tests; the invariant
#: interval < suspect_after < ttl still holds
FAST_LIVENESS = {
    "heartbeat_interval_s": 0.1,
    "suspect_after_s": 0.25,
    "heartbeat_ttl_s": 0.5,
    "tick_s": 0.05,
}


def make_fabric(clock, tmp_path, **kwargs):
    kwargs.setdefault("n_side", 3)
    kwargs.setdefault(
        "sweep_overrides",
        {"shard_scenarios": 2, "inter_shard_pause_s": 0.2},
    )
    return FleetFabric(clock, spill_root=str(tmp_path), **kwargs)


def make_tracker(clock, names=("a", "b"), **overrides):
    counters = CounterMap()
    membership = FleetMembership(list(names), counters=counters)
    kw = dict(FAST_LIVENESS)
    kw.update(overrides)
    tracker = LivenessTracker(clock, membership, counters=counters, **kw)
    return membership, tracker, counters


# ---------------------------------------------------------------------------
# heartbeat codec + beacon
# ---------------------------------------------------------------------------


def test_heartbeat_codec_roundtrip_and_malformed():
    v = heartbeat_value("fab1", 4200, 7, 2500)
    assert v.version == 7 and v.originator_id == "fab1" and v.ttl == 2500
    assert parse_heartbeat(v) == {"incarnation": 4200, "seq": 7}
    # seq falls back to the value version when the payload omits it
    legacy = Value(
        version=3,
        originator_id="fab1",
        value=json.dumps({"incarnation": 9}).encode(),
        ttl=2500,
    )
    assert parse_heartbeat(legacy) == {"incarnation": 9, "seq": 3}
    # malformed heartbeats must parse to None, never raise
    for bad in (
        Value(version=1, originator_id="x", value=None, ttl=1),
        Value(version=1, originator_id="x", value=b"\xff\xfe", ttl=1),
        Value(version=1, originator_id="x", value=b"not json", ttl=1),
        Value(version=1, originator_id="x", value=b"{\"seq\": 1}", ttl=1),
    ):
        assert parse_heartbeat(bad) is None


def test_member_beacon_incarnation_and_stall():
    clock = SimClock(5.0)
    pubs = []
    b = MemberBeacon(
        "fab1",
        clock,
        publish=pubs.append,
        heartbeat_interval_s=0.1,
        heartbeat_ttl_s=0.5,
    )
    # node.start_ms discipline: incarnation minted from the clock
    assert b.incarnation == 5000 and b.seq == 0
    b.beat_now()
    b.beat_now()
    assert len(pubs) == 2
    hb = parse_heartbeat(pubs[-1].key_vals[fleet_member_key("fab1")])
    assert hb == {"incarnation": 5000, "seq": 2}
    b.stall()
    assert b.stalled
    # restart inside the same clock millisecond: incarnation must still
    # STRICTLY advance (the fleet refuses same-incarnation rejoins)
    assert b.reincarnate() == 5001
    assert b.seq == 0 and not b.stalled
    clock._now = 10.0
    assert b.reincarnate() == 10000


# ---------------------------------------------------------------------------
# suspicion machine: up -> suspect -> down, incarnation-monotone rejoin
# ---------------------------------------------------------------------------


def test_tracker_suspicion_machine_and_ttl_expiry():
    clock = SimClock()
    m, tr, counters = make_tracker(clock)
    tr.on_heartbeat("a", 100, 1)
    tr.on_heartbeat("b", 100, 1)
    assert m.epoch == 0
    # a misses refreshes past suspect_after: SUSPECT, still live, and
    # the epoch does not move (the live set is unchanged)
    clock._now += 0.3
    tr.on_heartbeat("b", 100, 2)
    tr.tick()
    assert m.suspects() == ("a",) and m.is_live("a")
    assert tr.member_state("a") == "suspect" and m.epoch == 0
    # a refresh clears suspicion
    tr.on_heartbeat("a", 100, 2)
    assert m.suspects() == () and tr.member_state("a") == "live"
    assert counters.get("fleet.liveness.recoveries") == 1
    # silence past the TTL: DOWN, epoch bumps
    clock._now += 0.3
    tr.on_heartbeat("b", 100, 3)
    tr.tick()
    clock._now += 0.3
    tr.on_heartbeat("b", 100, 4)
    tr.tick()
    assert not m.is_live("a") and tr.member_state("a") == "down"
    assert m.epoch == 1
    assert counters.get("fleet.liveness.expiries") == 1
    # a zombie replaying the dead incarnation is counted and refused
    tr.on_heartbeat("a", 100, 5)
    assert not m.is_live("a")
    assert counters.get("fleet.liveness.stale_incarnation") == 1
    # a strictly higher incarnation readmits (first flap: no damping)
    tr.on_heartbeat("a", 101, 1)
    assert m.is_live("a") and m.epoch == 2
    assert counters.get("fleet.liveness.rejoins") == 1


def test_tracker_publication_ingress_expiry_and_malformed():
    clock = SimClock()
    m, tr, counters = make_tracker(clock)
    tr.on_publication(
        Publication(
            key_vals={
                fleet_member_key("a"): heartbeat_value("a", 7, 1, 500),
                # malformed value: counted, never raised
                fleet_member_key("b"): Value(
                    version=1, originator_id="b", value=b"junk", ttl=500
                ),
                # non-fleet keys are ignored
                "adj:node0": Value(
                    version=1, originator_id="x", value=b"{}", ttl=500
                ),
            },
            area="0",
        )
    )
    assert tr._m["a"].incarnation == 7
    assert counters.get("fleet.liveness.malformed") == 1
    # a heartbeat for a node outside the fleet is ignored, no KeyError
    tr.on_heartbeat("not-a-member", 1, 1)
    # the KvStore TTL-expiry notification is the death signal
    tr.on_publication(
        Publication(expired_keys=[fleet_member_key("b")], area="0")
    )
    assert not m.is_live("b") and m.epoch == 1
    assert counters.get("fleet.liveness.expiries") == 1


# ---------------------------------------------------------------------------
# flap damping: exponential, deterministic, held out while beating
# ---------------------------------------------------------------------------


def _bounce_twice(seed):
    """Bounce node a through two full down/rejoin cycles; returns the
    (membership, tracker, counters, damped_until) after the second
    rejoin attempt armed the damping hold."""
    clock = SimClock()
    m, tr, counters = make_tracker(
        clock, flap_hold_base_s=2.0, flap_hold_max_s=60.0, seed=seed
    )
    tr.on_heartbeat("a", 100, 1)
    tr.on_heartbeat("b", 100, 1)
    clock._now += 0.6
    tr.on_heartbeat("b", 100, 2)
    tr.tick()  # a down (flap cycle 1)
    assert not m.is_live("a")
    tr.on_heartbeat("a", 101, 1)  # first rejoin: immediate
    assert m.is_live("a")
    clock._now += 0.6
    tr.on_heartbeat("b", 100, 3)
    tr.tick()  # a down (flap cycle 2)
    assert not m.is_live("a")
    tr.on_heartbeat("a", 102, 1)  # second rejoin inside the window: DAMPED
    return clock, m, tr, counters, tr._m["a"].damped_until


def test_flap_damping_exponential_deterministic_and_released_by_tick():
    clock, m, tr, counters, damped_until = _bounce_twice(seed=0)
    assert not m.is_live("a") and tr.member_state("a") == "damped"
    assert counters.get("fleet.flap_damped") == 1
    # hold = base * 2^(flaps-2) +/- 10% jitter
    hold = damped_until - clock.now()
    assert 2.0 * 0.9 <= hold <= 2.0 * 1.1
    # deterministic: same seed draws the same hold; another seed differs
    assert _bounce_twice(seed=0)[4] == damped_until
    assert _bounce_twice(seed=3)[4] != damped_until
    # refreshes during the hold keep bookkeeping warm but do NOT readmit
    clock._now += 0.2
    tr.on_heartbeat("a", 102, 2)
    tr.tick()
    assert not m.is_live("a") and tr.member_state("a") == "damped"
    # once the hold elapses and the node is still beating, the tick
    # readmits it
    while not m.is_live("a"):
        clock._now += 0.1
        tr.on_heartbeat("a", 102, tr._m["a"].seq + 1)
        tr.tick()
        assert clock.now() < damped_until + 1.0, "hold never released"
    assert tr._m["a"].damped_until == 0.0
    assert counters.get("fleet.liveness.rejoins") == 2
    assert tr.status()["members"]["a"]["flaps_in_window"] == 2


# ---------------------------------------------------------------------------
# membership view + epoch semantics, gray-failure health plane
# ---------------------------------------------------------------------------


def test_membership_view_epoch_semantics_and_gray_alert():
    clock = SimClock()
    counters = CounterMap()
    m = FleetMembership(["a", "b", "c"], counters=counters)
    v = m.view()
    assert isinstance(v, MembershipView)
    assert v.epoch == 0 and v.live == ("a", "b", "c") and v.suspects == ()
    with pytest.raises(dataclasses.FrozenInstanceError):
        v.epoch = 99
    # suspicion is bookkeeping over an unchanged live set: no epoch bump
    assert m.mark_suspect("b")
    assert not m.mark_suspect("b")  # idempotent
    assert m.epoch == 0 and m.view().suspects == ("b",)
    assert m.clear_suspect("b") and m.epoch == 0
    # composition changes bump the epoch exactly once each
    assert m.node_down("b") and m.epoch == 1
    assert m.drain_node("c", reason="gray_failure") and m.epoch == 2
    firing = m.health_firing()
    assert firing["fleet_node_loss"]["nodes"] == ["b"]
    assert firing["fleet_drain_migration"]["nodes"] == ("c",) or firing[
        "fleet_drain_migration"
    ]["nodes"] == ["c"]
    assert firing["fleet_gray_failure"] == {"nodes": ["c"]}
    # the registry knows the ticket; the sink accepts the firing set
    sink = AlertSink("agg", clock, CounterMap())
    sink.report(firing)
    assert sink.counters.get(alert_counter_key("fleet_gray_failure")) == 1.0
    assert sink.counters.get(alert_counter_key("fleet_node_loss")) == 1.0
    # undrain clears the gray ticket (and bumps the epoch again)
    assert m.undrain_node("c") and m.epoch == 3
    assert "fleet_gray_failure" not in m.health_firing()
    assert m.status()["drain_reasons"] == {}


# ---------------------------------------------------------------------------
# the KvStore origination surface: the TTL refresh loop IS the heartbeat
# ---------------------------------------------------------------------------


def test_kvstore_heartbeat_surface_is_version_noop_per_incarnation():
    from openr_tpu.config import KvStoreConfig
    from openr_tpu.kvstore.kv_store import KvStore
    from openr_tpu.kvstore.transport import InProcessTransport
    from openr_tpu.messaging.queue import ReplicateQueue

    async def main():
        clock = SimClock(1.0)
        pub_q = ReplicateQueue("hb.kvStoreUpdates")
        peer_q = ReplicateQueue("hb.peerUpdates")
        kv_q = ReplicateQueue("hb.kvRequests")
        store = KvStore(
            node_name="n1",
            clock=clock,
            config=KvStoreConfig(),
            areas=["0"],
            transport=InProcessTransport(clock),
            publications_queue=pub_q,
            peer_updates_reader=peer_q.get_reader(),
            kv_request_reader=kv_q.get_reader(),
            initialization_cb=lambda ev: None,
        )
        store.start()
        v1 = store.advertise_fleet_heartbeat("0", incarnation=1000)
        assert v1.version == 1
        # same incarnation re-advertised: a version NO-OP network-wide
        # (the periodic refresh must not churn versions)
        v2 = store.advertise_fleet_heartbeat("0", incarnation=1000)
        assert v2.version == 1
        # a restart's higher incarnation is a real new version
        v3 = store.advertise_fleet_heartbeat("0", incarnation=2000)
        assert v3.version == 2
        hbs = store.fleet_member_heartbeats("0")
        assert hbs == {
            "n1": {
                "incarnation": 2000,
                "version": 2,
                "ttl_version": v3.ttl_version,
                "originator": "n1",
            }
        }
        assert (
            store.counters.get("kvstore.fleet_heartbeat_advertised") == 3
        )
        await store.stop()

    run(main())


# ---------------------------------------------------------------------------
# coordinator ctrl discipline: breaker + gray strikes (unit)
# ---------------------------------------------------------------------------


def test_coordinator_member_call_breaker_and_gray_demotion(tmp_path):
    clock = SimClock()
    counters = CounterMap()
    m = FleetMembership(["a", "b"], counters=counters)
    coord = FleetSweepCoordinator(
        clock,
        m,
        services={},
        spill_root=str(tmp_path),
        counters=counters,
        ctrl_failure_threshold=3,
        ctrl_backoff_initial_s=0.5,
        ctrl_backoff_max_s=0.5,
        gray_strike_threshold=3,
    )

    def boom():
        raise ConnectionError("ctrl plane gone")

    # three raising touches: three failures, three strikes, sentinel
    # every time — the pump never sees the exception
    for _ in range(3):
        assert coord._member_call("a", "state", boom) is _CTRL_UNAVAILABLE
    assert counters.get("fleet.ctrl.errors") == 3
    assert counters.get("fleet.gray.strikes") == 3
    assert coord.status()["strikes"] == {"a": {"ctrl": 3}}
    # at the strike threshold the member is demoted to DRAINED: still
    # up (it answers, or at least heartbeats), owns nothing
    assert not m.is_live("a") and m.is_up("a")
    assert counters.get("fleet.gray.demotions") == 1
    assert m.health_firing()["fleet_gray_failure"] == {"nodes": ["a"]}
    # the breaker is now open: the next touch short-circuits without
    # invoking the member at all
    def must_not_run():
        raise AssertionError("short-circuited call must not execute")

    assert (
        coord._member_call("a", "state", must_not_run) is _CTRL_UNAVAILABLE
    )
    assert counters.get("fleet.ctrl.short_circuits") == 1
    assert coord.status()["breakers"]["a"] == "open"
    # past the backoff hold, a successful probe closes the breaker
    clock._now += 1.0
    assert coord._member_call("a", "state", lambda: "idle") == "idle"
    assert coord.status()["breakers"]["a"] == "closed"


# ---------------------------------------------------------------------------
# epoch fencing: the sweep service refuses stale-epoch dispatches
# ---------------------------------------------------------------------------


def test_service_fences_stale_epoch_dispatch(tmp_path):
    async def main():
        clock = SimClock()
        fab = make_fabric(clock, tmp_path)  # never started: fence is sync
        svc = fab.nodes["fab0"].sweep
        svc.attach_fleet(lambda: {}, epoch_fn=lambda: 3)
        res = svc.start_sweep({**SWEEP_PARAMS, "fleet_epoch": 2})
        assert res["fenced"] and res["state"] == "fenced"
        assert res["dispatch_epoch"] == 2 and res["current_epoch"] == 3
        # counted and returned — never raised, never started
        assert svc.state == "idle" and svc.num_sweeps_fenced == 1
        assert svc.get_sweep_status()["sweeps_fenced"] == 1
        assert (
            fab.nodes["fab0"].counters.get("fleet.fenced.sweep_rejected")
            == 1
        )

    run(main())


async def _drive_to_done(fab, clock, max_steps=6000):
    for _ in range(max_steps):
        await clock.run_for(0.05)
        if fab.coordinator.state != "running":
            break
    assert fab.coordinator.state == "done", fab.coordinator.state
    s = fab.coordinator.summary()
    return s["summary_digest"], fab.coordinator.manifest_bytes()


async def _clean_sweep(root, **fab_kwargs):
    """The uninterrupted reference run every chaos digest compares to."""
    clock = SimClock()
    fab = make_fabric(clock, root, **fab_kwargs)
    fab.start()
    await clock.run_for(2.0)
    fab.coordinator.prepare(SWEEP_PARAMS)
    fab.coordinator.start()
    digest, manifest = await _drive_to_done(fab, clock)
    await fab.stop()
    return digest, manifest


def test_stale_epoch_sweep_dispatch_fenced_then_repacked(tmp_path):
    """Tasks assigned under epoch E and dispatched after the epoch
    moved are FENCED by the receiving services (never run), counted,
    and re-derived under the current epoch — the digest still matches
    an uninterrupted run byte-for-byte."""

    async def main():
        d0, m0 = await _clean_sweep(tmp_path / "clean")
        clock = SimClock()
        fab = make_fabric(clock, tmp_path / "fenced")
        fab.start()
        await clock.run_for(2.0)
        fab.coordinator.prepare(SWEEP_PARAMS)  # assigns at epoch 0
        await fab.kill_node("fab1")  # epoch 0 -> 1 before any launch
        fab.coordinator.start()
        d1, m1 = await _drive_to_done(fab, clock)
        st = fab.coordinator.status()
        # the survivors' epoch-0 dispatches were refused at the door
        assert st["fenced_worlds"] > 0
        assert fab.counters.get("fleet.fenced.sweep") >= 1
        fenced_rows = [
            t for t in st["assignments"] if t["state"] == "fenced"
        ]
        assert fenced_rows and all(t["epoch"] == 0 for t in fenced_rows)
        assert (
            sum(f.sweep.num_sweeps_fenced for f in fab.nodes.values()) >= 1
        )
        # the dead node's worlds re-packed; everything merged exactly once
        assert st["repacked_worlds"] > 0
        assert st["worlds_merged"] == st["worlds_total"]
        assert st["scenarios_merged"] == st["scenarios_total"]
        assert d1 == d0 and m1 == m0
        await fab.stop()

    run(main())


# ---------------------------------------------------------------------------
# the detection-tier acceptance: unannounced kill, heartbeat silence only
# ---------------------------------------------------------------------------


async def _unannounced_kill_scenario(root):
    clock = SimClock()
    fab = make_fabric(clock, root, liveness_overrides=dict(FAST_LIVENESS))
    fab.start()
    await clock.run_for(2.0)
    watchers = [
        fab.router.watch("route_db", {"node": f"node{i}"})
        for i in range(6)
    ]
    await clock.run_for(1.0)
    fab.coordinator.prepare(SWEEP_PARAMS)
    fab.coordinator.start()
    victim = None
    t_kill = t_detect = None
    for _ in range(8000):
        await clock.run_for(0.05)
        st = fab.coordinator.status()
        if victim is None:
            running = sorted(
                t["node"]
                for t in st["assignments"]
                if t["state"] == "running"
            )
            if running:
                victim = running[0]
                await fab.kill_node_unannounced(victim)
                t_kill = clock.now()
        elif t_detect is None and not fab.membership.is_live(victim):
            t_detect = clock.now()
            # churn after detection: the migrated watchers must keep
            # applying deltas with the invariants intact
            fab.announce_prefix("node0", "10.98.0.0/24")
        if fab.coordinator.state != "running":
            break
    assert fab.coordinator.state == "done"
    assert victim is not None and t_detect is not None
    await clock.run_for(1.0)
    logs = b"\x00".join(w.log_bytes() for w in watchers)
    st = fab.coordinator.status()
    out = {
        "victim": victim,
        "detection_s": round(t_detect - t_kill, 6),
        "digest": fab.coordinator.summary()["summary_digest"],
        "manifest": fab.coordinator.manifest_bytes(),
        "logs": logs,
        "status": st,
        "violations": fab.router.invariant_violations(),
        "re_emissions": fab.router.pre_migration_re_emissions(),
        "victim_watchers": [
            (w.migrations, w.serving_node)
            for w in watchers
            if w.serving_node == victim or victim in [
                n for n, _s in w.stale_subs
            ] or (w.migrations and w.emissions)
        ],
        "watchers": [
            (w.migrations, w.serving_node) for w in watchers
        ],
        "suspects_seen": fab.counters.get("fleet.membership.suspect"),
        "gray_demotions": fab.counters.get("fleet.gray.demotions"),
    }
    await fab.stop()
    return out


@pytest.mark.chaos
def test_unannounced_kill_detected_by_heartbeat_silence_alone(tmp_path):
    async def main():
        d0, m0 = await _clean_sweep(
            tmp_path / "clean",
            liveness_overrides=dict(FAST_LIVENESS),
        )
        a = await _unannounced_kill_scenario(tmp_path / "killed")
        # detection from heartbeat silence ALONE: bounded by the TTL
        # plus one tick plus the harness sampling step — and the node
        # passed through suspicion first
        assert 0.25 <= a["detection_s"] <= 0.75, a["detection_s"]
        assert a["suspects_seen"] >= 1
        # death is not gray failure: no strikes, no demotion
        assert a["gray_demotions"] == 0
        # the victim's unmerged worlds re-packed; the merged digest and
        # manifest are byte-equal to the uninterrupted run
        assert a["status"]["repacked_worlds"] > 0
        assert a["status"]["worlds_merged"] == a["status"]["worlds_total"]
        assert a["digest"] == d0 and a["manifest"] == m0
        # zero invariant violations across the migration
        assert a["violations"] == 0 and a["re_emissions"] == 0
        for migrations, serving in a["watchers"]:
            assert serving is not None and serving != a["victim"]
            assert migrations <= 1
        # byte-identical seeded replay of the whole scenario
        b = await _unannounced_kill_scenario(tmp_path / "replay")
        assert (a["victim"], a["detection_s"]) == (
            b["victim"],
            b["detection_s"],
        )
        assert a["digest"] == b["digest"]
        assert a["manifest"] == b["manifest"]
        assert a["logs"] == b["logs"]

    run(main())


# ---------------------------------------------------------------------------
# split brain: asymmetric partition, stale-epoch stream pushes fenced
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_asymmetric_partition_fences_stale_stream_pushes(tmp_path):
    async def main():
        clock = SimClock()
        fab = make_fabric(
            clock, tmp_path, liveness_overrides=dict(FAST_LIVENESS)
        )
        fab.start()
        await clock.run_for(2.0)
        watchers = [
            fab.router.watch("route_db", {"node": f"node{i}"})
            for i in range(6)
        ]
        await clock.run_for(1.0)
        placement = {}
        for w in watchers:
            placement.setdefault(w.serving_node, []).append(w)
        victim = max(
            sorted(placement), key=lambda n: len(placement[n])
        )
        epoch0 = fab.membership.epoch
        # the victim's heartbeats stop REACHING the tracker; its
        # services keep running and pushing — the split-brain shape
        fab.partition_asymmetric(victim)
        await clock.run_for(1.0)
        assert not fab.membership.is_live(victim)
        assert fab.nodes[victim].running  # daemon alive: asymmetric
        assert fab.membership.epoch == epoch0 + 1
        assert fab.counters.get("fleet.hb_dropped") > 0
        # the watchers migrated off; the dead-to-us daemon could not be
        # unsubscribed, so its subscriptions linger behind the fence
        for w in placement[victim]:
            assert w.serving_node != victim and w.migrations == 1
        assert fab.router.status()["stale_subscriptions"] >= len(
            placement[victim]
        )
        # churn: EVERY service pushes, including the stale owner — its
        # deliveries are fenced (counted), never applied, never doubled
        fab.announce_prefix("node1", "10.97.0.0/24")
        await clock.run_for(1.0)
        assert fab.router.fenced_deliveries() > 0
        assert fab.counters.get("fleet.fenced.stream") > 0
        assert fab.router.invariant_violations() == 0
        assert fab.router.pre_migration_re_emissions() == 0
        # heal: a higher-incarnation rejoin readmits the member and the
        # next resync garbage-collects the stale subscriptions
        fab.heal_partition(victim)
        await clock.run_for(1.0)
        assert fab.membership.is_live(victim)
        assert fab.membership.epoch == epoch0 + 2
        assert fab.router.status()["stale_subscriptions"] == 0
        assert (
            fab.counters.get("fleet.directory.stale_unsubscribed")
            >= len(placement[victim])
        )
        fab.announce_prefix("node2", "10.96.0.0/24")
        await clock.run_for(1.0)
        assert fab.router.invariant_violations() == 0
        assert fab.router.pre_migration_re_emissions() == 0
        await fab.stop()

    run(main())


# ---------------------------------------------------------------------------
# stragglers: re-pack without waiting for death, first-committed-wins
# ---------------------------------------------------------------------------


async def _straggler_run(root, pause_s):
    """One fleet sweep where the busiest member turns slow mid-round
    (``pause_s`` between shards).  Returns (digest, manifest, status)."""
    clock = SimClock()
    fab = make_fabric(
        clock,
        root,
        sweep_overrides={"shard_scenarios": 2, "inter_shard_pause_s": 0.2},
        # above the busiest member's natural round (~4.8s: 4 worlds x
        # 12 scenarios / 2 per shard x 0.2s), below any slowed round
        coordinator_overrides={"straggler_deadline_s": 6.0},
    )
    fab.start()
    await clock.run_for(2.0)
    fab.coordinator.prepare(SWEEP_PARAMS)
    if pause_s is not None:
        counts = {}
        for t in fab.coordinator.tasks:
            counts[t.node] = counts.get(t.node, 0) + len(t.worlds)
        slow = max(sorted(counts), key=lambda n: counts[n])
        fab.nodes[slow].sweep.config.inter_shard_pause_s = pause_s
    fab.coordinator.start()
    digest, manifest = await _drive_to_done(fab, clock)
    st = fab.coordinator.status()
    await fab.stop()
    return digest, manifest, st


@pytest.mark.chaos
def test_straggler_repack_is_first_committed_wins(tmp_path):
    async def main():
        d0, m0, st0 = await _straggler_run(tmp_path / "clean", None)
        assert st0["straggler_repacks"] == 0
        # the straggler NEVER finishes: its unfinished worlds re-packed
        # onto the others past the deadline, its leftover copy cancelled
        # as a duplicate at completion
        d1, m1, st1 = await _straggler_run(tmp_path / "never", 60.0)
        assert st1["straggler_repacks"] >= 1
        assert st1["straggler_repacked_worlds"] >= 1
        assert st1["duplicate_completions"] >= 1
        assert any(
            "straggler" in per for per in st1["strikes"].values()
        )
        # the straggler finishes LATE: both copies exist, merge keeps
        # the first-committed world and drops the duplicate
        d2, m2, st2 = await _straggler_run(tmp_path / "late", 0.4)
        assert st2["straggler_repacks"] >= 1
        # whichever way the race lands, the content contract holds:
        # every scenario merged exactly once, digest and manifest
        # byte-identical to the clean run
        for d, m, st in ((d1, m1, st1), (d2, m2, st2)):
            assert st["worlds_merged"] == st["worlds_total"]
            assert st["scenarios_merged"] == st["scenarios_total"]
            assert d == d0 and m == m0

    run(main())


# ---------------------------------------------------------------------------
# gray failure: heartbeats fine, ctrl surface raising — demote, don't die
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_gray_failure_mid_round_demotes_and_survivors_finish(tmp_path):
    async def main():
        d0, m0 = await _clean_sweep(tmp_path / "clean")
        clock = SimClock()
        fab = make_fabric(clock, tmp_path / "gray")
        fab.start()
        await clock.run_for(2.0)
        fab.coordinator.prepare(SWEEP_PARAMS)
        fab.coordinator.start()
        victim = None
        for _ in range(6000):
            await clock.run_for(0.05)
            st = fab.coordinator.status()
            if victim is None:
                running = sorted(
                    t["node"]
                    for t in st["assignments"]
                    if t["state"] == "running"
                )
                if running:
                    victim = running[0]
                    fab.gray_sweep_failure(victim)
            if fab.coordinator.state != "running":
                break
        # the sweep COMPLETED on the survivors; the coordinator fiber
        # absorbed every member exception through the breaker
        assert fab.coordinator.state == "done"
        assert fab.counters.get("fleet.crash") == 0
        assert fab.counters.get("fleet.ctrl.errors") >= 3
        # the heartbeating-but-failing member was demoted to drained
        assert victim is not None
        assert not fab.membership.is_live(victim)
        assert fab.membership.is_up(victim)
        assert fab.counters.get("fleet.gray.demotions") >= 1
        assert fab.membership.status()["drain_reasons"][victim] == (
            "gray_failure"
        )
        st = fab.coordinator.status()
        assert victim in st["strikes"]
        firing = fab.membership.health_firing()
        assert firing["fleet_gray_failure"]["nodes"] == [victim]
        sink = AlertSink("agg", clock, CounterMap())
        sink.report(firing)
        assert (
            sink.counters.get(alert_counter_key("fleet_gray_failure"))
            == 1.0
        )
        # content contract intact
        assert fab.coordinator.summary()["summary_digest"] == d0
        assert fab.coordinator.manifest_bytes() == m0
        await fab.stop()

    run(main())


# ---------------------------------------------------------------------------
# flapping node: damping bounds ownership churn; byte-identical replay
# ---------------------------------------------------------------------------


async def _flap_scenario(root):
    clock = SimClock()
    fab = make_fabric(
        clock,
        root,
        liveness_overrides={
            **FAST_LIVENESS,
            "flap_hold_base_s": 1.0,
            "flap_hold_max_s": 4.0,
            "flap_window_s": 30.0,
        },
    )
    fab.start()
    await clock.run_for(2.0)
    watchers = [
        fab.router.watch("route_db", {"node": f"node{i}"})
        for i in range(6)
    ]
    await clock.run_for(1.0)
    placement = {}
    for w in watchers:
        placement.setdefault(w.serving_node, []).append(w)
    victim = max(sorted(placement), key=lambda n: len(placement[n]))
    epoch0 = fab.membership.epoch
    # -- cycle A: a bounce that straddles ONLY suspect_after — the node
    #    goes suspect, recovers, and nothing moves (suspicion is
    #    bookkeeping, not a composition change)
    fab.heartbeat_stall(victim)
    await clock.run_for(0.3)
    assert victim in fab.membership.suspects()
    assert fab.membership.is_live(victim)
    fab.beacons[victim].resume()
    fab.beacons[victim].beat_now()
    await clock.run_for(0.2)
    assert fab.membership.suspects() == ()
    assert fab.membership.epoch == epoch0
    assert all(w.migrations == 0 for w in watchers)
    # -- cycles B, C: full bounces past the TTL.  The first rejoin is
    #    immediate; the second inside the flap window is DAMPED.
    fab.announce_prefix("node2", "10.95.0.0/24")
    await clock.run_for(0.5)
    for _cycle in range(2):
        fab.heartbeat_stall(victim)
        await clock.run_for(0.8)
        assert not fab.membership.is_live(victim)
        fab.heal_heartbeat(victim)
        await clock.run_for(0.2)
    # second rejoin attempt armed the damping hold: the node stays out
    # while its heartbeats keep arriving, and the watchers stay PUT
    assert fab.counters.get("fleet.flap_damped") == 1
    assert not fab.membership.is_live(victim)
    assert fab.liveness.member_state(victim) == "damped"
    moves_mid_damp = [w.migrations for w in placement[victim]]
    fab.announce_prefix("node0", "10.94.0.0/24")
    await clock.run_for(0.5)
    assert [w.migrations for w in placement[victim]] == moves_mid_damp
    # the hold (~1s) elapses while the beacon keeps beating: readmitted
    await clock.run_for(1.5)
    assert fab.membership.is_live(victim)
    fab.announce_prefix("node1", "10.93.0.0/24")
    await clock.run_for(0.5)
    # churn bound: <=2 ownership moves per full flap cycle (out + back),
    # and zero for everyone else
    for w in watchers:
        if w in placement[victim]:
            assert w.migrations == 4  # 2 full cycles x (out + back)
        else:
            assert w.migrations == 0
    # down(B) + up(B) + down(C) + up(after hold) = 4 epoch bumps
    assert fab.membership.epoch == epoch0 + 4
    assert fab.router.invariant_violations() == 0
    assert fab.router.pre_migration_re_emissions() == 0
    logs = b"\x00".join(w.log_bytes() for w in watchers)
    damped = fab.counters.get("fleet.flap_damped")
    await fab.stop()
    return victim, logs, damped, fab.membership.epoch


@pytest.mark.chaos
def test_flapping_node_damping_bounds_churn_and_replays_identically(
    tmp_path,
):
    async def main():
        v1, log_a, damped_a, ep_a = await _flap_scenario(tmp_path / "a")
        v2, log_b, damped_b, ep_b = await _flap_scenario(tmp_path / "b")
        assert (v1, damped_a, ep_a) == (v2, damped_b, ep_b)
        assert log_a == log_b

    run(main())


# ---------------------------------------------------------------------------
# router resync coalescing: one derivation pass per epoch bump
# ---------------------------------------------------------------------------


def test_router_resync_coalesced_once_per_epoch_bump(tmp_path):
    async def main():
        clock = SimClock()
        fab = make_fabric(
            clock, tmp_path, liveness_overrides=dict(FAST_LIVENESS)
        )
        fab.start()
        await clock.run_for(2.0)
        watchers = [
            fab.router.watch("route_db", {"node": f"node{i}"})
            for i in range(6)
        ]
        await clock.run_for(1.0)
        assert fab.router.owner_derivations == 0
        placement = {}
        for w in watchers:
            placement.setdefault(w.serving_node, []).append(w)
        victim = max(sorted(placement), key=lambda n: len(placement[n]))
        epoch0 = fab.membership.epoch
        # one stalled beacon throws TWO membership events (suspect,
        # then down) — but only ONE epoch bump, so placement re-derives
        # exactly once per watcher
        fab.heartbeat_stall(victim)
        await clock.run_for(1.0)
        assert not fab.membership.is_live(victim)
        assert fab.membership.epoch == epoch0 + 1
        assert fab.counters.get("fleet.membership.suspect") >= 1
        assert fab.router.owner_derivations == len(watchers)
        for w in watchers:
            if w in placement[victim]:
                assert w.migrations == 1
                assert (
                    len(
                        [
                            e
                            for e in w.emissions
                            if e.get("type") == "snapshot"
                        ]
                    )
                    == 2
                )
            else:
                assert w.migrations == 0
        await fab.stop()

    run(main())


# ---------------------------------------------------------------------------
# observability: the ctrl verb + breeze rendering
# ---------------------------------------------------------------------------


def test_fleet_status_verb_and_breeze_render():
    from openr_tpu.cli.breeze import render_fleet_status
    from openr_tpu.ctrl.handler import OpenrCtrlHandler

    assert render_fleet_status({"state": "disabled"}) == [
        "fleet tier disabled"
    ]
    # a node with only the liveness plane attached still answers
    clock = SimClock()
    m, tr, _counters = make_tracker(clock, names=("fab0", "fab1"))
    tr.on_heartbeat("fab0", 1000, 1)
    handler = OpenrCtrlHandler(
        SimpleNamespace(fleet=None, fleet_liveness=tr)
    )
    doc = handler.get_fleet_status()
    assert doc["state"] == "liveness-only"
    assert doc["liveness"]["members"]["fab0"]["state"] == "live"
    lines = render_fleet_status(doc)
    assert any("fab0: live" in ln and "inc=1000" in ln for ln in lines)
    assert any("suspect_after=0.25s" in ln for ln in lines)
    # neither plane attached: disabled
    bare = OpenrCtrlHandler(SimpleNamespace())
    assert bare.get_fleet_status() == {"state": "disabled"}
    # the full coordinator document renders the runbook columns
    doc = {
        "fleet_id": "abc123",
        "state": "running",
        "epoch": 3,
        "nodes_live": 2,
        "nodes_total": 3,
        "worlds_merged": 5,
        "worlds_total": 8,
        "fenced_worlds": 2,
        "straggler_repacks": 1,
        "duplicate_completions": 1,
        "strikes": {"fab1": {"ctrl": 2, "straggler": 1}},
        "liveness": {
            "epoch": 3,
            "suspect_after_s": 1.25,
            "heartbeat_ttl_s": 2.5,
            "members": {
                "fab1": {
                    "state": "damped",
                    "incarnation": 7,
                    "heartbeat_age_s": 0.2,
                    "damped_for_s": 1.5,
                    "flaps_in_window": 2,
                }
            },
        },
    }
    lines = render_fleet_status(doc)
    assert any(
        "epoch=3" in ln and "worlds 5/8" in ln and "fenced=2" in ln
        for ln in lines
    )
    assert any("strikes fab1: ctrl=2 straggler=1" in ln for ln in lines)
    assert any(
        "fab1: damped" in ln and "damped_for=1.5s" in ln for ln in lines
    )
