"""SpfSolver scalar-core tests — semantics ported in spirit from
openr/decision/tests/SpfSolverTest.cpp (drained-node choice, multipath,
MPLS labels, best-route selection, min-nexthop, cross-area merge)."""

from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.rib import DecisionRouteDb, RibUnicastEntry
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.emulation.topology import build_adj_dbs, line_edges, ring_edges
from openr_tpu.types import (
    NextHop,
    PrefixEntry,
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
    PrefixMetrics,
)

P1 = "10.1.0.0/16"
P2 = "2001:db8::/64"


def make_area(edges, area="0", **kwargs) -> LinkState:
    ls = LinkState(area)
    for db in build_adj_dbs(edges, area=area, **kwargs).values():
        ls.update_adjacency_database(db)
    return ls


def advertise(ps: PrefixState, node, prefix, area="0", **metrics_kwargs):
    extra = {}
    for k in ("forwarding_type", "forwarding_algorithm", "min_nexthop"):
        if k in metrics_kwargs:
            extra[k] = metrics_kwargs.pop(k)
    entry = PrefixEntry(
        prefix=prefix, metrics=PrefixMetrics(**metrics_kwargs), **extra
    )
    ps.update_prefix(node, area, entry)
    return entry


def test_line_route_via_next_hop():
    ls = make_area(line_edges(3))  # node0-node1-node2
    ps = PrefixState()
    advertise(ps, "node2", P1)
    solver = SpfSolver("node0")
    db = solver.build_route_db({"0": ls}, ps)
    assert db is not None
    route = db.unicast_routes[P1]
    assert route.igp_cost == 2
    nhs = list(route.nexthops)
    assert len(nhs) == 1
    assert nhs[0].neighbor_node_name == "node1"
    assert nhs[0].if_name == "if_node0_node1"


def test_ecmp_two_nexthops():
    edges = [("a", "b", 1), ("a", "c", 1), ("b", "d", 1), ("c", "d", 1)]
    ls = make_area(edges)
    ps = PrefixState()
    advertise(ps, "d", P1)
    solver = SpfSolver("a")
    db = solver.build_route_db({"0": ls}, ps)
    route = db.unicast_routes[P1]
    assert {nh.neighbor_node_name for nh in route.nexthops} == {"b", "c"}
    assert all(nh.metric == 2 for nh in route.nexthops)


def test_skip_route_for_self_advertised_prefix():
    ls = make_area(line_edges(3))
    ps = PrefixState()
    advertise(ps, "node0", P1)  # we advertise it ourselves
    advertise(ps, "node2", P1)
    solver = SpfSolver("node0")
    db = solver.build_route_db({"0": ls}, ps)
    assert P1 not in db.unicast_routes


def test_best_route_selection_path_preference_wins():
    ls = make_area(line_edges(4))
    ps = PrefixState()
    advertise(ps, "node1", P1, path_preference=500)
    advertise(ps, "node3", P1, path_preference=1000)  # farther but preferred
    solver = SpfSolver("node0")
    db = solver.build_route_db({"0": ls}, ps)
    route = db.unicast_routes[P1]
    assert route.igp_cost == 3  # routes to node3 despite node1 being closer
    assert route.best_prefix_entry.metrics.path_preference == 1000


def test_best_route_selection_distance_tiebreak():
    ls = make_area(line_edges(4))
    ps = PrefixState()
    advertise(ps, "node1", P1, distance=2)
    advertise(ps, "node3", P1, distance=1)  # smaller redistribution distance
    solver = SpfSolver("node0")
    db = solver.build_route_db({"0": ls}, ps)
    assert db.unicast_routes[P1].igp_cost == 3


def test_equal_metrics_multiple_winners_union_nexthops():
    # both ends of a ring advertise; equal metrics -> ECMP toward nearest
    ls = make_area(ring_edges(4))
    ps = PrefixState()
    advertise(ps, "node1", P1)
    advertise(ps, "node3", P1)
    solver = SpfSolver("node0")
    db = solver.build_route_db({"0": ls}, ps)
    route = db.unicast_routes[P1]
    # node1 and node3 both at distance 1 -> nexthops to both
    assert {nh.neighbor_node_name for nh in route.nexthops} == {"node1", "node3"}


def test_hard_drained_candidate_filtered():
    ls = make_area(line_edges(4), overloaded=["node1"])
    ps = PrefixState()
    advertise(ps, "node1", P1)
    advertise(ps, "node3", P1)
    solver = SpfSolver("node0")
    db = solver.build_route_db({"0": ls}, ps)
    route = db.unicast_routes[P1]
    # node1 hard-drained -> winner is node3 (3 hops, via node1 as transit?
    # no: node1 overloaded -> no transit -> node3 unreachable... but node1 is
    # the only path; unreachable nodes were already filtered, so the route
    # falls back to node1 per all-drained fallback
    assert route.best_prefix_entry is not None


def test_hard_drain_fallback_when_all_drained():
    ls = make_area(line_edges(2), overloaded=["node1"])
    ps = PrefixState()
    advertise(ps, "node1", P1)
    solver = SpfSolver("node0")
    db = solver.build_route_db({"0": ls}, ps)
    # only candidate is drained: still routed (filterHardDrainedNodes noop)
    route = db.unicast_routes[P1]
    assert route.best_prefix_entry.metrics.drain_metric == 1  # marked drained


def test_soft_drained_node_less_preferred():
    # two advertisers, one soft-drained -> other wins
    edges = [("a", "b", 1), ("a", "c", 1)]
    ls = make_area(edges, soft_drained={"b": 100})
    ps = PrefixState()
    advertise(ps, "b", P1)
    advertise(ps, "c", P1)
    solver = SpfSolver("a")
    db = solver.build_route_db({"0": ls}, ps)
    route = db.unicast_routes[P1]
    assert {nh.neighbor_node_name for nh in route.nexthops} == {"c"}
    assert route.best_prefix_entry.metrics.drain_metric == 0


def test_min_nexthop_gate():
    ls = make_area(line_edges(3))
    ps = PrefixState()
    advertise(ps, "node2", P1, min_nexthop=2)  # need >= 2 nexthops; only 1
    solver = SpfSolver("node0")
    db = solver.build_route_db({"0": ls}, ps)
    assert P1 not in db.unicast_routes


def test_cross_area_min_metric_merge():
    # areas A (a-b-dst) and B (a-c-dst2); dst in A advertises at igp 2,
    # dst2 in B at igp 1 -> only area B nexthops survive
    ls_a = make_area([("a", "b", 1), ("b", "dstA", 1)], area="A")
    ls_b = make_area([("a", "dstB", 1)], area="B")
    ps = PrefixState()
    advertise(ps, "dstA", P1, area="A")
    advertise(ps, "dstB", P1, area="B")
    solver = SpfSolver("a")
    db = solver.build_route_db({"A": ls_a, "B": ls_b}, ps)
    route = db.unicast_routes[P1]
    assert route.igp_cost == 1
    assert {nh.neighbor_node_name for nh in route.nexthops} == {"dstB"}


def test_static_routes_overlay():
    ls = make_area(line_edges(2))
    ps = PrefixState()
    solver = SpfSolver("node0")
    static = RibUnicastEntry(
        prefix=P2, nexthops={NextHop(address="fe80::1", if_name="if_s")}
    )
    solver.update_static_unicast_routes({P2: static}, [])
    db = solver.build_route_db({"0": ls}, ps)
    assert P2 in db.unicast_routes
    # prefixState wins over static for same prefix
    advertise(ps, "node1", P2)
    db2 = solver.build_route_db({"0": ls}, ps)
    assert db2.unicast_routes[P2].best_prefix_entry.prefix == P2
    assert db2.unicast_routes[P2].igp_cost == 1


def test_node_segment_label_routes():
    labels = {"a": 101, "b": 102, "c": 103}
    edges = [("a", "b", 1), ("b", "c", 1)]
    ls = make_area(edges, node_labels=labels)
    solver = SpfSolver("a", enable_node_segment_label=True)
    db = solver.build_route_db({"0": ls}, PrefixState())
    # own label: POP_AND_LOOKUP
    from openr_tpu.types import MplsActionCode

    own = db.mpls_routes[101]
    assert next(iter(own.nexthops)).mpls_action.action == MplsActionCode.POP_AND_LOOKUP
    # directly-connected neighbor: PHP (implicit null)
    php = db.mpls_routes[102]
    nh_b = next(iter(php.nexthops))
    assert nh_b.mpls_action.action == MplsActionCode.PHP
    assert nh_b.mpls_action.swap_label is None
    # two hops away: SWAP with same label
    swap = db.mpls_routes[103]
    nh_c = next(iter(swap.nexthops))
    assert nh_c.mpls_action.action == MplsActionCode.SWAP
    assert nh_c.mpls_action.swap_label == 103


def test_ksp2_two_disjoint_paths():
    # a-b-d cost 2; a-c-d cost 4: KSP2 programs both
    edges = [("a", "b", 1), ("b", "d", 1), ("a", "c", 2), ("c", "d", 2)]
    labels = {"a": 101, "b": 102, "c": 103, "d": 104}
    ls = make_area(edges, node_labels=labels)
    ps = PrefixState()
    advertise(
        ps,
        "d",
        P1,
        forwarding_type=PrefixForwardingType.SR_MPLS,
        forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
    )
    solver = SpfSolver("a")
    db = solver.build_route_db({"0": ls}, ps)
    route = db.unicast_routes[P1]
    by_neighbor = {nh.neighbor_node_name: nh for nh in route.nexthops}
    assert set(by_neighbor) == {"b", "c"}
    assert by_neighbor["b"].metric == 2
    assert by_neighbor["c"].metric == 4
    # label stack pins the path through the downstream node
    assert by_neighbor["b"].mpls_action.push_labels == (104,)
    assert by_neighbor["c"].mpls_action.push_labels == (104,)


def test_route_db_calculate_update():
    old = DecisionRouteDb()
    new = DecisionRouteDb()
    e1 = RibUnicastEntry(prefix=P1, nexthops={NextHop(address="fe80::1")})
    e2 = RibUnicastEntry(prefix=P2, nexthops={NextHop(address="fe80::2")})
    old.add_unicast_route(e1)
    new.add_unicast_route(
        RibUnicastEntry(prefix=P1, nexthops={NextHop(address="fe80::9")})
    )
    new.add_unicast_route(e2)
    delta = old.calculate_update(new)
    assert set(delta.unicast_routes_to_update) == {P1, P2}  # changed + added
    assert delta.unicast_routes_to_delete == []
    delta2 = new.calculate_update(old)
    assert delta2.unicast_routes_to_delete == [P2]
    # no-op diff
    assert new.calculate_update(new).empty()


def test_build_route_db_none_when_node_unknown():
    ls = make_area(line_edges(2))
    solver = SpfSolver("ghost")
    assert solver.build_route_db({"0": ls}, PrefixState()) is None


def test_v4_disabled_skips_v4_prefix():
    ls = make_area(line_edges(2))
    ps = PrefixState()
    advertise(ps, "node1", P1)
    advertise(ps, "node1", P2)
    solver = SpfSolver("node0", enable_v4=False)
    db = solver.build_route_db({"0": ls}, ps)
    assert P1 not in db.unicast_routes
    assert P2 in db.unicast_routes


def test_calculate_update_ignores_igp_cost_only_change():
    # remote metric shift w/ unchanged nexthops must NOT churn the FIB
    nh = {NextHop(address="fe80::1", neighbor_node_name="b")}
    old = DecisionRouteDb()
    new = DecisionRouteDb()
    old.add_unicast_route(RibUnicastEntry(prefix=P1, nexthops=set(nh), igp_cost=2))
    new.add_unicast_route(RibUnicastEntry(prefix=P1, nexthops=set(nh), igp_cost=5))
    assert old.calculate_update(new).empty()


def test_node_label_collision_smaller_name_wins():
    labels = {"a": 101, "bbb": 200, "zzz": 200}  # collision on 200
    edges = [("a", "bbb", 1), ("a", "zzz", 1)]
    ls = make_area(edges, node_labels=labels)
    solver = SpfSolver("a", enable_node_segment_label=True)
    db = solver.build_route_db({"0": ls}, PrefixState())
    nh = next(iter(db.mpls_routes[200].nexthops))
    assert nh.neighbor_node_name == "bbb"  # smaller node name wins


def test_path_a_in_path_b_contiguous_ordered():
    from openr_tpu.decision.link_state import LinkState as LS

    ls = make_area(line_edges(5))
    full = ls.get_kth_paths("node0", "node4", 1)[0]  # 4 links in order
    assert LS.path_a_in_path_b(full[1:3], full)  # contiguous slice
    assert not LS.path_a_in_path_b([full[0], full[2]], full)  # gap
    assert not LS.path_a_in_path_b(list(reversed(full)), full)  # wrong order
    assert LS.path_a_in_path_b([], full)
