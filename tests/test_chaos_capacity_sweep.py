"""Chaos × capacity sweep: the orchestrator under chip faults (ISSUE 14).

Acceptance: a seeded ``tpu_corrupt(device_index=…)`` landing MID-SWEEP
quarantines exactly one chip of the victim's pool while the sweep keeps
going — dispatches re-pack onto the survivors, every scenario completes,
and the network-level invariants (no blackholes, monotone change_seq)
hold throughout.

Determinism: the faulted run and a fault-free control run drive the
IDENTICAL virtual-time schedule (same churn, same link events); only
the corruption differs.  The sweep's ranked summary must be byte-equal
across the two — the sweep kernels never consume the corrupted backend
outputs, and scenario identity is content-addressed, never
device-addressed, so losing a chip changes WHERE shards solve, not what
they produce.
"""

import asyncio

import pytest

from openr_tpu.chaos import ChaosController, FaultPlan, InvariantChecker
from openr_tpu.common.runtime import SimClock
from openr_tpu.config import ParallelConfig, ResilienceConfig
from openr_tpu.emulation.network import EmulatedNetwork
from openr_tpu.emulation.topology import grid_edges
from openr_tpu.types import PrefixEntry

pytestmark = [pytest.mark.chaos, pytest.mark.sweep, pytest.mark.multichip]

SEED = 7
CONVERGE_S = 18.0
VICTIM = "node4"
BAD_CHIP = 3


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        pending = asyncio.all_tasks(loop)
        for t in pending:
            t.cancel()
        if pending:
            loop.run_until_complete(
                asyncio.gather(*pending, return_exceptions=True)
            )
        loop.close()


def overrides(tmp_path):
    def apply(cfg):
        cfg.tpu_compute_config.min_device_prefixes = 0  # always device
        cfg.parallel_config = ParallelConfig(min_shard_rows=0)
        cfg.resilience_config = ResilienceConfig(
            shadow_sample_every=2,
            failure_threshold=2,
            probe_backoff_initial_s=0.5,
            probe_backoff_max_s=4.0,
            jitter_pct=0.1,
            seed=SEED,
        )
        cfg.sweep_config.shard_scenarios = 1
        # stretch the sweep over ~14 virtual seconds so the corruption,
        # the detection rebuilds and the quarantine all land MID-sweep
        cfg.sweep_config.inter_shard_pause_s = 0.8
        cfg.sweep_config.spill_dir = str(
            tmp_path / f"sweep.{cfg.node_name}"
        )

    return apply


async def _sweep_under_schedule(tmp_path, inject: bool) -> str:
    """One seeded scenario (identical schedule either way); returns the
    final ranked-summary digest."""
    clock = SimClock()
    net = EmulatedNetwork(
        clock, use_tpu_backend=True, config_overrides=overrides(tmp_path)
    )
    net.build(grid_edges(3))
    net.start()
    await clock.run_for(CONVERGE_S)
    ok, why = net.converged_full_mesh()
    assert ok, why
    # widen the candidate table so every chip's shard holds rows
    net.nodes["node0"].advertise_prefixes(
        [PrefixEntry(f"10.99.{i}.0/24") for i in range(9)]
    )
    await clock.run_for(3.0)

    victim = net.nodes[VICTIM]
    checker = InvariantChecker(net)
    controller = None
    if inject:
        plan = FaultPlan().tpu_corrupt(
            VICTIM, at=1.0, duration=200.0, device_index=BAD_CHIP
        )
        controller = ChaosController(net, plan, seed=SEED)
        controller.start()

    rep = victim.sweep.start_sweep(
        {"combo_k": 2, "max_combo_scenarios": 12, "combo_seed": SEED}
    )
    assert rep["state"] == "running"
    assert rep["shards"] == 24, "one scenario per shard spans the fault"
    await clock.run_for(2.0)
    assert victim.sweep.state == "running", "the fault must land MID-sweep"

    # the FIXED churn schedule (identical in both runs): link flaps
    # drive shadow-checked full device rebuilds while the sweep commits
    # shards — in the faulted run they catch chip 3 lying.  Both links
    # are restored on the same schedule, so the two runs' sweeps see
    # the identical topology timeline.
    for a, b in [("node0", "node1"), ("node1", "node2")]:
        net.fail_link(a, b)
        await clock.run_for(2.0)
        net.restore_link(a, b)
        await clock.run_for(2.0)

    if inject:
        gov = victim.decision.backend.governor
        assert gov.num_shadow_mismatches >= 1, (
            "shadow verification must catch the corrupted chip"
        )
        assert gov.num_chip_quarantines >= 1, "chip 3 must quarantine"
        pool = victim.decision.backend.dispatch_pool()
        assert pool.quarantined_indices() == [BAD_CHIP], (
            "exactly the corrupted chip quarantines"
        )
        assert victim.decision.device_available(), (
            "7 survivors keep the device plane up"
        )
        assert victim.sweep.state == "running", (
            "the quarantine must land while shards are still pending"
        )

    for _ in range(200):
        if victim.sweep.state != "running":
            break
        await clock.run_for(0.5)
    assert victim.sweep.state == "done", victim.sweep.error
    status = victim.sweep.get_sweep_status()
    assert status["scenarios_completed"] == status["scenarios_total"] == 24
    assert status["spill"]["rows"] == 24
    summary = victim.sweep.get_sweep_summary()
    assert summary["complete"] is True

    if inject:
        # post-quarantine shards dispatched on survivors only
        pool = victim.decision.backend.dispatch_pool()
        assert BAD_CHIP in pool.quarantined_indices()

    # network invariants held through the whole scenario
    checker.check_change_seq_monotonic()
    checker.check_no_blackholes()
    if controller is not None:
        await controller.stop()
    digest = summary["summary_digest"]
    await net.stop()
    return digest


def test_tpu_corrupt_mid_sweep_quarantines_one_chip_sweep_completes(
    tmp_path,
):
    """THE ISSUE-14 chaos acceptance (see module docstring)."""
    faulted = run(_sweep_under_schedule(tmp_path / "faulted", True))
    clean = run(_sweep_under_schedule(tmp_path / "clean", False))
    assert faulted == clean, (
        "a chip quarantine mid-sweep must change WHERE shards solve, "
        "never what they produce"
    )
