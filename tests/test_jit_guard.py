"""call_jit_guarded: error passthrough + observable guard trips.

The guard exists for exactly one failure (the jax-0.9.0 executable-cache
corruption, ops/jit_guard.py docstring); anything else must propagate
untouched, and every heal must be visible in prod counter dumps via the
`jit_guard.cache_clear` gauge (registered with
Monitor.add_counter_provider in main.py).
"""

import pytest

from openr_tpu.ops import jit_guard
from openr_tpu.ops.jit_guard import call_jit_guarded, counter_snapshot


def test_non_matching_value_error_propagates_unchanged():
    err = ValueError("some unrelated shape problem")

    def fn():
        raise err

    before = counter_snapshot()["jit_guard.cache_clear"]
    with pytest.raises(ValueError) as ei:
        call_jit_guarded(fn)
    assert ei.value is err  # same object, not rewrapped
    assert counter_snapshot()["jit_guard.cache_clear"] == before


def test_non_value_error_propagates():
    with pytest.raises(TypeError):
        call_jit_guarded(lambda: (_ for _ in ()).throw(TypeError("boom")))


def test_signature_match_clears_retries_and_counts(monkeypatch):
    import jax

    cleared = []
    monkeypatch.setattr(jax, "clear_caches", lambda: cleared.append(True))

    calls = []

    def flaky():
        calls.append(True)
        if len(calls) == 1:
            raise ValueError(
                "INVALID_ARGUMENT: Execution supplied 3 buffers but "
                "compiled program expected 5 buffers"
            )
        return 42

    before = counter_snapshot()["jit_guard.cache_clear"]
    assert call_jit_guarded(flaky) == 42
    assert cleared == [True]
    assert len(calls) == 2
    assert counter_snapshot()["jit_guard.cache_clear"] == before + 1


def test_second_failure_propagates(monkeypatch):
    import jax

    monkeypatch.setattr(jax, "clear_caches", lambda: None)

    def always_corrupt():
        raise ValueError("supplied 1 buffers but compiled program expected 2")

    with pytest.raises(ValueError):
        call_jit_guarded(always_corrupt)


def test_counter_snapshot_is_a_copy():
    baseline = jit_guard._counters["jit_guard.cache_clear"]
    snap = counter_snapshot()
    snap["jit_guard.cache_clear"] += 100
    assert jit_guard._counters["jit_guard.cache_clear"] == baseline
    assert counter_snapshot()["jit_guard.cache_clear"] == baseline
