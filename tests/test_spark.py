"""Spark FSM + discovery tests over MockIoProvider in virtual time
(scenarios ported in spirit from openr/spark/tests/SparkTest.cpp)."""

import asyncio

import pytest

from openr_tpu.common.runtime import SimClock
from openr_tpu.config import SparkConfig
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.spark.io_provider import MockIoProvider
from openr_tpu.spark.spark import Spark, get_next_state
from openr_tpu.types import (
    InitializationEvent,
    InterfaceDatabase,
    InterfaceInfo,
    NeighborEventType,
    SparkNeighEvent,
    SparkNeighState,
)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def fast_config(**kwargs) -> SparkConfig:
    return SparkConfig(
        hello_time_s=2.0,
        fastinit_hello_time_ms=500,
        handshake_time_ms=500,
        heartbeat_time_s=1.0,
        hold_time_s=3.0,
        graceful_restart_time_s=6.0,
        min_neighbor_discovery_interval_s=1.0,
        max_neighbor_discovery_interval_s=5.0,
        **kwargs,
    )


class Rig:
    """N Spark instances over one MockIoProvider."""

    def __init__(self, clock, names, config=None, area_lookup=None):
        self.clock = clock
        self.io = MockIoProvider(clock)
        self.sparks = {}
        self.events = {}
        self.init_events = {n: [] for n in names}
        for n in names:
            q = ReplicateQueue(f"{n}.neighborEvents")
            self.events[n] = q.get_reader()
            self.sparks[n] = Spark(
                node_name=n,
                clock=clock,
                config=config or fast_config(),
                io=self.io,
                neighbor_updates_queue=q,
                area_lookup=area_lookup,
                initialization_cb=lambda ev, n=n: self.init_events[n].append(ev),
            )
            self.sparks[n].start()

    def up_interface(self, node, if_name, v6="fe80::1", v4="192.168.1.1"):
        self.sparks[node]._on_interface_db(
            InterfaceDatabase(
                interfaces={
                    if_name: InterfaceInfo(
                        if_name=if_name,
                        is_up=True,
                        if_index=1,
                        networks=[f"{v6}/64", f"{v4}/31"],
                    )
                }
            )
        )

    def drain_events(self, node):
        out = []
        while (e := self.events[node].try_get()) is not None:
            out.append(e)
        return out

    async def stop(self):
        for s in self.sparks.values():
            await s.stop()
        await self.io.stop()


def wire(rig, a, ifa, b, ifb, latency=0.001):
    rig.io.connect_pair(a, ifa, b, ifb, latency)
    rig.up_interface(a, ifa)
    rig.up_interface(b, ifb)


def test_fsm_matrix():
    S, E = SparkNeighState, SparkNeighEvent
    assert get_next_state(S.IDLE, E.HELLO_RCVD_INFO) == S.WARM
    assert get_next_state(S.IDLE, E.HELLO_RCVD_NO_INFO) == S.WARM
    assert get_next_state(S.WARM, E.HELLO_RCVD_INFO) == S.NEGOTIATE
    assert get_next_state(S.NEGOTIATE, E.HANDSHAKE_RCVD) == S.ESTABLISHED
    assert get_next_state(S.NEGOTIATE, E.NEGOTIATE_TIMER_EXPIRE) == S.WARM
    assert get_next_state(S.NEGOTIATE, E.NEGOTIATION_FAILURE) == S.WARM
    assert get_next_state(S.ESTABLISHED, E.HELLO_RCVD_NO_INFO) == S.IDLE
    assert get_next_state(S.ESTABLISHED, E.HELLO_RCVD_RESTART) == S.RESTART
    assert get_next_state(S.ESTABLISHED, E.HEARTBEAT_TIMER_EXPIRE) == S.IDLE
    assert get_next_state(S.RESTART, E.HELLO_RCVD_INFO) == S.NEGOTIATE
    assert get_next_state(S.RESTART, E.GR_TIMER_EXPIRE) == S.IDLE
    assert get_next_state(S.WARM, E.HANDSHAKE_RCVD) is None  # invalid


def test_two_nodes_establish_adjacency():
    async def main():
        clock = SimClock()
        rig = Rig(clock, ["alice", "bob"])
        wire(rig, "alice", "if_a_b", "bob", "if_b_a")
        await clock.run_for(5.0)
        a_events = rig.drain_events("alice")
        b_events = rig.drain_events("bob")
        up_a = [e for e in a_events if e.event_type == NeighborEventType.NEIGHBOR_UP]
        up_b = [e for e in b_events if e.event_type == NeighborEventType.NEIGHBOR_UP]
        assert len(up_a) == 1 and up_a[0].node_name == "bob"
        assert up_a[0].local_if_name == "if_a_b"
        assert up_a[0].remote_if_name == "if_b_a"
        assert up_a[0].area == "0"
        assert len(up_b) == 1 and up_b[0].node_name == "alice"
        n = rig.sparks["alice"].get_neighbors()[0]
        assert n.state == SparkNeighState.ESTABLISHED
        await rig.stop()

    run(main())


def test_heartbeats_keep_adjacency_alive():
    async def main():
        clock = SimClock()
        rig = Rig(clock, ["a", "b"])
        wire(rig, "a", "if1", "b", "if2")
        await clock.run_for(5.0)
        rig.drain_events("a")
        # run far beyond hold time (3s): heartbeats every 1s keep it alive
        await clock.run_for(60.0)
        assert rig.drain_events("a") == []  # no down events
        assert (
            rig.sparks["a"].get_neighbors()[0].state == SparkNeighState.ESTABLISHED
        )
        await rig.stop()

    run(main())


def test_partition_triggers_hold_timer_down():
    async def main():
        clock = SimClock()
        rig = Rig(clock, ["a", "b"])
        wire(rig, "a", "if1", "b", "if2")
        await clock.run_for(5.0)
        rig.drain_events("a")
        rig.io.partition("a", "b")
        await clock.run_for(10.0)  # hold time 3s
        downs = [
            e
            for e in rig.drain_events("a")
            if e.event_type == NeighborEventType.NEIGHBOR_DOWN
        ]
        assert len(downs) == 1 and downs[0].node_name == "b"
        assert rig.sparks["a"].get_neighbors() == []
        await rig.stop()

    run(main())


def test_reconnect_after_partition_reestablishes():
    async def main():
        clock = SimClock()
        rig = Rig(clock, ["a", "b"])
        wire(rig, "a", "if1", "b", "if2")
        await clock.run_for(5.0)
        rig.drain_events("a")
        rig.io.partition("a", "b")
        await clock.run_for(10.0)
        rig.drain_events("a")
        rig.io.heal("a", "b")
        # fast-init is over; hello period is 2s here
        await clock.run_for(15.0)
        ups = [
            e
            for e in rig.drain_events("a")
            if e.event_type == NeighborEventType.NEIGHBOR_UP
        ]
        assert len(ups) == 1
        await rig.stop()

    run(main())


def test_discovery_with_offset_hello_phase_after_fast_init():
    """Two peers discovering each other AFTER the fast-init window, with
    hello phases offset by half a period, must still reach ESTABLISHED.

    With offset phase every hello reflects the peer's *latest* seq (the
    reflection is minted after the latest hello was heard), so a stale-
    incarnation guard of ``>=`` instead of ``>`` parks both sides in
    WARM forever: no solicited bumps (fast-init is over), no heartbeats
    (nothing ESTABLISHED on the interface), and the phase never drifts.
    This is the netns-lab churn hang in miniature — real daemons start
    staggered, so their steady-state hello phases are always offset."""

    async def main():
        clock = SimClock()
        rig = Rig(clock, ["a", "b"])
        # let the 1s fast-init window lapse with no interfaces up: every
        # hello from here on is periodic (2s) and unsolicited
        await clock.run_for(1.5)
        rig.io.connect_pair("a", "if1", "b", "if2", 0.001)
        rig.up_interface("a", "if1")
        await clock.run_for(0.5)  # stagger b's hello loop by half a slot
        rig.up_interface("b", "if2")
        await clock.run_for(12.0)
        for n in ("a", "b"):
            states = [x.state for x in rig.sparks[n].get_neighbors()]
            assert states == [SparkNeighState.ESTABLISHED], (n, states)
        await rig.stop()

    run(main())


def test_graceful_restart_holds_and_recovers():
    async def main():
        clock = SimClock()
        rig = Rig(clock, ["a", "b"])
        wire(rig, "a", "if1", "b", "if2")
        await clock.run_for(5.0)
        rig.drain_events("b")
        # a announces graceful restart
        await rig.sparks["a"].stop_gracefully()
        await clock.run_for(1.0)
        evs = rig.drain_events("b")
        assert [e.event_type for e in evs] == [NeighborEventType.NEIGHBOR_RESTARTING]
        assert (
            rig.sparks["b"].get_neighbors()[0].state == SparkNeighState.RESTART
        )
        # a comes back as a fresh instance (new seq number space)
        await rig.sparks["a"].stop()
        q = ReplicateQueue("a2.neighborEvents")
        rig.events["a"] = q.get_reader()
        rig.sparks["a"] = Spark(
            node_name="a",
            clock=clock,
            config=fast_config(),
            io=rig.io,
            neighbor_updates_queue=q,
        )
        rig.sparks["a"].start()
        rig.up_interface("a", "if1")
        await clock.run_for(5.0)
        ups = [
            e
            for e in rig.drain_events("b")
            if e.event_type == NeighborEventType.NEIGHBOR_UP
        ]
        assert len(ups) == 1  # adjacency re-established, no DOWN in between
        await rig.stop()

    run(main())


def test_flood_restarting_msg_is_one_shot():
    """The ctrl-surface GR flood must NOT set the sticky restarting flag:
    a node that keeps running would otherwise re-trigger every peer's GR
    hold on each periodic hello — an endless adjacency flap loop
    (code-review regression).  The peer enters RESTART once, then the
    continuing normal hellos re-establish the adjacency."""

    async def main():
        clock = SimClock()
        rig = Rig(clock, ["a", "b"])
        wire(rig, "a", "if1", "b", "if2")
        await clock.run_for(5.0)
        rig.drain_events("b")
        rig.sparks["a"].flood_restarting_msg()
        assert rig.sparks["a"]._restarting is False  # one-shot, not sticky
        await clock.run_for(10.0)
        # a never went away: peer must be back ESTABLISHED, not flapping
        assert (
            rig.sparks["b"].get_neighbors()[0].state
            == SparkNeighState.ESTABLISHED
        )
        await rig.stop()

    run(main())


def test_graceful_restart_expiry_brings_neighbor_down():
    async def main():
        clock = SimClock()
        rig = Rig(clock, ["a", "b"])
        wire(rig, "a", "if1", "b", "if2")
        await clock.run_for(5.0)
        rig.drain_events("b")
        await rig.sparks["a"].stop_gracefully()
        await rig.sparks["a"].stop()
        rig.io.unregister("a")
        # GR hold is 6s
        await clock.run_for(10.0)
        evs = [e.event_type for e in rig.drain_events("b")]
        assert evs == [
            NeighborEventType.NEIGHBOR_RESTARTING,
            NeighborEventType.NEIGHBOR_DOWN,
        ]
        await rig.stop()

    run(main())


def test_interface_down_brings_neighbors_down():
    async def main():
        clock = SimClock()
        rig = Rig(clock, ["a", "b"])
        wire(rig, "a", "if1", "b", "if2")
        await clock.run_for(5.0)
        rig.drain_events("a")
        # empty interface db: if1 is gone
        rig.sparks["a"]._on_interface_db(InterfaceDatabase(interfaces={}))
        await clock.run_for(1.0)
        downs = [e.event_type for e in rig.drain_events("a")]
        assert downs == [NeighborEventType.NEIGHBOR_DOWN]
        await rig.stop()

    run(main())


def test_area_mismatch_blocks_adjacency():
    async def main():
        clock = SimClock()

        def lookup(neighbor, if_name):
            # a puts everyone in area "X"; b puts everyone in area "Y"
            return {"a": "Y", "b": "X"}[neighbor]

        rig = Rig(clock, ["a", "b"], area_lookup=lookup)
        wire(rig, "a", "if1", "b", "if2")
        await clock.run_for(10.0)
        assert rig.drain_events("a") == []
        assert rig.drain_events("b") == []
        states = [n.state for n in rig.sparks["a"].get_neighbors()]
        assert SparkNeighState.ESTABLISHED not in states
        assert rig.sparks["a"].counters.get("spark.handshake.area_mismatch") > 0
        await rig.stop()

    run(main())


def test_rtt_measured_from_link_latency():
    async def main():
        clock = SimClock()
        rig = Rig(clock, ["a", "b"])
        wire(rig, "a", "if1", "b", "if2", latency=0.005)  # 5ms one way
        await clock.run_for(8.0)
        n = rig.sparks["a"].get_neighbors()[0]
        assert n.rtt_us == pytest.approx(10_000, rel=0.3)  # ~10ms round trip
        up = [
            e
            for e in rig.drain_events("a")
            if e.event_type == NeighborEventType.NEIGHBOR_UP
        ][0]
        assert up.rtt_us > 0
        await rig.stop()

    run(main())


def test_neighbor_discovered_initialization_event():
    async def main():
        clock = SimClock()
        rig = Rig(clock, ["a", "b"])
        wire(rig, "a", "if1", "b", "if2")
        await clock.run_for(20.0)
        assert InitializationEvent.NEIGHBOR_DISCOVERED in rig.init_events["a"]
        assert rig.init_events["a"].count(InitializationEvent.NEIGHBOR_DISCOVERED) == 1
        await rig.stop()

    run(main())


def test_malformed_packet_counted_not_crashing():
    async def main():
        clock = SimClock()
        rig = Rig(clock, ["a"])
        rig.up_interface("a", "if1")
        await rig.sparks["a"]._on_packet("if1", {"kind": "garbage", "body": {}}, 0.0)
        await rig.sparks["a"]._on_packet("if1", {"nonsense": 1}, 0.0)
        assert rig.sparks["a"].counters.get("spark.packet_parse_error") == 2
        await rig.stop()

    run(main())


def test_three_nodes_on_shared_segment():
    """Multicast semantics: three nodes on one L2 segment all peer."""

    async def main():
        clock = SimClock()
        rig = Rig(clock, ["a", "b", "c"])
        # full mesh of if pairs simulates a shared segment
        rig.io.connect_pair("a", "if1", "b", "if2")
        rig.io.connect_pair("a", "if1", "c", "if3")
        rig.io.connect_pair("b", "if2", "c", "if3")
        rig.up_interface("a", "if1")
        rig.up_interface("b", "if2")
        rig.up_interface("c", "if3")
        await clock.run_for(8.0)
        for node in ("a", "b", "c"):
            neighbors = {
                n.node_name: n.state for n in rig.sparks[node].get_neighbors()
            }
            assert len(neighbors) == 2, (node, neighbors)
            assert all(
                s == SparkNeighState.ESTABLISHED for s in neighbors.values()
            ), (node, neighbors)
        await rig.stop()

    run(main())


def test_warm_neighbor_expires_on_unidirectional_link():
    """A neighbor we hear but who never hears us must not park in WARM
    forever (state leak on transient/one-way peers)."""

    async def main():
        clock = SimClock()
        rig = Rig(clock, ["a", "b"])
        rig.io.connect_pair("a", "if1", "b", "if2")
        rig.up_interface("a", "if1")
        rig.up_interface("b", "if2")
        # b -> a works; a -> b drops: b never sees a's hellos reflected
        rig.io._partitioned.add(("a", "b"))  # a's packets to b dropped
        await clock.run_for(3.0)
        # a heard b -> WARM entry exists
        states = [n.state for n in rig.sparks["a"].get_neighbors()]
        assert states == [SparkNeighState.WARM]
        # b vanishes entirely; the WARM entry must be reaped by GR hold (6s)
        await rig.sparks["b"].stop()
        await clock.run_for(10.0)
        assert rig.sparks["a"].get_neighbors() == []
        assert rig.drain_events("a") == []  # never up -> no DOWN event
        await rig.stop()

    run(main())


def test_interface_down_during_peer_restart_reports_down():
    async def main():
        clock = SimClock()
        rig = Rig(clock, ["a", "b"])
        wire(rig, "a", "if1", "b", "if2")
        await clock.run_for(5.0)
        rig.drain_events("b")
        await rig.sparks["a"].stop_gracefully()
        await clock.run_for(1.0)
        assert rig.sparks["b"].get_neighbors()[0].state == SparkNeighState.RESTART
        rig.drain_events("b")
        # b's interface goes away while holding the restarting adjacency
        rig.sparks["b"]._on_interface_db(InterfaceDatabase(interfaces={}))
        await clock.run_for(1.0)
        evs = [e.event_type for e in rig.drain_events("b")]
        assert evs == [NeighborEventType.NEIGHBOR_DOWN]
        await rig.stop()

    run(main())


def test_stopped_spark_ignores_inbound():
    async def main():
        clock = SimClock()
        rig = Rig(clock, ["a", "b"])
        wire(rig, "a", "if1", "b", "if2")
        await clock.run_for(5.0)
        await rig.sparks["a"].stop()
        sent_before = rig.io.packets_sent
        await clock.run_for(30.0)
        # a must not participate: no handshake/hello from a anymore
        a_neighbors = rig.sparks["a"].get_neighbors()
        for n in a_neighbors:
            assert n.state != SparkNeighState.NEGOTIATE
        # b times a out and tears down
        assert any(
            e.event_type == NeighborEventType.NEIGHBOR_DOWN
            for e in rig.drain_events("b")
        )
        await rig.stop()

    run(main())


def test_neighbor_discovered_at_min_window_when_adjacency_early():
    async def main():
        clock = SimClock()
        # min 1s; adjacency establishes ~1.5s with fast-init hellos
        rig = Rig(clock, ["a", "b"])
        wire(rig, "a", "if1", "b", "if2")
        await clock.run_for(3.0)  # well before max window (5s)
        assert InitializationEvent.NEIGHBOR_DISCOVERED in rig.init_events["a"]
        await rig.stop()

    run(main())
