"""Thrift Compact protocol interop: golden byte vectors + adapter
round-trips.

The golden vectors are hand-assembled from the public compact-protocol
spec (field-header delta/type packing, ULEB128 varints, zigzag ints,
length-prefixed binaries) — they pin the exact bytes
``apache::thrift::CompactSerializer`` produces for the same structs, so
a regression here means we stopped speaking the reference's wire
encoding (openr floods CompactSerializer-encoded AdjacencyDatabase /
PrefixDatabase payloads in its KvStore values)."""

import random

from openr_tpu import types as T
from openr_tpu.interop import (
    decode_adjacency_database,
    decode_prefix_database,
    decode_publication,
    decode_route_database,
    decode_value,
    encode_adjacency_database,
    encode_prefix_database,
    encode_publication,
    encode_route_database,
    encode_value,
)
from openr_tpu.interop.compact import (
    CompactReader,
    CompactWriter,
    decode_struct,
    encode_struct,
)
from openr_tpu.interop.openr_wire import VALUE


def test_varint_zigzag_primitives():
    w = CompactWriter()
    w.write_varint(0)
    w.write_varint(127)
    w.write_varint(128)
    w.write_varint(300)
    w.write_zigzag(0)
    w.write_zigzag(-1)
    w.write_zigzag(1)
    w.write_zigzag(-2)
    w.write_zigzag(2147483647)
    w.write_zigzag(-2147483648)
    data = w.getvalue()
    assert data[:5] == bytes([0x00, 0x7F, 0x80, 0x01, 0xAC])
    r = CompactReader(data)
    assert [r.read_varint() for _ in range(4)] == [0, 127, 128, 300]
    assert [r.read_zigzag() for _ in range(6)] == [
        0, -1, 1, -2, 2147483647, -2147483648,
    ]


def test_value_golden_bytes():
    """Hand-assembled compact encoding of a KvStore Value."""
    v = T.Value(version=1, originator_id="a", ttl=100, ttl_version=0)
    got = encode_value(v)
    want = bytes(
        [
            0x16, 0x02,              # field 1 (i64) version, zigzag(1)
            0x28, 0x01, 0x61,        # field 3 (+2, string) "a"
            0x16, 0xC8, 0x01,        # field 4 (+1, i64) zigzag(100)=200
            0x16, 0x00,              # field 5 (+1, i64) zigzag(0)
            0x00,                    # stop
        ]
    )
    assert got == want
    assert decode_value(got) == v


def test_bool_field_folds_into_type_and_long_field_ids():
    """Bool struct fields carry the value in the type nibble; field-id
    jumps > 15 use the long form (type byte + zigzag id) — NextHopThrift
    jumps 3 -> 51."""
    spec = (
        (1, "flag", "bool", None),
        (40, "far", "i32", None),
    )
    got = encode_struct(spec, {"flag": True, "far": 7})
    want = bytes(
        [
            0x11,              # field 1, BOOL_TRUE
            0x05, 0x50,        # long form: type I32, zigzag(40)=80
            0x0E,              # zigzag(7)
            0x00,
        ]
    )
    assert got == want
    assert decode_struct(spec, got) == {"flag": True, "far": 7}
    got_f = encode_struct(spec, {"flag": False})
    assert got_f == bytes([0x12, 0x00])
    assert decode_struct(spec, got_f) == {"flag": False}


def test_containers_large_list_set_map_and_bool_elements():
    spec = (
        (1, "names", "list", ("string", None)),
        (2, "bits", "list", ("bool", None)),
        (3, "tags", "set", ("string", None)),
        (4, "m", "map", (("string", None), ("i32", None))),
        (5, "empty_m", "map", (("string", None), ("i32", None))),
    )
    obj = {
        "names": [f"n{i}" for i in range(20)],  # > 15: long list header
        "bits": [True, False, True],
        "tags": {"x", "y"},
        "m": {"a": 1, "b": -2},
        "empty_m": {},
    }
    back = decode_struct(spec, encode_struct(spec, obj))
    assert back == obj


def test_unknown_fields_are_skipped():
    """A newer peer's extra fields (any wire type, incl. folded bools
    and nested structs) must not break decoding."""
    newer = (
        (1, "version", "i64", None),
        (3, "originatorId", "string", None),
        (4, "ttl", "i64", None),
        (5, "ttlVersion", "i64", None),
        (8, "extra_s", "string", None),
        (9, "extra_flag", "bool", None),
        (10, "extra_struct", "struct", (
            (1, "x", "i32", None),
            (2, "b", "bool", None),
        )),
        (11, "extra_map", "map", (("i32", None), ("bool", None))),
        (12, "extra_d", "double", None),
    )
    data = encode_struct(
        newer,
        {
            "version": 5,
            "extra_s": "ignore me",
            "originatorId": "node1",
            "ttl": 3600000,
            "ttlVersion": 2,
            "extra_flag": True,
            "extra_struct": {"x": 9, "b": False},
            "extra_map": {1: True, 2: False},
            "extra_d": 2.5,
        },
    )
    v = decode_value(data)
    assert v == T.Value(
        version=5, originator_id="node1", ttl=3600000, ttl_version=2
    )
    # and the old spec re-encodes only what it knows
    assert decode_struct(VALUE, encode_value(v)) == {
        "version": 5,
        "originatorId": "node1",
        "ttl": 3600000,
        "ttlVersion": 2,
    }


def test_adjacency_database_round_trip():
    db = T.AdjacencyDatabase(
        this_node_name="node1",
        is_overloaded=True,
        adjacencies=[
            T.Adjacency(
                other_node_name="node2",
                if_name="if_1_2",
                metric=10,
                adj_label=65002,
                is_overloaded=False,
                rtt=1250,
                timestamp=1700000000,
                weight=1,
                other_if_name="if_2_1",
                next_hop_v6="fe80::2",
                next_hop_v4="169.254.0.2",
            ),
            T.Adjacency(
                other_node_name="node3",
                if_name="if_1_3",
                metric=20,
                adj_only_used_by_other_node=True,
                next_hop_v6="fe80::3",
                next_hop_v4="",
            ),
        ],
        node_label=1,
        perf_events=T.PerfEvents(
            events=[T.PerfEvent("node1", "ADJ_DB_UPDATED", 1700000001000)]
        ),
        area="area51",
        node_metric_increment_val=50,
        link_status_records=T.LinkStatusRecords(
            link_status_map={"if_1_2": (1, 1700000002000)}
        ),
    )
    back = decode_adjacency_database(encode_adjacency_database(db))
    assert back == db


def test_prefix_database_round_trip():
    db = T.PrefixDatabase(
        this_node_name="node9",
        prefix_entries=[
            T.PrefixEntry(
                prefix="10.1.0.0/16",
                type=T.PrefixType.LOOPBACK,
                metrics=T.PrefixMetrics(
                    version=1,
                    drain_metric=0,
                    path_preference=1000,
                    source_preference=200,
                    distance=3,
                ),
                tags={"COMMODITY", "65000:1"},
                area_stack=["area1", "area2"],
                min_nexthop=2,
                weight=7,
            ),
            T.PrefixEntry(prefix="2001:db8::/64"),
        ],
        delete_prefix=False,
    )
    back = decode_prefix_database(encode_prefix_database(db))
    assert back == db


def test_value_with_embedded_adjacency_database():
    """The actual openr flood shape: Value.value holds a
    CompactSerializer-encoded AdjacencyDatabase."""
    adj = T.AdjacencyDatabase(
        this_node_name="spine1",
        adjacencies=[
            T.Adjacency(
                other_node_name="leaf1",
                if_name="eth0",
                metric=1,
                next_hop_v6="fe80::1",
            )
        ],
        area="0",
    )
    v = T.Value(
        version=3,
        originator_id="spine1",
        value=encode_adjacency_database(adj),
        ttl=-1,
        ttl_version=0,
    )
    wire = encode_value(v)
    got = decode_value(wire)
    assert got.version == 3 and got.originator_id == "spine1"
    assert decode_adjacency_database(got.value) == adj


def test_publication_round_trip():
    pub = T.Publication(
        key_vals={
            "adj:node1": T.Value(
                version=1, originator_id="node1", value=b"\x01\x02", ttl=-1
            ),
            "prefix:node1:[10.0.0.0/8]": T.Value(
                version=2, originator_id="node1", ttl=3600000, hash=12345
            ),
        },
        expired_keys=["adj:gone"],
        node_ids=["node1", "node2"],
        tobe_updated_keys=["adj:stale"],
        area="7",
        timestamp_ms=1700000003000,
    )
    assert decode_publication(encode_publication(pub)) == pub


def test_route_database_round_trip():
    db = T.RouteDatabase(
        this_node_name="node0",
        unicast_routes=[
            T.UnicastRoute(
                dest="10.2.0.0/24",
                next_hops=[
                    T.NextHop(
                        address="fe80::9",
                        if_name="eth1",
                        metric=20,
                        weight=0,
                        area="0",
                        neighbor_node_name="node9",
                    ),
                    T.NextHop(
                        address="fe80::a",
                        if_name="eth2",
                        metric=20,
                        mpls_action=T.MplsAction(
                            action=T.MplsActionCode.PUSH,
                            push_labels=(65001, 65002),
                        ),
                    ),
                ],
            )
        ],
        mpls_routes=[
            T.MplsRoute(
                top_label=65000,
                next_hops=[
                    T.NextHop(
                        address="fe80::b",
                        if_name="eth3",
                        mpls_action=T.MplsAction(
                            action=T.MplsActionCode.SWAP, swap_label=65003
                        ),
                    )
                ],
            )
        ],
    )
    assert decode_route_database(encode_route_database(db)) == db


def test_fuzz_value_round_trip():
    rng = random.Random(7)
    for _ in range(200):
        v = T.Value(
            version=rng.randrange(0, 1 << 60),
            originator_id="".join(
                rng.choice("abcdefgh") for _ in range(rng.randrange(0, 12))
            ),
            value=(
                bytes(rng.randrange(256) for _ in range(rng.randrange(0, 40)))
                if rng.random() < 0.7
                else None
            ),
            ttl=rng.choice([-1, 0, 1, 3600000, (1 << 31) - 1]),
            ttl_version=rng.randrange(0, 1 << 20),
            hash=rng.choice([None, rng.randrange(-(1 << 62), 1 << 62)]),
        )
        assert decode_value(encode_value(v)) == v


def test_breeze_decode_thrift_command():
    """Operator surface: `breeze kvstore decode-thrift` turns a
    reference network's compact-encoded flood value into wire JSON,
    including the embedded AdjacencyDatabase payload."""
    from click.testing import CliRunner

    from openr_tpu import interop
    from openr_tpu.cli.breeze import breeze

    adj = T.AdjacencyDatabase(
        this_node_name="spine1",
        adjacencies=[
            T.Adjacency(
                other_node_name="leaf1",
                if_name="eth0",
                metric=1,
                next_hop_v6="fe80::1",
            )
        ],
        area="0",
    )
    v = T.Value(
        version=3,
        originator_id="spine1",
        value=interop.encode_adjacency_database(adj),
        ttl=-1,
    )
    r = CliRunner().invoke(
        breeze,
        [
            "kvstore", "decode-thrift",
            "--hex", interop.encode_value(v).hex(),
            "--key", "adj:spine1",
        ],
        obj={},
    )
    assert r.exit_code == 0, r.output
    assert '"spine1"' in r.output and '"leaf1"' in r.output
    # --kind adj decodes a bare AdjacencyDatabase too
    r2 = CliRunner().invoke(
        breeze,
        [
            "kvstore", "decode-thrift",
            "--hex", interop.encode_adjacency_database(adj).hex(),
            "--kind", "adj",
        ],
        obj={},
    )
    assert r2.exit_code == 0 and '"leaf1"' in r2.output


def test_wire_type_mismatch_skips_instead_of_desyncing():
    """A peer that changed a field's type (or a spec mistake) must
    degrade to a skipped field — decoding by the stale spec type would
    desync the whole stream."""
    changed = (
        (1, "version", "string", None),  # was i64 in our VALUE spec
        (3, "originatorId", "string", None),
        (4, "ttl", "i64", None),
    )
    data = encode_struct(
        changed, {"version": "hello", "originatorId": "n1", "ttl": 5}
    )
    v = decode_value(data)
    assert v.version == 0  # mismatched field skipped, default kept
    assert v.originator_id == "n1" and v.ttl == 5


def test_set_encoding_is_sorted_and_deterministic():
    """fbthrift C++ emits thrift sets from std::set (ordered); Python
    set iteration is hash-seed dependent — encoded bytes must not be."""
    spec = ((1, "tags", "set", ("string", None)),)
    a = encode_struct(spec, {"tags": {"b", "a", "c"}})
    b = encode_struct(spec, {"tags": {"c", "b", "a"}})
    assert a == b
    # 'a' < 'b' < 'c' on the wire
    assert a == bytes([0x1A, 0x38, 0x01, 0x61, 0x01, 0x62, 0x01, 0x63, 0x00])


def test_breeze_decode_thrift_rejects_bad_input_cleanly():
    from click.testing import CliRunner

    from openr_tpu.cli.breeze import breeze

    r = CliRunner().invoke(
        breeze, ["kvstore", "decode-thrift", "--hex", "abc"], obj={}
    )
    assert r.exit_code != 0
    assert "bad hex input" in r.output and "Traceback" not in r.output
    r2 = CliRunner().invoke(
        breeze,
        ["kvstore", "decode-thrift", "--hex", "ffffffffff", "--kind", "adj"],
        obj={},
    )
    assert r2.exit_code != 0
    assert "not a valid compact" in r2.output and "Traceback" not in r2.output


def test_crafted_deep_nesting_fails_as_value_error():
    """Untrusted input guard: 0x1C repeated parses as one nested-struct
    field header per byte — must fail as ValueError (clean CLI error),
    never RecursionError (raw traceback)."""
    import pytest

    from openr_tpu.interop import decode_adjacency_database

    payload = bytes([0x1C]) * 4096
    with pytest.raises(ValueError):
        decode_adjacency_database(payload)
    # the CLI surfaces it as a clean click error, not a traceback
    from click.testing import CliRunner

    from openr_tpu.cli.breeze import breeze

    r = CliRunner().invoke(
        breeze,
        ["kvstore", "decode-thrift", "--hex", payload.hex(), "--kind", "adj"],
        obj={},
    )
    assert r.exit_code != 0
    assert "not a valid compact" in r.output and "Traceback" not in r.output


def test_unknown_wire_format_rejected():
    import pytest

    from openr_tpu.config import OpenrConfig
    from openr_tpu.lsdb_codec import serialize_adj_db

    with pytest.raises(ValueError):
        OpenrConfig(node_name="x", lsdb_wire_format="msgpack")
    with pytest.raises(ValueError):
        serialize_adj_db(
            T.AdjacencyDatabase(this_node_name="x"), "msgpack"
        )


def test_crafted_deep_container_nesting_fails_as_value_error():
    """0x19 repeated parses as a size-1 list-of-lists per byte in the
    unknown-field skip path — must fail as ValueError like the struct
    variant (the skip recursion is depth-capped too)."""
    import pytest

    from openr_tpu.interop import decode_adjacency_database

    with pytest.raises(ValueError):
        decode_adjacency_database(bytes([0x19]) * 4096)


def test_fuzz_decoder_never_crashes_on_garbage():
    """Untrusted-input contract: ANY byte string either decodes or
    raises ValueError — no RecursionError, no hang, no IndexError.
    Mutated-valid payloads probe deeper than pure-random bytes."""
    rng = random.Random(1234)
    adj = T.AdjacencyDatabase(
        this_node_name="n1",
        adjacencies=[
            T.Adjacency(
                other_node_name="n2", if_name="e0", next_hop_v6="fe80::2"
            )
        ],
    )
    valid = encode_adjacency_database(adj)
    cases = []
    for _ in range(300):
        cases.append(
            bytes(rng.randrange(256) for _ in range(rng.randrange(0, 80)))
        )
    for _ in range(300):  # bit-flip / truncate / extend a valid payload
        b = bytearray(valid)
        op = rng.randrange(3)
        if op == 0 and b:
            b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
        elif op == 1 and b:
            del b[rng.randrange(len(b)) :]
        else:
            b += bytes(rng.randrange(256) for _ in range(rng.randrange(8)))
        cases.append(bytes(b))
    for data in cases:
        for dec in (decode_adjacency_database, decode_value):
            try:
                dec(data)
            except (ValueError, UnicodeDecodeError):
                pass  # the contract: clean parse errors only


def test_container_element_type_mismatch_degrades_to_unset():
    """A peer that changed a CONTAINER's element type must not desync
    the stream mid-payload: the container is skipped by its declared
    wire type and the field degrades to unset, same as the field-level
    wire-type check (ADVICE r4)."""
    # peer now sends tags as list<i32>; our spec still says list<string>
    peer = ((1, "tags", "list", ("i32", None)), (2, "ttl", "i64", None))
    ours = ((1, "tags", "list", ("string", None)), (2, "ttl", "i64", None))
    data = encode_struct(peer, {"tags": [7, 8, 9], "ttl": 42})
    out = decode_struct(ours, data)
    assert "tags" not in out  # mismatched container dropped...
    assert out["ttl"] == 42  # ...without desyncing the later field

    # map: peer changed the VALUE type string->i64
    peer_m = (
        (1, "kv", "map", (("string", None), ("i64", None))),
        (2, "ttl", "i64", None),
    )
    ours_m = (
        (1, "kv", "map", (("string", None), ("string", None))),
        (2, "ttl", "i64", None),
    )
    data = encode_struct(peer_m, {"kv": {"a": 1, "b": 2}, "ttl": 9})
    out = decode_struct(ours_m, data)
    assert "kv" not in out and out["ttl"] == 9

    # empty containers carry a declared-type byte too; same rule applies
    # but nothing can desync — current behavior: empty map decodes {}
    # (no kv-type byte exists on the wire to check)
    data = encode_struct(peer_m, {"kv": {}, "ttl": 9})
    out = decode_struct(ours_m, data)
    assert out["kv"] == {} and out["ttl"] == 9

    # nested: list<list<i32>> received against spec list<list<string>>
    peer_n = ((1, "m", "list", ("list", ("i32", None))),)
    ours_n = ((1, "m", "list", ("list", ("string", None))),)
    data = encode_struct(peer_n, {"m": [[1, 2], [3]]})
    out = decode_struct(ours_n, data)
    assert "m" not in out


def test_map_encoding_is_sorted_and_deterministic():
    """Maps sort by key for the same determinism reason as sets: our
    self-emitted Publication/linkStatusMap bytes must not vary with
    dict insertion order across processes (ADVICE r4)."""
    spec = ((1, "kv", "map", (("string", None), ("i64", None))),)
    a = encode_struct(spec, {"kv": {"b": 2, "a": 1}})
    b = encode_struct(spec, {"kv": {"a": 1, "b": 2}})
    assert a == b
    # key 'a' first on the wire
    assert a == bytes(
        [0x1B, 0x02, 0x86, 0x01, 0x61, 0x02, 0x01, 0x62, 0x04, 0x00]
    )
