"""Ctrl API surface + transport tests.

Reference models: openr/ctrl-server/tests/OpenrCtrlHandlerTest.cpp (method
surface over live modules), LongPollTest.cpp, and the breeze client tests.
Handler tests run in-process over a converged emulated network in virtual
time; transport tests exercise the TCP framed-JSON server/client on a real
socket.
"""

import asyncio
import json

import pytest

from openr_tpu import constants as C
from openr_tpu.common.runtime import SimClock, WallClock
from openr_tpu.ctrl.client import OpenrCtrlClient, OpenrCtrlError
from openr_tpu.ctrl.handler import OpenrCtrlHandler
from openr_tpu.ctrl.server import OpenrCtrlServer
from openr_tpu.emulation.network import EmulatedNetwork
from openr_tpu.emulation.topology import line_edges
from openr_tpu.types import InitializationEvent, Value, adj_key


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


CONVERGE_S = 12.0


async def converged_net(clock, n=3):
    net = EmulatedNetwork(clock)
    net.build(line_edges(n))
    net.start()
    await clock.run_for(CONVERGE_S)
    ok, why = net.converged_full_mesh()
    assert ok, why
    return net


# ---------------------------------------------------------------------------
# handler surface (in-process, virtual time)
# ---------------------------------------------------------------------------


def test_handler_info_and_counters():
    async def main():
        clock = SimClock()
        net = await converged_net(clock, 2)
        h = OpenrCtrlHandler(net.nodes["node0"])
        assert h.get_node_name() == "node0"
        v = h.get_openr_version()
        assert v["version"] >= v["lowestSupportedVersion"]
        assert h.initialization_converged() is True
        evs = h.get_initialization_events()
        assert int(InitializationEvent.INITIALIZED) in evs
        counters = h.get_counters()
        assert counters["decision.route_build_runs"] >= 1
        sub = h.get_regex_counters("decision.")
        assert sub and all(k.startswith("decision.") for k in sub)
        cfg = json.loads(h.get_running_config())
        assert cfg["node_name"] == "node0"
        await net.stop()

    run(main())


def test_handler_routes_and_adj_dbs():
    async def main():
        clock = SimClock()
        net = await converged_net(clock, 3)
        h = OpenrCtrlHandler(net.nodes["node0"])
        rdb = h.get_route_db()
        dests = [r["dest"] for r in rdb["unicast_routes"]]
        assert net.loopback("node2") in dests
        fib = h.get_fib_routes()
        assert sorted(r["dest"] for r in fib["unicast_routes"]) == sorted(dests)
        # route db computed for a *different* node (OpenrCtrl.thrift:482)
        other = h.get_route_db_computed("node2")
        other_dests = [r["dest"] for r in other["unicast_routes"]]
        assert net.loopback("node0") in other_dests
        adj_dbs = h.get_decision_adjacency_dbs()
        names = {db["this_node_name"] for db in adj_dbs}
        assert names == {"node0", "node1", "node2"}
        filtered = h.get_unicast_routes_filtered([net.loopback("node2")])
        assert len(filtered) == 1
        assert h.fib_synced() is True
        assert len(h.get_perf_db()) >= 1
        await net.stop()

    run(main())


def test_handler_kvstore_and_neighbors():
    async def main():
        clock = SimClock()
        net = await converged_net(clock, 2)
        h = OpenrCtrlHandler(net.nodes["node0"])
        dump = h.dump_kv_store_area()
        assert adj_key("node0") in dump and adj_key("node1") in dump
        got = h.get_kv_store_key_vals_area([adj_key("node1")])
        assert got[adj_key("node1")]["originator_id"] == "node1"
        summaries = h.get_kv_store_area_summaries()
        assert summaries[C.DEFAULT_AREA]["key_vals_count"] == len(dump)
        peers = h.get_kv_store_peers_area()
        assert "node1" in peers
        nbrs = h.get_spark_neighbors()
        assert [n["node_name"] for n in nbrs] == ["node1"]
        assert nbrs[0]["state"] == "ESTABLISHED"
        ifaces = h.get_interfaces()
        assert ifaces["is_overloaded"] is False
        assert ifaces["interface_details"]
        await net.stop()

    run(main())


def test_handler_drain_and_advertise():
    async def main():
        clock = SimClock()
        net = await converged_net(clock, 3)
        h0 = OpenrCtrlHandler(net.nodes["node0"])
        h1 = OpenrCtrlHandler(net.nodes["node1"])
        # drain middle node -> node0 loses transit route to node2
        h1.set_node_overload()
        await clock.run_for(3)
        assert h1.get_interfaces()["is_overloaded"] is True
        routes = net.fib_routes("node0")
        assert net.loopback("node2") not in routes
        h1.unset_node_overload()
        await clock.run_for(3)
        assert net.loopback("node2") in net.fib_routes("node0")
        # prefix advertise/withdraw through the API
        h0.advertise_prefixes([{"prefix": "99.1.0.0/16"}])
        await clock.run_for(3)
        assert "99.1.0.0/16" in net.fib_routes("node2")
        advertised = [p["prefix"] for p in h0.get_advertised_routes()]
        assert "99.1.0.0/16" in advertised
        h0.withdraw_prefixes([{"prefix": "99.1.0.0/16"}])
        await clock.run_for(3)
        assert "99.1.0.0/16" not in net.fib_routes("node2")
        await net.stop()

    run(main())


def test_handler_rib_policy_roundtrip():
    async def main():
        clock = SimClock()
        net = await converged_net(clock, 2)
        h = OpenrCtrlHandler(net.nodes["node0"])
        assert h.get_rib_policy() is None
        h.set_rib_policy(
            {
                "ttl_remaining_s": 300,
                "statements": [
                    {
                        "name": "s1",
                        "prefixes": [net.loopback("node1")],
                        "action": {"default_weight": 3},
                    }
                ],
            }
        )
        pol = h.get_rib_policy()
        assert pol["statements"][0]["name"] == "s1"
        assert 0 < pol["ttl_remaining_s"] <= 300
        h.clear_rib_policy()
        assert h.get_rib_policy() is None
        with pytest.raises(ValueError):
            h.set_rib_policy({"ttl_remaining_s": 0, "statements": []})
        await net.stop()

    run(main())


def test_handler_kvstore_stream_snapshot_plus_delta():
    async def main():
        clock = SimClock()
        net = await converged_net(clock, 2)
        node = net.nodes["node0"]
        h = OpenrCtrlHandler(node)
        items = []

        async def consume():
            async for item in h.subscribe_and_get_kv_store(
                key_prefixes=["adj:"]
            ):
                items.append(item)

        task = asyncio.get_running_loop().create_task(consume())
        await clock.run_for(1)
        # snapshot first: one publication containing both adj keys
        assert len(items) == 1
        assert set(items[0]["key_vals"]) == {adj_key("node0"), adj_key("node1")}
        # a topology change streams an incremental delta
        net.nodes["node1"].set_link_metric(
            net.nodes["node1"].link_monitor.build_adjacency_database(
                C.DEFAULT_AREA
            ).adjacencies[0].if_name,
            7777,
        )
        await clock.run_for(3)
        assert len(items) >= 2
        assert adj_key("node1") in items[-1]["key_vals"]
        task.cancel()
        await clock.run_for(0.1)
        await net.stop()

    run(main())


def test_handler_fib_stream():
    async def main():
        clock = SimClock()
        net = await converged_net(clock, 2)
        h = OpenrCtrlHandler(net.nodes["node0"])
        items = []

        async def consume():
            async for item in h.subscribe_and_get_fib():
                items.append(item)

        task = asyncio.get_running_loop().create_task(consume())
        await clock.run_for(1)
        assert len(items) == 1  # snapshot RouteDatabase
        assert "unicast_routes" in items[0]
        net.nodes["node1"].advertise_prefixes(
            [__import__("openr_tpu.types", fromlist=["PrefixEntry"]).PrefixEntry("55.5.0.0/16")]
        )
        await clock.run_for(3)
        deltas = items[1:]
        assert any(
            "55.5.0.0/16" in [r["dest"] for r in d.get("unicast_routes_to_update", [])]
            for d in deltas
        )
        task.cancel()
        await clock.run_for(0.1)
        await net.stop()

    run(main())


def test_handler_serving_route_db_stream():
    """subscribe_and_get_serving_route_db: generation-stamped snapshot
    first, then a coalesced delta per generation bump; cancelling the
    stream unsubscribes (no subscriber leak)."""

    async def main():
        clock = SimClock()
        net = await converged_net(clock, 3)
        node = net.nodes["node0"]
        h = OpenrCtrlHandler(node)
        items = []

        async def consume():
            async for item in h.subscribe_and_get_serving_route_db(
                "node2", client_id="ctrl-test"
            ):
                items.append(item)

        task = asyncio.get_running_loop().create_task(consume())
        await clock.run_for(1)
        assert len(items) == 1
        assert items[0]["type"] == "snapshot"
        assert items[0]["route_db"]["this_node_name"] == "node2"
        seq0 = items[0]["seq"]
        # an LSDB change streams a delta carrying a LATER generation
        net.nodes["node1"].advertise_prefixes(
            [__import__("openr_tpu.types", fromlist=["PrefixEntry"])
             .PrefixEntry("55.6.0.0/16")]
        )
        await clock.run_for(3)
        assert len(items) >= 2
        delta = items[-1]
        assert delta["type"] == "delta" and delta["seq"] > seq0
        assert "55.6.0.0/16" in [
            r["dest"] for r in delta["unicast_updated"]
        ]
        task.cancel()
        await clock.run_for(0.1)
        assert len(node.streaming._subs) == 0, "cancel must unsubscribe"
        await net.stop()

    run(main())


def test_handler_long_poll_adj():
    async def main():
        clock = SimClock()
        net = await converged_net(clock, 2)
        node = net.nodes["node0"]
        h = OpenrCtrlHandler(node)
        # stale snapshot -> immediate True
        assert await h.long_poll_kv_store_adj_area(snapshot={}) is True
        # current snapshot -> parks; adjacency change wakes it
        current = {
            k: v.version
            for k, v in node.kv_store.dump_all(C.DEFAULT_AREA, "adj:").items()
        }
        fut = asyncio.get_running_loop().create_task(
            h.long_poll_kv_store_adj_area(snapshot=current)
        )
        await clock.run_for(1)
        assert not fut.done()
        node.set_node_metric_increment(50)  # bumps adj: key version
        await clock.run_for(3)
        assert fut.done() and fut.result() is True
        # current snapshot + no change -> False after hold time
        current2 = {
            k: v.version
            for k, v in node.kv_store.dump_all(C.DEFAULT_AREA, "adj:").items()
        }
        fut2 = asyncio.get_running_loop().create_task(
            h.long_poll_kv_store_adj_area(snapshot=current2)
        )
        await clock.run_for(C.LONG_POLL_REQ_HOLD_TIME_S + 1)
        assert fut2.done() and fut2.result() is False
        await net.stop()

    run(main())


def test_stream_reader_cleanup():
    """Transient subscribers must not leave backlogged readers behind
    (the reference drops the ServerStreamPublisher on stream close)."""

    async def main():
        clock = SimClock()
        net = await converged_net(clock, 2)
        node = net.nodes["node0"]
        h = OpenrCtrlHandler(node)
        before = len(node.dispatcher.get_filters())
        gen = h.subscribe_and_get_kv_store(key_prefixes=["adj:"])
        assert (await gen.__anext__()) is not None
        assert len(node.dispatcher.get_filters()) == before + 1
        await gen.aclose()
        assert len(node.dispatcher.get_filters()) == before
        await net.stop()

    run(main())


# ---------------------------------------------------------------------------
# TCP transport (real sockets, wall clock)
# ---------------------------------------------------------------------------


def test_tcp_server_unary_stream_and_error():
    async def main():
        clock = WallClock()
        net = EmulatedNetwork(clock)
        net.build(line_edges(2))
        net.start()
        node = net.nodes["node0"]
        server = OpenrCtrlServer(node, port=0)
        await server.start()
        try:
            async with OpenrCtrlClient(port=server.port) as client:
                # unary
                assert await client.call("get_node_name") == "node0"
                counters = await client.call("get_counters")
                assert isinstance(counters, dict)
                # adjacencies appear once Spark establishes (~2s wall time)
                for _ in range(100):
                    dump = await client.call(
                        "dump_kv_store_area", prefix="adj:", area=C.DEFAULT_AREA
                    )
                    if adj_key("node0") in dump:
                        break
                    await asyncio.sleep(0.1)
                assert adj_key("node0") in dump
                # concurrent unary calls multiplex over one connection
                r = await asyncio.gather(
                    client.call("get_node_name"),
                    client.call("get_openr_version"),
                    client.call("fib_synced"),
                )
                assert r[0] == "node0" and "version" in r[1]
                # errors propagate
                with pytest.raises(OpenrCtrlError):
                    await client.call("no_such_method")
                with pytest.raises(OpenrCtrlError):
                    await client.call("get_kv_store_peers_area", area="nope")
                # stream: snapshot arrives, then cancel mid-stream
                filters_before = len(node.dispatcher.get_filters())
                items = []
                async for item in client.stream(
                    "subscribe_and_get_kv_store", key_prefixes=["adj:"]
                ):
                    items.append(item)
                    break  # cancels server-side
                assert items and adj_key("node0") in items[0]["key_vals"]
                # after cancel the transient dispatcher reader is dropped
                for _ in range(50):
                    if len(node.dispatcher.get_filters()) == filters_before:
                        break
                    await asyncio.sleep(0.1)
                assert len(node.dispatcher.get_filters()) == filters_before
        finally:
            await server.stop()
            await net.stop()

    run(main())


def test_tcp_long_poll_roundtrip():
    async def main():
        clock = WallClock()
        net = EmulatedNetwork(clock)
        net.build(line_edges(2))
        net.start()
        node = net.nodes["node0"]
        server = OpenrCtrlServer(node, port=0)
        await server.start()
        try:
            async with OpenrCtrlClient(port=server.port) as client:
                assert (
                    await client.call(
                        "long_poll_kv_store_adj_area", snapshot={}
                    )
                    is True
                )
        finally:
            await server.stop()
            await net.stop()

    run(main())


def test_handler_fleet_status_verb():
    """get_fleet_status: "disabled" on a node with no fleet attachment
    (every node outside a fleet deployment); a node carrying one serves
    its coordinator's status verbatim."""

    async def main():
        clock = SimClock()
        net = await converged_net(clock, 2)
        node = net.nodes["node0"]
        h = OpenrCtrlHandler(node)
        assert h.get_fleet_status() == {"state": "disabled"}

        class _Fleet:
            def status(self):
                return {"state": "running", "fleet_id": "0ddfab1e"}

        node.fleet = _Fleet()
        try:
            assert h.get_fleet_status()["fleet_id"] == "0ddfab1e"
        finally:
            del node.fleet
        await net.stop()

    run(main())


def test_handler_config_and_init_parity_methods():
    """dryrunConfig / getRunningConfigThrift / getInitializationDurationMs
    equivalents (OpenrCtrl.thrift:264,274,302)."""
    import tempfile

    async def main():
        clock = SimClock()
        net = await converged_net(clock, 2)
        node = net.nodes["node0"]
        h = OpenrCtrlHandler(node)
        # typed config mirrors the JSON form exactly
        typed = h.get_running_config_thrift()
        assert typed["node_name"] == "node0"
        assert json.loads(h.get_running_config()) == typed
        # dryrun: valid file loads + normalizes, bad file raises
        with tempfile.NamedTemporaryFile("w", suffix=".conf") as f:
            f.write('{"node_name": "candidate", "domain": "lab"}')
            f.flush()
            loaded = json.loads(h.dryrun_config(f.name))
            assert loaded["node_name"] == "candidate"
            assert loaded["domain"] == "lab"
        with pytest.raises(Exception):
            h.dryrun_config("/no/such/file.conf")
        # duration: raises until INITIALIZED, then returns milliseconds
        if not node.init_tracker.initialized:
            with pytest.raises(ValueError):
                h.get_initialization_duration_ms()
            from openr_tpu.types import InitializationEvent

            for ev in node.init_tracker.REQUIRED:
                node.init_tracker.on_event(ev)
        assert h.get_initialization_duration_ms() >= 0
        await net.stop()

    run(main())


def test_handler_kvstore_depth_methods():
    """areas / kv-signature / erase-key: the signature changes exactly
    when content changes, and an erase tombstone supersedes + expires
    network-wide."""

    async def main():
        clock = SimClock()
        net = await converged_net(clock, 2)
        h0 = OpenrCtrlHandler(net.nodes["node0"])
        h1 = OpenrCtrlHandler(net.nodes["node1"])
        assert h0.get_kv_store_areas() == ["0"]
        # converged stores agree on the signature
        assert h0.get_kv_store_signature() == h1.get_kv_store_signature()
        # inject a non-self-originated key, flood it, then erase it
        # network-wide (a LIVE self-originated key would be resurrected
        # by its owner's TTL refresh — correct protocol behavior; erase
        # targets stale/foreign keys)
        h0.set_kv_store_key_vals_area(
            {
                "prefix:ghost": Value(
                    version=1,
                    originator_id="ghost",
                    value=b"{}",
                    ttl=300_000,
                ).to_wire()
            }
        )
        await clock.run_for(1)
        assert "prefix:ghost" in h1.dump_kv_store_area()
        sig0 = h0.get_kv_store_signature()
        h0.erase_kv_store_key("prefix:ghost", ttl_ms=200)
        await clock.run_for(1)
        for h in (h0, h1):
            assert "prefix:ghost" not in h.dump_kv_store_area()
        assert h0.get_kv_store_signature() != sig0
        with pytest.raises(KeyError):
            h0.erase_kv_store_key("nope:key")
        await net.stop()

    run(main())


def test_stream_drain_cancellation_not_swallowed():
    """Cancelling the stream's request task in the same event-loop pass
    where an emission's drain completes must still cancel it.
    asyncio.wait_for swallows cancellation in exactly that window on
    Python < 3.12 (bpo-42130), and a watch client that reads one
    emission and disconnects lands the connection task's EOF-cancel
    there — the lost cancellation parked the request task in its
    long-poll forever, leaking the stream subscriber.  drain_bounded
    must re-raise on every phasing of cancel vs drain completion."""

    from openr_tpu.ctrl.server import drain_bounded

    class _Writer:
        async def drain(self):
            return None

    async def main():
        for steps in (1, 2, 3):

            async def use():
                await drain_bounded(_Writer())
                await asyncio.sleep(3600)  # the long-poll park

            t = asyncio.ensure_future(use())
            for _ in range(steps):
                await asyncio.sleep(0)
            t.cancel()
            done, _ = await asyncio.wait({t}, timeout=2.0)
            assert done, (
                f"cancellation swallowed at phasing {steps}; "
                "request task still parked"
            )
            assert t.cancelled(), f"phasing {steps}: {t}"

    run(main())
