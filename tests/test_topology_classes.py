"""Topology-class catalog: determinism + structural invariants.

Every registered class must (a) be byte-deterministic from
``(class, scale, seed)`` — the trajectory bench's replay contract rides
on it, (b) produce exactly the node/edge counts its ``params`` table
derives, (c) be connected, and (d) hold class-specific shape
invariants (bisection sanity: cutting the joining layer actually
severs the hierarchy it joins).
"""

import pytest

from openr_tpu.emulation.topology import (
    TOPOLOGY_CLASSES,
    build_adj_dbs,
    is_connected,
    multipod_fattree_edges,
    topology_nodes,
    wan_area_of,
    wan_hierarchy_edges,
    wan_multi_area_dbs,
)

SCALES = (64, 256)


def undirected(edges):
    return {frozenset((a, b)) for a, b, _m in edges}


@pytest.mark.parametrize("name", sorted(TOPOLOGY_CLASSES))
@pytest.mark.parametrize("scale", SCALES)
def test_same_seed_identical_edge_list(name, scale):
    row = TOPOLOGY_CLASSES[name]
    assert row.build(scale, 7) == row.build(scale, 7)
    if row.seed_sensitive:
        # a different seed must actually reshuffle a seeded class
        assert row.build(scale, 7) != row.build(scale, 8)
    else:
        # structural classes document seed-invariance — hold them to it
        assert row.build(scale, 7) == row.build(scale, 8)


@pytest.mark.parametrize("name", sorted(TOPOLOGY_CLASSES))
@pytest.mark.parametrize("scale", SCALES)
def test_node_edge_counts_match_params(name, scale):
    row = TOPOLOGY_CLASSES[name]
    edges = row.build(scale, 7)
    p = row.params(scale)
    assert len(topology_nodes(edges)) == p["nodes"]
    assert len(undirected(edges)) == p["undirected_edges"]
    # the class must land in the scale's ballpark, not a token graph
    assert p["nodes"] >= scale * 0.75


@pytest.mark.parametrize("name", sorted(TOPOLOGY_CLASSES))
def test_connected(name):
    row = TOPOLOGY_CLASSES[name]
    assert is_connected(row.build(SCALES[0], 7))


def test_fattree_bisection_and_tiers():
    """Cutting every super-spine must disconnect pods from each other
    (the super-spine layer IS the inter-pod bisection), and each tier
    must have the full bipartite degree the pod design promises."""
    edges = multipod_fattree_edges(
        num_pods=3, rsws_per_pod=4, fsws_per_pod=2, ssws_per_pod=2,
        num_spines=4,
    )
    assert is_connected(edges)
    no_spine = [
        (a, b, m)
        for a, b, m in edges
        if not a.startswith("spine") and not b.startswith("spine")
    ]
    pod0 = [e for e in no_spine if e[0].startswith(("rsw0", "fsw0", "ssw0"))]
    assert not is_connected(no_spine), "pods must only join via spines"
    assert is_connected(pod0), "a pod must stay internally connected"
    deg = {}
    for a, b, _m in edges:
        deg[a] = deg.get(a, 0) + 1
        deg[b] = deg.get(b, 0) + 1
    for p in range(3):
        for r in range(4):
            assert deg[f"rsw{p}_{r}"] == 2  # one uplink per pod fsw
        for f in range(2):
            assert deg[f"fsw{p}_{f}"] == 4 + 2  # racks below + spines up
    for k in range(4):
        assert deg[f"spine{k}"] == 3  # one pod-spine per pod


def test_wan_hierarchy_shape_and_asymmetry():
    edges = wan_hierarchy_edges(
        num_backbone=8, num_metros=4, metro_size=6, backbone_extra=4,
        seed=11,
    )
    assert is_connected(edges)
    # long-haul metrics are drawn per direction: at least one backbone
    # pair must come out asymmetric at this size
    directed = {(a, b): m for a, b, m in edges}
    core_pairs = [
        (a, b)
        for (a, b) in directed
        if a.startswith("core") and b.startswith("core")
    ]
    assert core_pairs
    assert any(
        directed[(a, b)] != directed.get((b, a), directed[(a, b)])
        for a, b in core_pairs
    ), "backbone metrics should be asymmetric"
    # every metro dual-homes: removing the backbone leaves each ring
    # intact but disconnects metros from each other
    no_core = [
        (a, b, m)
        for a, b, m in edges
        if not a.startswith("core") and not b.startswith("core")
    ]
    assert not is_connected(no_core)
    m0 = [e for e in no_core if e[0].startswith("m0_")]
    assert is_connected(m0), "a metro ring must stay internally connected"
    for m in range(4):
        homing = [
            (a, b)
            for a, b, _ in edges
            if a.startswith(f"m{m}_") and b.startswith("core")
        ]
        assert len({b for _a, b in homing}) == 2, (
            f"metro {m} must dual-home onto two distinct cores"
        )


def test_wan_multi_area_dbs_are_abr_shaped():
    dbs = wan_multi_area_dbs(128, seed=7)
    assert "0" in dbs and len(dbs) >= 2
    p = TOPOLOGY_CLASSES["wan_multi_area"].params(128)
    assert len([a for a in dbs if a.startswith("metro")]) == p["metros"]
    for area, area_dbs in dbs.items():
        for name, db in area_dbs.items():
            assert db.area == area
        if not area.startswith("metro"):
            continue
        # exactly the ring members, and the two gateways also speak
        # area 0 (the ABR contract)
        members = set(area_dbs)
        assert all(wan_area_of(n) == area for n in members)
        gateways = members & set(dbs["0"])
        assert len(gateways) == 2, (area, sorted(gateways))


def test_adj_dbs_build_from_every_class():
    """build_adj_dbs accepts every class's edge list (asymmetric WAN
    entries included) and yields one db per node."""
    for name, row in TOPOLOGY_CLASSES.items():
        edges = row.build(64, 7)
        dbs = build_adj_dbs(edges)
        assert set(dbs) == set(topology_nodes(edges)), name
