"""Tier-1 smoke: the checked-in BENCH_RESILIENCE artifact obeys the
schema the bench emits (shared validator — bench.validate_resilience_bench)
and holds the acceptance bounds from ISSUE 5: shadow-verification
overhead <= 5% on the rebuild p50, SDC detected within one
shadow-sample interval, probed recovery, deterministic replay.

The validator lives in bench.py so the emitter and this gate can never
drift apart; regenerate the artifact with `python bench.py --resilience`.
"""

import json
import pathlib

import pytest

import bench

ARTIFACT = pathlib.Path(__file__).resolve().parent.parent / (
    "BENCH_RESILIENCE_r01.json"
)


def test_artifact_exists_and_matches_schema():
    doc = json.loads(ARTIFACT.read_text())
    bench.validate_resilience_bench(doc)


def test_sdc_scenario_holds_the_acceptance_bounds():
    doc = json.loads(ARTIFACT.read_text())
    sc = doc["detail"]["sdc_scenario"]
    # detection within ONE shadow-sample interval of rebuilds
    assert sc["rebuilds_to_detect"] <= sc["shadow_sample_every"]
    # the same seed replayed byte-identically (chaos + resilience dumps)
    assert sc["deterministic_replay"] is True
    # recovery went through the probe path, not a blind flip
    assert sc["probes"] >= 1 and sc["restores"] >= 1


def test_validator_rejects_malformed_doc():
    doc = json.loads(ARTIFACT.read_text())
    doc["value"] = 50.0  # a 50% p50 overhead must never pass the gate
    with pytest.raises(AssertionError):
        bench.validate_resilience_bench(doc)
