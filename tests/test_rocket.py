"""fbthrift Rocket transport tests.

Golden frames are HAND-ASSEMBLED from the RSocket 1.0 spec + the public
fbthrift rocket protocol layout (kRocketProtocolKey-prefixed SETUP
metadata, Compact RequestRpcMetadata/ResponseRpcMetadata) the way
test_thrift_interop.py pins struct bytes — any encoder regression shows
up at the byte level.  Then the full stack runs over real TCP: the four
adapted ctrl methods against a live emulated node, and a two-store
KvStore anti-entropy sync + flood where every RPC rides rocket framing
(reference: KvStore peer thrift sessions, KvStore.h:460-466; ctrl
ThriftServer, Main.cpp:399-416).
"""

import asyncio
import struct
import types as pytypes

from openr_tpu import constants as C
from openr_tpu.common.runtime import WallClock
from openr_tpu.config import KvStoreConfig
from openr_tpu.emulation.network import EmulatedNetwork
from openr_tpu.emulation.topology import line_edges
from openr_tpu.interop import rocket, rsocket as rs
from openr_tpu.interop.ctrl_rocket import (
    DeclaredError,
    RocketCtrlServer,
    rocket_call,
)
from openr_tpu.kvstore.kv_store import KvStore
from openr_tpu.kvstore.transport import RocketKvStoreTransport
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.types import PeerSpec, adj_key


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# golden frames (hand-assembled bytes)
# ---------------------------------------------------------------------------


def test_golden_setup_frame():
    """SETUP: rsocket 1.0 header + version + timers + mimes, metadata =
    u32 kRocketProtocolKey(1) | Compact RequestSetupMetadata."""
    got = rs.encode_setup(
        keepalive_ms=30000,
        max_lifetime_ms=3600000,
        metadata_mime="text/plain",
        data_mime="text/plain",
        metadata=rocket.encode_setup_metadata(),
    )
    md = (
        b"\x00\x00\x00\x01"  # kRocketProtocolKey
        b"\x25\x00"  # field 2 minVersion i32 zigzag(0)
        b"\x15\x00"  # field 3 maxVersion
        b"\x00"  # stop
    )
    want = (
        b"\x00\x00\x00\x00"  # stream 0
        b"\x05\x00"  # type SETUP(0x01)<<10 | METADATA(0x100)
        b"\x00\x01\x00\x00"  # version 1.0
        + struct.pack(">II", 30000, 3600000)
        + b"\x0atext/plain" * 2  # metadata + data mime
        + b"\x00\x00\x09"  # u24 metadata length
        + md
    )
    assert got == want
    f = rs.decode_frame(got)
    assert f.ftype == rs.FT_SETUP and f.keepalive_ms == 30000
    assert rocket.decode_setup_metadata(f.metadata) == {
        "minVersion": 0,
        "maxVersion": 0,
    }


def test_golden_request_response_frame():
    """REQUEST_RESPONSE for getRouteDbComputed(nodeName="b"): metadata
    is Compact RequestRpcMetadata{1:protocol=COMPACT, 2:name, 3:kind},
    data is the Compact args struct {1: "b"}."""
    md = rocket.encode_request_metadata("getRouteDbComputed")
    args = b"\x18\x01b\x00"  # field 1 string "b", stop
    got = rs.encode_request_response(1, md, args)
    want_md = (
        b"\x15\x04"  # 1: protocol i32 zigzag(2)=4
        b"\x18\x12getRouteDbComputed"  # 2: name (len 18)
        b"\x15\x00"  # 3: kind SINGLE_REQUEST_SINGLE_RESPONSE
        b"\x00"
    )
    want = (
        b"\x00\x00\x00\x01"  # stream 1 (client streams odd)
        b"\x11\x00"  # REQUEST_RESPONSE(0x04)<<10 | METADATA
        + len(want_md).to_bytes(3, "big")
        + want_md
        + args
    )
    assert got == want


def test_golden_void_success_payload():
    """setKvStoreKeyVals success: PAYLOAD NEXT|COMPLETE, metadata =
    ResponseRpcMetadata{3: payloadMetadata{1: responseMetadata{}}},
    data = empty result struct."""
    md = rocket.encode_response_metadata()
    got = rs.encode_payload(1, md, b"\x00", complete=True, next_=True)
    want = (
        b"\x00\x00\x00\x01"
        b"\x29\x60"  # PAYLOAD(0x0A)<<10 | METADATA|COMPLETE|NEXT
        b"\x00\x00\x05"  # metadata length
        b"\x3c\x1c\x00\x00\x00"  # 3: union{1: empty struct}, stops
        b"\x00"  # data: empty result struct
    )
    assert got == want


def test_frame_codec_round_trips():
    cases = [
        rs.encode_keepalive(7, respond=True, data=b"ka"),
        rs.encode_request_fnf(3, b"m", b"d"),
        rs.encode_request_stream(5, 128, b"meta", b"data"),
        rs.encode_request_n(5, 64),
        rs.encode_cancel(9),
        rs.encode_payload(5, None, b"only-data", complete=False),
        rs.encode_error(7, rs.ERR_APPLICATION_ERROR, "boom"),
    ]
    k = rs.decode_frame(cases[0])
    assert k.ftype == rs.FT_KEEPALIVE and k.flags & rs.FLAG_RESPOND
    assert k.last_position == 7 and k.data == b"ka"
    f = rs.decode_frame(cases[1])
    assert (f.metadata, f.data) == (b"m", b"d")
    s = rs.decode_frame(cases[2])
    assert s.initial_n == 128 and s.metadata == b"meta" and s.data == b"data"
    assert rs.decode_frame(cases[3]).initial_n == 64
    assert rs.decode_frame(cases[4]).ftype == rs.FT_CANCEL
    p = rs.decode_frame(cases[5])
    assert p.metadata is None and p.data == b"only-data"
    e = rs.decode_frame(cases[6])
    assert e.error_code == rs.ERR_APPLICATION_ERROR
    assert e.error_message == "boom"


def test_fragmented_frames_rejected_not_truncated():
    raw = rs.encode_request_response(1, b"m", b"d")
    sid, tf = struct.unpack(">IH", raw[:6])
    frag = struct.pack(">IH", sid, tf | rs.FLAG_FOLLOWS) + raw[6:]
    try:
        rs.decode_frame(frag)
        assert False, "FOLLOWS must raise"
    except ValueError as e:
        assert "fragment" in str(e)


# ---------------------------------------------------------------------------
# live RPC: the four adapted methods against an emulated node
# ---------------------------------------------------------------------------


def test_rocket_ctrl_four_methods_end_to_end():
    async def main():
        net = EmulatedNetwork(WallClock())
        net.build(line_edges(2))
        net.start()
        node = net.nodes["node0"]
        server = RocketCtrlServer(node, port=0)
        await server.start()
        try:
            # wait for spark/kvstore/decision convergence on the wall
            # clock — generous: the suite runs on a loaded single core
            for _ in range(600):
                adjs_seen = node.kv_store.dump_all(C.DEFAULT_AREA, "adj:")
                if (
                    adj_key("node0") in adjs_seen
                    and adj_key("node1") in adjs_seen
                    and len(node.decision.get_adj_dbs(None)) >= 2
                ):
                    break
                await asyncio.sleep(0.1)
            async with rocket.RocketClient("127.0.0.1", server.port) as c:
                # 1. filtered dump (no hashes)
                pub = await rocket_call(
                    c,
                    "getKvStoreKeyValsFilteredArea",
                    {
                        "filter": {"keys": ["adj:"]},
                        "area": C.DEFAULT_AREA,
                    },
                )
                assert adj_key("node0") in pub["keyVals"]
                assert pub["keyVals"][adj_key("node0")]["version"] >= 1

                # 2. adjacency dump
                adjs = await rocket_call(
                    c, "getDecisionAdjacenciesFiltered", {"filter": {}}
                )
                names = {a["thisNodeName"] for a in adjs}
                assert {"node0", "node1"} <= names

                # 3. computed routes for the OTHER node (global topology)
                rdb = await rocket_call(
                    c, "getRouteDbComputed", {"nodeName": "node1"}
                )
                assert rdb["thisNodeName"] == "node1"

                # 4. setKvStoreKeyVals round-trips a value in
                await rocket_call(
                    c,
                    "setKvStoreKeyVals",
                    {
                        "setParams": {
                            "keyVals": {
                                "test:rocket": {
                                    "version": 9,
                                    "originatorId": "ext",
                                    "value": b"hello-rocket",
                                    "ttl": 60000,
                                    "ttlVersion": 0,
                                }
                            },
                            "senderId": "test-client",
                        },
                        "area": C.DEFAULT_AREA,
                    },
                )
                got = node.kv_store.get_key_vals(
                    C.DEFAULT_AREA, ["test:rocket"]
                )
                assert got["test:rocket"].value == b"hello-rocket"
                assert got["test:rocket"].version == 9

                # declared exception: unknown area -> KvStoreError
                try:
                    await rocket_call(
                        c,
                        "getKvStoreKeyValsFilteredArea",
                        {"filter": {}, "area": "no-such-area"},
                    )
                    assert False, "expected DeclaredError"
                except DeclaredError as e:
                    assert "no-such-area" in str(e)

                # unknown method -> rsocket APPLICATION_ERROR
                try:
                    await c.request_response("noSuchMethod", b"\x00")
                    assert False, "expected RocketError"
                except rocket.RocketError as e:
                    assert "noSuchMethod" in str(e)
        finally:
            await server.stop()
            await net.stop()

    run(main())


def test_setup_without_protocol_key_rejected():
    async def main():
        async def nope(name, data, peer):  # pragma: no cover
            raise AssertionError("must not dispatch")

        server = await rocket.RocketServer(nope, port=0).start()
        try:
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", server.port
            )
            # plain rsocket SETUP without fbthrift's protocol key
            writer.write(
                rs.frame_stream(
                    rs.encode_setup(
                        keepalive_ms=1000,
                        max_lifetime_ms=1000,
                        metadata_mime="application/binary",
                        data_mime="application/binary",
                        metadata=b"\x00\x00\x00\x99",
                    )
                )
            )
            await writer.drain()
            frame = await asyncio.wait_for(rs.read_stream_frame(reader), 5)
            assert frame.ftype == rs.FT_ERROR
            assert frame.error_code == rs.ERR_INVALID_SETUP
            writer.close()
        finally:
            await server.stop()

    run(main())


# ---------------------------------------------------------------------------
# KvStore peer plane over rocket: sync + flood with reference wire shapes
# ---------------------------------------------------------------------------


def make_store(name: str) -> KvStore:
    return KvStore(
        node_name=name,
        clock=WallClock(),
        config=KvStoreConfig(),
        areas=["0"],
        transport=RocketKvStoreTransport(),
        publications_queue=ReplicateQueue(f"{name}.pubs"),
    )


async def serve_store(store: KvStore) -> RocketCtrlServer:
    node_stub = pytypes.SimpleNamespace(kv_store=store)
    return await RocketCtrlServer(node_stub, port=0).start()


def test_two_stores_sync_and_flood_over_rocket():
    async def main():
        a, b = make_store("a"), make_store("b")
        a.start()
        b.start()
        sa, sb = await serve_store(a), await serve_store(b)
        try:
            a.areas["0"].persist_self_originated_key("prefix:a", b"va")
            a.areas["0"].add_peers(
                {"b": PeerSpec(peer_addr="127.0.0.1", ctrl_port=sb.port)}
            )
            b.areas["0"].add_peers(
                {"a": PeerSpec(peer_addr="127.0.0.1", ctrl_port=sa.port)}
            )
            for _ in range(100):
                await asyncio.sleep(0.05)
                if "prefix:a" in b.areas["0"].key_vals:
                    break
            assert "prefix:a" in b.areas["0"].key_vals
            assert b.areas["0"].key_vals["prefix:a"].value == b"va"

            # flood: a new key on b reaches a via rocket setKvStoreKeyVals
            b.areas["0"].persist_self_originated_key("prefix:b", b"vb")
            for _ in range(100):
                await asyncio.sleep(0.05)
                if "prefix:b" in a.areas["0"].key_vals:
                    break
            assert a.areas["0"].key_vals["prefix:b"].value == b"vb"
        finally:
            await a.stop()
            await b.stop()
            await a.transport.close()
            await b.transport.close()
            await sa.stop()
            await sb.stop()

    run(main())


# ---------------------------------------------------------------------------
# round-5 review regressions
# ---------------------------------------------------------------------------


def test_truncated_frame_bodies_raise_value_error():
    """Short KEEPALIVE/ERROR/SETUP bodies must surface as ValueError
    (one except clause in connection handlers), never struct.error."""
    cases = [
        struct.pack(">IH", 0, rs.FT_KEEPALIVE << 10) + b"\x00\x01",  # <8B
        struct.pack(">IH", 1, rs.FT_ERROR << 10) + b"\x00\x02",  # <4B
        struct.pack(">IH", 0, rs.FT_SETUP << 10) + b"\x00\x01",  # no timers
        struct.pack(">IH", 5, rs.FT_REQUEST_N << 10) + b"\x01",  # <4B
    ]
    for raw in cases:
        try:
            rs.decode_frame(raw)
            assert False, f"must raise: {raw!r}"
        except ValueError:
            pass


def test_dead_client_fails_fast_not_timeout():
    """A peer that closed while the client was idle must fail the NEXT
    rpc immediately (so the kv transport redials), not after the full
    request timeout."""

    async def main():
        async def ok(name, data, peer):
            return rocket.encode_response_metadata(), b"\x00"

        server = await rocket.RocketServer(ok, port=0).start()
        client = await rocket.RocketClient("127.0.0.1", server.port).connect()
        try:
            await server.stop()  # peer goes away while client is idle
            for _ in range(100):
                if client._dead is not None:
                    break
                await asyncio.sleep(0.02)
            t0 = asyncio.get_running_loop().time()
            try:
                await client.request_response("x", b"\x00", timeout_s=30.0)
                assert False, "expected RocketError"
            except rocket.RocketError:
                pass
            assert asyncio.get_running_loop().time() - t0 < 1.0
        finally:
            await client.close()

    run(main())


def test_client_emits_periodic_keepalives():
    """RSocket 1.0: the client must emit KEEPALIVE at its declared
    interval or a spec-compliant responder may drop the connection."""

    async def main():
        got = asyncio.Event()
        count = 0

        async def on_conn(reader, writer):
            nonlocal count
            while True:
                frame = await rs.read_stream_frame(reader)
                if frame is None:
                    return
                if frame.ftype == rs.FT_KEEPALIVE:
                    count += 1
                    if count >= 2:
                        got.set()

        server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        client = await rocket.RocketClient(
            "127.0.0.1", port, keepalive_ms=50
        ).connect()
        try:
            await asyncio.wait_for(got.wait(), 5)
        finally:
            await client.close()
            server.close()
            # NOT wait_closed(): py3.12 blocks it on handler completion,
            # and the raw on_conn handler may still be parked in read

    run(main())


def test_config_rejects_rocket_with_flood_optimization():
    from openr_tpu.config import KvStoreConfig as KvCfg, OpenrConfig

    try:
        OpenrConfig(
            node_name="x",
            lsdb_rpc_transport="rocket",
            kvstore_config=KvCfg(enable_flood_optimization=True),
        )
        assert False, "expected ValueError"
    except ValueError as e:
        assert "flood_optimization" in str(e)


def test_result_spec_cache_bounded_across_calls():
    """Each RPC must reuse the per-method result spec: compact.py's
    _BY_ID_CACHE pins every spec it sees, so per-call spec construction
    would leak one entry per RPC on the peer hot path."""

    async def main():
        from openr_tpu.interop import compact

        async def ok(name, data, peer):
            return rocket.encode_response_metadata(), b"\x00"

        server = await rocket.RocketServer(ok, port=0).start()
        client = await rocket.RocketClient("127.0.0.1", server.port).connect()
        try:
            await rocket_call(client, "setKvStoreKeyVals",
                              {"setParams": {}, "area": "0"})
            before = len(compact._BY_ID_CACHE)
            for _ in range(50):
                await rocket_call(client, "setKvStoreKeyVals",
                                  {"setParams": {}, "area": "0"})
            assert len(compact._BY_ID_CACHE) == before
        finally:
            await client.close()
            await server.stop()

    run(main())


def test_empty_hash_sync_gets_flood_ttl_semantics():
    """A cold initiator's full sync (present-but-EMPTY keyValHashes map)
    must flow through handle_full_sync_request — values arrive with the
    flood-copy TTL decrement, same as the jsonrpc transport — not the
    plain operator dump."""

    async def main():
        store = make_store("resp")
        store.start()
        server = await serve_store(store)
        transport = RocketKvStoreTransport()
        transport.register_peer(
            "resp", PeerSpec(peer_addr="127.0.0.1", ctrl_port=server.port)
        )
        try:
            store.areas["0"].persist_self_originated_key("k1", b"v1")
            ttl_in_store = store.areas["0"].key_vals["k1"].ttl
            pub = await transport.get_key_vals_filtered_area(
                "resp", "0", {}, "cold-node"
            )
            assert "k1" in pub.key_vals
            # flood-copy semantics: ttl decremented relative to stored
            assert pub.key_vals["k1"].ttl < ttl_in_store
            assert pub.tobe_updated_keys == []
        finally:
            await transport.close()
            await store.stop()
            await server.stop()

    run(main())


def test_rocket_extra_methods_version_routedb_peers():
    """The adapter's wider method rows: getOpenrVersion (the first call
    every reference client makes), getRouteDb (own computed routes) and
    getKvStorePeers[Area]."""

    async def main():
        net = EmulatedNetwork(WallClock())
        net.build(line_edges(2))
        net.start()
        node = net.nodes["node0"]
        server = RocketCtrlServer(node, port=0)
        await server.start()
        try:
            for _ in range(600):
                if node.decision.route_db.unicast_routes and (
                    node.kv_store.areas[C.DEFAULT_AREA].peers
                ):
                    break
                await asyncio.sleep(0.1)
            async with rocket.RocketClient("127.0.0.1", server.port) as c:
                v = await rocket_call(c, "getOpenrVersion", {})
                assert v["version"] >= v["lowestSupportedVersion"] > 0
                rdb = await rocket_call(c, "getRouteDb", {})
                assert rdb["thisNodeName"] == "node0"
                assert rdb["unicastRoutes"], rdb
                peers = await rocket_call(c, "getKvStorePeers", {})
                assert "node1" in peers, peers
                assert peers["node1"]["ctrlPort"] >= 0
                peers_a = await rocket_call(
                    c, "getKvStorePeersArea", {"area": C.DEFAULT_AREA}
                )
                assert peers_a == peers
                try:
                    await rocket_call(
                        c, "getKvStorePeersArea", {"area": "nope"}
                    )
                    assert False, "expected DeclaredError"
                except DeclaredError as e:
                    assert "nope" in str(e)
        finally:
            await server.stop()
            await net.stop()

    run(main())
