"""breeze CLI tests (reference: py/openr/cli/tests/* — click CliRunner
driving per-module commands; ours run against a real 2-node emulated
network served over the TCP ctrl server instead of a mocked client, which
exercises CLI + transport + handler in one pass)."""

import asyncio
import json
import threading

import pytest
from click.testing import CliRunner

from openr_tpu.cli.breeze import breeze
from openr_tpu.common.runtime import WallClock
from openr_tpu.ctrl.server import OpenrCtrlServer
from openr_tpu.emulation.network import EmulatedNetwork
from openr_tpu.emulation.topology import line_edges
from openr_tpu.types import adj_key


import contextlib


@contextlib.contextmanager
def _live_ctrl_node(num_nodes=2, use_tpu_backend=False, ready=None):
    """Background-thread network + ctrl server lifecycle (the CLI runs
    asyncio.run() internally, so the server must live on a different
    thread's loop — exactly the daemon-vs-CLI process split).  Yields
    the ctrl port.  ``ready(net)`` gates startup."""
    if ready is None:
        def ready(net):
            return adj_key("node1") in net.nodes["node0"].kv_store.dump_all(
                "0"
            )

    started = threading.Event()
    stop = None
    result = {}

    def runner():
        nonlocal stop
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        result["loop"] = loop
        stop = asyncio.Event()

        async def main():
            clock = WallClock()
            net = EmulatedNetwork(clock, use_tpu_backend=use_tpu_backend)
            net.build(line_edges(num_nodes))
            net.start()
            server = OpenrCtrlServer(net.nodes["node0"], port=0)
            await server.start()
            result["port"] = server.port
            result["net"] = net
            for _ in range(200):
                if ready(net):
                    break
                await asyncio.sleep(0.1)
            started.set()
            await stop.wait()
            await server.stop()
            await net.stop()

        loop.run_until_complete(main())
        loop.close()

    t = threading.Thread(target=runner, daemon=True)
    t.start()
    assert started.wait(timeout=60), "live node failed to start"
    try:
        yield result["port"]
    finally:
        result["loop"].call_soon_threadsafe(stop.set)
        t.join(timeout=30)


@pytest.fixture(scope="module")
def live_node():
    """A 2-node wall-clock network + ctrl server on a background loop."""
    with _live_ctrl_node() as port:
        yield port


def _run(port, *args):
    r = CliRunner().invoke(breeze, ["--port", str(port), *args], obj={})
    assert r.exit_code == 0, r.output
    return r.output


def test_cli_openr_group(live_node):
    assert _run(live_node, "openr", "node-name").strip() == "node0"
    v = json.loads(_run(live_node, "openr", "version"))
    assert v["version"] >= v["lowestSupportedVersion"]
    out = _run(live_node, "openr", "init-events")
    assert "INITIALIZING" in out


def test_cli_config_show(live_node):
    cfg = json.loads(_run(live_node, "config", "show"))
    assert cfg["node_name"] == "node0"


def test_cli_kvstore_group(live_node):
    out = _run(live_node, "kvstore", "keys")
    assert adj_key("node0") in out and adj_key("node1") in out
    out = _run(live_node, "kvstore", "keys", "--prefix", "prefix:")
    assert "adj:" not in out
    kv = json.loads(_run(live_node, "kvstore", "key-vals", adj_key("node1")))
    assert kv[adj_key("node1")]["originator_id"] == "node1"
    out = _run(live_node, "kvstore", "peers")
    assert "node1" in out and "INITIALIZED" in out
    summ = json.loads(_run(live_node, "kvstore", "summary"))
    assert "0" in summ


def test_cli_decision_and_fib(live_node):
    routes = json.loads(_run(live_node, "decision", "routes"))
    assert routes["this_node_name"] == "node0"
    assert routes["unicast_routes"]
    out = _run(live_node, "decision", "adj")
    assert "node0" in out and "-> node1" in out
    fib = json.loads(_run(live_node, "fib", "routes"))
    assert fib["unicast_routes"]
    dest = fib["unicast_routes"][0]["dest"]
    filtered = json.loads(_run(live_node, "fib", "unicast", dest))
    assert filtered and filtered[0]["dest"] == dest


def test_cli_lm_drain_cycle(live_node):
    out = _run(live_node, "lm", "set-node-overload")
    assert "drained" in out
    links = json.loads(_run(live_node, "lm", "links"))
    assert links["is_overloaded"] is True
    _run(live_node, "lm", "unset-node-overload")
    links = json.loads(_run(live_node, "lm", "links"))
    assert links["is_overloaded"] is False


def test_cli_spark_neighbors(live_node):
    out = _run(live_node, "spark", "neighbors")
    assert "node1" in out and "ESTABLISHED" in out


def test_cli_prefixmgr_cycle(live_node):
    _run(live_node, "prefixmgr", "advertise", "44.4.0.0/16")
    view = _run(live_node, "prefixmgr", "view")
    assert "44.4.0.0/16" in view
    _run(live_node, "prefixmgr", "withdraw", "44.4.0.0/16")
    view = _run(live_node, "prefixmgr", "view")
    assert "44.4.0.0/16" not in view


def test_cli_monitor_counters(live_node):
    counters = json.loads(_run(live_node, "monitor", "counters", "--prefix", "kvstore."))
    assert counters and all(k.startswith("kvstore.") for k in counters)


def test_cli_monitor_trace(live_node):
    spans = json.loads(_run(live_node, "monitor", "trace", "--json"))
    assert spans, "a converged node should have recorded spans"
    assert {"name", "trace_id", "span_id", "node", "start_ms"} <= set(
        spans[0]
    )
    # tree rendering names traces and indents spans under them — with
    # the drop-accounting summary first (ISSUE 7 satellite: dropped/
    # evicted spans must be operator-visible in the trace view)
    out = _run(live_node, "monitor", "trace")
    assert "trace " in out and "kvstore.key_arrival" in out
    assert "completed," in out and "dropped," in out and "evicted" in out
    # narrowing to one trace returns only that trace's spans
    tid = spans[-1]["trace_id"]
    one = json.loads(
        _run(live_node, "monitor", "trace", "--json", "--trace-id", tid)
    )
    assert one and all(s["trace_id"] == tid for s in one)


def test_cli_monitor_histograms(live_node):
    hists = json.loads(_run(live_node, "monitor", "histograms", "--json"))
    assert "convergence.event_to_fib_ms" in hists
    h = hists["convergence.event_to_fib_ms"]
    assert h["count"] > 0 and h["p50"] is not None
    table = _run(live_node, "monitor", "histograms")
    assert "p50" in table and "convergence.event_to_fib_ms" in table
    filtered = json.loads(
        _run(
            live_node, "monitor", "histograms", "--json",
            "--prefix", "convergence.",
        )
    )
    assert set(filtered) and all(
        k.startswith("convergence.") for k in filtered
    )


def test_cli_monitor_export(live_node, tmp_path):
    """breeze monitor export: Prometheus text exposition (parsed back
    with the strict parser) and the raw snapshot JSON."""
    from openr_tpu.monitor.metrics import parse_prometheus

    text = _run(live_node, "monitor", "export")
    parsed = parse_prometheus(text)
    key = ("openr_decision_route_build_runs", ("node", "node0"))
    assert parsed["openr_decision_route_build_runs"]["samples"][key] >= 1
    # histogram families carry buckets + sum + count
    hist_names = [k for k, v in parsed.items() if v["type"] == "histogram"]
    assert hist_names, "no histogram families in the exposition"
    doc = json.loads(_run(live_node, "monitor", "export", "--format", "json"))
    assert doc["node"] == "node0" and doc["counters"]
    assert doc["generation"] is not None and doc["env"]["python"]
    # --output writes the same payload to a file
    out_file = tmp_path / "metrics.prom"
    msg = _run(
        live_node, "monitor", "export", "--output", str(out_file)
    )
    assert "wrote" in msg
    assert parse_prometheus(out_file.read_text())


def test_cli_monitor_flight_dump(live_node):
    """breeze monitor flight-dump: graceful when no dump fired, full
    JSON once one has (driven via the ctrl verb surface)."""
    out = _run(live_node, "monitor", "flight-dump")
    assert "no flight-recorder dump" in out


def test_cli_serving_stats_and_queries(live_node):
    """breeze serving stats / routes / whatif against a live node: the
    serving plane answers through the ctrl server, and its counters
    reflect the served queries."""
    # a served query first, so stats have something to show
    db = json.loads(_run(live_node, "serving", "routes", "node1"))
    assert db["this_node_name"] == "node1"
    assert db["unicast_routes"], "node1 must compute routes"
    wf = json.loads(_run(live_node, "serving", "whatif", "node0:node1"))
    assert wf["eligible"] and len(wf["failures"]) == 1
    assert wf["failures"][0]["link"] == ["node0", "node1"]

    stats = json.loads(_run(live_node, "serving", "stats", "--json"))
    assert stats["enabled"] and stats["node"] == "node0"
    assert stats["counters"]["serving.requests"] >= 2
    assert stats["counters"]["serving.num_batches"] >= 2
    assert stats["config"]["max_batch"] == 64
    assert "serving.queue_wait_ms" in stats["histograms"]
    # repeated query = cache hit, visible in the stats surface
    again = json.loads(_run(live_node, "serving", "routes", "node1"))
    assert again == db
    stats2 = json.loads(_run(live_node, "serving", "stats", "--json"))
    assert (
        stats2["counters"]["serving.cache.hits"]
        > stats["counters"].get("serving.cache.hits", 0)
    )
    # human-readable table renders knobs + counters
    table = _run(live_node, "serving", "stats")
    assert "serving on node0" in table and "max_batch=64" in table


def test_cli_sweep_run_status_summary(live_node):
    """breeze sweep run/status/summary/cancel against a live node: the
    capacity-sweep orchestrator runs through the ctrl server and its
    ranked summary surfaces (ISSUE 14).  The 2-node world has ONE link
    — a 3-world grammar still proves the end-to-end plumbing."""
    import time

    rep = json.loads(
        _run(
            live_node,
            "sweep",
            "run",
            "--drain", "",
            "--drain", "node1",
            "--metric-scale", "node.*:5",
            "--no-resume",
        )
    )
    assert rep["state"] == "running"
    assert rep["scenarios"] == 4  # 1 link x (2 drains x 2 metrics)
    for _ in range(100):
        st_out = _run(live_node, "sweep", "status")
        if "done" in st_out.splitlines()[0]:
            break
        time.sleep(0.2)
    assert "scenarios 4/4" in st_out
    doc = json.loads(_run(live_node, "sweep", "summary", "--json"))
    assert doc["complete"] is True
    assert doc["summary"]["scenarios"] == 4
    # node1 drained: node0's single prefix route to node1 is gone in
    # that world's base, and failing the only link in the identity
    # world withdraws it -> the link ranks as a SPOF
    assert doc["summary"]["spof_links"] == ["node0|node1"]
    table = _run(live_node, "sweep", "summary")
    assert "worst case" in table
    out = json.loads(_run(live_node, "sweep", "cancel"))
    assert out["state"] == "done"  # nothing running: cancel is a no-op


#: a mid-repack fleet sweep, frozen (the coordinator itself is proven
#: in tests/test_fleet_fabric.py — this exercises the ctrl + breeze
#: rendering path for the per-node assignment rows, ISSUE 19)
FLEET_SWEEP_STATUS = {
    "fleet_id": "0ddfab1e00c0ffee",
    "set_hash": "0ddfab1e00c0ffee" * 4,
    "state": "running",
    "nodes_live": 2,
    "nodes_total": 3,
    "worlds_total": 8,
    "worlds_merged": 5,
    "scenarios_total": 96,
    "scenarios_merged": 60,
    "repacked_worlds": 2,
    "rounds": 2,
    "assignments": [
        {"node": "fab0", "round": 0, "worlds": 3, "scenarios": 36,
         "state": "merged"},
        {"node": "fab1", "round": 0, "worlds": 2, "scenarios": 24,
         "state": "lost"},
        {"node": "fab2", "round": 0, "worlds": 3, "scenarios": 36,
         "state": "merged"},
        {"node": "fab0", "round": 1, "worlds": 1, "scenarios": 12,
         "state": "merged"},
        {"node": "fab2", "round": 1, "worlds": 1, "scenarios": 12,
         "state": "running"},
    ],
}


def test_cli_sweep_status_renders_fleet_assignment_rows():
    """`breeze sweep status` with an active fleet sweep appends the
    coordinator header and one row per (node, round) assignment."""

    def ready(net):
        net.nodes["node0"].sweep.attach_fleet(
            lambda: dict(FLEET_SWEEP_STATUS)
        )
        return adj_key("node1") in net.nodes["node0"].kv_store.dump_all(
            "0"
        )

    with _live_ctrl_node(ready=ready) as port:
        out = _run(port, "sweep", "status")
        assert "fleet 0ddfab1e00c0ffee: running" in out
        assert "nodes 2/3" in out and "worlds 5/8" in out
        assert "scenarios 60/96" in out
        assert "repacked=2 rounds=2" in out
        assert "fab1 r0: lost  worlds=2 scenarios=24" in out
        assert "fab2 r1: running  worlds=1 scenarios=12" in out


def test_cli_serving_watch_snapshot_and_stream_stats(live_node):
    """breeze serving watch NODE --deltas 0: one generation-stamped
    snapshot through the ctrl server-stream, then exit; stream-stats
    reflects the (now departed) subscriber."""
    out = _run(live_node, "serving", "watch", "node1", "--deltas", "0")
    snap = json.loads(out)
    assert snap["type"] == "snapshot" and snap["kind"] == "route_db"
    assert snap["reason"] == "subscribe"
    assert isinstance(snap["seq"], int) and snap["generation"]
    assert snap["route_db"]["this_node_name"] == "node1"
    assert snap["route_db"]["unicast_routes"]
    stats = json.loads(_run(live_node, "serving", "stream-stats"))
    assert stats["node"] == "node0"
    assert stats["counters"]["streaming.snapshots"] >= 1
    assert stats["counters"].get("streaming.num_invariant_violations", 0) == 0
    # the watch unsubscribed on exit: no subscriber retained.  The
    # server-side detach runs when the stream's cancellation lands on
    # the node loop — asynchronous wrt a FRESH stats connection, so
    # assert the eventual state, not the first sample
    import time

    for _ in range(50):
        if stats["counters"]["streaming.subscribers"] == 0:
            break
        time.sleep(0.1)
        stats = json.loads(_run(live_node, "serving", "stream-stats"))
    assert stats["counters"]["streaming.subscribers"] == 0


def test_cli_health_status_alerts_slo(live_node):
    """breeze health status/alerts/slo against a live node: the fleet
    rollup (both emulated nodes), the SLO catalog, and an empty alert
    surface on a healthy network."""
    status = json.loads(_run(live_node, "health", "status", "--json"))
    assert status["node"] == "node0" and status["sweeps"] >= 1
    assert {r["node"] for r in status["nodes"]} == {"node0", "node1"}
    assert status["active_alerts"] == []
    assert {s["name"] for s in status["slos"]} == {
        "slo_convergence_p99",
        "slo_serving_queue_wait_p95",
    }
    human = _run(live_node, "health", "status")
    assert "fleet health via node0: 2 nodes, 0 active alerts" in human
    assert "active alerts: none" in human
    alerts = json.loads(_run(live_node, "health", "alerts", "--json"))
    assert alerts["active"] == [] and alerts["log"] == []
    assert "0 active alerts (0 fired, 0 resolved, 0 page dumps)" in _run(
        live_node, "health", "alerts"
    )
    slo_lines = _run(live_node, "health", "slo").splitlines()
    assert any(
        line.startswith("slo_convergence_p99 [page]") for line in slo_lines
    )
    slo_json = json.loads(_run(live_node, "health", "slo", "--json"))
    assert all(s["firing"] is False for s in slo_json)
    # the no-refresh path serves the last sweep without adding one
    cached = json.loads(
        _run(live_node, "health", "status", "--json", "--no-refresh")
    )
    cached2 = json.loads(
        _run(live_node, "health", "status", "--json", "--no-refresh")
    )
    assert cached2["sweeps"] - cached["sweeps"] <= 1  # periodic only
    assert {r["node"] for r in cached["nodes"]} == {"node0", "node1"}


def test_cli_monitor_trajectory(live_node):
    """breeze monitor trajectory: the benchtrack timeline over the
    checked-in artifacts, served by ctrl get_bench_trajectory, with the
    ratchet verdict appended."""
    doc = json.loads(_run(live_node, "monitor", "trajectory", "--json"))
    assert "families" in doc and "check" in doc
    assert doc["orphans"] == []
    conv = doc["families"]["convergence"]
    assert conv["rounds"] and conv["rounds"][0]["round"] == 1
    assert conv["ratcheted"] == ["value"]
    assert doc["check"]["ok"] is True, doc["check"]["problems"]
    human = _run(live_node, "monitor", "trajectory")
    assert "ratchet check: OK" in human
    assert "convergence" in human and "ratcheted" in human


def test_cli_resilience_status_scalar_node(live_node):
    """breeze resilience status on a scalar deployment: no device
    governor, but the FIB agent breaker is always reported."""
    out = _run(live_node, "resilience", "status")
    assert "resilience on node0" in out
    assert "device backend: none" in out
    assert "fib agent: breaker=closed" in out
    st = json.loads(_run(live_node, "resilience", "status", "--json"))
    assert st["device_backend"] == {"present": False}
    assert st["fib_agent"]["state"] == "closed"


def test_cli_resilience_quarantine_and_probe_tpu_node():
    """force-quarantine / force-probe / status against a TPU-backend
    live node: the ctrl verbs drive the governor end to end."""
    with _live_ctrl_node(num_nodes=2, use_tpu_backend=True) as port:
        st = json.loads(_run(port, "resilience", "status", "--json"))
        assert st["device_backend"]["present"]
        assert not st["device_backend"]["quarantined"]
        after = json.loads(
            _run(port, "resilience", "force-quarantine", "--reason", "drill")
        )
        assert after["device_backend"]["quarantined"]
        assert "operator:drill" in after["device_backend"]["quarantine_reason"]
        table = _run(port, "resilience", "status")
        assert "QUARANTINED" in table
        probe = json.loads(_run(port, "resilience", "force-probe"))
        assert "probe" in probe and "status" in probe
        assert {"probed"} <= set(probe["probe"])


def test_cli_resilience_per_device_verbs():
    """force-quarantine/force-probe --device drive ONE chip of the pool
    through the ctrl verbs; status renders the per-device rows."""
    with _live_ctrl_node(num_nodes=2, use_tpu_backend=True) as port:
        after = json.loads(
            _run(
                port, "resilience", "force-quarantine",
                "--reason", "chipdrill", "--device", "2",
            )
        )
        dev = after["device_backend"]
        # one chip drained: the node-level latch stays DOWN
        assert not dev["quarantined"]
        assert dev["pool"]["num_healthy"] == dev["pool"]["size"] - 1
        rows = {r["device"]: r for r in dev["devices"]}
        assert rows[2]["healthy"] is False and rows[2]["injected"]
        assert "operator:chipdrill" in rows[2]["reason"]
        table = _run(port, "resilience", "status")
        assert "devices healthy" in table
        assert "dev2: QUARANTINED" in table
        probe = json.loads(
            _run(port, "resilience", "force-probe", "--device", "2")
        )
        assert "probe" in probe and {"probed"} <= set(probe["probe"])


def test_cli_kvstore_snoop_snapshot(live_node):
    out = _run(
        live_node,
        "kvstore",
        "snoop",
        "--count",
        "1",
        "--prefix",
        "adj:",
        "--print-initial",
    )
    pub = json.loads(out.strip().splitlines()[0])
    assert adj_key("node0") in pub["key_vals"]


def test_cli_tech_support(live_node):
    out = _run(live_node, "tech-support")
    for section in ("version", "routes", "kvstore-summary", "counters"):
        assert f"= {section} =" in out


def test_cli_kvstore_set_key_roundtrip(live_node):
    """set-key must produce a BYTES value (the _value_hex marker) that the
    merge path can hash and compare, and a SECOND set must supersede the
    first (auto version bump — a blind v1 rewrite would be silently
    dropped by the merge; code-review regressions)."""
    _run(live_node, "kvstore", "set-key", "op:canary", "hello-world")
    kv = json.loads(_run(live_node, "kvstore", "key-vals", "op:canary"))
    assert bytes.fromhex(kv["op:canary"]["value"]) == b"hello-world"
    v1 = kv["op:canary"]["version"]
    _run(live_node, "kvstore", "set-key", "op:canary", "second-write")
    kv = json.loads(_run(live_node, "kvstore", "key-vals", "op:canary"))
    assert bytes.fromhex(kv["op:canary"]["value"]) == b"second-write"
    assert kv["op:canary"]["version"] == v1 + 1


def test_cli_negative_drain_values_rejected(live_node):
    """Negative increments / non-positive adjacency metrics would feed
    SPF negative edge weights; the RPC must reject them."""
    r = CliRunner().invoke(
        breeze,
        ["--port", str(live_node), "lm", "set-link-increment", "if0", "--",
         "-10"],
        obj={},
    )
    assert r.exit_code != 0
    r = CliRunner().invoke(
        breeze,
        ["--port", str(live_node), "lm", "set-adj-metric", "if0", "node1",
         "--", "0"],
        obj={},
    )
    assert r.exit_code != 0


def test_cli_graceful_restart_rpc(live_node):
    _run(live_node, "spark", "graceful-restart")
    # the node keeps running; its adjacency view stays served
    out = _run(live_node, "spark", "neighbors")
    assert "node1" in out


def test_fib_agent_cli_commands():
    """breeze fib add/del/routes-installed/counters/alive-since talk to
    the FIB AGENT directly (the reference's fib add/del/sync debug
    commands ride fib_port, not the daemon ctrl)."""
    import asyncio
    import threading

    from click.testing import CliRunner

    from openr_tpu.cli.breeze import breeze
    from openr_tpu.platform.fib_service import (
        FibServiceServer,
        NetlinkFibHandler,
    )
    from openr_tpu.platform.nl import (
        MockNetlinkProtocolSocket,
        NetlinkEventsInjector,
    )

    started = threading.Event()
    info = {}

    def runner():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        stop = asyncio.Event()
        info["loop"], info["stop"] = loop, stop

        async def main():
            nl = MockNetlinkProtocolSocket()
            inj = NetlinkEventsInjector(nl)
            inj.set_link(2, "eth0", True)
            server = FibServiceServer(NetlinkFibHandler(nl))
            await server.start()
            info["port"] = server.port
            started.set()
            await stop.wait()
            await server.stop()

        loop.run_until_complete(main())

    t = threading.Thread(target=runner, daemon=True)
    t.start()
    assert started.wait(10)
    opts = ["--agent-port", str(info["port"])]

    def run(*args):
        r = CliRunner().invoke(breeze, ["fib", *args], obj={})
        assert r.exit_code == 0, r.output
        return r.output

    assert "added" in run("add", "10.9.0.0/24", "eth0@fe80::9", *opts)
    out = run("routes-installed", *opts)
    assert "10.9.0.0/24" in out and "fe80::9" in out
    assert float(run("alive-since", *opts).strip()) > 0
    assert "deleted 1 prefix(es)" in run("del", "10.9.0.0/24", *opts)
    assert "10.9.0.0/24" not in run("routes-installed", *opts)
    run("counters", *opts)
    info["loop"].call_soon_threadsafe(info["stop"].set)
    t.join(10)


# ---- round-4 CLI option depth (reference flag parity) ----------------------


def test_cli_openr_validate(live_node):
    """breeze openr validate: aggregate of every module's checks
    (reference py/openr/cli/clis/openr.py validate)."""
    import time

    # earlier tests may have planted operator keys outside the
    # adj:/prefix: namespaces (op:canary); erase and wait for the
    # tombstone to expire so the kvstore check sees a clean store
    CliRunner().invoke(
        breeze,
        ["--port", str(live_node), "kvstore", "erase-key", "op:canary",
         "--ttl-ms", "100"],
        obj={},
    )
    for _ in range(50):
        if "op:canary" not in _run(live_node, "kvstore", "keys"):
            break
        time.sleep(0.1)
    out = _run(live_node, "openr", "validate")
    for mod in ("spark", "link-monitor", "kvstore", "decision",
                "prefixmgr", "fib"):
        assert f"[PASS] {mod}" in out, out
    # --suppress-error prints only the final OK line when all pass
    out = _run(live_node, "openr", "validate", "--suppress-error")
    assert out.strip() == "all modules validated OK"


def test_cli_config_compare(live_node, tmp_path):
    cfg = _run(live_node, "config", "show")
    same = tmp_path / "same.json"
    same.write_text(cfg)
    assert "configs match" in _run(live_node, "config", "compare", str(same))
    changed = json.loads(cfg)
    changed["domain"] = "other-domain"
    diff = tmp_path / "diff.json"
    diff.write_text(json.dumps(changed))
    r = CliRunner().invoke(
        breeze, ["--port", str(live_node), "config", "compare", str(diff)],
        obj={},
    )
    assert r.exit_code == 1
    assert "domain" in r.output


def test_cli_config_module_views(live_node):
    # no drain ops issued by this test module -> no persisted LM state
    out = _run(live_node, "config", "link-monitor")
    assert "link-monitor" in out or "{" in out
    _run(live_node, "config", "prefix-manager")


def test_cli_monitor_statistics(live_node):
    out = _run(live_node, "monitor", "statistics")
    assert "process." in out or "no process statistics" in out


def test_cli_decision_routes_options(live_node):
    all_dbs = json.loads(_run(live_node, "decision", "routes", "--nodes", "all"))
    assert set(all_dbs) == {"node0", "node1"}
    # prefix filter: keep only node1's loopback
    full = json.loads(_run(live_node, "decision", "routes"))
    dests = [r["dest"] for r in full["unicast_routes"]]
    assert dests
    keep = dests[0]
    filtered = json.loads(_run(live_node, "decision", "routes", keep))
    assert [r["dest"] for r in filtered["unicast_routes"]] == [keep]
    # --labels drops the unicast table
    lab = json.loads(_run(live_node, "decision", "routes", "--labels"))
    assert "unicast_routes" not in lab


def test_cli_decision_adj_options(live_node):
    dbs = json.loads(_run(live_node, "decision", "adj", "--json"))
    assert {db["this_node_name"] for db in dbs} == {"node0", "node1"}
    only0 = json.loads(
        _run(live_node, "decision", "adj", "--json", "--nodes", "node0")
    )
    assert {db["this_node_name"] for db in only0} == {"node0"}
    # a healthy 2-node line is fully bidirectional: --bidir keeps all
    assert all(db["adjacencies"] for db in dbs)
    # --nodes narrowing must NOT defeat the --bidir reverse check: the
    # reverse entries live in the PEERS' dbs, which the filter removes
    # from view (found by the r4 verify drive — a single-node view came
    # back with zero adjacencies)
    assert only0[0]["adjacencies"], "bidir must be computed before --nodes"


def test_cli_decision_path_area(live_node):
    out = _run(
        live_node, "decision", "path", "--src", "node0", "--dst", "node1",
        "--area", "0",
    )
    assert "node0 -> node1" in out
    # nonexistent area -> no traversable nexthops -> zero paths
    out = _run(
        live_node, "decision", "path", "--src", "node0", "--dst", "node1",
        "--area", "no-such-area",
    )
    assert "0 path(s)" in out


def test_cli_fib_routes_options(live_node):
    db = json.loads(_run(live_node, "fib", "routes"))
    dests = [r["dest"] for r in db.get("unicast_routes", [])]
    assert dests
    keep = dests[0]
    filtered = json.loads(_run(live_node, "fib", "routes", "-p", keep))
    assert [r["dest"] for r in filtered["unicast_routes"]] == [keep]
    lab = json.loads(_run(live_node, "fib", "routes", "--labels"))
    assert "unicast_routes" not in lab


def test_cli_lm_links_options(live_node):
    ifaces = json.loads(_run(live_node, "lm", "links"))
    details = ifaces["interface_details"]
    assert all("is_active" in d for d in details.values())
    # nothing is flap-suppressed in a steady-state lab
    sup = json.loads(_run(live_node, "lm", "links", "--only-suppressed"))
    assert sup["interface_details"] == {}


def test_cli_lm_yes_quiet_flags(live_node):
    out = _run(live_node, "lm", "set-link-metric", "if-node0-node1", "77",
               "--yes")
    assert "metric 77 set" in out
    out = _run(live_node, "lm", "unset-link-metric", "if-node0-node1",
               "--yes", "--quiet")
    assert out.strip() == ""


def test_cli_spark_neighbors_detail(live_node):
    nbrs = json.loads(_run(live_node, "spark", "neighbors", "--detail"))
    assert nbrs and nbrs[0]["node_name"] == "node1"
    table = _run(live_node, "spark", "neighbors")
    assert "Neighbor" in table


def test_cli_snoop_duration_bounds_idle_stream(live_node):
    """--duration must terminate the snoop even when NO publication ever
    arrives (the deadline is enforced by asyncio.wait_for around the
    stream, not by a check inside the message loop; code-review r4)."""
    import time

    t0 = time.monotonic()
    _run(live_node, "kvstore", "snoop", "--duration", "1",
         "--prefix", "no-such-prefix:")
    assert time.monotonic() - t0 < 10
    t0 = time.monotonic()
    _run(live_node, "fib", "snoop", "--duration", "1", "--no-initial-dump")
    assert time.monotonic() - t0 < 10


def test_cli_whatif_simultaneous(live_node):
    """breeze decision whatif --simultaneous: all listed links fail at
    once; on a 2-node line failing the only link withdraws node1's
    routes."""
    out = _run(
        live_node, "decision", "whatif", "node0,node1", "--simultaneous"
    )
    assert "node0-node1" in out
    assert "withdrawn" in out or "route(s) change" in out


def test_cli_decision_criticality():
    """breeze decision criticality against a TPU-backend live node."""
    with _live_ctrl_node(
        num_nodes=3,
        use_tpu_backend=True,
        ready=lambda net: len(net.nodes["node0"].fib.get_route_db()) >= 2,
    ) as port:
        out = _run(port, "decision", "criticality", "--pairs", "10")
        # on a 3-node line from node0: node0-node1 withdraws 2 routes,
        # node1-node2 withdraws 1
        assert "node0-node1" in out and "node1-node2" in out
        lines = [l for l in out.splitlines() if l.startswith("node")]
        assert lines[0].startswith("node0-node1")
        assert "double-failure scan" in out


def test_criticality_after_fleet_kernels_compiled():
    """Regression (jax-0.9 executable-cache corruption): a fleet-summary
    on one node used to poison a LATER node's criticality report in the
    same process — the selector's fresh _select_chunk signature drew a
    corrupted cache entry ('supplied 15 buffers but compiled program
    expected 17') and the swallowed ValueError surfaced as a bogus
    'needs the device what-if engine'.  The guarded dispatch must heal
    it (ops/jit_guard.py)."""
    with _live_ctrl_node(
        num_nodes=3,
        use_tpu_backend=True,
        ready=lambda net: len(net.nodes["node0"].fib.get_route_db()) >= 2,
    ) as port:
        _run(port, "decision", "fleet-summary")
    with _live_ctrl_node(
        num_nodes=3,
        use_tpu_backend=True,
        ready=lambda net: len(net.nodes["node0"].fib.get_route_db()) >= 2,
    ) as port:
        out = _run(port, "decision", "criticality", "--pairs", "10")
        assert "node0-node1" in out, out
