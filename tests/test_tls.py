"""TLS on the ctrl server + KvStore peer RPC plane.

Reference parity: thrift-over-TLS via wangle/fizz
(/root/reference/openr/Main.cpp:399-416), cert/key/CA from flags
(/root/reference/openr/common/Flags.cpp:10-37).  Covers: mutual-auth RPC,
plaintext-client rejection, wrong-CA rejection, missing-client-cert
rejection, KvStore full sync + flood over TLS peers, breeze over TLS,
and the non-strict plaintext fallback."""

import asyncio
import datetime
import types as pytypes

import pytest

from openr_tpu.common.runtime import WallClock
from openr_tpu.common.tls import TlsConfig, client_ssl_context, server_ssl_context
from openr_tpu.config import KvStoreConfig
from openr_tpu.ctrl.client import OpenrCtrlClient, OpenrCtrlError
from openr_tpu.ctrl.server import OpenrCtrlServer
from openr_tpu.kvstore.kv_store import KvStore
from openr_tpu.kvstore.transport import TcpKvStoreTransport
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.types import PeerSpec

cryptography = pytest.importorskip("cryptography")


# -- test-cert generation ---------------------------------------------------


def _make_key():
    from cryptography.hazmat.primitives.asymmetric import ec

    return ec.generate_private_key(ec.SECP256R1())


def _name(cn):
    from cryptography import x509
    from cryptography.x509.oid import NameOID

    return x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])


def _write_pem(path, key, cert):
    from cryptography.hazmat.primitives import serialization

    path.with_suffix(".key").write_bytes(
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        )
    )
    path.with_suffix(".pem").write_bytes(
        cert.public_bytes(serialization.Encoding.PEM)
    )


def make_pki(tmp_path, ca_cn="openr-test-ca"):
    """CA + 'node' leaf cert (signed) + a SECOND independent CA for
    negative tests.  Returns dict of paths."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes

    now = datetime.datetime.now(datetime.timezone.utc)

    def make_ca(cn, path):
        key = _make_key()
        cert = (
            x509.CertificateBuilder()
            .subject_name(_name(cn))
            .issuer_name(_name(cn))
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=1))
            .add_extension(x509.BasicConstraints(ca=True, path_length=0), True)
            .sign(key, hashes.SHA256())
        )
        _write_pem(path, key, cert)
        return key, cert

    def make_leaf(cn, ca_key, ca_cert, path):
        key = _make_key()
        cert = (
            x509.CertificateBuilder()
            .subject_name(_name(cn))
            .issuer_name(ca_cert.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=1))
            .add_extension(
                x509.SubjectAlternativeName([x509.DNSName("localhost")]),
                False,
            )
            .sign(ca_key, hashes.SHA256())
        )
        _write_pem(path, key, cert)

    ca_key, ca_cert = make_ca(ca_cn, tmp_path / "ca")
    make_leaf("node-a", ca_key, ca_cert, tmp_path / "node_a")
    make_leaf("node-b", ca_key, ca_cert, tmp_path / "node_b")
    make_ca("other-ca", tmp_path / "other_ca")
    return {
        "ca": str(tmp_path / "ca.pem"),
        "other_ca": str(tmp_path / "other_ca.pem"),
        "a_cert": str(tmp_path / "node_a.pem"),
        "a_key": str(tmp_path / "node_a.key"),
        "b_cert": str(tmp_path / "node_b.pem"),
        "b_key": str(tmp_path / "node_b.key"),
    }


def tls_cfg(pki, who="a", **kw):
    return TlsConfig(
        enabled=True,
        cert_path=pki[f"{who}_cert"],
        key_path=pki[f"{who}_key"],
        ca_path=pki["ca"],
        **kw,
    )


def make_store(name: str, tls=None) -> KvStore:
    return KvStore(
        node_name=name,
        clock=WallClock(),
        config=KvStoreConfig(),
        areas=["0"],
        transport=TcpKvStoreTransport(tls=tls),
        publications_queue=ReplicateQueue(f"{name}.pubs"),
    )


async def serve_store(store: KvStore, tls=None) -> OpenrCtrlServer:
    node_stub = pytypes.SimpleNamespace(kv_store=store)
    server = OpenrCtrlServer(node_stub, port=0, tls=tls)
    await server.start()
    return server


def test_context_builders(tmp_path):
    pki = make_pki(tmp_path)
    assert server_ssl_context(None) is None
    assert server_ssl_context(TlsConfig()) is None  # disabled = plaintext
    assert server_ssl_context(tls_cfg(pki)) is not None
    assert client_ssl_context(tls_cfg(pki)) is not None
    # DEFAULT is fail-closed: enabled + unusable certs must refuse to
    # start rather than silently downgrade the mutation/LSDB plane to
    # plaintext (ADVICE r3; the reference's wangle/fizz behavior)
    missing = TlsConfig(enabled=True, cert_path="/nope", key_path="/nope")
    with pytest.raises(FileNotFoundError):
        server_ssl_context(missing)
    with pytest.raises(FileNotFoundError):
        client_ssl_context(
            TlsConfig(enabled=True, ca_path="/nope", strict=True)
        )
    # lab bringup: explicit strict=False opt-in falls back to plaintext
    lab = TlsConfig(
        enabled=True, cert_path="/nope", key_path="/nope", strict=False
    )
    assert server_ssl_context(lab) is None


def test_ctrl_rpc_mutual_tls(tmp_path):
    pki = make_pki(tmp_path)

    async def run():
        store = make_store("a")
        store.start()
        server = await serve_store(store, tls=tls_cfg(pki, "a"))
        assert server.tls_active
        try:
            # good client (mTLS cert signed by the CA)
            async with OpenrCtrlClient(
                port=server.port, tls=tls_cfg(pki, "b")
            ) as c:
                keys = await c.call("get_kv_store_area_summaries")
                assert isinstance(keys, (dict, list))

            # plaintext client must NOT get through
            with pytest.raises((OpenrCtrlError, OSError, asyncio.TimeoutError)):
                async with OpenrCtrlClient(port=server.port) as c:
                    await asyncio.wait_for(
                        c.call("get_kv_store_area_summaries"), 3.0
                    )

            # client trusting a different CA refuses the server cert
            import ssl as _ssl

            wrong = TlsConfig(
                enabled=True,
                cert_path=pki["b_cert"],
                key_path=pki["b_key"],
                ca_path=pki["other_ca"],
            )
            with pytest.raises((_ssl.SSLError, ConnectionError, OSError)):
                await OpenrCtrlClient(port=server.port, tls=wrong).connect()

            # client WITHOUT a cert fails the mutual-auth handshake
            nocert = TlsConfig(enabled=True, ca_path=pki["ca"])
            with pytest.raises(
                (_ssl.SSLError, ConnectionError, OSError, OpenrCtrlError)
            ):
                c = await OpenrCtrlClient(port=server.port, tls=nocert).connect()
                await asyncio.wait_for(
                    c.call("get_kv_store_area_summaries"), 3.0
                )
        finally:
            await store.stop()
            await server.stop()

    asyncio.run(run())


def test_kvstore_sync_and_flood_over_tls(tmp_path):
    """The LSDB plane over mTLS peers: full sync + incremental flood."""
    pki = make_pki(tmp_path)

    async def run():
        a = make_store("a", tls=tls_cfg(pki, "a"))
        b = make_store("b", tls=tls_cfg(pki, "b"))
        a.start()
        b.start()
        sa = await serve_store(a, tls=tls_cfg(pki, "a"))
        sb = await serve_store(b, tls=tls_cfg(pki, "b"))
        assert sa.tls_active and sb.tls_active
        try:
            a.areas["0"].persist_self_originated_key("prefix:a", b"va")
            a.areas["0"].add_peers(
                {"b": PeerSpec(peer_addr="127.0.0.1", ctrl_port=sb.port)}
            )
            b.areas["0"].add_peers(
                {"a": PeerSpec(peer_addr="127.0.0.1", ctrl_port=sa.port)}
            )
            for _ in range(100):
                await asyncio.sleep(0.05)
                if "prefix:a" in b.areas["0"].key_vals:
                    break
            assert "prefix:a" in b.areas["0"].key_vals

            b.areas["0"].persist_self_originated_key("prefix:b", b"vb")
            for _ in range(100):
                await asyncio.sleep(0.05)
                if "prefix:b" in a.areas["0"].key_vals:
                    break
            assert "prefix:b" in a.areas["0"].key_vals
        finally:
            await a.stop()
            await b.stop()
            await a.transport.close()
            await b.transport.close()
            await sa.stop()
            await sb.stop()

    asyncio.run(run())


def test_breeze_over_tls(tmp_path):
    """The operator CLI connects with --cert/--key/--ca.  The TLS server
    runs on a background thread's loop because breeze drives its own
    event loop per invocation."""
    import threading

    from click.testing import CliRunner

    from openr_tpu.cli.breeze import breeze

    pki = make_pki(tmp_path)
    started = threading.Event()
    holder = {}

    def server_thread():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        async def main():
            store = make_store("a")
            store.start()
            server = await serve_store(store, tls=tls_cfg(pki, "a"))
            holder["port"] = server.port
            holder["stop"] = stop = asyncio.Event()
            holder["loop"] = loop
            started.set()
            await stop.wait()
            await store.stop()
            await server.stop()

        loop.run_until_complete(main())
        loop.close()

    t = threading.Thread(target=server_thread, daemon=True)
    t.start()
    assert started.wait(10)
    try:
        result = CliRunner().invoke(
            breeze,
            [
                "--port", str(holder["port"]),
                "--cert", pki["b_cert"],
                "--key", pki["b_key"],
                "--ca", pki["ca"],
                "kvstore", "summary",
            ],
        )
        assert result.exit_code == 0, result.output
        # and without certs it must fail against the TLS server
        result = CliRunner().invoke(
            breeze, ["--port", str(holder["port"]), "kvstore", "summary"]
        )
        assert result.exit_code != 0
    finally:
        holder["loop"].call_soon_threadsafe(holder["stop"].set)
        t.join(timeout=10)
