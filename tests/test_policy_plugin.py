"""PolicyManager, Plugin boundary, and NeighborMonitor tests.

Reference parity: openr/policy/PolicyManager (apply at origination +
area import), openr/plugin/Plugin.h hooks, openr/neighbor-monitor
AddressEvent -> Spark fast neighbor teardown.
"""

import asyncio

from openr_tpu.common.runtime import SimClock
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.neighbor_monitor import NeighborMonitor
from openr_tpu.plugin import Plugin, PluginArgs, PluginManager
from openr_tpu.policy import (
    FilterAction,
    FilterCriteria,
    PolicyConfig,
    PolicyDefinition,
    PolicyManager,
    PolicyStatement,
    PrefixMatch,
)
from openr_tpu.types import PrefixEntry, PrefixEventType


def make_policy_manager():
    return PolicyManager(
        PolicyConfig(
            definitions=[
                PolicyDefinition(
                    name="import-from-spine",
                    statements=[
                        PolicyStatement(
                            name="reject-private",
                            criteria=[
                                FilterCriteria(
                                    prefixes=[
                                        PrefixMatch(
                                            prefix="10.0.0.0/8", ge=8, le=32
                                        )
                                    ]
                                )
                            ],
                            action=FilterAction(accept=False),
                        ),
                        PolicyStatement(
                            name="tag-and-prefer",
                            criteria=[FilterCriteria(always_match=True)],
                            action=FilterAction(
                                accept=True,
                                set_path_preference=700,
                                add_tags=["FROM_SPINE"],
                            ),
                        ),
                    ],
                )
            ]
        )
    )


class TestPolicyManager:
    def test_first_match_wins_and_reject(self):
        pm = make_policy_manager()
        rejected, hit = pm.apply_policy(
            "import-from-spine", PrefixEntry(prefix="10.1.0.0/24")
        )
        assert rejected is None
        assert hit == "reject-private"

    def test_action_rewrites_without_mutating_input(self):
        pm = make_policy_manager()
        entry = PrefixEntry(prefix="2001:db8::/64", tags={"ORIG"})
        out, hit = pm.apply_policy("import-from-spine", entry)
        assert hit == "tag-and-prefer"
        assert out.metrics.path_preference == 700
        assert out.tags == {"ORIG", "FROM_SPINE"}
        # input untouched (entries are shared across areas)
        assert entry.metrics.path_preference != 700
        assert entry.tags == {"ORIG"}

    def test_unknown_policy_accepts_unchanged(self):
        pm = make_policy_manager()
        entry = PrefixEntry(prefix="2001:db8::/64")
        out, hit = pm.apply_policy("nope", entry)
        assert out is entry
        assert hit == ""

    def test_implicit_deny_when_nothing_matches(self):
        pm = PolicyManager(
            PolicyConfig(
                definitions=[
                    PolicyDefinition(
                        name="only-v4",
                        statements=[
                            PolicyStatement(
                                name="v4",
                                criteria=[
                                    FilterCriteria(
                                        prefixes=[
                                            PrefixMatch(
                                                prefix="0.0.0.0/0", ge=0, le=32
                                            )
                                        ]
                                    )
                                ],
                            )
                        ],
                    )
                ]
            )
        )
        out, _ = pm.apply_policy("only-v4", PrefixEntry(prefix="2001:db8::/64"))
        assert out is None

    def test_prefix_range_semantics(self):
        m = PrefixMatch(prefix="10.0.0.0/8", ge=16, le=24)
        assert m.matches("10.1.0.0/16")
        assert m.matches("10.1.2.0/24")
        assert not m.matches("10.0.0.0/8")  # too short
        assert not m.matches("10.1.2.3/32")  # too long
        assert not m.matches("11.0.0.0/16")  # outside
        exact = PrefixMatch(prefix="192.168.0.0/16")
        assert exact.matches("192.168.0.0/16")
        assert not exact.matches("192.168.1.0/24")

    def test_igp_cost_window(self):
        crit = FilterCriteria(igp_cost_min=10, igp_cost_max=100)
        e = PrefixEntry(prefix="2001:db8::/64")
        assert crit.matches(e, igp_cost=50)
        assert not crit.matches(e, igp_cost=5)
        assert not crit.matches(e, igp_cost=500)


class TestPluginManager:
    def test_plugin_lifecycle_and_queue_access(self):
        class AdvertisePlugin(Plugin):
            def __init__(self):
                self.started = False

            async def start(self, args: PluginArgs):
                self.started = True
                self.args = args
                # advertise through the queue like the VIP plugin would
                from openr_tpu.types import PrefixEvent

                args.prefix_updates_queue.push(
                    PrefixEvent(
                        event_type=PrefixEventType.ADD_PREFIXES,
                        prefixes=[PrefixEntry(prefix="203.0.113.0/24")],
                    )
                )

            async def stop(self):
                self.started = False

        async def run():
            mgr = PluginManager()
            plugin_holder = []

            def factory():
                p = AdvertisePlugin()
                plugin_holder.append(p)
                return p

            mgr.register(factory)
            q = ReplicateQueue("prefixUpdates")
            reader = q.get_reader()
            args = PluginArgs(
                node_name="n1", config=None, prefix_updates_queue=q
            )
            await mgr.start_all(args)
            assert plugin_holder[0].started
            ev = await reader.get()
            assert ev.prefixes[0].prefix == "203.0.113.0/24"
            await mgr.stop_all()
            assert not plugin_holder[0].started

        asyncio.run(run())


class TestNeighborMonitor:
    def test_address_events_reach_queue(self):
        async def run():
            clock = SimClock()
            q = ReplicateQueue("addrEvents")
            reader = q.get_reader()
            mon = NeighborMonitor(clock, q)
            mon.start()
            mon.report_address("fe80::1", is_reachable=False)
            ev = await reader.get()
            assert ev.address == "fe80::1"
            assert not ev.is_reachable
            await mon.stop()

        asyncio.run(run())

    def test_nl_neighbor_translation(self):
        from openr_tpu.platform.nl.codec import NlNeighbor

        async def run():
            clock = SimClock()
            addr_q = ReplicateQueue("addrEvents")
            nl_q = ReplicateQueue("nlNeigh")
            reader = addr_q.get_reader()
            mon = NeighborMonitor(
                clock, addr_q, nl_neighbor_reader=nl_q.get_reader()
            )
            mon.start()
            nl_q.push(NlNeighbor(if_index=2, address="fe80::9", state=0x20))
            await clock.run_for(0.1)
            ev = reader.try_get()
            assert ev is not None and not ev.is_reachable
            await mon.stop()

        asyncio.run(run())


class TestPrefixManagerPolicyIntegration:
    def test_origination_and_import_policies(self):
        """Origination policy rejects one aggregate; area import policy
        rewrites path preference on redistribution into area B only."""
        import dataclasses

        from openr_tpu.config import OriginatedPrefix
        from openr_tpu.decision.rib import DecisionRouteUpdate, RibUnicastEntry
        from openr_tpu.prefix_manager.prefix_manager import (
            PrefixManager,
            deserialize_prefix_db,
        )
        from openr_tpu.types import KvRequestType, NextHop

        policy = PolicyManager(
            PolicyConfig(
                definitions=[
                    PolicyDefinition(
                        name="no-test-nets",
                        statements=[
                            PolicyStatement(
                                name="drop-test",
                                criteria=[
                                    FilterCriteria(
                                        prefixes=[
                                            PrefixMatch(
                                                prefix="198.51.100.0/24",
                                                ge=24,
                                                le=32,
                                            )
                                        ]
                                    )
                                ],
                                action=FilterAction(accept=False),
                            ),
                            PolicyStatement(
                                name="rest",
                                criteria=[FilterCriteria(always_match=True)],
                            ),
                        ],
                    ),
                    PolicyDefinition(
                        name="b-import",
                        statements=[
                            PolicyStatement(
                                name="prefer",
                                criteria=[FilterCriteria(always_match=True)],
                                action=FilterAction(
                                    set_path_preference=900,
                                    add_tags=["VIA_B_IMPORT"],
                                ),
                            )
                        ],
                    ),
                ]
            )
        )

        async def run():
            clock = SimClock()
            kv_q = ReplicateQueue("kvreq")
            kv_r = kv_q.get_reader()
            fib_q = ReplicateQueue("fibUpdates")
            pm = PrefixManager(
                node_name="me",
                clock=clock,
                kv_request_queue=kv_q,
                fib_route_updates_reader=fib_q.get_reader(),
                areas=["A", "B"],
                originated_prefixes=[
                    OriginatedPrefix(
                        prefix="198.51.100.0/24",
                        origination_policy="no-test-nets",
                    ),
                    OriginatedPrefix(prefix="203.0.113.0/24"),
                ],
                policy_manager=policy,
                area_import_policies={"B": "b-import"},
            )
            pm.start()
            await clock.run_for(0.5)
            reqs = [kv_r.try_get() for _ in range(kv_r.size())]
            persists = [
                r for r in reqs if r.request_type == KvRequestType.PERSIST_KEY
            ]
            # the policy-rejected aggregate is never advertised
            advertised = {deserialize_prefix_db(r.value).prefix_entries[0].prefix
                          for r in persists}
            assert "203.0.113.0/24" in advertised
            assert "198.51.100.0/24" not in advertised

            # redistribution A->B goes through b-import
            entry = RibUnicastEntry(
                prefix="10.5.0.0/24",
                nexthops={NextHop(address="fe80::1")},
                best_prefix_entry=PrefixEntry("10.5.0.0/24"),
                best_area="A",
                igp_cost=3,
            )
            fib_q.push(
                DecisionRouteUpdate(
                    unicast_routes_to_update={"10.5.0.0/24": entry}
                )
            )
            await clock.run_for(0.5)
            reqs = [kv_r.try_get() for _ in range(kv_r.size())]
            redist = [
                r
                for r in reqs
                if r.request_type == KvRequestType.PERSIST_KEY
                and "10.5.0.0" in r.key
            ]
            assert len(redist) == 1 and redist[0].area == "B"
            db = deserialize_prefix_db(redist[0].value)
            assert db.prefix_entries[0].metrics.path_preference == 900
            assert "VIA_B_IMPORT" in db.prefix_entries[0].tags
            await pm.stop()

        asyncio.run(run())


def test_prefix_match_ge_without_le_goes_to_addrlen():
    m = PrefixMatch(prefix="10.0.0.0/8", ge=16)
    assert m.matches("10.1.0.0/16")
    assert m.matches("10.1.2.3/32")
    assert not m.matches("10.0.0.0/8")
    m6 = PrefixMatch(prefix="2001:db8::/32", ge=48)
    assert m6.matches("2001:db8:1::/64")
    assert m6.matches("2001:db8::1/128")


def test_neighbor_monitor_ignores_transient_churn():
    import asyncio as aio

    from openr_tpu.common.runtime import SimClock
    from openr_tpu.platform.nl.codec import NlNeighbor

    async def run():
        clock = SimClock()
        addr_q = ReplicateQueue("addrEvents")
        nl_q = ReplicateQueue("nlNeigh")
        reader = addr_q.get_reader()
        mon = NeighborMonitor(
            clock, addr_q, nl_neighbor_reader=nl_q.get_reader()
        )
        mon.start()
        # GC delete and INCOMPLETE (0x01) must NOT produce events
        nl_q.push(NlNeighbor(if_index=2, address="fe80::9", state=0x02,
                             is_del=True))
        nl_q.push(NlNeighbor(if_index=2, address="fe80::9", state=0x01))
        await clock.run_for(0.1)
        assert reader.try_get() is None
        # NUD_FAILED -> unreachable
        nl_q.push(NlNeighbor(if_index=2, address="fe80::9", state=0x20))
        await clock.run_for(0.1)
        ev = reader.try_get()
        assert ev is not None and not ev.is_reachable
        await mon.stop()

    aio.run(run())
