"""Netlink platform layer tests: native codec round-trip, mock kernel,
and (permission-gated) real AF_NETLINK dumps.

Reference test parity: openr/nl/tests/NetlinkProtocolSocketTest.cpp and
openr/tests/mocks/MockNetlinkProtocolSocket.h usage.
"""

import asyncio
import socket as pysocket

import pytest

from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.platform.nl import (
    LabelAction,
    MockNetlinkProtocolSocket,
    NetlinkEventsInjector,
    NlCodec,
    NlNexthop,
    NlRoute,
)
from openr_tpu.platform.nl.codec import NlAck, NlAddr, NlLink, RTM_GETLINK


@pytest.fixture(scope="module")
def codec():
    return NlCodec()


def roundtrip_route(codec, route, **kw):
    data = codec.encode_route(route, **kw)
    msgs = codec.decode(data)
    assert len(msgs) == 1
    decoded, is_del = msgs[0]
    return decoded, is_del


class TestCodecRoundtrip:
    def test_v4_single_nexthop(self, codec):
        r = NlRoute(
            prefix="10.1.0.0/24",
            nexthops=[NlNexthop(gateway="10.0.0.1", if_index=3)],
            priority=10,
        )
        d, is_del = roundtrip_route(codec, r)
        assert not is_del
        assert d.prefix == "10.1.0.0/24"
        assert d.priority == 10
        assert d.protocol == 99
        assert len(d.nexthops) == 1
        assert d.nexthops[0].gateway == "10.0.0.1"
        assert d.nexthops[0].if_index == 3

    def test_v6_multipath(self, codec):
        r = NlRoute(
            prefix="2001:db8:1::/64",
            nexthops=[
                NlNexthop(gateway="fe80::1", if_index=2, weight=1),
                NlNexthop(gateway="fe80::2", if_index=4, weight=2),
            ],
        )
        d, _ = roundtrip_route(codec, r)
        assert d.prefix == "2001:db8:1::/64"
        assert len(d.nexthops) == 2
        assert {nh.gateway for nh in d.nexthops} == {"fe80::1", "fe80::2"}
        assert {nh.if_index for nh in d.nexthops} == {2, 4}
        assert {nh.weight for nh in d.nexthops} == {1, 2}

    def test_v4_mpls_push_encap(self, codec):
        r = NlRoute(
            prefix="10.2.0.0/16",
            nexthops=[
                NlNexthop(
                    gateway="10.0.0.2",
                    if_index=5,
                    label_action=LabelAction.PUSH,
                    labels=(100101, 100102),
                )
            ],
        )
        d, _ = roundtrip_route(codec, r)
        nh = d.nexthops[0]
        assert nh.label_action == LabelAction.PUSH
        assert nh.labels == (100101, 100102)
        assert nh.gateway == "10.0.0.2"

    def test_mpls_swap_route(self, codec):
        r = NlRoute(
            label=100200,
            nexthops=[
                NlNexthop(
                    gateway="fe80::9",
                    if_index=7,
                    label_action=LabelAction.SWAP,
                    labels=(100300,),
                )
            ],
        )
        d, _ = roundtrip_route(codec, r)
        assert d.label == 100200
        assert d.prefix is None
        nh = d.nexthops[0]
        assert nh.label_action == LabelAction.SWAP
        assert nh.labels == (100300,)
        assert nh.gateway == "fe80::9"

    def test_mpls_php_route(self, codec):
        # PHP: pop-and-forward, no NEWDST stack
        r = NlRoute(
            label=100400,
            nexthops=[NlNexthop(gateway="fe80::a", if_index=2,
                                label_action=LabelAction.PHP)],
        )
        d, _ = roundtrip_route(codec, r)
        assert d.label == 100400
        assert d.nexthops[0].gateway == "fe80::a"
        assert d.nexthops[0].labels == ()

    def test_delete_flag(self, codec):
        r = NlRoute(prefix="10.3.0.0/24", nexthops=[NlNexthop(if_index=1)])
        _, is_del = roundtrip_route(codec, r, is_del=True)
        assert is_del

    def test_addr_roundtrip(self, codec):
        data = codec.encode_addr(4, "192.168.1.7/24")
        msgs = codec.decode(data)
        assert len(msgs) == 1
        a = msgs[0]
        assert isinstance(a, NlAddr)
        assert a.if_index == 4
        assert a.prefix == "192.168.1.7/24"
        assert not a.is_del

    def test_dump_encode(self, codec):
        data = codec.encode_dump(RTM_GETLINK, seq=42)
        assert len(data) >= 16
        # nlmsg header: len, type, flags, seq
        import struct

        ln, typ, flags, seq = struct.unpack_from("=IHHI", data)
        assert ln == len(data)
        assert typ == RTM_GETLINK
        assert seq == 42
        assert flags & 0x300  # NLM_F_ROOT|NLM_F_MATCH (DUMP)

    def test_large_ecmp_width(self, codec):
        r = NlRoute(
            prefix="10.9.0.0/24",
            nexthops=[
                NlNexthop(gateway=f"10.0.{i}.1", if_index=i + 1)
                for i in range(64)
            ],
        )
        d, _ = roundtrip_route(codec, r)
        assert len(d.nexthops) == 64


class TestMockNetlink:
    def test_routes_and_failure_injection(self):
        async def run():
            nl = MockNetlinkProtocolSocket()
            r = NlRoute(prefix="10.0.0.0/24", nexthops=[NlNexthop(if_index=1)])
            await nl.add_route(r)
            assert len(await nl.get_all_routes()) == 1
            assert await nl.get_all_routes(protocol=99)
            assert not await nl.get_all_routes(protocol=3)
            nl.fail = True
            with pytest.raises(OSError):
                await nl.add_route(r)
            nl.fail = False
            await nl.delete_route(r)
            assert not await nl.get_all_routes()

        asyncio.run(run())

    def test_injector_interface_events(self):
        async def run():
            q = ReplicateQueue("netlinkEvents")
            reader = q.get_reader()
            nl = MockNetlinkProtocolSocket(events_queue=q)
            inj = NetlinkEventsInjector(nl)
            inj.set_link(2, "eth0", True)
            inj.add_address(2, "fe80::1/64")
            ev1 = await reader.get()
            ev2 = await reader.get()
            assert ev1.if_name == "eth0" and ev1.is_up
            assert ev2.networks == ["fe80::1/64"]
            # merged view
            infos = await nl.get_all_interfaces()
            assert len(infos) == 1
            assert infos[0].networks == ["fe80::1/64"]
            inj.set_link(2, "eth0", False)
            ev3 = await reader.get()
            assert not ev3.is_up

        asyncio.run(run())


def _can_open_netlink() -> bool:
    try:
        s = pysocket.socket(pysocket.AF_NETLINK, pysocket.SOCK_RAW, 0)
        s.close()
        return True
    except OSError:
        return False


@pytest.mark.skipif(not _can_open_netlink(), reason="no AF_NETLINK access")
class TestRealNetlink:
    def test_get_all_links_and_interfaces(self):
        from openr_tpu.platform.nl import NetlinkProtocolSocket

        async def run():
            nl = NetlinkProtocolSocket()
            try:
                nl.start()
                links = await nl.get_all_links()
                # every kernel has at least loopback
                assert any(l.if_name == "lo" for l in links)
                infos = await nl.get_all_interfaces()
                lo = next(i for i in infos if i.if_name == "lo")
                assert lo.if_index > 0
            finally:
                nl.close()

        asyncio.run(run())
