"""Trajectory suite sweep: the tier-1 256-node smoke variant, the
byte-identical replay contract, and the slow-marked full-scale sweep.

The checked-in BENCH_TRAJECTORY artifact is schema-gated through the
benchtrack manifest (tests/test_bench_artifacts.py); these tests prove
the SWEEP itself live — a seeded chaos flap/drain run through the
SimClock emulation converges, scores only chaos-driven samples, fires
zero unexpected alerts, warm-starts every perturbation tick, and
replays byte for byte from one seed.
"""

import pytest

import bench

pytestmark = [pytest.mark.chaos]


def test_smoke_suite_sweep_256_grid():
    """The tier-1 smoke variant: the grid class at 256 nodes (the
    full-scale 1k+ sweeps are `slow`)."""
    detail, fingerprint = bench.suite_sweep_class(
        "grid",
        bench.SUITE_SMOKE_SCALE,
        bench.SUITE_SEED,
        flaps=4,
        drains=1,
        phase_shares=False,
    )
    assert detail["nodes"] == 256
    conv = detail["convergence"]
    assert conv["samples"] > 0
    assert 0 < conv["p50_ms"] <= conv["p95_ms"] <= conv["p99_ms"]
    assert conv["p99_ms"] <= detail["slo"]["convergence_slo_ms"]
    assert detail["slo"]["p99_within_slo"] is True
    # every flap/drain tick must take the warm generation-delta path
    assert detail["warm"]["hits"] >= 1
    assert detail["warm"]["hit_ratio"] == 1.0
    assert detail["warm"]["cold_fallbacks"] == 0
    # chaos-clean fidelity: a flap/drain sweep on a path-redundant
    # class fires NO health alerts
    assert detail["alerts"]["unexpected"] == 0
    assert detail["alerts"]["health_sweeps"] >= 1
    assert fingerprint


def test_smoke_replay_byte_identical():
    """SimClock determinism: two sweeps from one seed produce the
    identical fingerprint (alert JSONL + chaos counter dump +
    convergence histogram buckets) AND the identical detail block."""
    runs = [
        bench.suite_sweep_class(
            "grid", 64, 11, flaps=3, drains=1, phase_shares=False
        )
        for _ in range(2)
    ]
    assert runs[0][1] == runs[1][1]
    assert runs[0][0] == runs[1][0]


def test_distinct_seeds_change_the_sweep():
    """The seed is load-bearing: a different seed must pick a
    different flap/drain schedule (fingerprints diverge)."""
    a = bench.suite_sweep_class(
        "grid", 64, 11, flaps=3, drains=1, phase_shares=False
    )
    b = bench.suite_sweep_class(
        "grid", 64, 12, flaps=3, drains=1, phase_shares=False
    )
    assert a[1] != b[1]


@pytest.mark.slow
@pytest.mark.parametrize("cls", bench.SUITE_CLASSES)
def test_full_scale_suite_sweep(cls):
    """The 1k+-node per-class sweep the checked-in artifact records —
    hours-class on a loaded host, hence `slow`."""
    detail, _fp = bench.suite_sweep_class(
        cls, bench.SUITE_FULL_SCALE, bench.SUITE_SEED
    )
    assert detail["nodes"] >= bench.SUITE_MIN_FULL_NODES
    assert detail["convergence"]["samples"] > 0
    assert detail["alerts"]["unexpected"] == 0
    assert detail["warm"]["hit_ratio"] >= 0.9
    assert detail["pipeline_phase_share_pct"]
