"""Device-backed KSP2_ED_ECMP differential tests.

The device path (decision/ksp2.py: batched masked re-solves + host trace
over device distance fields) must be bit-identical to the scalar chain
(LinkState.get_kth_paths / SpfSolver._select_best_paths_ksp2, mirroring
LinkState.cpp:675-699 + SpfSolver KSP2 semantics).
"""

import pytest

from openr_tpu.decision.backend import ScalarBackend, TpuBackend
from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.emulation.topology import (
    build_adj_dbs,
    fabric_edges,
    grid_edges,
    random_connected_edges,
)
from openr_tpu.types import (
    PrefixEntry,
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
    PrefixMetrics,
)

KSP2 = PrefixForwardingAlgorithm.KSP2_ED_ECMP
SR_MPLS = PrefixForwardingType.SR_MPLS


def make_ls(edges, area="0", me="", **kwargs) -> LinkState:
    ls = LinkState(area, me)
    for db in build_adj_dbs(edges, area=area, **kwargs).values():
        ls.update_adjacency_database(db)
    return ls


def _nh_view(entry):
    return sorted(
        (
            nh.neighbor_node_name,
            nh.if_name,
            nh.metric,
            nh.area,
            None
            if nh.mpls_action is None
            else (nh.mpls_action.action, nh.mpls_action.push_labels),
        )
        for nh in entry.nexthops
    )


def _db_view(db):
    assert db is not None
    return {
        p: (round(e.igp_cost, 1), e.best_area, _nh_view(e))
        for p, e in db.unicast_routes.items()
    }


def assert_backends_match(area_link_states, ps, me="node0", **solver_kwargs):
    scalar = ScalarBackend(SpfSolver(me, **solver_kwargs)).build_route_db(
        area_link_states, ps
    )
    # fresh LinkStates for the device run so scalar memoization cannot leak
    backend = TpuBackend(SpfSolver(me, **solver_kwargs))
    tpu = backend.build_route_db(area_link_states, ps)
    assert _db_view(tpu) == _db_view(scalar)
    return backend


def test_engine_seeded_paths_match_scalar_kth_paths():
    """k=2 paths traced from device distance fields == scalar get_kth_paths."""
    from openr_tpu.decision.ksp2 import Ksp2DeviceEngine
    from openr_tpu.ops.csr import encode_link_state

    edges = fabric_edges(num_pods=2, rsws_per_pod=3, fsws_per_pod=2, num_ssws=4)
    ls_dev = make_ls(edges)
    ls_ref = make_ls(edges)
    root = "rsw0_0"
    dests = [n for n in ls_ref.get_adjacency_databases() if n != root]

    topo = encode_link_state(ls_dev)
    eng = Ksp2DeviceEngine(ls_dev, topo, root)
    eng.seed(dests)
    assert eng.num_device_batches == 1
    assert eng.num_seeded == len(dests)

    for d in dests:
        seeded = ls_dev.get_kth_paths(root, d, 2)
        scalar = ls_ref.get_kth_paths(root, d, 2)
        assert seeded == scalar, d
    # k=1 untouched by seeding: also identical
    for d in dests:
        assert ls_dev.get_kth_paths(root, d, 1) == ls_ref.get_kth_paths(
            root, d, 1
        ), d


def test_engine_seed_is_memoized_until_topology_change():
    from openr_tpu.decision.ksp2 import Ksp2DeviceEngine
    from openr_tpu.ops.csr import encode_link_state

    ls = make_ls(grid_edges(3))
    topo = encode_link_state(ls)
    eng = Ksp2DeviceEngine(ls, topo, "node0")
    eng.seed(["node8", "node4"])
    assert eng.num_device_batches == 1
    eng.seed(["node8", "node4"])  # memo hit: no second device call
    assert eng.num_device_batches == 1
    eng.seed(["node8", "node4", "node7"])  # only the new dest solves
    assert eng.num_device_batches == 2


def test_tpu_backend_ksp2_fabric_matches_scalar():
    edges = fabric_edges(num_pods=3, rsws_per_pod=4, fsws_per_pod=2, num_ssws=4)
    nodes = sorted({n for e in edges for n in e[:2]})
    ps = PrefixState()
    for i, n in enumerate(r for r in nodes if r.startswith("rsw")):
        ps.update_prefix(
            n,
            "0",
            PrefixEntry(f"10.{i}.0.0/24", forwarding_algorithm=KSP2),
        )
    backend = assert_backends_match({"0": make_ls(edges)}, ps, me="rsw0_0")
    assert backend.num_scalar_builds == 0
    assert backend.num_device_builds == 1


def test_tpu_backend_ksp2_sr_mpls_label_stacks():
    # labels pin the non-shortest path: stacks must match scalar exactly
    edges = fabric_edges(num_pods=2, rsws_per_pod=2, fsws_per_pod=2, num_ssws=2)
    nodes = sorted({n for e in edges for n in e[:2]})
    labels = {n: 100 + i for i, n in enumerate(nodes)}

    def mk(me):
        ls = LinkState("0", me)
        for db in build_adj_dbs(edges, node_labels=labels).values():
            ls.update_adjacency_database(db)
        return ls

    ps = PrefixState()
    ps.update_prefix(
        "rsw1_1",
        "0",
        PrefixEntry(
            "2001:db8::/64",
            forwarding_type=SR_MPLS,
            forwarding_algorithm=KSP2,
        ),
    )
    me = "rsw0_0"
    scalar = ScalarBackend(SpfSolver(me)).build_route_db({"0": mk(me)}, ps)
    backend = TpuBackend(SpfSolver(me))
    tpu = backend.build_route_db({"0": mk(me)}, ps)
    assert _db_view(tpu) == _db_view(scalar)
    assert backend.num_scalar_builds == 0
    # the KSP2 second path must actually carry a PUSH stack
    stacks = [
        nh.mpls_action.push_labels
        for nh in tpu.unicast_routes["2001:db8::/64"].nexthops
        if nh.mpls_action is not None
    ]
    assert stacks, "expected SR-MPLS push stacks on non-shortest paths"


def test_tpu_backend_ksp2_anycast_and_min_nexthop():
    edges = grid_edges(4)
    ps = PrefixState()
    # anycast from two corners, KSP2
    for n in ("node15", "node12"):
        ps.update_prefix(
            n, "0", PrefixEntry("10.0.0.0/24", forwarding_algorithm=KSP2)
        )
    # min-nexthop too high -> withheld (gate applies to the k-path union)
    ps.update_prefix(
        "node9",
        "0",
        PrefixEntry(
            "10.1.0.0/24", forwarding_algorithm=KSP2, min_nexthop=64
        ),
    )
    backend = assert_backends_match(
        {"0": make_ls(edges, me="node0")}, ps, me="node0"
    )
    assert backend.num_scalar_builds == 0


def test_tpu_backend_mixed_ksp2_and_spf_prefixes():
    edges = grid_edges(4)
    ps = PrefixState()
    ps.update_prefix(
        "node15", "0", PrefixEntry("10.0.0.0/24", forwarding_algorithm=KSP2)
    )
    ps.update_prefix("node12", "0", PrefixEntry("10.1.0.0/24"))
    ps.update_prefix("node3", "0", PrefixEntry("2001:db8::/64"))
    backend = assert_backends_match(
        {"0": make_ls(edges, me="node0")}, ps, me="node0"
    )
    assert backend.num_scalar_builds == 0


def test_ksp2_algorithm_chosen_by_min_winner_not_any_advertiser():
    """A KSP2 advertisement that LOSES selection must not switch the
    prefix to the KSP2 path (SpfSolver.cpp:247-250)."""
    edges = grid_edges(3)
    ps = PrefixState()
    # node8 wins on path_preference with SP_ECMP; node4's KSP2 entry loses
    ps.update_prefix(
        "node8",
        "0",
        PrefixEntry("10.0.0.0/24", metrics=PrefixMetrics(path_preference=1000)),
    )
    ps.update_prefix(
        "node4",
        "0",
        PrefixEntry(
            "10.0.0.0/24",
            forwarding_algorithm=KSP2,
            metrics=PrefixMetrics(path_preference=100),
        ),
    )
    backend = assert_backends_match(
        {"0": make_ls(edges, me="node0")}, ps, me="node0"
    )
    assert backend.num_scalar_builds == 0


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_tpu_backend_ksp2_random_topologies(seed):
    edges = random_connected_edges(24, 30, seed=seed)
    ps = PrefixState()
    for i, n in enumerate(["node5", "node11", "node17", "node23"]):
        ps.update_prefix(
            n,
            "0",
            PrefixEntry(f"10.{i}.0.0/24", forwarding_algorithm=KSP2),
        )
    backend = assert_backends_match(
        {"0": make_ls(edges, me="node0")}, ps, me="node0"
    )
    assert backend.num_scalar_builds == 0


def test_tpu_backend_ksp2_with_drains():
    edges = grid_edges(4)
    ps = PrefixState()
    for n in ("node15", "node5", "node10"):
        ps.update_prefix(
            n, "0", PrefixEntry("10.0.0.0/24", forwarding_algorithm=KSP2)
        )
    ls_kwargs = dict(overloaded=["node5"], soft_drained={"node10": 60})
    me = "node0"
    scalar = ScalarBackend(SpfSolver(me)).build_route_db(
        {"0": make_ls(edges, me=me, **ls_kwargs)}, ps
    )
    backend = TpuBackend(SpfSolver(me))
    tpu = backend.build_route_db(
        {"0": make_ls(edges, me=me, **ls_kwargs)}, ps
    )
    assert _db_view(tpu) == _db_view(scalar)
    assert backend.num_scalar_builds == 0
