// Native single-threaded SPF baseline — the honest denominator.
//
// The reference's Decision hot loop is a single-threaded heap Dijkstra
// with all-shortest-paths nexthop tracking (LinkState::runSpf,
// /root/reference/openr/decision/LinkState.cpp:721-800, custom heap
// LinkState.h:606-660).  BASELINE.md's north star is ">=100x vs
// single-threaded SpfSolver" — so the batched TPU kernel must be measured
// against THIS (a C++ Dijkstra producing identical outputs: f32 distances
// + first-hop lane sets), not against the pure-Python oracle.  Loaded via
// ctypes by bench.py and the parity tests.
//
// Graph comes in as the EncodedTopology directed edge list (dst-sorted,
// openr_tpu/ops/csr.py) plus a CSR-by-src index built once per topology by
// spf_scalar_prepare.  Lane semantics match the device kernel: lane r =
// r-th directed out-edge of the root in edge order; nh[v] bit r set iff
// some shortest path root->v leaves the root over that edge.  Node
// hard-drain: an overloaded node is reachable but never relaxes unless it
// is the root (LinkState.cpp:739-752).

#include <cstdint>
#include <cstring>
#include <limits>

namespace {

struct HeapEntry {
  float dist;
  int32_t node;
};

// classic binary min-heap with lazy deletion (matches the reference's
// DijkstraQ in role; std::priority_queue avoided to keep the hot loop
// allocation-free across solves)
class Heap {
 public:
  Heap(HeapEntry* buf) : buf_(buf), n_(0) {}
  void push(float d, int32_t v) {
    int64_t i = n_++;
    while (i > 0) {
      int64_t p = (i - 1) >> 1;
      if (buf_[p].dist <= d) break;
      buf_[i] = buf_[p];
      i = p;
    }
    buf_[i] = {d, v};
  }
  bool pop(HeapEntry* out) {
    if (n_ == 0) return false;
    *out = buf_[0];
    HeapEntry last = buf_[--n_];
    int64_t i = 0;
    for (;;) {
      int64_t l = 2 * i + 1, r = l + 1, m = i;
      if (l < n_ && buf_[l].dist < last.dist) m = l;
      if (r < n_ && buf_[r].dist < (m == i ? last.dist : buf_[l].dist)) m = r;
      if (m == i) break;
      buf_[i] = buf_[m];
      i = m;
    }
    buf_[i] = last;
    return true;
  }
  void clear() { n_ = 0; }

 private:
  HeapEntry* buf_;
  int64_t n_;
};

}  // namespace

extern "C" {

// Build CSR-by-src: row_ptr[V+1], edge_order[E] = edge indices grouped by
// src node (stable, preserving dst-sorted order within a row).  One pass
// counting sort; call once per topology.
int spf_scalar_prepare(int32_t num_edges,
                       int32_t num_nodes,
                       const int32_t* src,
                       int32_t* row_ptr,    // [V+1]
                       int32_t* edge_order  // [E]
) {
  if (num_edges < 0 || num_nodes <= 0) return -1;
  for (int32_t v = 0; v <= num_nodes; ++v) row_ptr[v] = 0;
  for (int32_t e = 0; e < num_edges; ++e) {
    const int32_t s = src[e];
    if (s < 0 || s >= num_nodes) return -1;
    row_ptr[s + 1]++;
  }
  for (int32_t v = 0; v < num_nodes; ++v) row_ptr[v + 1] += row_ptr[v];
  // temp cursor reuses a stack copy pattern: second pass fills
  int32_t* cursor = new int32_t[num_nodes];
  std::memcpy(cursor, row_ptr, sizeof(int32_t) * num_nodes);
  for (int32_t e = 0; e < num_edges; ++e) edge_order[cursor[src[e]]++] = e;
  delete[] cursor;
  return 0;
}

// One full SPF solve (distances + lane bitmasks).  Outputs:
//   dist[V] f32 (+inf unreachable), nh_mask[V] u64 (lane bits).
// lane_of_edge[E]: precomputed lane index per directed edge (-1 = not a
// root out-edge); max 64 lanes.  failed_link: undirected link id whose
// two directed edges are skipped (-1 = none), matching the what-if sweep.
// scratch buffers (caller-allocated, reused across solves):
//   heap_buf[>=4E] HeapEntry-sized (16 bytes), settled[V] u8.
int spf_scalar_solve(int32_t num_edges,
                     int32_t num_nodes,
                     const int32_t* dst,
                     const float* w,
                     const uint8_t* edge_ok,
                     const int32_t* link_index,
                     const uint8_t* overloaded,
                     const int32_t* row_ptr,
                     const int32_t* edge_order,
                     const int32_t* lane_of_edge,
                     int32_t root,
                     int32_t failed_link,
                     float* dist,
                     uint64_t* nh_mask,
                     void* heap_buf,
                     uint8_t* settled) {
  if (root < 0 || root >= num_nodes) return -1;
  const float inf = std::numeric_limits<float>::infinity();
  for (int32_t v = 0; v < num_nodes; ++v) {
    dist[v] = inf;
    nh_mask[v] = 0;
    settled[v] = 0;
  }
  Heap heap(reinterpret_cast<HeapEntry*>(heap_buf));
  heap.clear();
  dist[root] = 0.0f;
  heap.push(0.0f, root);
  HeapEntry top;
  while (heap.pop(&top)) {
    const int32_t u = top.node;
    if (settled[u] || top.dist > dist[u]) continue;  // stale entry
    settled[u] = 1;
    if (overloaded[u] && u != root) continue;  // hard-drain: no transit
    const uint64_t mask_u = nh_mask[u];
    for (int32_t i = row_ptr[u]; i < row_ptr[u + 1]; ++i) {
      const int32_t e = edge_order[i];
      if (!edge_ok[e]) continue;
      if (failed_link >= 0 && link_index[e] == failed_link) continue;
      const int32_t v = dst[e];
      if (settled[v]) continue;
      const float nd = dist[u] + w[e];
      const int32_t lane = lane_of_edge[e];
      const uint64_t contrib = (u == root && lane >= 0)
                                   ? (uint64_t(1) << lane)
                                   : mask_u;
      if (nd < dist[v]) {
        dist[v] = nd;
        nh_mask[v] = contrib;
        heap.push(nd, v);
      } else if (nd == dist[v]) {
        nh_mask[v] |= contrib;  // all-shortest-paths accumulation
      }
    }
  }
  return 0;
}

// Timed sweep: `num_solves` sequential single-threaded solves with
// per-solve failed links, exactly what a single-threaded SpfSolver would
// do for the what-if batch.  Writes a checksum so the work cannot be
// optimized away; outputs of the LAST solve stay in dist/nh_mask for
// parity checks.
int spf_scalar_sweep(int32_t num_edges,
                     int32_t num_nodes,
                     const int32_t* dst,
                     const float* w,
                     const uint8_t* edge_ok,
                     const int32_t* link_index,
                     const uint8_t* overloaded,
                     const int32_t* row_ptr,
                     const int32_t* edge_order,
                     const int32_t* lane_of_edge,
                     int32_t root,
                     const int32_t* failed_links,
                     int32_t num_solves,
                     float* dist,
                     uint64_t* nh_mask,
                     void* heap_buf,
                     uint8_t* settled,
                     double* checksum) {
  double acc = 0.0;
  for (int32_t s = 0; s < num_solves; ++s) {
    int rc = spf_scalar_solve(num_edges, num_nodes, dst, w, edge_ok,
                              link_index, overloaded, row_ptr, edge_order,
                              lane_of_edge, root, failed_links[s], dist,
                              nh_mask, heap_buf, settled);
    if (rc != 0) return rc;
    acc += dist[num_nodes - 1] == std::numeric_limits<float>::infinity()
               ? -1.0
               : dist[num_nodes - 1];
  }
  *checksum = acc;
  return 0;
}

}  // extern "C"
