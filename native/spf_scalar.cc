// Native single-threaded SPF baseline — the honest denominator.
//
// The reference's Decision hot loop is a single-threaded heap Dijkstra
// with all-shortest-paths nexthop tracking (LinkState::runSpf,
// /root/reference/openr/decision/LinkState.cpp:721-800, custom heap
// LinkState.h:606-660).  BASELINE.md's north star is ">=100x vs
// single-threaded SpfSolver" — so the batched TPU kernel must be measured
// against THIS (a C++ Dijkstra producing identical outputs: f32 distances
// + first-hop lane sets), not against the pure-Python oracle.  Loaded via
// ctypes by bench.py and the parity tests.
//
// Graph comes in as the EncodedTopology directed edge list (dst-sorted,
// openr_tpu/ops/csr.py) plus a CSR-by-src index built once per topology by
// spf_scalar_prepare.  Lane semantics match the device kernel: lane r =
// r-th directed out-edge of the root in edge order; nh[v] bit r set iff
// some shortest path root->v leaves the root over that edge.  Node
// hard-drain: an overloaded node is reachable but never relaxes unless it
// is the root (LinkState.cpp:739-752).

#include <cstdint>
#include <cstring>
#include <limits>

namespace {

struct HeapEntry {
  float dist;
  int32_t node;
};

// classic binary min-heap with lazy deletion (matches the reference's
// DijkstraQ in role; std::priority_queue avoided to keep the hot loop
// allocation-free across solves)
class Heap {
 public:
  Heap(HeapEntry* buf) : buf_(buf), n_(0) {}
  void push(float d, int32_t v) {
    int64_t i = n_++;
    while (i > 0) {
      int64_t p = (i - 1) >> 1;
      if (buf_[p].dist <= d) break;
      buf_[i] = buf_[p];
      i = p;
    }
    buf_[i] = {d, v};
  }
  bool pop(HeapEntry* out) {
    if (n_ == 0) return false;
    *out = buf_[0];
    HeapEntry last = buf_[--n_];
    int64_t i = 0;
    for (;;) {
      int64_t l = 2 * i + 1, r = l + 1, m = i;
      if (l < n_ && buf_[l].dist < last.dist) m = l;
      if (r < n_ && buf_[r].dist < (m == i ? last.dist : buf_[l].dist)) m = r;
      if (m == i) break;
      buf_[i] = buf_[m];
      i = m;
    }
    buf_[i] = last;
    return true;
  }
  void clear() { n_ = 0; }

 private:
  HeapEntry* buf_;
  int64_t n_;
};

}  // namespace

extern "C" {

// Build CSR-by-src: row_ptr[V+1], edge_order[E] = edge indices grouped by
// src node (stable, preserving dst-sorted order within a row).  One pass
// counting sort; call once per topology.
int spf_scalar_prepare(int32_t num_edges,
                       int32_t num_nodes,
                       const int32_t* src,
                       int32_t* row_ptr,    // [V+1]
                       int32_t* edge_order  // [E]
) {
  if (num_edges < 0 || num_nodes <= 0) return -1;
  for (int32_t v = 0; v <= num_nodes; ++v) row_ptr[v] = 0;
  for (int32_t e = 0; e < num_edges; ++e) {
    const int32_t s = src[e];
    if (s < 0 || s >= num_nodes) return -1;
    row_ptr[s + 1]++;
  }
  for (int32_t v = 0; v < num_nodes; ++v) row_ptr[v + 1] += row_ptr[v];
  // temp cursor reuses a stack copy pattern: second pass fills
  int32_t* cursor = new int32_t[num_nodes];
  std::memcpy(cursor, row_ptr, sizeof(int32_t) * num_nodes);
  for (int32_t e = 0; e < num_edges; ++e) edge_order[cursor[src[e]]++] = e;
  delete[] cursor;
  return 0;
}

// One full SPF solve (distances + lane bitmasks).  Outputs:
//   dist[V] f32 (+inf unreachable), nh_mask[V] u64 (lane bits).
// lane_of_edge[E]: precomputed lane index per directed edge (-1 = not a
// root out-edge); max 64 lanes.  failed_link: undirected link id whose
// two directed edges are skipped (-1 = none), matching the what-if sweep.
// scratch buffers (caller-allocated, reused across solves):
//   heap_buf[>=4E] HeapEntry-sized (16 bytes), settled[V] u8.
int spf_scalar_solve(int32_t num_edges,
                     int32_t num_nodes,
                     const int32_t* dst,
                     const float* w,
                     const uint8_t* edge_ok,
                     const int32_t* link_index,
                     const uint8_t* overloaded,
                     const int32_t* row_ptr,
                     const int32_t* edge_order,
                     const int32_t* lane_of_edge,
                     int32_t root,
                     int32_t failed_link,
                     float* dist,
                     uint64_t* nh_mask,
                     void* heap_buf,
                     uint8_t* settled) {
  if (root < 0 || root >= num_nodes) return -1;
  const float inf = std::numeric_limits<float>::infinity();
  for (int32_t v = 0; v < num_nodes; ++v) {
    dist[v] = inf;
    nh_mask[v] = 0;
    settled[v] = 0;
  }
  Heap heap(reinterpret_cast<HeapEntry*>(heap_buf));
  heap.clear();
  dist[root] = 0.0f;
  heap.push(0.0f, root);
  HeapEntry top;
  while (heap.pop(&top)) {
    const int32_t u = top.node;
    if (settled[u] || top.dist > dist[u]) continue;  // stale entry
    settled[u] = 1;
    if (overloaded[u] && u != root) continue;  // hard-drain: no transit
    const uint64_t mask_u = nh_mask[u];
    for (int32_t i = row_ptr[u]; i < row_ptr[u + 1]; ++i) {
      const int32_t e = edge_order[i];
      if (!edge_ok[e]) continue;
      if (failed_link >= 0 && link_index[e] == failed_link) continue;
      const int32_t v = dst[e];
      if (settled[v]) continue;
      const float nd = dist[u] + w[e];
      const int32_t lane = lane_of_edge[e];
      const uint64_t contrib = (u == root && lane >= 0)
                                   ? (uint64_t(1) << lane)
                                   : mask_u;
      if (nd < dist[v]) {
        dist[v] = nd;
        nh_mask[v] = contrib;
        heap.push(nd, v);
      } else if (nd == dist[v]) {
        nh_mask[v] |= contrib;  // all-shortest-paths accumulation
      }
    }
  }
  return 0;
}

// Simultaneous-set variant: skip every directed edge whose undirected
// link id is in failed_links[0..n_failed) — "what if ALL these links
// fail at once" (maintenance-window analysis).  n_failed is tiny
// (operator-listed links), so a linear membership scan beats building
// a lookup table per solve.
int spf_scalar_solve_set(int32_t num_edges,
                         int32_t num_nodes,
                         const int32_t* dst,
                         const float* w,
                         const uint8_t* edge_ok,
                         const int32_t* link_index,
                         const uint8_t* overloaded,
                         const int32_t* row_ptr,
                         const int32_t* edge_order,
                         const int32_t* lane_of_edge,
                         int32_t root,
                         const int32_t* failed_links,
                         int32_t n_failed,
                         float* dist,
                         uint64_t* nh_mask,
                         void* heap_buf,
                         uint8_t* settled) {
  if (root < 0 || root >= num_nodes) return -1;
  const float inf = std::numeric_limits<float>::infinity();
  for (int32_t v = 0; v < num_nodes; ++v) {
    dist[v] = inf;
    nh_mask[v] = 0;
    settled[v] = 0;
  }
  Heap heap(reinterpret_cast<HeapEntry*>(heap_buf));
  heap.clear();
  dist[root] = 0.0f;
  heap.push(0.0f, root);
  HeapEntry top;
  while (heap.pop(&top)) {
    const int32_t u = top.node;
    if (settled[u] || top.dist > dist[u]) continue;
    settled[u] = 1;
    if (overloaded[u] && u != root) continue;
    const uint64_t mask_u = nh_mask[u];
    for (int32_t i = row_ptr[u]; i < row_ptr[u + 1]; ++i) {
      const int32_t e = edge_order[i];
      if (!edge_ok[e]) continue;
      const int32_t li = link_index[e];
      bool skip = false;
      for (int32_t k = 0; k < n_failed; ++k) {
        if (li >= 0 && li == failed_links[k]) { skip = true; break; }
      }
      if (skip) continue;
      const int32_t v = dst[e];
      if (settled[v]) continue;
      const float nd = dist[u] + w[e];
      const int32_t lane = lane_of_edge[e];
      const uint64_t contrib = (u == root && lane >= 0)
                                   ? (uint64_t(1) << lane)
                                   : mask_u;
      if (nd < dist[v]) {
        dist[v] = nd;
        nh_mask[v] = contrib;
        heap.push(nd, v);
      } else if (nd == dist[v]) {
        nh_mask[v] |= contrib;
      }
    }
  }
  return 0;
}

// Timed sweep: `num_solves` sequential single-threaded solves with
// per-solve failed links, exactly what a single-threaded SpfSolver would
// do for the what-if batch.  Writes a checksum so the work cannot be
// optimized away; outputs of the LAST solve stay in dist/nh_mask for
// parity checks.
int spf_scalar_sweep(int32_t num_edges,
                     int32_t num_nodes,
                     const int32_t* dst,
                     const float* w,
                     const uint8_t* edge_ok,
                     const int32_t* link_index,
                     const uint8_t* overloaded,
                     const int32_t* row_ptr,
                     const int32_t* edge_order,
                     const int32_t* lane_of_edge,
                     int32_t root,
                     const int32_t* failed_links,
                     int32_t num_solves,
                     float* dist,
                     uint64_t* nh_mask,
                     void* heap_buf,
                     uint8_t* settled,
                     double* checksum) {
  double acc = 0.0;
  for (int32_t s = 0; s < num_solves; ++s) {
    int rc = spf_scalar_solve(num_edges, num_nodes, dst, w, edge_ok,
                              link_index, overloaded, row_ptr, edge_order,
                              lane_of_edge, root, failed_links[s], dist,
                              nh_mask, heap_buf, settled);
    if (rc != 0) return rc;
    acc += dist[num_nodes - 1] == std::numeric_limits<float>::infinity()
               ? -1.0
               : dist[num_nodes - 1];
  }
  *checksum = acc;
  return 0;
}

// ---------------------------------------------------------------------------
// Warm-start (incremental-repair) sweep — the CPU form of the device
// kernel's trick (openr_tpu/ops/repair.py), so the TPU speedup can be
// compared against a native baseline using the SAME algorithmic
// advantage (VERDICT r3 weak #1): failing an off-DAG link provably
// changes nothing (base aliased); otherwise only the base-DAG
// descendants of the failed edge heads are re-solved, seeded from the
// frontier of provably-unchanged vertices, and lane masks are rebuilt
// for the affected region in settle order.  Exact, not approximate —
// the same invariants as the device kernel's docstring.
// ---------------------------------------------------------------------------

// Build the warm-start context from a completed base solve.  Outputs:
//   edge_on_dag[E] u8, dag_row_ptr[V+1] + dag_edges[E] (DAG out-CSR),
//   in_row_ptr[V+1] + in_edge_order[E] (in-edge CSR over dst),
//   link_on_dag[L] u8.
int spf_warm_prepare(int32_t num_edges,
                     int32_t num_nodes,
                     const int32_t* src,
                     const int32_t* dst,
                     const float* w,
                     const uint8_t* edge_ok,
                     const int32_t* link_index,
                     const uint8_t* overloaded,
                     int32_t root,
                     int32_t num_links,
                     const float* base_dist,
                     uint8_t* edge_on_dag,
                     int32_t* dag_row_ptr,
                     int32_t* dag_edges,
                     int32_t* in_row_ptr,
                     int32_t* in_edge_order,
                     uint8_t* link_on_dag) {
  const float inf = std::numeric_limits<float>::infinity();
  for (int32_t l = 0; l < num_links; ++l) link_on_dag[l] = 0;
  for (int32_t e = 0; e < num_edges; ++e) {
    const int32_t u = src[e];
    const bool transit = !overloaded[u] || u == root;
    edge_on_dag[e] = edge_ok[e] && transit && base_dist[u] < inf &&
                     base_dist[dst[e]] < inf &&
                     base_dist[u] + w[e] == base_dist[dst[e]];
    if (edge_on_dag[e] && link_index[e] >= 0 && link_index[e] < num_links)
      link_on_dag[link_index[e]] = 1;
  }
  // DAG out-CSR by src
  for (int32_t v = 0; v <= num_nodes; ++v) dag_row_ptr[v] = 0;
  for (int32_t e = 0; e < num_edges; ++e)
    if (edge_on_dag[e]) dag_row_ptr[src[e] + 1]++;
  for (int32_t v = 0; v < num_nodes; ++v) dag_row_ptr[v + 1] += dag_row_ptr[v];
  {
    int32_t* cursor = new int32_t[num_nodes];
    std::memcpy(cursor, dag_row_ptr, sizeof(int32_t) * num_nodes);
    for (int32_t e = 0; e < num_edges; ++e)
      if (edge_on_dag[e]) dag_edges[cursor[src[e]]++] = e;
    delete[] cursor;
  }
  // in-edge CSR by dst (all usable edges)
  for (int32_t v = 0; v <= num_nodes; ++v) in_row_ptr[v] = 0;
  for (int32_t e = 0; e < num_edges; ++e)
    if (edge_ok[e]) in_row_ptr[dst[e] + 1]++;
  for (int32_t v = 0; v < num_nodes; ++v) in_row_ptr[v + 1] += in_row_ptr[v];
  {
    int32_t* cursor = new int32_t[num_nodes];
    std::memcpy(cursor, in_row_ptr, sizeof(int32_t) * num_nodes);
    for (int32_t e = 0; e < num_edges; ++e)
      if (edge_ok[e]) in_edge_order[cursor[dst[e]]++] = e;
    delete[] cursor;
  }
  return 0;
}

// Warm-start sweep: num_solves sequential warm repairs.  dist_work /
// nh_work must arrive initialized to the base solution and are restored
// to it after every solve (so each solve is independent).  aff[V] u8 and
// settled[V] u8 must arrive zeroed.  Outputs: checksum (anti-DCE), plus
// the LAST solve's results left in dist_last/nh_last when non-null (for
// parity tests; pass nullptr in the timed path to skip the copy).
int spf_warm_sweep(int32_t num_edges,
                   int32_t num_nodes,
                   const int32_t* src,
                   const int32_t* dst,
                   const float* w,
                   const uint8_t* edge_ok,
                   const int32_t* link_index,
                   const uint8_t* overloaded,
                   const int32_t* row_ptr,
                   const int32_t* edge_order,
                   const int32_t* dag_row_ptr,
                   const int32_t* dag_edges,
                   const int32_t* in_row_ptr,
                   const int32_t* in_edge_order,
                   const int32_t* lane_of_edge,
                   int32_t root,
                   int32_t num_links,
                   const float* base_dist,
                   const uint64_t* base_nh,
                   const uint8_t* link_on_dag,
                   const int32_t* failed_links,
                   int32_t num_solves,
                   float* dist_work,
                   uint64_t* nh_work,
                   uint8_t* aff,
                   int32_t* aff_list,
                   int32_t* settle_order,
                   void* heap_buf,
                   uint8_t* settled,
                   float* dist_last,
                   uint64_t* nh_last,
                   double* checksum) {
  const float inf = std::numeric_limits<float>::infinity();
  Heap heap(reinterpret_cast<HeapEntry*>(heap_buf));
  double acc = 0.0;
  const int32_t last = num_nodes - 1;
  for (int32_t s = 0; s < num_solves; ++s) {
    const int32_t fl = failed_links[s];
    if (fl < 0 || fl >= num_links || !link_on_dag[fl]) {
      // off-DAG / no-op failure: provably identical to the base solve
      acc += base_dist[last] == inf ? -1.0 : base_dist[last];
      if (s == num_solves - 1 && dist_last != nullptr && nh_last != nullptr) {
        std::memcpy(dist_last, dist_work, sizeof(float) * num_nodes);
        std::memcpy(nh_last, nh_work, sizeof(uint64_t) * num_nodes);
      }
      continue;
    }
    // affected set = DAG descendants of the failed edges' heads
    int32_t na = 0;
    for (int32_t e = 0; e < num_edges; ++e) {
      if (link_index[e] != fl) continue;
      // cheap: links have exactly 2 directed edges; scan cost is
      // dominated by the Dijkstra below at the bench scale
      const int32_t u = src[e];
      const bool transit = !overloaded[u] || u == root;
      if (edge_ok[e] && transit && base_dist[u] < inf &&
          base_dist[dst[e]] < inf &&
          base_dist[u] + w[e] == base_dist[dst[e]]) {
        const int32_t h = dst[e];
        if (!aff[h]) {
          aff[h] = 1;
          aff_list[na++] = h;
        }
      }
    }
    for (int32_t i = 0; i < na; ++i) {
      const int32_t v = aff_list[i];
      for (int32_t j = dag_row_ptr[v]; j < dag_row_ptr[v + 1]; ++j) {
        const int32_t d2 = dst[dag_edges[j]];
        if (!aff[d2]) {
          aff[d2] = 1;
          aff_list[na++] = d2;
        }
      }
    }
    // seed: best distance into each affected vertex from the unchanged
    // frontier (base distances are exact lower bounds that removal can
    // only raise, so non-affected vertices are final)
    heap.clear();
    for (int32_t i = 0; i < na; ++i) dist_work[aff_list[i]] = inf;
    for (int32_t i = 0; i < na; ++i) {
      const int32_t v = aff_list[i];
      float best = inf;
      for (int32_t j = in_row_ptr[v]; j < in_row_ptr[v + 1]; ++j) {
        const int32_t e = in_edge_order[j];
        if (link_index[e] == fl) continue;
        const int32_t u = src[e];
        if (aff[u]) continue;
        if (overloaded[u] && u != root) continue;
        if (dist_work[u] == inf) continue;
        const float nd = dist_work[u] + w[e];
        if (nd < best) best = nd;
      }
      if (best < inf) {
        dist_work[v] = best;
        heap.push(best, v);
      }
    }
    // Dijkstra restricted to the affected region
    int32_t ns = 0;
    HeapEntry top;
    while (heap.pop(&top)) {
      const int32_t u = top.node;
      if (settled[u] || top.dist > dist_work[u]) continue;
      settled[u] = 1;
      settle_order[ns++] = u;
      if (overloaded[u] && u != root) continue;
      for (int32_t i = row_ptr[u]; i < row_ptr[u + 1]; ++i) {
        const int32_t e = edge_order[i];
        if (!edge_ok[e] || link_index[e] == fl) continue;
        const int32_t v = dst[e];
        if (!aff[v] || settled[v]) continue;
        const float nd = dist_work[u] + w[e];
        if (nd < dist_work[v]) {
          dist_work[v] = nd;
          heap.push(nd, v);
        }
      }
    }
    // lane masks for the affected region, in settle (ascending-dist)
    // order; predecessors are either non-affected (base lanes, final)
    // or settled earlier (strictly smaller dist since w >= 1)
    for (int32_t i = 0; i < ns; ++i) {
      const int32_t v = settle_order[i];
      uint64_t mask = 0;
      for (int32_t j = in_row_ptr[v]; j < in_row_ptr[v + 1]; ++j) {
        const int32_t e = in_edge_order[j];
        if (link_index[e] == fl) continue;
        const int32_t u = src[e];
        if (overloaded[u] && u != root) continue;
        if (dist_work[u] == inf) continue;
        if (dist_work[u] + w[e] != dist_work[v]) continue;
        const int32_t lane = lane_of_edge[e];
        mask |= (u == root && lane >= 0) ? (uint64_t(1) << lane)
                                         : nh_work[u];
      }
      nh_work[v] = mask;
    }
    // affected but now unreachable: clear lanes
    for (int32_t i = 0; i < na; ++i)
      if (dist_work[aff_list[i]] == inf) nh_work[aff_list[i]] = 0;
    acc += dist_work[last] == inf ? -1.0 : dist_work[last];
    if (s == num_solves - 1 && dist_last != nullptr && nh_last != nullptr) {
      std::memcpy(dist_last, dist_work, sizeof(float) * num_nodes);
      std::memcpy(nh_last, nh_work, sizeof(uint64_t) * num_nodes);
    }
    // restore base state for the next solve
    for (int32_t i = 0; i < na; ++i) {
      const int32_t v = aff_list[i];
      dist_work[v] = base_dist[v];
      nh_work[v] = base_nh[v];
      aff[v] = 0;
      settled[v] = 0;
    }
  }
  *checksum = acc;
  return 0;
}

}  // extern "C"
