// Batched LSDB prefix-advertisement decoder.
//
// The reference pays generated-C++ thrift decode for every flooded
// publication (openr/kvstore/KvStoreUtil.cpp:391 mergeKeyValues feeds
// CompactSerializer-decoded values straight into C++ structs); this
// framework's equivalent hot path — Decision ingesting hundreds of
// thousands of `prefix:...` values on cold boot — was pure-Python
// json+dataclass decode at ~20 us/prefix.  This kernel batch-decodes
// the CANONICAL advertisement shape (single entry, no tags/area_stack/
// perf events — the overwhelming majority of a real LSDB) into flat
// columns in C++, for BOTH wire encodings this framework floods:
//
//   * wire-JSON   (openr_tpu.lsdb_codec, payload starts '{')
//   * thrift-compact (openr_tpu/interop/openr_wire.py PREFIX_DATABASE,
//     the reference's CompactSerializer bytes)
//
// Anything off the fast shape is flagged FALLBACK and re-decoded by the
// Python scalar path, so semantics never fork: the kernel is an
// accelerator, not a second decoder of record.  Prefixes are emitted
// CANONICAL (host bits zeroed, RFC 5952 text) so downstream never needs
// normalize_prefix; v4-embedded v6 ranges fall back (inet_ntop and
// Python ipaddress disagree on their text form).
//
// Exposed via ctypes (see openr_tpu/decision/ingest.py).

#include <arpa/inet.h>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace {

constexpr uint8_t ST_FAST = 0;      // columns valid
constexpr uint8_t ST_FALLBACK = 1;  // python must decode this payload
constexpr uint8_t ST_DELETE = 2;    // delete_prefix / no entries

constexpr int PREFIX_CHARS = 64;  // per-row output slot (CIDR max ~48)

struct Cols {
  uint8_t* status;
  char* prefix;  // [n][PREFIX_CHARS]
  int32_t* ptype;
  int32_t* fwd_type;
  int32_t* fwd_alg;
  int32_t* m_version;
  int32_t* m_path_pref;
  int32_t* m_source_pref;
  int32_t* m_distance;
  int32_t* m_drain;
  int64_t* min_nexthop;  // -1 = absent
  int64_t* weight;       // INT64_MIN = absent
};

struct Row {
  char prefix_text[PREFIX_CHARS] = {0};
  uint8_t addr[16] = {0};
  int addr_len = 0;  // 4 or 16 when set via binary (compact)
  long prefix_len = -1;
  int32_t ptype = 1;  // LOOPBACK default (types.py PrefixEntry)
  int32_t fwd_type = 0;
  int32_t fwd_alg = 0;
  int32_t m_version = 1;
  int32_t m_path_pref = 0;
  int32_t m_source_pref = 0;
  int32_t m_distance = 0;
  int32_t m_drain = 0;
  int64_t min_nexthop = -1;
  int64_t weight = INT64_MIN;
  bool del_flag = false;
  int entries = 0;
};

// ---------------------------------------------------------------- canonical

// Zero host bits in-place; true if any were set (needs canonical text
// either way — we always reformat).
bool zero_host_bits(uint8_t* addr, int nbytes, int plen) {
  bool changed = false;
  for (int i = 0; i < nbytes; i++) {
    int bit0 = i * 8;
    uint8_t keep;
    if (plen <= bit0) {
      keep = 0;
    } else if (plen >= bit0 + 8) {
      keep = 0xFF;
    } else {
      keep = static_cast<uint8_t>(0xFF << (8 - (plen - bit0)));
    }
    uint8_t v = addr[i] & keep;
    if (v != addr[i]) changed = true;
    addr[i] = v;
  }
  return changed;
}

bool is_v4_embedded_v6(const uint8_t* a) {
  // ::/96 (v4-compatible incl. :: and ::1) except plain zeros is fine?
  // inet_ntop renders ::a.b.c.d for v4-compatible with nonzero low 32
  // bits, and ::ffff:a.b.c.d for v4-mapped; Python ipaddress uses hex
  // groups for the former.  Fall back for both ranges (rare in LSDBs).
  static const uint8_t zeros12[12] = {0};
  if (memcmp(a, zeros12, 10) == 0) {
    uint16_t g5 = static_cast<uint16_t>((a[10] << 8) | a[11]);
    if (g5 == 0xFFFF) return true;  // v4-mapped
    if (g5 == 0) {
      // v4-compatible with something in the low 32 bits beyond ::1
      uint32_t low;
      memcpy(&low, a + 12, 4);
      if (low != 0 && ntohl(low) != 1) return true;
    }
  }
  return false;
}

// Format canonical "addr/len" into out; false -> fallback.
bool format_prefix(Row& r, char* out) {
  if (r.addr_len == 4) {
    if (r.prefix_len < 0 || r.prefix_len > 32) return false;
    zero_host_bits(r.addr, 4, static_cast<int>(r.prefix_len));
    char buf[INET_ADDRSTRLEN];
    if (!inet_ntop(AF_INET, r.addr, buf, sizeof(buf))) return false;
    snprintf(out, PREFIX_CHARS, "%s/%ld", buf, r.prefix_len);
    return true;
  }
  if (r.addr_len == 16) {
    if (r.prefix_len < 0 || r.prefix_len > 128) return false;
    if (is_v4_embedded_v6(r.addr)) return false;
    zero_host_bits(r.addr, 16, static_cast<int>(r.prefix_len));
    char buf[INET6_ADDRSTRLEN];
    if (!inet_ntop(AF_INET6, r.addr, buf, sizeof(buf))) return false;
    snprintf(out, PREFIX_CHARS, "%s/%ld", buf, r.prefix_len);
    return true;
  }
  return false;
}

// Parse "a.b.c.d/len" or "x::y/len" text into r.addr/prefix_len.
bool parse_prefix_text(Row& r, const char* s, size_t len) {
  if (len >= PREFIX_CHARS) return false;
  char tmp[PREFIX_CHARS];
  memcpy(tmp, s, len);
  tmp[len] = 0;
  char* slash = strchr(tmp, '/');
  if (!slash) return false;
  *slash = 0;
  char* end = nullptr;
  r.prefix_len = strtol(slash + 1, &end, 10);
  if (end == slash + 1 || *end != 0) return false;
  if (strchr(tmp, ':')) {
    if (inet_pton(AF_INET6, tmp, r.addr) != 1) return false;
    r.addr_len = 16;
  } else {
    if (inet_pton(AF_INET, tmp, r.addr) != 1) return false;
    r.addr_len = 4;
  }
  return true;
}

// ------------------------------------------------------------------- JSON

struct JParser {
  const char* p;
  const char* end;
  bool fail = false;

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      p++;
  }
  bool lit(char c) {
    ws();
    if (p < end && *p == c) {
      p++;
      return true;
    }
    fail = true;
    return false;
  }
  bool peek(char c) {
    ws();
    return p < end && *p == c;
  }
  // string WITHOUT escapes (LSDB keys/prefixes never carry them); any
  // backslash -> fail (caller falls back to python)
  bool str(const char** out, size_t* out_len) {
    if (!lit('"')) return false;
    const char* s = p;
    while (p < end && *p != '"') {
      if (*p == '\\') {
        fail = true;
        return false;
      }
      p++;
    }
    if (p >= end) {
      fail = true;
      return false;
    }
    *out = s;
    *out_len = static_cast<size_t>(p - s);
    p++;  // closing quote
    return true;
  }
  bool integer(long long* out) {
    ws();
    char* e = nullptr;
    long long v = strtoll(p, &e, 10);
    if (e == p) {
      fail = true;
      return false;
    }
    // floats (metrics are ints on this wire) -> fallback
    if (e < end && (*e == '.' || *e == 'e' || *e == 'E')) {
      fail = true;
      return false;
    }
    p = e;
    *out = v;
    return true;
  }
  bool kw(const char* w) {  // null / true / false
    ws();
    size_t n = strlen(w);
    if (static_cast<size_t>(end - p) >= n && memcmp(p, w, n) == 0) {
      p += n;
      return true;
    }
    return false;
  }
  // generic skip of any value (for unknown keys)
  void skip_value() {
    ws();
    if (p >= end) {
      fail = true;
      return;
    }
    char c = *p;
    if (c == '"') {
      p++;
      while (p < end && *p != '"') {
        if (*p == '\\') p++;  // skip escaped char
        p++;
      }
      if (p < end) p++;
      return;
    }
    if (c == '{' || c == '[') {
      char close = (c == '{') ? '}' : ']';
      p++;
      int depth = 1;
      while (p < end && depth > 0) {
        char d = *p;
        if (d == '"') {
          p++;
          while (p < end && *p != '"') {
            if (*p == '\\') p++;
            p++;
          }
        } else if (d == c) {
          depth++;
        } else if (d == close) {
          depth--;
        }
        p++;
      }
      if (depth != 0) fail = true;
      return;
    }
    if (kw("null") || kw("true") || kw("false")) return;
    long long tmp;
    // number (accept floats here — we're skipping)
    char* e = nullptr;
    double dv = strtod(p, &e);
    (void)dv;
    (void)tmp;
    if (e == p) {
      fail = true;
      return;
    }
    p = e;
  }
};

bool jkey_is(const char* k, size_t klen, const char* want) {
  return klen == strlen(want) && memcmp(k, want, klen) == 0;
}

// parse the "metrics" object
bool json_metrics(JParser& j, Row& r) {
  if (!j.lit('{')) return false;
  if (j.peek('}')) {
    j.p++;
    return true;
  }
  while (true) {
    const char* k;
    size_t klen;
    if (!j.str(&k, &klen) || !j.lit(':')) return false;
    long long v;
    if (!j.integer(&v)) return false;
    if (jkey_is(k, klen, "version")) r.m_version = static_cast<int32_t>(v);
    else if (jkey_is(k, klen, "drain_metric")) r.m_drain = static_cast<int32_t>(v);
    else if (jkey_is(k, klen, "path_preference")) r.m_path_pref = static_cast<int32_t>(v);
    else if (jkey_is(k, klen, "source_preference")) r.m_source_pref = static_cast<int32_t>(v);
    else if (jkey_is(k, klen, "distance")) r.m_distance = static_cast<int32_t>(v);
    // unknown metric keys: ignore (ints consumed either way)
    if (j.peek('}')) {
      j.p++;
      return true;
    }
    if (!j.lit(',')) return false;
  }
}

// one prefix_entries[i] object; false -> fallback
bool json_entry(JParser& j, Row& r) {
  if (!j.lit('{')) return false;
  if (j.peek('}')) {
    j.p++;
    return false;  // entry without prefix: fallback
  }
  bool have_prefix = false;
  while (true) {
    const char* k;
    size_t klen;
    if (!j.str(&k, &klen) || !j.lit(':')) return false;
    if (jkey_is(k, klen, "prefix")) {
      const char* s;
      size_t slen;
      if (!j.str(&s, &slen)) return false;
      if (!parse_prefix_text(r, s, slen)) return false;
      have_prefix = true;
    } else if (jkey_is(k, klen, "type")) {
      long long v;
      if (!j.integer(&v)) return false;
      r.ptype = static_cast<int32_t>(v);
    } else if (jkey_is(k, klen, "forwarding_type")) {
      long long v;
      if (!j.integer(&v)) return false;
      r.fwd_type = static_cast<int32_t>(v);
    } else if (jkey_is(k, klen, "forwarding_algorithm")) {
      long long v;
      if (!j.integer(&v)) return false;
      r.fwd_alg = static_cast<int32_t>(v);
    } else if (jkey_is(k, klen, "min_nexthop")) {
      if (j.kw("null")) {
        r.min_nexthop = -1;
      } else {
        long long v;
        if (!j.integer(&v) || v < 0) return false;
        r.min_nexthop = v;
      }
    } else if (jkey_is(k, klen, "weight")) {
      if (j.kw("null")) {
        r.weight = INT64_MIN;
      } else {
        long long v;
        if (!j.integer(&v)) return false;
        r.weight = v;
      }
    } else if (jkey_is(k, klen, "metrics")) {
      if (!json_metrics(j, r)) return false;
    } else if (jkey_is(k, klen, "tags") || jkey_is(k, klen, "area_stack")) {
      if (!j.lit('[')) return false;
      if (!j.peek(']')) return false;  // non-empty -> fallback
      j.p++;
    } else {
      j.skip_value();  // unknown entry field
      if (j.fail) return false;
    }
    if (j.peek('}')) {
      j.p++;
      return have_prefix;
    }
    if (!j.lit(',')) return false;
  }
}

uint8_t decode_json(const char* data, size_t len, Row& r) {
  JParser j{data, data + len};
  if (!j.lit('{')) return ST_FALLBACK;
  if (j.peek('}')) return ST_FALLBACK;  // scalar decoder REQUIRES
                                        // this_node_name; bare {} raises
  bool saw_entries = false;
  bool saw_node = false;
  while (true) {
    const char* k;
    size_t klen;
    if (!j.str(&k, &klen) || !j.lit(':')) return ST_FALLBACK;
    if (jkey_is(k, klen, "this_node_name")) {
      const char* s;
      size_t slen;
      if (!j.str(&s, &slen)) return ST_FALLBACK;
      saw_node = true;
    } else if (jkey_is(k, klen, "prefix_entries")) {
      if (!j.lit('[')) return ST_FALLBACK;
      if (j.peek(']')) {
        j.p++;
        saw_entries = true;  // zero entries => delete semantics
      } else {
        if (!json_entry(j, r)) return ST_FALLBACK;
        r.entries = 1;
        saw_entries = true;
        if (!j.peek(']')) return ST_FALLBACK;  // >1 entry -> fallback
        j.p++;
      }
    } else if (jkey_is(k, klen, "delete_prefix")) {
      if (j.kw("true")) r.del_flag = true;
      else if (j.kw("false")) r.del_flag = false;
      else return ST_FALLBACK;
    } else if (jkey_is(k, klen, "perf_events")) {
      if (!j.kw("null")) return ST_FALLBACK;  // perf breadcrumbs: python
    } else {
      j.skip_value();  // this_node_name, area, unknown
      if (j.fail) return ST_FALLBACK;
    }
    if (j.peek('}')) break;
    if (!j.lit(',')) return ST_FALLBACK;
  }
  if (!saw_node) return ST_FALLBACK;  // scalar from_wire would raise
  if (!saw_entries || r.del_flag || r.entries == 0) {
    return (saw_entries || r.del_flag) ? ST_DELETE : ST_FALLBACK;
  }
  return ST_FAST;
}

// --------------------------------------------------------- thrift compact

struct CReader {
  const uint8_t* p;
  const uint8_t* end;
  bool fail = false;

  uint8_t byte() {
    if (p >= end) {
      fail = true;
      return 0;
    }
    return *p++;
  }
  uint64_t varint() {
    uint64_t out = 0;
    int shift = 0;
    while (true) {
      uint8_t b = byte();
      if (fail) return 0;
      out |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) return out;
      shift += 7;
      if (shift > 70) {
        fail = true;
        return 0;
      }
    }
  }
  int64_t zigzag() {
    uint64_t v = varint();
    return static_cast<int64_t>(v >> 1) ^ -static_cast<int64_t>(v & 1);
  }
  void skip_bytes(uint64_t n) {
    if (static_cast<uint64_t>(end - p) < n) {
      fail = true;
      return;
    }
    p += n;
  }
  // generic skip of one value of wire-type ct
  void skip(int ct, int depth = 0) {
    if (fail || depth > 16) {
      fail = true;
      return;
    }
    switch (ct) {
      case 1:
      case 2:
      case 3:
        byte();
        return;
      case 4:
      case 5:
      case 6:
        varint();
        return;
      case 7:
        skip_bytes(8);
        return;
      case 8:
        skip_bytes(varint());
        return;
      case 9:
      case 10: {
        uint8_t head = byte();
        uint64_t size = (head >> 4) & 0x0F;
        if (size == 0x0F) size = varint();
        for (uint64_t i = 0; i < size && !fail; i++) skip(head & 0x0F, depth + 1);
        return;
      }
      case 11: {
        uint64_t size = varint();
        if (!size) return;
        uint8_t kv = byte();
        for (uint64_t i = 0; i < size && !fail; i++) {
          skip((kv >> 4) & 0x0F, depth + 1);
          skip(kv & 0x0F, depth + 1);
        }
        return;
      }
      case 12: {  // struct
        while (!fail) {
          uint8_t head = byte();
          if (fail) return;
          if (head == 0) return;
          if (!((head >> 4) & 0x0F)) zigzag();  // long-form id
          int inner = head & 0x0F;
          if (inner == 1 || inner == 2) continue;  // bool folded in type
          skip(inner, depth + 1);
        }
        return;
      }
      default:
        fail = true;
    }
  }
};

// IP_PREFIX struct: {1: BINARY_ADDRESS{1: addr binary}, 2: prefixLength}
bool compact_ip_prefix(CReader& c, Row& r) {
  int16_t last = 0;
  while (true) {
    uint8_t head = c.byte();
    if (c.fail) return false;
    if (head == 0) break;
    int delta = (head >> 4) & 0x0F;
    int ct = head & 0x0F;
    int fid = delta ? last + delta : static_cast<int>(c.zigzag());
    last = static_cast<int16_t>(fid);
    if (fid == 1 && ct == 12) {  // prefixAddress BinaryAddress
      int16_t last2 = 0;
      while (true) {
        uint8_t h2 = c.byte();
        if (c.fail) return false;
        if (h2 == 0) break;
        int d2 = (h2 >> 4) & 0x0F;
        int ct2 = h2 & 0x0F;
        int f2 = d2 ? last2 + d2 : static_cast<int>(c.zigzag());
        last2 = static_cast<int16_t>(f2);
        if (f2 == 1 && ct2 == 8) {  // addr binary
          uint64_t alen = c.varint();
          if (alen == 4 || alen == 16) {
            if (static_cast<uint64_t>(c.end - c.p) < alen) return false;
            memcpy(r.addr, c.p, alen);
            r.addr_len = static_cast<int>(alen);
            c.p += alen;
          } else {
            c.skip_bytes(alen);  // weird length -> fallback later
          }
        } else if (ct2 == 1 || ct2 == 2) {
          continue;
        } else {
          c.skip(ct2);
        }
        if (c.fail) return false;
      }
    } else if (fid == 2 && (ct == 4 || ct == 5 || ct == 6)) {
      r.prefix_len = static_cast<long>(c.zigzag());
    } else if (ct == 1 || ct == 2) {
      continue;
    } else {
      c.skip(ct);
    }
    if (c.fail) return false;
  }
  return r.addr_len != 0 && r.prefix_len >= 0;
}

bool compact_metrics(CReader& c, Row& r) {
  int16_t last = 0;
  while (true) {
    uint8_t head = c.byte();
    if (c.fail) return false;
    if (head == 0) return true;
    int delta = (head >> 4) & 0x0F;
    int ct = head & 0x0F;
    int fid = delta ? last + delta : static_cast<int>(c.zigzag());
    last = static_cast<int16_t>(fid);
    if (ct == 1 || ct == 2) continue;
    if (ct == 4 || ct == 5 || ct == 6) {
      int64_t v = c.zigzag();
      if (c.fail) return false;
      switch (fid) {
        case 1: r.m_version = static_cast<int32_t>(v); break;
        case 2: r.m_path_pref = static_cast<int32_t>(v); break;
        case 3: r.m_source_pref = static_cast<int32_t>(v); break;
        case 4: r.m_distance = static_cast<int32_t>(v); break;
        case 5: r.m_drain = static_cast<int32_t>(v); break;
        default: break;
      }
    } else {
      c.skip(ct);
      if (c.fail) return false;
    }
  }
}

// one PREFIX_ENTRY struct; false -> fallback
bool compact_entry(CReader& c, Row& r) {
  int16_t last = 0;
  bool have_prefix = false;
  while (true) {
    uint8_t head = c.byte();
    if (c.fail) return false;
    if (head == 0) return have_prefix;
    int delta = (head >> 4) & 0x0F;
    int ct = head & 0x0F;
    int fid = delta ? last + delta : static_cast<int>(c.zigzag());
    last = static_cast<int16_t>(fid);
    // scalar integer fields must carry an int wire type (i16/i32/i64);
    // a foreign encoder changing a field's type must fall back, never
    // misdecode (the Python compact decoder skips mismatched types)
    bool int_ct = (ct >= 4 && ct <= 6);
    switch (fid) {
      case 1:  // prefix IpPrefix
        if (ct != 12 || !compact_ip_prefix(c, r)) return false;
        have_prefix = true;
        break;
      case 2:
        if (!int_ct) return false;
        r.ptype = static_cast<int32_t>(c.zigzag());
        break;
      case 4:
        if (!int_ct) return false;
        r.fwd_type = static_cast<int32_t>(c.zigzag());
        break;
      case 7:
        if (!int_ct) return false;
        r.fwd_alg = static_cast<int32_t>(c.zigzag());
        break;
      case 8: {
        if (!int_ct) return false;
        int64_t v = c.zigzag();
        if (v < 0) return false;
        r.min_nexthop = v;
        break;
      }
      case 10:
        if (ct != 12 || !compact_metrics(c, r)) return false;
        break;
      case 11:
      case 12: {  // tags set / area_stack list
        if (ct != 9 && ct != 10) return false;
        uint8_t h = c.byte();
        uint64_t size = (h >> 4) & 0x0F;
        if (size == 0x0F) size = c.varint();
        if (size != 0) return false;  // non-empty -> fallback
        break;
      }
      case 13:
        if (!int_ct) return false;
        r.weight = c.zigzag();
        break;
      default:
        if (ct == 1 || ct == 2) break;  // folded bool
        c.skip(ct);
        break;
    }
    if (c.fail) return false;
  }
}

uint8_t decode_compact(const uint8_t* data, size_t len, Row& r) {
  CReader c{data, data + len};
  int16_t last = 0;
  bool saw_entries = false;
  while (true) {
    uint8_t head = c.byte();
    if (c.fail) return ST_FALLBACK;
    if (head == 0) break;
    int delta = (head >> 4) & 0x0F;
    int ct = head & 0x0F;
    int fid = delta ? last + delta : static_cast<int>(c.zigzag());
    last = static_cast<int16_t>(fid);
    if (fid == 3) {  // prefixEntries list<struct>
      if (ct != 9) return ST_FALLBACK;
      uint8_t h = c.byte();
      uint64_t size = (h >> 4) & 0x0F;
      if (size == 0x0F) size = c.varint();
      if ((h & 0x0F) != 12) return ST_FALLBACK;
      saw_entries = true;
      if (size == 0) {
        // zero entries => delete semantics
      } else if (size == 1) {
        if (!compact_entry(c, r)) return ST_FALLBACK;
        r.entries = 1;
      } else {
        return ST_FALLBACK;  // multi-entry -> python
      }
    } else if (fid == 4) {  // perfEvents -> python
      return ST_FALLBACK;
    } else if (fid == 5 && (ct == 1 || ct == 2)) {  // deletePrefix
      if (ct == 1) r.del_flag = true;
    } else if (ct == 1 || ct == 2) {
      continue;
    } else {
      c.skip(ct);
      if (c.fail) return ST_FALLBACK;
    }
  }
  if (r.del_flag || !saw_entries || r.entries == 0) {
    return (saw_entries || r.del_flag) ? ST_DELETE : ST_FALLBACK;
  }
  return ST_FAST;
}

}  // namespace

extern "C" {

// Returns number of rows processed (== n); each row's status selects
// which columns are meaningful.
int32_t lsdb_decode_prefix_batch(
    const uint8_t* buf, const int64_t* offs, int32_t n, Cols cols) {
  for (int32_t i = 0; i < n; i++) {
    const uint8_t* data = buf + offs[i];
    size_t len = static_cast<size_t>(offs[i + 1] - offs[i]);
    Row r;
    uint8_t st;
    if (len == 0) {
      st = ST_FALLBACK;
    } else if (data[0] == '{') {
      st = decode_json(reinterpret_cast<const char*>(data), len, r);
    } else {
      st = decode_compact(data, len, r);
    }
    char* out_prefix = cols.prefix + static_cast<size_t>(i) * PREFIX_CHARS;
    if (st == ST_FAST) {
      if (!format_prefix(r, out_prefix)) st = ST_FALLBACK;
    }
    if (st != ST_FAST) {
      out_prefix[0] = 0;
    }
    cols.status[i] = st;
    cols.ptype[i] = r.ptype;
    cols.fwd_type[i] = r.fwd_type;
    cols.fwd_alg[i] = r.fwd_alg;
    cols.m_version[i] = r.m_version;
    cols.m_path_pref[i] = r.m_path_pref;
    cols.m_source_pref[i] = r.m_source_pref;
    cols.m_distance[i] = r.m_distance;
    cols.m_drain[i] = r.m_drain;
    cols.min_nexthop[i] = r.min_nexthop;
    cols.weight[i] = r.weight;
  }
  return n;
}

}  // extern "C"
