// rtnetlink message codec — the native core of openr_tpu.platform.nl.
//
// Role (reference parity): openr/nl/NetlinkRouteMessage.{h,cpp},
// NetlinkLinkMessage, NetlinkAddrMessage, NetlinkNeighborMessage — the
// message build/parse layer under NetlinkProtocolSocket
// (openr/nl/NetlinkProtocolSocket.h:99).  The reference implements a
// libnl-free codec in C++; so do we.  This library speaks the Linux
// rtnetlink ABI directly (linux/rtnetlink.h) and exposes a flat C ABI that
// Python binds via ctypes (openr_tpu/platform/nl/codec.py).  All hot
// encode/decode work happens here; Python only moves buffers.
//
// Capabilities:
//   * encode RTM_NEWROUTE/DELROUTE for AF_INET/AF_INET6 unicast routes,
//     single and multipath (RTA_MULTIPATH), with optional MPLS push
//     encap (RTA_ENCAP/LWTUNNEL_ENCAP_MPLS) — and AF_MPLS label routes
//     (RTA_DST label, RTA_VIA gateway, RTA_NEWDST swap stack)
//   * encode RTM_NEWADDR/DELADDR, RTM_GETLINK/GETADDR/GETROUTE dumps
//   * decode kernel replies/events: link, addr, route, neigh, ack/error,
//     done — into flat structs

#include <cstring>
#include <cstdint>

#include <sys/socket.h>
#include <net/if.h>
#include <linux/lwtunnel.h>
#include <linux/mpls.h>
#include <linux/mpls_iptunnel.h>
#include <linux/netlink.h>
#include <linux/rtnetlink.h>

extern "C" {

enum {
  ONL_MAX_NEXTHOPS = 128,
  ONL_MAX_LABELS = 16,
  ONL_IFNAME = 32,
};

enum {  // OnlNexthop.label_action
  ONL_LBL_NONE = 0,
  ONL_LBL_PUSH = 1,
  ONL_LBL_SWAP = 2,
  ONL_LBL_PHP = 3,          // pop-and-forward: plain AF_MPLS nexthop
  ONL_LBL_POP_LOOKUP = 4,   // pop-and-lookup (RTA_OIF lo / dev lookup)
};

enum {  // OnlMsg.kind
  ONL_KIND_NONE = 0,
  ONL_KIND_LINK = 1,
  ONL_KIND_ADDR = 2,
  ONL_KIND_ROUTE = 3,
  ONL_KIND_NEIGH = 4,
  ONL_KIND_ACK = 5,   // NLMSG_ERROR with error==0, or error<0 (failure)
  ONL_KIND_DONE = 6,  // NLMSG_DONE (end of dump)
};

#pragma pack(push, 1)
struct OnlNexthop {
  uint8_t family;            // AF_INET/AF_INET6 of gateway; 0 = no gateway
  uint8_t gateway[16];
  int32_t if_index;          // -1 = unset
  uint32_t weight;           // 0 = equal
  uint8_t label_action;      // ONL_LBL_*
  uint8_t label_count;
  uint32_t labels[ONL_MAX_LABELS];
};

struct OnlRoute {
  uint8_t family;            // AF_INET / AF_INET6 / AF_MPLS
  uint8_t prefix_len;
  uint8_t dst[16];           // network byte order (unused for AF_MPLS)
  uint32_t mpls_label;       // AF_MPLS: incoming label
  uint8_t table;             // RT_TABLE_MAIN
  uint8_t protocol;          // e.g. 99 (openr)
  uint8_t route_type;        // RTN_UNICAST
  uint32_t priority;         // RTA_PRIORITY; 0 = omit
  uint32_t nh_count;
  OnlNexthop nh[ONL_MAX_NEXTHOPS];
};

struct OnlMsg {
  uint16_t kind;             // ONL_KIND_*
  uint16_t nlmsg_type;       // raw RTM_* type
  uint32_t seq;
  int32_t error;             // ONL_KIND_ACK: 0 ok, else -errno
  uint8_t is_del;            // RTM_DEL* event
  // link
  int32_t if_index;
  uint32_t if_flags;
  uint8_t is_up;
  char if_name[ONL_IFNAME];
  // addr / neigh
  uint8_t family;
  uint8_t prefix_len;
  uint8_t addr_valid;
  uint8_t addr[16];
  uint16_t neigh_state;
  // route
  OnlRoute route;
};
#pragma pack(pop)

namespace {

inline int addr_len(uint8_t family) { return family == AF_INET ? 4 : 16; }

// ---- attribute writer ----------------------------------------------------

struct Writer {
  uint8_t* buf;
  int cap;
  int len = 0;
  bool overflow = false;

  void* claim(int n) {
    int aligned = NLMSG_ALIGN(n);
    if (len + aligned > cap) {
      overflow = true;
      return nullptr;
    }
    void* p = buf + len;
    memset(p, 0, aligned);
    len += aligned;
    return p;
  }

  rtattr* put_attr(int type, const void* data, int dlen) {
    auto* rta = static_cast<rtattr*>(claim(RTA_LENGTH(dlen)));
    if (!rta) return nullptr;
    rta->rta_type = type;
    rta->rta_len = RTA_LENGTH(dlen);
    if (dlen) memcpy(RTA_DATA(rta), data, dlen);
    return rta;
  }

  rtattr* begin_nest(int type) {
    auto* rta = static_cast<rtattr*>(claim(RTA_LENGTH(0)));
    if (rta) rta->rta_type = type;
    return rta;
  }

  void end_nest(rtattr* nest) {
    if (nest) nest->rta_len = (uint16_t)((buf + len) - (uint8_t*)nest);
  }
};

// struct rtvia has a trailing flexible address — build it by hand.
void put_via(Writer& w, const OnlNexthop& nh) {
  uint8_t via[2 + 16];
  uint16_t fam = nh.family;
  memcpy(via, &fam, 2);
  int alen = addr_len(nh.family);
  memcpy(via + 2, nh.gateway, alen);
  w.put_attr(RTA_VIA, via, 2 + alen);
}

// MPLS label stack in wire format (mpls_entry: 20-bit label << 12, S-bit
// on the last entry), for RTA_DST/RTA_NEWDST/MPLS_IPTUNNEL_DST.
int encode_label_stack(const uint32_t* labels, int count, uint8_t* out) {
  for (int i = 0; i < count; ++i) {
    uint32_t entry = (labels[i] & 0xFFFFF) << MPLS_LS_LABEL_SHIFT;
    if (i == count - 1) entry |= 1u << MPLS_LS_S_SHIFT;
    entry = __builtin_bswap32(entry);
    memcpy(out + 4 * i, &entry, 4);
  }
  return 4 * count;
}

int decode_label_stack(const uint8_t* data, int dlen, uint32_t* out, int cap) {
  int n = 0;
  for (int off = 0; off + 4 <= dlen && n < cap; off += 4) {
    uint32_t entry;
    memcpy(&entry, data + off, 4);
    entry = __builtin_bswap32(entry);
    out[n++] = (entry >> MPLS_LS_LABEL_SHIFT) & 0xFFFFF;
    if (entry & (1u << MPLS_LS_S_SHIFT)) break;
  }
  return n;
}

// Per-nexthop attributes shared by single-path and multipath encodings.
void put_nexthop_attrs(Writer& w, const OnlRoute& r, const OnlNexthop& nh) {
  if (r.family == AF_MPLS) {
    // label route: gateway via RTA_VIA, swap stack via RTA_NEWDST
    if (nh.label_action == ONL_LBL_SWAP && nh.label_count > 0) {
      uint8_t stack[4 * ONL_MAX_LABELS];
      int n = encode_label_stack(nh.labels, nh.label_count, stack);
      w.put_attr(RTA_NEWDST, stack, n);
    }
    if (nh.family) put_via(w, nh);
  } else {
    if (nh.label_action == ONL_LBL_PUSH && nh.label_count > 0) {
      uint16_t encap_type = LWTUNNEL_ENCAP_MPLS;
      w.put_attr(RTA_ENCAP_TYPE, &encap_type, 2);
      rtattr* nest = w.begin_nest(RTA_ENCAP | NLA_F_NESTED);
      uint8_t stack[4 * ONL_MAX_LABELS];
      int n = encode_label_stack(nh.labels, nh.label_count, stack);
      w.put_attr(MPLS_IPTUNNEL_DST, stack, n);
      w.end_nest(nest);
    }
    if (nh.family && nh.family != r.family) {
      // cross-family gateway (RFC 5549: v4 route via v6 nexthop) rides
      // RTA_VIA; same-family uses the classic RTA_GATEWAY
      put_via(w, nh);
    } else if (nh.family) {
      w.put_attr(RTA_GATEWAY, nh.gateway, addr_len(nh.family));
    }
  }
}

}  // namespace

// ---- encoders ------------------------------------------------------------

// Returns encoded length, or -1 on overflow / bad input.
int onl_encode_route(const OnlRoute* r, int is_del, int replace, uint32_t seq,
                     uint32_t pid, uint8_t* out, int cap) {
  if (!r || r->nh_count > ONL_MAX_NEXTHOPS) return -1;
  for (uint32_t i = 0; i < r->nh_count; ++i) {
    if (r->nh[i].label_count > ONL_MAX_LABELS) return -1;
  }
  Writer w{out, cap};
  auto* nlh = static_cast<nlmsghdr*>(w.claim(NLMSG_LENGTH(sizeof(rtmsg))));
  if (!nlh) return -1;
  nlh->nlmsg_type = is_del ? RTM_DELROUTE : RTM_NEWROUTE;
  nlh->nlmsg_flags = NLM_F_REQUEST | NLM_F_ACK;
  if (!is_del) {
    nlh->nlmsg_flags |= NLM_F_CREATE | (replace ? NLM_F_REPLACE : 0);
  }
  nlh->nlmsg_seq = seq;
  nlh->nlmsg_pid = pid;

  auto* rtm = static_cast<rtmsg*>(NLMSG_DATA(nlh));
  rtm->rtm_family = r->family;
  rtm->rtm_table = r->table ? r->table : RT_TABLE_MAIN;
  rtm->rtm_protocol = r->protocol;
  rtm->rtm_scope = RT_SCOPE_UNIVERSE;
  rtm->rtm_type = r->route_type ? r->route_type : RTN_UNICAST;
  rtm->rtm_dst_len = r->family == AF_MPLS ? 20 : r->prefix_len;

  if (r->family == AF_MPLS) {
    uint8_t stack[4];
    encode_label_stack(&r->mpls_label, 1, stack);
    w.put_attr(RTA_DST, stack, 4);
  } else {
    w.put_attr(RTA_DST, r->dst, addr_len(r->family));
  }
  if (r->priority) w.put_attr(RTA_PRIORITY, &r->priority, 4);

  if (r->nh_count == 1) {
    const OnlNexthop& nh = r->nh[0];
    put_nexthop_attrs(w, *r, nh);
    if (nh.if_index >= 0) {
      uint32_t oif = (uint32_t)nh.if_index;
      w.put_attr(RTA_OIF, &oif, 4);
    }
  } else if (r->nh_count > 1) {
    rtattr* nest = w.begin_nest(RTA_MULTIPATH);
    for (uint32_t i = 0; i < r->nh_count; ++i) {
      const OnlNexthop& nh = r->nh[i];
      auto* rtnh = static_cast<rtnexthop*>(w.claim(sizeof(rtnexthop)));
      if (!rtnh) return -1;
      rtnh->rtnh_ifindex = nh.if_index >= 0 ? nh.if_index : 0;
      rtnh->rtnh_hops = nh.weight ? (uint8_t)(nh.weight - 1) : 0;
      put_nexthop_attrs(w, *r, nh);
      rtnh->rtnh_len = (uint16_t)((w.buf + w.len) - (uint8_t*)rtnh);
    }
    w.end_nest(nest);
  }

  if (w.overflow) return -1;
  nlh->nlmsg_len = w.len;
  return w.len;
}

int onl_encode_addr(int is_del, uint32_t seq, uint32_t pid, int if_index,
                    uint8_t family, const uint8_t* addr, uint8_t prefix_len,
                    uint8_t* out, int cap) {
  Writer w{out, cap};
  auto* nlh = static_cast<nlmsghdr*>(w.claim(NLMSG_LENGTH(sizeof(ifaddrmsg))));
  if (!nlh) return -1;
  nlh->nlmsg_type = is_del ? RTM_DELADDR : RTM_NEWADDR;
  nlh->nlmsg_flags = NLM_F_REQUEST | NLM_F_ACK | (is_del ? 0 : NLM_F_CREATE | NLM_F_REPLACE);
  nlh->nlmsg_seq = seq;
  nlh->nlmsg_pid = pid;
  auto* ifa = static_cast<ifaddrmsg*>(NLMSG_DATA(nlh));
  ifa->ifa_family = family;
  ifa->ifa_prefixlen = prefix_len;
  ifa->ifa_index = if_index;
  w.put_attr(IFA_LOCAL, addr, addr_len(family));
  w.put_attr(IFA_ADDRESS, addr, addr_len(family));
  if (w.overflow) return -1;
  nlh->nlmsg_len = w.len;
  return w.len;
}

// Dump request: type is RTM_GETLINK / RTM_GETADDR / RTM_GETROUTE / RTM_GETNEIGH.
int onl_encode_dump(uint16_t type, uint8_t family, uint32_t seq, uint32_t pid,
                    uint8_t* out, int cap) {
  Writer w{out, cap};
  // GETLINK wants ifinfomsg; the others take rtgenmsg/ifaddrmsg — a
  // zeroed ifinfomsg-sized payload with the family in byte 0 covers all.
  auto* nlh = static_cast<nlmsghdr*>(w.claim(NLMSG_LENGTH(sizeof(ifinfomsg))));
  if (!nlh) return -1;
  nlh->nlmsg_type = type;
  nlh->nlmsg_flags = NLM_F_REQUEST | NLM_F_DUMP;
  nlh->nlmsg_seq = seq;
  nlh->nlmsg_pid = pid;
  auto* ifi = static_cast<ifinfomsg*>(NLMSG_DATA(nlh));
  ifi->ifi_family = family;
  nlh->nlmsg_len = w.len;
  return w.len;
}

// ---- decoder -------------------------------------------------------------

namespace {

void decode_link(const nlmsghdr* nlh, OnlMsg& m) {
  auto* ifi = static_cast<const ifinfomsg*>(NLMSG_DATA(nlh));
  m.kind = ONL_KIND_LINK;
  m.is_del = nlh->nlmsg_type == RTM_DELLINK;
  m.if_index = ifi->ifi_index;
  m.if_flags = ifi->ifi_flags;
  m.is_up = (ifi->ifi_flags & IFF_UP) && (ifi->ifi_flags & IFF_RUNNING);
  int alen = nlh->nlmsg_len - NLMSG_LENGTH(sizeof(ifinfomsg));
  for (const rtattr* rta = IFLA_RTA(ifi); RTA_OK(rta, alen);
       rta = RTA_NEXT(rta, alen)) {
    if (rta->rta_type == IFLA_IFNAME) {
      strncpy(m.if_name, static_cast<const char*>(RTA_DATA(rta)),
              ONL_IFNAME - 1);
    }
  }
}

void decode_addr(const nlmsghdr* nlh, OnlMsg& m) {
  auto* ifa = static_cast<const ifaddrmsg*>(NLMSG_DATA(nlh));
  m.kind = ONL_KIND_ADDR;
  m.is_del = nlh->nlmsg_type == RTM_DELADDR;
  m.if_index = (int32_t)ifa->ifa_index;
  m.family = ifa->ifa_family;
  m.prefix_len = ifa->ifa_prefixlen;
  int alen = nlh->nlmsg_len - NLMSG_LENGTH(sizeof(ifaddrmsg));
  for (const rtattr* rta = IFA_RTA(ifa); RTA_OK(rta, alen);
       rta = RTA_NEXT(rta, alen)) {
    if (rta->rta_type == IFA_ADDRESS || rta->rta_type == IFA_LOCAL) {
      memcpy(m.addr, RTA_DATA(rta), addr_len(ifa->ifa_family));
      m.addr_valid = 1;
      if (rta->rta_type == IFA_LOCAL) break;  // prefer IFA_LOCAL
    }
  }
}

void decode_nh_attrs(const rtattr* rta, int alen, OnlNexthop& nh,
                     uint8_t family) {
  for (; RTA_OK(rta, alen); rta = RTA_NEXT(rta, alen)) {
    switch (rta->rta_type & ~NLA_F_NESTED) {
      case RTA_GATEWAY:
        nh.family = family == AF_INET ? AF_INET : AF_INET6;
        memcpy(nh.gateway, RTA_DATA(rta), addr_len(nh.family));
        break;
      case RTA_VIA: {
        const uint8_t* d = static_cast<const uint8_t*>(RTA_DATA(rta));
        uint16_t fam;
        memcpy(&fam, d, 2);
        nh.family = (uint8_t)fam;
        memcpy(nh.gateway, d + 2, addr_len(nh.family));
        break;
      }
      case RTA_OIF:
        memcpy(&nh.if_index, RTA_DATA(rta), 4);
        break;
      case RTA_NEWDST:
        nh.label_action = ONL_LBL_SWAP;
        nh.label_count = (uint8_t)decode_label_stack(
            static_cast<const uint8_t*>(RTA_DATA(rta)),
            (int)RTA_PAYLOAD(rta), nh.labels, ONL_MAX_LABELS);
        break;
      case RTA_ENCAP: {
        int nlen = (int)RTA_PAYLOAD(rta);
        for (const rtattr* e = static_cast<const rtattr*>(RTA_DATA(rta));
             RTA_OK(e, nlen); e = RTA_NEXT(e, nlen)) {
          if (e->rta_type == MPLS_IPTUNNEL_DST) {
            nh.label_action = ONL_LBL_PUSH;
            nh.label_count = (uint8_t)decode_label_stack(
                static_cast<const uint8_t*>(RTA_DATA(e)),
                (int)RTA_PAYLOAD(e), nh.labels, ONL_MAX_LABELS);
          }
        }
        break;
      }
    }
  }
}

void decode_route(const nlmsghdr* nlh, OnlMsg& m) {
  auto* rtm = static_cast<const rtmsg*>(NLMSG_DATA(nlh));
  m.kind = ONL_KIND_ROUTE;
  m.is_del = nlh->nlmsg_type == RTM_DELROUTE;
  OnlRoute& r = m.route;
  r.family = rtm->rtm_family;
  r.prefix_len = rtm->rtm_dst_len;
  r.table = rtm->rtm_table;
  r.protocol = rtm->rtm_protocol;
  r.route_type = rtm->rtm_type;

  OnlNexthop top{};
  top.if_index = -1;
  bool have_top = false;

  int alen = nlh->nlmsg_len - NLMSG_LENGTH(sizeof(rtmsg));
  for (const rtattr* rta = RTM_RTA(rtm); RTA_OK(rta, alen);
       rta = RTA_NEXT(rta, alen)) {
    switch (rta->rta_type & ~NLA_F_NESTED) {
      case RTA_DST:
        if (rtm->rtm_family == AF_MPLS) {
          uint32_t lbl;
          decode_label_stack(static_cast<const uint8_t*>(RTA_DATA(rta)),
                             (int)RTA_PAYLOAD(rta), &lbl, 1);
          r.mpls_label = lbl;
        } else {
          memcpy(r.dst, RTA_DATA(rta), addr_len(rtm->rtm_family));
        }
        break;
      case RTA_PRIORITY:
        memcpy(&r.priority, RTA_DATA(rta), 4);
        break;
      case RTA_MULTIPATH: {
        int mlen = (int)RTA_PAYLOAD(rta);
        for (const rtnexthop* rtnh = static_cast<const rtnexthop*>(RTA_DATA(rta));
             mlen >= (int)sizeof(rtnexthop) && rtnh->rtnh_len >= sizeof(rtnexthop) &&
             rtnh->rtnh_len <= mlen;
             mlen -= NLMSG_ALIGN(rtnh->rtnh_len),
             rtnh = reinterpret_cast<const rtnexthop*>(
                 reinterpret_cast<const uint8_t*>(rtnh) + NLMSG_ALIGN(rtnh->rtnh_len))) {
          if (r.nh_count >= ONL_MAX_NEXTHOPS) break;
          OnlNexthop& nh = r.nh[r.nh_count++];
          memset(&nh, 0, sizeof(nh));
          nh.if_index = rtnh->rtnh_ifindex;
          nh.weight = rtnh->rtnh_hops + 1;
          decode_nh_attrs(reinterpret_cast<const rtattr*>(RTNH_DATA(rtnh)),
                          rtnh->rtnh_len - sizeof(rtnexthop), nh,
                          rtm->rtm_family);
        }
        break;
      }
      default: {
        // top-level single-nexthop attributes
        decode_nh_attrs(rta, RTA_LENGTH(RTA_PAYLOAD(rta)), top,
                        rtm->rtm_family);
        if (rta->rta_type == RTA_GATEWAY || rta->rta_type == RTA_VIA ||
            rta->rta_type == RTA_OIF || rta->rta_type == RTA_NEWDST ||
            (rta->rta_type & ~NLA_F_NESTED) == RTA_ENCAP) {
          have_top = true;
        }
        break;
      }
    }
  }
  if (r.nh_count == 0 && have_top) {
    r.nh[0] = top;
    r.nh_count = 1;
  }
}

void decode_neigh(const nlmsghdr* nlh, OnlMsg& m) {
  auto* ndm = static_cast<const ndmsg*>(NLMSG_DATA(nlh));
  m.kind = ONL_KIND_NEIGH;
  m.is_del = nlh->nlmsg_type == RTM_DELNEIGH;
  m.if_index = ndm->ndm_ifindex;
  m.family = ndm->ndm_family;
  m.neigh_state = ndm->ndm_state;
  int alen = nlh->nlmsg_len - NLMSG_LENGTH(sizeof(ndmsg));
  for (const rtattr* rta = reinterpret_cast<const rtattr*>(
           reinterpret_cast<const uint8_t*>(ndm) + NLMSG_ALIGN(sizeof(ndmsg)));
       RTA_OK(rta, alen); rta = RTA_NEXT(rta, alen)) {
    if (rta->rta_type == NDA_DST) {
      memcpy(m.addr, RTA_DATA(rta), addr_len(ndm->ndm_family));
      m.addr_valid = 1;
    }
  }
}

}  // namespace

// Decode a recv buffer of netlink messages into `out[0..cap)`.
// Returns number of messages decoded (unknown types are skipped).
// `consumed` (optional) reports bytes processed so a caller can resume
// decoding a buffer holding more than `cap` messages.
int onl_decode(const uint8_t* buf, int len, OnlMsg* out, int cap,
               int* consumed) {
  int n = 0;
  const int total = len;
  const nlmsghdr* nlh = reinterpret_cast<const nlmsghdr*>(buf);
  for (; NLMSG_OK(nlh, (unsigned)len) && n < cap; nlh = NLMSG_NEXT(nlh, len)) {
    OnlMsg& m = out[n];
    memset(&m, 0, sizeof(m));
    m.nlmsg_type = nlh->nlmsg_type;
    m.seq = nlh->nlmsg_seq;
    m.if_index = -1;
    switch (nlh->nlmsg_type) {
      case NLMSG_DONE:
        m.kind = ONL_KIND_DONE;
        ++n;
        break;
      case NLMSG_ERROR: {
        auto* err = static_cast<const nlmsgerr*>(NLMSG_DATA(nlh));
        m.kind = ONL_KIND_ACK;
        m.error = err->error;
        m.seq = err->msg.nlmsg_seq;  // ack carries the request's seq
        ++n;
        break;
      }
      case RTM_NEWLINK:
      case RTM_DELLINK:
        decode_link(nlh, m);
        ++n;
        break;
      case RTM_NEWADDR:
      case RTM_DELADDR:
        decode_addr(nlh, m);
        ++n;
        break;
      case RTM_NEWROUTE:
      case RTM_DELROUTE:
        decode_route(nlh, m);
        ++n;
        break;
      case RTM_NEWNEIGH:
      case RTM_DELNEIGH:
        decode_neigh(nlh, m);
        ++n;
        break;
      default:
        break;  // skip
    }
  }
  if (consumed) {
    *consumed = NLMSG_OK(nlh, (unsigned)len)
                    ? (int)(reinterpret_cast<const uint8_t*>(nlh) - buf)
                    : total;
  }
  return n;
}

int onl_msg_size(void) { return (int)sizeof(OnlMsg); }
int onl_route_size(void) { return (int)sizeof(OnlRoute); }

}  // extern "C"
