// CSR bridge — native fill path for the LinkState -> device-array encoder.
//
// Role (SURVEY §7 hard-part 4 / design stance): the thrift⇄CSR bridge that
// feeds the TPU must fit inside Decision's 10-250ms debounce budget.  The
// Python encoder's per-element fill loop costs ~11ms at 4096 nodes /
// 32k directed edges; this translation unit does the same expansion in one
// C pass over caller-provided numpy buffers (zero copies, zero Python
// objects).  Loaded via ctypes by openr_tpu/ops/csr.py, which keeps a
// pure-Python fallback.
//
// Contract (mirrors encode_link_state, openr_tpu/ops/csr.py):
//   inputs: per-undirected-link columns a[L], b[L] (node ids),
//           metric[L] (float32), ok[L] (uint8)
//   outputs (pre-allocated, length padded_e >= 2L):
//           src/dst int32 (0-padded), w float32 (+inf padded),
//           edge_ok uint8 (0-padded), link_index int32 (-1 padded)
//   directed expansion: link i becomes edges 2i (a->b) and 2i+1 (b->a),
//   both carrying link_index=i; down links keep w=+inf / edge_ok=0.
// Returns 0 on success, -1 on bad sizes, -2 on non-positive metric of an
// up link (the device SPF's DAG-equality propagation requires metric>=1).

#include <cmath>
#include <cstdint>
#include <limits>

extern "C" {

// pad_node: node id used for padding edges' endpoints.  It must be the
// HIGHEST padded node id so that after the encoder's dst-sort the padding
// lands at the tail — root-out lane ranks (cumsum over src==root) would
// otherwise be polluted for low-id roots.
int csr_expand_fill(int32_t num_links,
                    const int32_t* a,
                    const int32_t* b,
                    const float* metric,
                    const uint8_t* ok,
                    int32_t padded_e,
                    int32_t pad_node,
                    int32_t* src,
                    int32_t* dst,
                    float* w,
                    uint8_t* edge_ok,
                    int32_t* link_index) {
  const int64_t E = 2 * (int64_t)num_links;
  if (num_links < 0 || padded_e < E) return -1;
  const float inf = std::numeric_limits<float>::infinity();
  for (int32_t i = 0; i < num_links; ++i) {
    const int64_t e = 2 * (int64_t)i;
    const uint8_t up = ok[i];
    if (up && !(metric[i] > 0.0f)) return -2;
    src[e] = a[i];
    dst[e] = b[i];
    src[e + 1] = b[i];
    dst[e + 1] = a[i];
    link_index[e] = i;
    link_index[e + 1] = i;
    const float m = up ? metric[i] : inf;
    w[e] = m;
    w[e + 1] = m;
    edge_ok[e] = up;
    edge_ok[e + 1] = up;
  }
  for (int64_t e = E; e < padded_e; ++e) {
    src[e] = pad_node;
    dst[e] = pad_node;
    w[e] = inf;
    edge_ok[e] = 0;
    link_index[e] = -1;
  }
  return 0;
}

// Batched what-if expansion: for each snapshot s, failed_links[s*F..] lists
// undirected link ids to fail (-1 = unused slot); writes mask[s][e] = 0 for
// both directed edges of each failed link, 1 elsewhere.  link_edge_pos
// ([num_links][2], from EncodedTopology) maps a link id to its directed
// edges' positions in the dst-sorted layout.  One pass replaces a Python
// loop over (snapshots x fails).
int csr_failure_masks(int32_t num_snapshots,
                      int32_t fails_per_snapshot,
                      const int32_t* failed_links,
                      const int32_t* link_edge_pos,
                      int32_t padded_e,
                      int32_t num_links,
                      uint8_t* mask) {
  if (num_snapshots < 0 || fails_per_snapshot < 0) return -1;
  const int64_t total = (int64_t)num_snapshots * padded_e;
  for (int64_t i = 0; i < total; ++i) mask[i] = 1;
  for (int32_t s = 0; s < num_snapshots; ++s) {
    uint8_t* row = mask + (int64_t)s * padded_e;
    for (int32_t f = 0; f < fails_per_snapshot; ++f) {
      const int32_t li = failed_links[(int64_t)s * fails_per_snapshot + f];
      if (li < 0 || li >= num_links) continue;
      const int32_t e0 = link_edge_pos[2 * li];
      const int32_t e1 = link_edge_pos[2 * li + 1];
      if (e0 >= 0 && e0 < padded_e) row[e0] = 0;
      if (e1 >= 0 && e1 < padded_e) row[e1] = 0;
    }
  }
  return 0;
}

}  // extern "C"
