"""netns lab — real multi-node deployment on one machine.

Reference parity: openr/orie/labs (orie_helper.sh + json2netns): every
node is a Linux network namespace, links are veth pairs, and each
namespace runs a REAL daemon (`python -m openr_tpu --real`): Spark
discovers neighbors over actual IPv6 link-local UDP multicast on the
veths, KvStore syncs over actual TCP to the neighbor's ctrl server, and
Fib programs actual kernel routes (proto 99) into the namespace FIB via
netlink.

Requires CAP_NET_ADMIN (root).  Usage:

    python -m labs.netns_lab up --topology line --nodes 3
    python -m labs.netns_lab status
    ip netns exec openr-lab-node0 ip route show proto 99
    python -m labs.netns_lab down

Programmatic use (tests): `NetnsLab(...)` as a context manager.
"""

from __future__ import annotations

import argparse
import json
import os
import shlex
import signal
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

NS_PREFIX = "openr-lab-"
ROUTE_PROTO = "99"


def sh(cmd: str, check: bool = True) -> subprocess.CompletedProcess:
    return subprocess.run(
        shlex.split(cmd), check=check, capture_output=True, text=True
    )


def in_ns(ns: str, cmd: str, check: bool = True) -> subprocess.CompletedProcess:
    return sh(f"ip netns exec {ns} {cmd}", check=check)


def have_netns_caps() -> bool:
    """Can we create/destroy namespaces + veths here?"""
    probe = f"{NS_PREFIX}probe"
    try:
        sh(f"ip netns add {probe}")
        sh(f"ip netns del {probe}")
        return True
    except (subprocess.CalledProcessError, FileNotFoundError):
        return False


def topology_edges(kind: str, n: int) -> List[Tuple[int, int]]:
    if kind == "line":
        return [(i, i + 1) for i in range(n - 1)]
    if kind == "ring":
        return [(i, (i + 1) % n) for i in range(n)]
    if kind == "full":
        return [(i, j) for i in range(n) for j in range(i + 1, n)]
    if kind == "grid":
        # cols=8 grid (redundant paths -> survives chaos churn); the
        # 32-node lab is 8x4
        cols = 8
        out = []
        for i in range(n):
            r, c = divmod(i, cols)
            if c + 1 < cols and i + 1 < n:
                out.append((i, i + 1))
            if (r + 1) * cols + c < n:
                out.append((i, i + cols))
        return out
    if kind == "multiarea":
        # two pods + spine (reference labs 201/202 shape):
        #   pod1: 0-1-2-3   spine: 3-4   pod2: 4-5-6-7
        # nodes 3 and 4 are the area border routers
        if n != 8:
            raise ValueError("multiarea topology requires exactly 8 nodes")
        return [(i, i + 1) for i in range(7)]
    raise ValueError(f"unknown topology {kind!r}")


@dataclass
class NetnsLab:
    num_nodes: int = 3
    topology: str = "line"
    ctrl_port: int = 2018  # same port in every namespace (isolated stacks)
    work_dir: str = ""
    fib_mode: str = "netlink"
    #: LSDB flood-payload encoding: "json", "thrift-compact", or
    #: "mixed" (even nodes compact, odd JSON — the migration shape;
    #: decode sniffs, so the formats interoperate)
    lsdb_wire_format: str = "json"
    #: peer RPC plane: "jsonrpc" or "rocket" (fbthrift Rocket framing —
    #: the reference's wire protocol; KvStore sync + floods then ride
    #: Compact thrift structs in rsocket frames on the ctrl port)
    lsdb_rpc_transport: str = "jsonrpc"
    procs: Dict[str, subprocess.Popen] = field(default_factory=dict)

    def node_name(self, i: int) -> str:
        return f"node{i}"

    def ns_name(self, i: int) -> str:
        return f"{NS_PREFIX}{self.node_name(i)}"

    def originated_prefix(self, i: int) -> str:
        return f"10.77.{i}.0/24"

    # -- bring-up -----------------------------------------------------------

    def up(self) -> None:
        if not self.work_dir:
            self.work_dir = tempfile.mkdtemp(prefix="openr_lab_")
        for i in range(self.num_nodes):
            # clear any leftover namespace from a crashed previous run
            for pid in sh(
                f"ip netns pids {self.ns_name(i)}", check=False
            ).stdout.split():
                sh(f"kill -9 {pid}", check=False)
            sh(f"ip netns del {self.ns_name(i)}", check=False)
            sh(f"ip netns add {self.ns_name(i)}")
            in_ns(self.ns_name(i), "ip link set lo up")
        for a, b in topology_edges(self.topology, self.num_nodes):
            va, vb = f"ve{a}_{b}", f"ve{b}_{a}"
            sh(f"ip link add {va} type veth peer name {vb}")
            sh(f"ip link set {va} netns {self.ns_name(a)}")
            sh(f"ip link set {vb} netns {self.ns_name(b)}")
            in_ns(self.ns_name(a), f"ip link set {va} up")
            in_ns(self.ns_name(b), f"ip link set {vb} up")
        # let IPv6 link-local DAD settle before daemons bind multicast
        time.sleep(1.0)
        for i in range(self.num_nodes):
            self.start_daemon(i)

    @property
    def POLICY_DROPPED_PREFIX(self) -> str:
        """The prefix the pod2 import policy drops in the multiarea lab
        (node1's originated prefix — derived, so a prefix-scheme change
        can't silently detune the policy assertions)."""
        return self.originated_prefix(1)

    def node_config(self, i: int) -> dict:
        name = self.node_name(i)
        cfg = {
            "node_name": name,
            "openr_ctrl_port": self.ctrl_port,
            "persistent_store_path": f"{self.work_dir}/{name}_store.bin",
            "rib_policy_file": f"{self.work_dir}/{name}_rib_policy.bin",
            "originated_prefixes": [
                {"prefix": self.originated_prefix(i), "install_to_fib": False}
            ],
            # faster discovery/liveness so convergence is robust under a
            # loaded CI host (defaults: hello 20s would stretch recovery
            # from any missed fast-init window past the test budget)
            "spark_config": {
                "hello_time_s": 2.0,
                "hold_time_s": 10.0,
                "heartbeat_time_s": 1.0,
            },
            # N daemons on one host must not contend for the one TPU chip;
            # small-topology SPF is scalar-fast anyway (see benchmarks)
            "tpu_compute_config": {"enable_tpu_spf": False},
            # v6-only veils carrying v4 prefixes (RFC 5549)
            "v4_over_v6_nexthop": True,
        }
        if self.lsdb_wire_format == "mixed":
            cfg["lsdb_wire_format"] = (
                "thrift-compact" if i % 2 == 0 else "json"
            )
        elif self.lsdb_wire_format != "json":
            cfg["lsdb_wire_format"] = self.lsdb_wire_format
        if self.lsdb_rpc_transport != "jsonrpc":
            cfg["lsdb_rpc_transport"] = self.lsdb_rpc_transport
        if self.topology == "multiarea":
            cfg["areas"] = self._multiarea_areas(i)
            if i == 4:
                # labs-202-style policy: pod2's border rejects node1's
                # prefix at area import; everything else passes
                cfg["policy_config"] = {
                    "definitions": [
                        {
                            "name": "pod2-import",
                            "statements": [
                                {
                                    "name": "drop-node1-prefix",
                                    "criteria": [
                                        {
                                            "prefixes": [
                                                {
                                                    "prefix": (
                                                        self.POLICY_DROPPED_PREFIX
                                                    )
                                                }
                                            ]
                                        }
                                    ],
                                    "action": {"accept": False},
                                },
                                {
                                    "name": "accept-rest",
                                    "criteria": [{"always_match": True}],
                                    "action": {"accept": True},
                                },
                            ],
                        }
                    ]
                }
        return cfg

    def _multiarea_areas(self, i: int) -> List[dict]:
        """pod1 = nodes 0-3, spine = 3-4, pod2 = 4-7; border nodes pin
        each area to its interfaces (AreaConfig regexes)."""
        if i <= 2:
            return [{"area_id": "pod1"}]
        if i == 3:
            return [
                {
                    "area_id": "pod1",
                    "include_interface_regexes": [r"ve3_2"],
                },
                {
                    "area_id": "spine",
                    "include_interface_regexes": [r"ve3_4"],
                },
            ]
        if i == 4:
            return [
                {
                    "area_id": "spine",
                    "include_interface_regexes": [r"ve4_3"],
                },
                {
                    "area_id": "pod2",
                    "include_interface_regexes": [r"ve4_5"],
                    "import_policy": "pod2-import",
                },
            ]
        return [{"area_id": "pod2"}]

    def start_daemon(self, i: int) -> None:
        name = self.node_name(i)
        cfg_path = f"{self.work_dir}/{name}.json"
        with open(cfg_path, "w") as f:
            json.dump(self.node_config(i), f)
        log = open(f"{self.work_dir}/{name}.log", "w")
        env = dict(os.environ)
        # lab daemons must never touch the (single, possibly busy) TPU —
        # any stray jax usage stays on CPU
        env["JAX_PLATFORMS"] = "cpu"
        self.procs[name] = subprocess.Popen(
            [
                "ip", "netns", "exec", self.ns_name(i),
                sys.executable, "-m", "openr_tpu",
                "--config", cfg_path, "--real", "--fib", self.fib_mode,
            ],
            stdout=log,
            stderr=subprocess.STDOUT,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )

    def stop_daemon(self, i: int) -> None:
        proc = self.procs.pop(self.node_name(i), None)
        if proc is None:
            return
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=5)

    # -- observation ---------------------------------------------------------

    def link_ifaces(self, a: int, b: int) -> Tuple[str, str]:
        """(iface in a's ns, iface in b's ns) for edge (a, b)."""
        if a > b:
            a, b = b, a
        return f"ve{a}_{b}", f"ve{b}_{a}"

    def fail_link(self, a: int, b: int) -> None:
        """Take the veth down on BOTH ends (kernel carrier loss — Spark
        hold timers + LinkMonitor netlink events drive reconvergence)."""
        va, vb = self.link_ifaces(a, b)
        lo, hi = (a, b) if a < b else (b, a)
        in_ns(self.ns_name(lo), f"ip link set {va} down")
        in_ns(self.ns_name(hi), f"ip link set {vb} down")

    def heal_link(self, a: int, b: int) -> None:
        va, vb = self.link_ifaces(a, b)
        lo, hi = (a, b) if a < b else (b, a)
        in_ns(self.ns_name(lo), f"ip link set {va} up")
        in_ns(self.ns_name(hi), f"ip link set {vb} up")

    def kernel_routes(self, i: int) -> List[str]:
        out = in_ns(
            self.ns_name(i), f"ip route show proto {ROUTE_PROTO}", check=False
        ).stdout
        return [line.strip() for line in out.splitlines() if line.strip()]

    def breeze(self, i: int, *args: str) -> str:
        # rocket mode: fbthrift Rocket owns ctrl_port (the reference
        # shape); the JSON-RPC operator listener breeze dials sits one up
        port = self.ctrl_port + (
            1 if self.lsdb_rpc_transport == "rocket" else 0
        )
        cmd = (
            f"{sys.executable} -m openr_tpu.cli.breeze "
            f"--port {port} " + " ".join(args)
        )
        return in_ns(self.ns_name(i), cmd, check=False).stdout

    def expected_prefixes(self, i: int) -> List[str]:
        """Prefixes node i's kernel must hold at convergence.  In the
        multiarea lab, pod2's interior (nodes 5-7) must NOT receive the
        policy-dropped prefix — node4's import policy rejects it at the
        pod2 boundary."""
        out = []
        for j in range(self.num_nodes):
            if j == i:
                continue
            p = self.originated_prefix(j)
            if (
                self.topology == "multiarea"
                and i >= 5
                and p == self.POLICY_DROPPED_PREFIX
            ):
                continue
            out.append(p)
        return out

    def converged(self) -> Tuple[bool, str]:
        """Every node's kernel has a proto-99 route to every expected
        prefix."""
        for i in range(self.num_nodes):
            routes = "\n".join(self.kernel_routes(i))
            for want in self.expected_prefixes(i):
                if want not in routes:
                    return False, f"{self.node_name(i)} missing {want}"
        return True, "all kernels programmed"

    def log_tails(self, n_chars: int = 1200) -> str:
        out = []
        for name in sorted(self.procs):
            try:
                tail = open(f"{self.work_dir}/{name}.log").read()[-n_chars:]
            except OSError:
                tail = "<no log>"
            out.append(f"----- {name} -----\n{tail}")
        return "\n".join(out)

    def wait_converged(self, timeout_s: float = 60.0) -> None:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            ok, why = self.converged()
            if ok:
                return
            # surface a crashed daemon immediately instead of timing out
            for name, proc in self.procs.items():
                if proc.poll() is not None:
                    log = open(f"{self.work_dir}/{name}.log").read()[-2000:]
                    raise RuntimeError(f"daemon {name} died:\n{log}")
            time.sleep(1.0)
        ok, why = self.converged()
        if not ok:
            raise TimeoutError(
                f"lab did not converge: {why}\n{self.log_tails()}"
            )

    # -- teardown ------------------------------------------------------------

    def down(self) -> None:
        for i in range(self.num_nodes):
            self.stop_daemon(i)
        for i in range(self.num_nodes):
            sh(f"ip netns del {self.ns_name(i)}", check=False)

    def __enter__(self) -> "NetnsLab":
        self.up()
        return self

    def __exit__(self, *exc) -> None:
        self.down()


def existing_lab_namespaces() -> List[str]:
    out = sh("ip netns list", check=False).stdout
    return [
        line.split()[0]
        for line in out.splitlines()
        if line.startswith(NS_PREFIX) and "probe" not in line
    ]


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    up = sub.add_parser("up")
    up.add_argument("--nodes", type=int, default=3)
    up.add_argument("--topology", default="line",
                    choices=["line", "ring", "full", "multiarea"])
    up.add_argument("--fib", default="netlink")
    sub.add_parser("down")
    sub.add_parser("status")
    args = p.parse_args()

    if args.cmd == "up":
        lab = NetnsLab(
            num_nodes=args.nodes, topology=args.topology, fib_mode=args.fib
        )
        lab.up()
        print(f"lab up: {args.nodes} nodes ({args.topology}), "
              f"work dir {lab.work_dir}")
        print("waiting for kernel-route convergence...")
        lab.wait_converged()
        print("converged; namespaces stay up (down with: "
              "python -m labs.netns_lab down)")
    elif args.cmd == "down":
        namespaces = existing_lab_namespaces()
        for ns in namespaces:
            for pid in sh(f"ip netns pids {ns}", check=False).stdout.split():
                sh(f"kill {pid}", check=False)
            sh(f"ip netns del {ns}", check=False)
        print(f"removed {len(namespaces)} namespaces")
    elif args.cmd == "status":
        for ns in existing_lab_namespaces():
            routes = sh(
                f"ip netns exec {ns} ip route show proto {ROUTE_PROTO}",
                check=False,
            ).stdout.strip()
            print(f"{ns}:")
            for line in routes.splitlines():
                print(f"  {line}")


if __name__ == "__main__":
    main()
