"""Dispatcher — KvStore publication fan-out with key-prefix filtering.

Reference: openr/dispatcher/Dispatcher.{h,cpp} + DispatcherQueue: sits
between KvStore and its subscribers, replicating each publication to
readers whose key-prefix filter matches at least one key (e.g. Decision
subscribes to ``adj:`` + ``prefix:``, PrefixManager to ``prefix:`` —
Main.cpp:316-326).  Publications are *narrowed* per subscriber: only
matching key_vals/expired_keys are delivered.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from openr_tpu.common.runtime import Actor, Clock, CounterMap
from openr_tpu.messaging.queue import RQueue, ReplicateQueue
from openr_tpu.types import Publication


class Dispatcher(Actor):
    def __init__(
        self,
        clock: Clock,
        kv_store_updates_reader: RQueue,
        counters: Optional[CounterMap] = None,
    ) -> None:
        super().__init__("dispatcher", clock, counters)
        self.kv_store_updates_reader = kv_store_updates_reader
        #: (prefixes, queue) per subscriber
        self._subscribers: List[Tuple[Tuple[str, ...], ReplicateQueue]] = []

    def get_reader(
        self, key_prefixes: Sequence[str] = (), name: str = ""
    ) -> RQueue:
        """Subscribe with key-prefix filters; empty = everything
        (Dispatcher.h:53-54)."""
        q = ReplicateQueue(name or f"dispatcher.sub{len(self._subscribers)}")
        reader = q.get_reader(name=name)
        self._subscribers.append((tuple(key_prefixes), q))
        return reader

    def remove_reader(self, reader: RQueue) -> None:
        """Unsubscribe a transient reader (ctrl streams / long-polls); the
        reference drops the ServerStreamPublisher on stream close
        (OpenrCtrlHandler.h:364-399)."""
        for i, (_, q) in enumerate(self._subscribers):
            if q.remove_reader(reader):
                q.close()
                del self._subscribers[i]
                return

    def start(self) -> None:
        self.spawn_queue_loop(
            self.kv_store_updates_reader, self._on_publication, "dispatcher.main"
        )

    def _on_publication(self, pub: Publication) -> None:
        self.counters.bump("dispatcher.publications")
        for prefixes, q in self._subscribers:
            filtered = self._filter(pub, prefixes)
            if filtered is not None:
                q.push(filtered)

    @staticmethod
    def _filter(pub: Publication, prefixes: Tuple[str, ...]) -> Optional[Publication]:
        if not prefixes:
            return pub
        kv = {
            k: v
            for k, v in pub.key_vals.items()
            if any(k.startswith(p) for p in prefixes)
        }
        expired = [
            k for k in pub.expired_keys if any(k.startswith(p) for p in prefixes)
        ]
        if not kv and not expired:
            return None
        return Publication(
            key_vals=kv,
            expired_keys=expired,
            node_ids=pub.node_ids,
            area=pub.area,
            timestamp_ms=pub.timestamp_ms,
            trace_ctx=pub.trace_ctx,
        )

    def get_filters(self) -> List[Tuple[str, ...]]:
        """ctrl surface: per-subscriber filter dump (Dispatcher.h:53)."""
        return [p for p, _ in self._subscribers]

    def queue_stats(self) -> dict:
        """Gauge provider (Monitor.add_counter_provider): depth/watermark
        telemetry of the per-subscriber fan-out queues, which sit OUTSIDE
        the node's primary queue list but are exactly where a slow
        Decision consumer backs up first."""
        out = {}
        for _, q in self._subscribers:
            for stat, v in q.stats().items():
                out[f"messaging.queue.{q.name}.{stat}"] = v
        return out
