"""Device-mesh sharding for what-if topology sweeps.

The reference's scale axis is N daemons on N network nodes; ours adds a
compute axis: thousands of topology snapshots data-parallel over a TPU
mesh (SURVEY §2.3, §5 "batched topology parallelism").  Batches shard on
the ``batch`` axis; the (small) shared edge list and candidate tables are
replicated.  XLA inserts the collectives; on multi-host TPU the same code
runs over ICI/DCN unchanged.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

BATCH_AXIS = "batch"


def shard_map_supported() -> bool:
    """True when this jax exposes the stable ``jax.shard_map`` entry
    point the sharded kernels are written against (its ``check_vma``
    signature landed with the stable export).  Older environments only
    carry the incompatible ``jax.experimental.shard_map`` API; the
    sharded code paths (and their tests) gate on this instead of
    failing at dispatch time."""
    return hasattr(jax, "shard_map")


def make_mesh(
    num_devices: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """One-axis (``batch``) device mesh.

    ``devices`` pins an explicit placement (survivor meshes after a
    chip quarantine, tests that must land on specific chips); else the
    first ``num_devices`` of ``jax.devices()`` are taken.  Requesting
    more devices than exist raises instead of silently truncating —
    a survivor mesh built on a miscounted pool would shard onto chips
    the health governor never verified."""
    if devices is not None:
        devices = list(devices)
        if num_devices is not None and num_devices != len(devices):
            raise ValueError(
                f"num_devices={num_devices} contradicts the explicit "
                f"devices sequence of length {len(devices)}"
            )
        if not devices:
            raise ValueError("make_mesh needs at least one device")
        return Mesh(np.array(devices), (BATCH_AXIS,))
    avail = jax.devices()
    if num_devices is not None:
        if num_devices < 1:
            raise ValueError(f"num_devices must be >= 1, got {num_devices}")
        if num_devices > len(avail):
            raise ValueError(
                f"requested num_devices={num_devices} but only "
                f"{len(avail)} jax devices are available"
            )
        avail = avail[:num_devices]
    return Mesh(np.array(avail), (BATCH_AXIS,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(BATCH_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def padded_batch_size(mesh: Mesh, batch: int) -> int:
    n = mesh.devices.size
    return ((batch + n - 1) // n) * n


def shard_batch(mesh: Mesh, *arrays):
    """Place [B, ...] arrays with B sharded across the mesh.

    When B is not a multiple of the mesh size, every array is padded by
    REPLICATING its last batch row — a duplicated snapshot is always a
    semantically valid input regardless of what the array encodes, so
    padding needs no per-array fill rules.  Callers slice kernel outputs
    back to the original B (``padded_batch_size`` tells them the padded
    extent)."""
    sh = batch_sharding(mesh)
    n = mesh.devices.size
    out = []
    for a in arrays:
        b = a.shape[0]
        if b % n:
            # pad path only: pull to host once, replicate the tail row
            a = np.asarray(a)
            pad = np.repeat(a[-1:], padded_batch_size(mesh, b) - b, axis=0)
            a = np.concatenate([a, pad], axis=0)
        out.append(jax.device_put(a, sh))
    return tuple(out) if len(out) > 1 else out[0]


class DevicePool:
    """The live-device set for data-parallel dispatch — per-device
    failure domains made first-class.

    The mesh-collective kernels (shard_map) treat the device set as one
    opaque computer: a single sick chip corrupts the collective output
    with no way to say WHICH chip lied.  The pool instead models each
    device as an individually health-governed shard owner: work batches
    split into contiguous per-device shards, each dispatched as its own
    committed computation on its own chip, so every output row is
    attributable to exactly one device — the property the
    BackendHealthGovernor's per-chip shadow verification and quarantine
    are built on.

    Health writes (``quarantine_device`` / ``restore_device``) are
    owned by the resilience plane (the governor) and chaos — enforced
    statically by orlint's ``resilience-latch`` rule, exactly like the
    whole-backend ``device_failed`` latch.  Everything else reads.
    """

    def __init__(
        self,
        devices: Optional[Sequence] = None,
        max_devices: int = 0,
    ) -> None:
        if devices is None:
            devices = jax.devices()
        devices = list(devices)
        if max_devices and max_devices > len(devices):
            raise ValueError(
                f"max_devices={max_devices} exceeds the {len(devices)} "
                "visible jax devices"
            )
        if max_devices:
            devices = devices[:max_devices]
        if not devices:
            raise ValueError("DevicePool needs at least one device")
        self.devices: List = devices
        self._healthy: List[bool] = [True] * len(devices)
        self.num_quarantines = 0
        self.num_restores = 0
        #: monotonic health-mask generation: bumps on every quarantine /
        #: restore.  Consumers holding per-chip device-resident state
        #: (the backend's SPF-table replicas, the warm-rebuild context)
        #: compare it against the value they captured to detect that the
        #: shard packing re-packed underneath them and stale per-chip
        #: residency must be dropped.
        self.health_seq = 0
        #: per-chip committed-dispatch tally (route-build shards, fleet
        #: root chunks, what-if failure shards all count here — the
        #: pool is the shared dispatch plane), read by the pipeline
        #: attribution gauges and `breeze resilience status`
        self.num_dispatches: List[int] = [0] * len(devices)
        #: per-chip in-flight slot ledger for the streamed dispatch
        #: loops: a dispatch occupies a slot (`note_inflight`) until its
        #: streamed completion drains it (`note_complete`), so a
        #: committed dispatch never queues behind — or waits on — an
        #: UNRELATED chip: the double-buffer loop checks `inflight()`
        #: per chip and drains only that chip's oldest work.
        self.num_inflight: List[int] = [0] * len(devices)
        #: high-watermark of concurrent in-flight dispatches per chip —
        #: the observable proof the double-buffer loop actually overlaps
        self.max_inflight: List[int] = [0] * len(devices)

    # -- read surface ------------------------------------------------------

    @property
    def size(self) -> int:
        return len(self.devices)

    @property
    def num_healthy(self) -> int:
        return sum(self._healthy)

    def is_healthy(self, index: int) -> bool:
        return self._healthy[index]

    def healthy_indices(self) -> List[int]:
        return [i for i, ok in enumerate(self._healthy) if ok]

    def quarantined_indices(self) -> List[int]:
        return [i for i, ok in enumerate(self._healthy) if not ok]

    def device(self, index: int):
        return self.devices[index]

    def healthy_mask(self) -> List[bool]:
        return list(self._healthy)

    def note_dispatch(self, index: int) -> None:
        """Count one committed dispatch on chip ``index`` (called by the
        per-shard dispatch loops alongside the actual device_put/jit
        call — the pool's view of how work actually spread)."""
        self.num_dispatches[index] += 1

    def note_inflight(self, index: int) -> None:
        """A committed dispatch on chip ``index`` entered flight (its
        outputs are not yet drained).  Counts the dispatch too."""
        self.num_dispatches[index] += 1
        self.num_inflight[index] += 1
        if self.num_inflight[index] > self.max_inflight[index]:
            self.max_inflight[index] = self.num_inflight[index]

    def note_complete(self, index: int) -> None:
        """Chip ``index``'s oldest in-flight dispatch was drained."""
        if self.num_inflight[index] > 0:
            self.num_inflight[index] -= 1

    def inflight(self, index: int) -> int:
        return self.num_inflight[index]

    def lead_index(self) -> Optional[int]:
        """Lowest-indexed healthy device (single-device dispatch target);
        None when every chip is quarantined."""
        for i, ok in enumerate(self._healthy):
            if ok:
                return i
        return None

    # -- health mutators (resilience/chaos-owned; orlint-enforced) ---------

    def quarantine_device(self, index: int) -> bool:
        """Mark one chip unhealthy; shard packing re-packs onto the
        survivors from the next dispatch on.  Returns True when the
        mask actually flipped."""
        if not self._healthy[index]:
            return False
        self._healthy[index] = False
        self.num_quarantines += 1
        self.health_seq += 1
        return True

    def restore_device(self, index: int) -> bool:
        if self._healthy[index]:
            return False
        self._healthy[index] = True
        self.num_restores += 1
        self.health_seq += 1
        return True

    # -- shard packing -----------------------------------------------------

    def shard_ranges(
        self, n_rows: int, indices: Optional[Sequence[int]] = None
    ) -> List[Tuple[int, int, int]]:
        """Deterministic contiguous packing of ``n_rows`` over the given
        device indices (default: the healthy set): ``(device_index,
        row_lo, row_hi)`` per shard, even split with the remainder on
        the leading shards.  Devices that would receive zero rows are
        dropped, so tiny batches never pay empty dispatches."""
        if indices is None:
            indices = self.healthy_indices()
        indices = list(indices)
        if not indices:
            raise ValueError("shard_ranges: no devices to pack onto")
        n_dev = len(indices)
        base, rem = divmod(n_rows, n_dev)
        out: List[Tuple[int, int, int]] = []
        lo = 0
        for k, dev in enumerate(indices):
            hi = lo + base + (1 if k < rem else 0)
            if hi > lo:
                out.append((dev, lo, hi))
            lo = hi
        return out

    def survivor_mesh(self) -> Optional[Mesh]:
        """Mesh over the CURRENT healthy set for the shard_map-collective
        engines; None when the stable ``jax.shard_map`` is unavailable
        or fewer than two chips survive (the collective path needs a
        real mesh to beat per-device dispatch)."""
        healthy = [self.devices[i] for i in self.healthy_indices()]
        if len(healthy) < 2 or not shard_map_supported():
            return None
        return make_mesh(devices=healthy)

    # -- observability -----------------------------------------------------

    def status(self) -> dict:
        return {
            "size": self.size,
            "num_healthy": self.num_healthy,
            "healthy_mask": self.healthy_mask(),
            "quarantines": self.num_quarantines,
            "restores": self.num_restores,
            "dispatches": list(self.num_dispatches),
            "inflight": list(self.num_inflight),
            "max_inflight": list(self.max_inflight),
            "devices": [str(d) for d in self.devices],
        }

    def counter_snapshot(self, prefix: str = "parallel.pool") -> dict:
        out = {
            f"{prefix}.size": float(self.size),
            f"{prefix}.healthy": float(self.num_healthy),
            f"{prefix}.quarantines": float(self.num_quarantines),
            f"{prefix}.restores": float(self.num_restores),
        }
        for i, n in enumerate(self.num_dispatches):
            out[f"{prefix}.dev{i}.dispatches"] = float(n)
            out[f"{prefix}.dev{i}.max_inflight"] = float(
                self.max_inflight[i]
            )
        return out


def sharded_spf_and_select(mesh: Mesh, max_degree: int):
    """Build the sharded flagship kernel: batch-sharded SPF + route
    selection over the mesh.  Shared topology/candidate inputs are
    replicated; per-snapshot inputs and all outputs are batch-sharded."""
    from openr_tpu.ops.route_select import spf_and_select

    b = batch_sharding(mesh)
    r = replicated(mesh)
    fn = functools.partial(spf_and_select, max_degree=max_degree)
    return jax.jit(
        fn,
        in_shardings=(
            r,  # src
            r,  # dst
            r,  # w
            r,  # edge_ok
            b,  # edge_enabled [B, E]
            b,  # overloaded [B, V]
            b,  # soft [B, V]
            b,  # roots [B]
            r,  # cand_node
            r,  # cand_ok
            r,  # drain_metric
            r,  # path_pref
            r,  # source_pref
            r,  # distance
            r,  # min_nexthop
        ),
        out_shardings=(b, b, b, b, b),
    )
