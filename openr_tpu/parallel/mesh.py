"""Device-mesh sharding for what-if topology sweeps.

The reference's scale axis is N daemons on N network nodes; ours adds a
compute axis: thousands of topology snapshots data-parallel over a TPU
mesh (SURVEY §2.3, §5 "batched topology parallelism").  Batches shard on
the ``batch`` axis; the (small) shared edge list and candidate tables are
replicated.  XLA inserts the collectives; on multi-host TPU the same code
runs over ICI/DCN unchanged.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

BATCH_AXIS = "batch"


def shard_map_supported() -> bool:
    """True when this jax exposes the stable ``jax.shard_map`` entry
    point the sharded kernels are written against (its ``check_vma``
    signature landed with the stable export).  Older environments only
    carry the incompatible ``jax.experimental.shard_map`` API; the
    sharded code paths (and their tests) gate on this instead of
    failing at dispatch time."""
    return hasattr(jax, "shard_map")


def make_mesh(num_devices: Optional[int] = None) -> Mesh:
    devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    return Mesh(np.array(devices), (BATCH_AXIS,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(BATCH_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def padded_batch_size(mesh: Mesh, batch: int) -> int:
    n = mesh.devices.size
    return ((batch + n - 1) // n) * n


def shard_batch(mesh: Mesh, *arrays):
    """Place [B, ...] arrays with B sharded across the mesh.

    When B is not a multiple of the mesh size, every array is padded by
    REPLICATING its last batch row — a duplicated snapshot is always a
    semantically valid input regardless of what the array encodes, so
    padding needs no per-array fill rules.  Callers slice kernel outputs
    back to the original B (``padded_batch_size`` tells them the padded
    extent)."""
    sh = batch_sharding(mesh)
    n = mesh.devices.size
    out = []
    for a in arrays:
        b = a.shape[0]
        if b % n:
            # pad path only: pull to host once, replicate the tail row
            a = np.asarray(a)
            pad = np.repeat(a[-1:], padded_batch_size(mesh, b) - b, axis=0)
            a = np.concatenate([a, pad], axis=0)
        out.append(jax.device_put(a, sh))
    return tuple(out) if len(out) > 1 else out[0]


def sharded_spf_and_select(mesh: Mesh, max_degree: int):
    """Build the sharded flagship kernel: batch-sharded SPF + route
    selection over the mesh.  Shared topology/candidate inputs are
    replicated; per-snapshot inputs and all outputs are batch-sharded."""
    from openr_tpu.ops.route_select import spf_and_select

    b = batch_sharding(mesh)
    r = replicated(mesh)
    fn = functools.partial(spf_and_select, max_degree=max_degree)
    return jax.jit(
        fn,
        in_shardings=(
            r,  # src
            r,  # dst
            r,  # w
            r,  # edge_ok
            b,  # edge_enabled [B, E]
            b,  # overloaded [B, V]
            b,  # soft [B, V]
            b,  # roots [B]
            r,  # cand_node
            r,  # cand_ok
            r,  # drain_metric
            r,  # path_pref
            r,  # source_pref
            r,  # distance
            r,  # min_nexthop
        ),
        out_shardings=(b, b, b, b, b),
    )
