"""Node-level health set — the failure domain ABOVE the chip.

``DevicePool`` (mesh.py) models per-chip failure domains inside one
daemon; the fleet compute fabric (openr_tpu.fleet) needs the same
discipline one level up: which NODES are alive, which are drained for
maintenance, and a monotonic membership generation consumers compare to
detect that assignment re-packed underneath them.  ``NodeSet`` is that
primitive — a pure bookkeeping structure with DevicePool's shape
(healthy mask, seq, deterministic ordering) at node granularity.

Ownership: the fabric's membership plane (``FleetMembership``) is the
only writer — the fleet/chaos/emulation tiers drive IT, and orlint's
``fleet-directory`` rule enforces the boundary at the membership
surface, exactly like ``resilience-latch`` does for the chip mask.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple


class NodeSet:
    """The fleet's member nodes with per-node liveness + drain state.

    A node is *live* when it is up and not drained: live nodes receive
    sweep-world assignments and feed-directory ownership.  ``down`` is
    the crash shape (unexpected — alerts page); ``drained`` is the
    maintenance shape (expected — its load migrates quietly).  Both
    bump ``membership_seq`` so any consumer holding an assignment can
    detect the re-pack.
    """

    def __init__(self, names: Sequence[str]) -> None:
        names = [str(n) for n in names]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate node names: {names}")
        if not names:
            raise ValueError("NodeSet needs at least one node")
        #: deterministic member order (sorted once, never by arrival)
        self.names: Tuple[str, ...] = tuple(sorted(names))
        self._up: Dict[str, bool] = {n: True for n in self.names}
        self._drained: Dict[str, bool] = {n: False for n in self.names}
        #: monotonic membership generation: bumps on every down/up/
        #: drain/undrain transition (the node-level ``health_seq``)
        self.membership_seq = 0
        self.num_downs = 0
        self.num_restores = 0
        self.num_drains = 0

    # -- read surface ------------------------------------------------------

    @property
    def size(self) -> int:
        return len(self.names)

    def is_up(self, name: str) -> bool:
        return self._up[name]

    def is_drained(self, name: str) -> bool:
        return self._drained[name]

    def is_live(self, name: str) -> bool:
        return self._up[name] and not self._drained[name]

    def live_nodes(self) -> Tuple[str, ...]:
        """The sorted live set — the ONLY membership input the fleet's
        content-derived assignment and directory hashes consume."""
        return tuple(n for n in self.names if self.is_live(n))

    def down_nodes(self) -> Tuple[str, ...]:
        return tuple(n for n in self.names if not self._up[n])

    def drained_nodes(self) -> Tuple[str, ...]:
        return tuple(
            n for n in self.names if self._up[n] and self._drained[n]
        )

    # -- transitions (membership-plane owned) ------------------------------

    def mark_down(self, name: str) -> bool:
        if not self._up[name]:
            return False
        self._up[name] = False
        self.num_downs += 1
        self.membership_seq += 1
        return True

    def mark_up(self, name: str) -> bool:
        if self._up[name]:
            return False
        self._up[name] = True
        self._drained[name] = False
        self.num_restores += 1
        self.membership_seq += 1
        return True

    def mark_drained(self, name: str) -> bool:
        if self._drained[name] or not self._up[name]:
            return False
        self._drained[name] = True
        self.num_drains += 1
        self.membership_seq += 1
        return True

    def clear_drained(self, name: str) -> bool:
        if not self._drained[name]:
            return False
        self._drained[name] = False
        self.membership_seq += 1
        return True

    # -- observability -----------------------------------------------------

    def status(self) -> dict:
        return {
            "size": self.size,
            "live": list(self.live_nodes()),
            "down": list(self.down_nodes()),
            "drained": list(self.drained_nodes()),
            "membership_seq": self.membership_seq,
            "downs": self.num_downs,
            "restores": self.num_restores,
            "drains": self.num_drains,
        }

    def counter_snapshot(self, prefix: str = "parallel.nodes") -> dict:
        return {
            f"{prefix}.size": float(self.size),
            f"{prefix}.live": float(len(self.live_nodes())),
            f"{prefix}.downs": float(self.num_downs),
            f"{prefix}.drains": float(self.num_drains),
            f"{prefix}.membership_seq": float(self.membership_seq),
        }


def node_shard_counts(n_items: int, nodes: Sequence[str]) -> List[int]:
    """DevicePool.shard_ranges' even-split law at node granularity:
    ``n_items`` over ``len(nodes)`` with the remainder on the leading
    nodes (deterministic in the given node order)."""
    nodes = list(nodes)
    if not nodes:
        raise ValueError("node_shard_counts: no nodes to pack onto")
    base, rem = divmod(n_items, len(nodes))
    return [base + (1 if k < rem else 0) for k in range(len(nodes))]
