"""Suppression comments for orlint.

Two forms, both parsed from the file's COMMENT tokens (a raw line scan
would also match marker text quoted inside string literals — this very
docstring would silently disable rules for this file):

* line-level — a trailing comment on the *reported* line::

      self._alive_since = time.time()  # orlint: disable=clock-now (epoch, not protocol time)

  Everything after the rule list is free-form justification.  Multi-line
  statements are reported at the statement's first line; put the comment
  there.

* file-level — anywhere in the file, on its own line or trailing::

      # orlint: disable-file=clock-sleep,clock-now

  Use sparingly: a file-level disable also hides *future* violations in
  that file.  Reserved for files whose entire purpose violates a rule
  (e.g. common/runtime.py's WallClock IS the wrapper the clock rules
  steer everyone toward).

``disable=all`` suppresses every rule at that scope.
"""

from __future__ import annotations

import io
import re
import tokenize
from typing import Dict, Iterable, Optional, Set, Tuple

_LINE_RE = re.compile(r"#\s*orlint:\s*disable=([\w\-,* ]+)")
_FILE_RE = re.compile(r"#\s*orlint:\s*disable-file=([\w\-,* ]+)")

ALL = "all"


def _parse_rules(blob: str) -> Set[str]:
    return {r.strip() for r in blob.split(",") if r.strip()}


def _comment_lines(source: str) -> Optional[Set[int]]:
    """Line numbers holding a real ``#`` comment token — the only places
    a suppression marker is honored (marker text inside a string literal
    is documentation, not a directive).  None when the source does not
    tokenize (syntax errors, truncated fixtures): the caller falls back
    to the permissive every-line scan rather than dropping suppressions
    on the floor."""
    try:
        return {
            tok.start[0]
            for tok in tokenize.generate_tokens(io.StringIO(source).readline)
            if tok.type == tokenize.COMMENT
        }
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return None


class Suppressions:
    """Parsed suppression state for one file."""

    def __init__(self, source: str) -> None:
        self.file_rules: Set[str] = set()
        self.line_rules: Dict[int, Set[str]] = {}
        comments = _comment_lines(source)
        for lineno, line in enumerate(source.splitlines(), start=1):
            if comments is not None and lineno not in comments:
                continue
            m = _FILE_RE.search(line)
            if m:
                self.file_rules |= _parse_rules(m.group(1))
                continue
            m = _LINE_RE.search(line)
            if m:
                self.line_rules.setdefault(lineno, set()).update(
                    _parse_rules(m.group(1))
                )

    def is_suppressed(self, rule: str, line: int) -> bool:
        if ALL in self.file_rules or rule in self.file_rules:
            return True
        rules = self.line_rules.get(line, ())
        return ALL in rules or rule in rules

    # -- cache round-trip (cache.py stores the parsed spec so a warm hit
    #    can classify findings without re-reading the source) -------------

    def to_spec(self) -> Dict[str, object]:
        return {
            "file": sorted(self.file_rules),
            "lines": {
                str(k): sorted(v) for k, v in sorted(self.line_rules.items())
            },
        }

    @classmethod
    def from_spec(cls, spec: Dict) -> "Suppressions":
        self = cls("")
        self.file_rules = set(spec.get("file", ()))
        self.line_rules = {
            int(k): set(v) for k, v in spec.get("lines", {}).items()
        }
        return self


# ---------------------------------------------------------------------------
# stale-suppression rewriting (--fix-stale-suppressions)
# ---------------------------------------------------------------------------

#: marker + its rule list, for narrowing a partially-stale marker
_LINE_EDIT_RE = re.compile(r"(#\s*orlint:\s*disable=)([\w\-,* ]+)")
_FILE_EDIT_RE = re.compile(r"(#\s*orlint:\s*disable-file=)([\w\-,* ]+)")
#: the whole comment through end-of-line (justification included), for
#: removing a fully-stale marker
_LINE_STRIP_RE = re.compile(r"\s*#\s*orlint:\s*disable=[\w\-,* ]+.*$")
_FILE_STRIP_RE = re.compile(r"\s*#\s*orlint:\s*disable-file=[\w\-,* ]+.*$")


def _rewrite_marker(line: str, stale: Set[str], edit_re, strip_re):
    """Drop ``stale`` rules from the marker on ``line``.  Returns the
    rewritten line, or None when the line should be deleted (the marker
    was the only thing on it)."""
    m = edit_re.search(line)
    if m is None:
        return line
    blob = m.group(2)
    remaining = sorted(_parse_rules(blob) - stale)
    if remaining:
        # the rule-list charclass eats the gap before any justification —
        # splice the narrowed list in front of the blob's own trailing
        # whitespace so `=a,b (why)` narrows to `=a (why)`, not `=a(why)`
        trail = blob[len(blob.rstrip()) :]
        return (
            line[: m.start(2)] + ",".join(remaining) + trail + line[m.end(2) :]
        )
    stripped = strip_re.sub("", line).rstrip()
    return stripped if stripped.strip() else None


def strip_stale(
    source: str, entries: Iterable[Tuple[int, Iterable[str]]]
) -> Tuple[str, int]:
    """Rewrite ``source`` removing the stale rules named by ``entries``
    ((marker line, stale rules); line 0 = the file-level form).  A marker
    whose rule list empties out is removed whole — justification comment
    included; a marker-only line is deleted.  Returns (new source, number
    of markers edited)."""
    line_stale: Dict[int, Set[str]] = {}
    file_stale: Set[str] = set()
    for lineno, rules in entries:
        if lineno:
            line_stale.setdefault(lineno, set()).update(rules)
        else:
            file_stale.update(rules)
    comments = _comment_lines(source)
    out = []
    edited = 0
    for lineno, line in enumerate(source.splitlines(), start=1):
        new_line = line
        if comments is not None and lineno not in comments:
            out.append(line)
            continue
        if file_stale and _FILE_EDIT_RE.search(line):
            new_line = _rewrite_marker(
                line, file_stale, _FILE_EDIT_RE, _FILE_STRIP_RE
            )
        elif lineno in line_stale:
            new_line = _rewrite_marker(
                line, line_stale[lineno], _LINE_EDIT_RE, _LINE_STRIP_RE
            )
        if new_line != line:
            edited += 1
            if new_line is None:
                continue
        out.append(new_line)
    text = "\n".join(out)
    if source.endswith("\n") and not text.endswith("\n"):
        text += "\n"
    return text, edited
