"""Suppression comments for orlint.

Two forms, both parsed from raw source lines (no tokenizer round-trip —
a regex over each physical line is exact enough because the marker must
live in a ``#`` comment to be legal Python on that line):

* line-level — a trailing comment on the *reported* line::

      self._alive_since = time.time()  # orlint: disable=clock-now (epoch, not protocol time)

  Everything after the rule list is free-form justification.  Multi-line
  statements are reported at the statement's first line; put the comment
  there.

* file-level — anywhere in the file, on its own line or trailing::

      # orlint: disable-file=clock-sleep,clock-now

  Use sparingly: a file-level disable also hides *future* violations in
  that file.  Reserved for files whose entire purpose violates a rule
  (e.g. common/runtime.py's WallClock IS the wrapper the clock rules
  steer everyone toward).

``disable=all`` suppresses every rule at that scope.
"""

from __future__ import annotations

import re
from typing import Dict, Set

_LINE_RE = re.compile(r"#\s*orlint:\s*disable=([\w\-,* ]+)")
_FILE_RE = re.compile(r"#\s*orlint:\s*disable-file=([\w\-,* ]+)")

ALL = "all"


def _parse_rules(blob: str) -> Set[str]:
    return {r.strip() for r in blob.split(",") if r.strip()}


class Suppressions:
    """Parsed suppression state for one file."""

    def __init__(self, source: str) -> None:
        self.file_rules: Set[str] = set()
        self.line_rules: Dict[int, Set[str]] = {}
        for lineno, line in enumerate(source.splitlines(), start=1):
            m = _FILE_RE.search(line)
            if m:
                self.file_rules |= _parse_rules(m.group(1))
                continue
            m = _LINE_RE.search(line)
            if m:
                self.line_rules.setdefault(lineno, set()).update(
                    _parse_rules(m.group(1))
                )

    def is_suppressed(self, rule: str, line: int) -> bool:
        if ALL in self.file_rules or rule in self.file_rules:
            return True
        rules = self.line_rules.get(line, ())
        return ALL in rules or rule in rules

    # -- cache round-trip (cache.py stores the parsed spec so a warm hit
    #    can classify findings without re-reading the source) -------------

    def to_spec(self) -> Dict[str, object]:
        return {
            "file": sorted(self.file_rules),
            "lines": {
                str(k): sorted(v) for k, v in sorted(self.line_rules.items())
            },
        }

    @classmethod
    def from_spec(cls, spec: Dict) -> "Suppressions":
        self = cls("")
        self.file_rules = set(spec.get("file", ()))
        self.line_rules = {
            int(k): set(v) for k, v in spec.get("lines", {}).items()
        }
        return self
