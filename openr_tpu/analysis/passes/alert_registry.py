"""Alert-name registry discipline — ``health.alert.*`` has ONE home.

The fleet health plane's whole value is that every alert rule is
chaos-verified: for each name in ``openr_tpu/health/alerts.py`` there
is a seeded fault family proving the alert fires, and a clean-run gate
proving it doesn't fire spuriously.  A free-spelled
``"health.alert.chip_quarntine"`` anywhere else would mint an alert
counter no dashboard, no fidelity test, and no runbook knows about —
firing forever into a void.  So the registry module is the single
place the ``health.alert.`` prefix may be spelled; everything else
goes through ``alert_counter_key(name)`` (which validates the name
against ``ALERTS``) or the name constants.

Rule (mirrors ``pipeline-phase-registry``):

* ``alert-name-registry`` — a string literal (or f-string head)
  beginning with ``health.alert.`` anywhere outside the registry
  module.  Reads through ``alert_counter_key`` are invisible to this
  pass by construction — that is the point.
"""

from __future__ import annotations

import ast
from typing import List

from openr_tpu.analysis.findings import Finding
from openr_tpu.analysis.passes.base import ParsedModule, Pass

#: the registry itself (the only module allowed to spell the prefix) —
#: and this pass, which must spell it to detect it
ALLOWED_PREFIXES = (
    "openr_tpu/health/alerts.py",
    "openr_tpu/analysis/passes/alert_registry.py",
)

_PREFIX = "health.alert."


class AlertRegistryPass(Pass):
    name = "alert-registry"
    rules = {
        "alert-name-registry": (
            "health.alert.* counter name spelled as a free string "
            "(use openr_tpu.health.alerts.alert_counter_key so every "
            "alert name is registered, chaos-verified, and "
            "enumerable)"
        ),
    }

    def run(self, mod: ParsedModule, ctx: dict) -> List[Finding]:
        if mod.rel.startswith(ALLOWED_PREFIXES):
            return []
        # constants living inside f-strings are reported once, via their
        # enclosing JoinedStr, not a second time as bare constants
        inside_fstring = {
            id(v)
            for node in ast.walk(mod.tree)
            if isinstance(node, ast.JoinedStr)
            for v in node.values
        }
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            value = None
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and id(node) not in inside_fstring
            ):
                value = node.value
            elif isinstance(node, ast.JoinedStr) and node.values:
                head = node.values[0]
                if isinstance(head, ast.Constant) and isinstance(
                    head.value, str
                ):
                    value = head.value
            if value is None or not value.startswith(_PREFIX):
                continue
            out.append(
                mod.finding(
                    "alert-name-registry",
                    node,
                    f"free-string alert name {value!r}; use the "
                    "openr_tpu.health.alerts registry "
                    "(ALERTS / alert_counter_key)",
                )
            )
        return out
