"""Alert-name registry discipline — ``health.alert.*`` has ONE home.

The fleet health plane's whole value is that every alert rule is
chaos-verified: for each name in ``openr_tpu/health/alerts.py`` there
is a seeded fault family proving the alert fires, and a clean-run gate
proving it doesn't fire spuriously.  A free-spelled
``"health.alert.chip_quarntine"`` anywhere else would mint an alert
counter no dashboard, no fidelity test, and no runbook knows about —
firing forever into a void.  So the registry module is the single
place the ``health.alert.`` prefix may be spelled; everything else
goes through ``alert_counter_key(name)`` (which validates the name
against ``ALERTS``) or the name constants.

Rule (mirrors ``pipeline-phase-registry``; both ride the shared
string-literal index + declarative base in registry_strings.py):

* ``alert-name-registry`` — a string literal (or f-string head)
  beginning with ``health.alert.`` anywhere outside the registry
  module.  Reads through ``alert_counter_key`` are invisible to this
  pass by construction — that is the point.
"""

from __future__ import annotations

from openr_tpu.analysis.passes.registry_strings import StringPrefixRegistryPass

#: the registry itself (the only module allowed to spell the prefix) —
#: and this pass, which must spell it to detect it
ALLOWED_PREFIXES = (
    "openr_tpu/health/alerts.py",
    "openr_tpu/analysis/passes/alert_registry.py",
)

_PREFIX = "health.alert."


class AlertRegistryPass(StringPrefixRegistryPass):
    name = "alert-registry"
    rule = "alert-name-registry"
    rules = {
        "alert-name-registry": (
            "health.alert.* counter name spelled as a free string "
            "(use openr_tpu.health.alerts.alert_counter_key so every "
            "alert name is registered, chaos-verified, and "
            "enumerable)"
        ),
    }
    prefix = _PREFIX
    allowed_prefixes = ALLOWED_PREFIXES
    what = "alert name"
    hint = (
        "use the openr_tpu.health.alerts registry "
        "(ALERTS / alert_counter_key)"
    )
    examples = {
        "alert-name-registry": {
            "trip": (
                "def fire(counters):\n"
                '    counters.bump("health.alert.chip_quarantine")\n'
            ),
            "fix": (
                "from openr_tpu.health.alerts import alert_counter_key\n"
                "\n"
                "def fire(counters):\n"
                '    counters.bump(alert_counter_key("chip_quarantine"))\n'
            ),
        },
    }
