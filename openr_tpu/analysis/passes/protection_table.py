"""Protection-table ownership — FIB patches apply through ONE gate.

The fast-reroute contract (docs/Robustness.md §fast-reroute) is that a
minted patch reaches the FIB only via Decision's generation-exact apply
path: ``_maybe_apply_protection`` checks the table generation against
the PREVIOUS generation key, refuses inside a dirty window, and arms
the warm-solve confirm.  A table mutator or ``apply_patch`` call from
anywhere else could install a patch minted for a different LSDB
generation — precisely the wrong-route window the staleness discipline
exists to make impossible — or flip the table lifecycle under the
service's mint fiber.

Rule:

* ``protection-table`` — a call to a protection-table mutator
  (``apply_patch``, ``begin_mint``, ``mark_ready``, ``mark_stale``,
  ``abort_mint``, ``purge_table``) anywhere outside
  ``openr_tpu/protection/`` or ``openr_tpu/decision/decision.py``.
  Reads (``lookup``, ``status``, ``classify_pairs``, the ctrl verbs)
  are fine everywhere.
"""

from __future__ import annotations

import ast
from typing import List

from openr_tpu.analysis.findings import Finding
from openr_tpu.analysis.passes.base import ParsedModule, Pass

ALLOWED_PREFIXES = (
    "openr_tpu/protection/",
    "openr_tpu/decision/decision.py",
)

_MUTATOR_CALLS = {
    "apply_patch",
    "begin_mint",
    "mark_ready",
    "mark_stale",
    "abort_mint",
    "purge_table",
}


class ProtectionTablePass(Pass):
    name = "protection-table"
    rules = {
        "protection-table": (
            "protection-table mutator called outside openr_tpu/"
            "protection/ or decision/decision.py (patches must apply "
            "through Decision's generation-exact apply path so a stale "
            "patch can never reach the FIB)"
        ),
    }
    examples = {
        "protection-table": {
            "trip": (
                "def shortcut(table, doc, prefix_state):\n"
                "    table.apply_patch(doc, prefix_state)\n"
            ),
            "fix": (
                "def shortcut(decision):\n"
                "    # fail the link in the LSDB; Decision's apply path\n"
                "    # serves the patch generation-exactly\n"
                "    decision.kvstore_sync()\n"
            ),
        },
    }

    def run(self, mod: ParsedModule, ctx: dict) -> List[Finding]:
        if mod.rel.startswith(ALLOWED_PREFIXES):
            return []
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not isinstance(f, ast.Attribute):
                continue
            if f.attr in _MUTATOR_CALLS:
                out.append(
                    mod.finding(
                        "protection-table",
                        node,
                        f"`{f.attr}(..)` outside openr_tpu/protection/ "
                        "bypasses Decision's generation-exact apply "
                        "gate; fail the link in the LSDB (or drive the "
                        "ProtectionService) instead",
                    )
                )
        return out
