"""Actor/queue discipline — "modules talk only through queues, no shared
mutable state" (common/runtime.py module docstring).

Every actor owns its state single-writer; the only sanctioned channels
between modules are ``openr_tpu.messaging`` queues and the registered
ctrl/RPC surfaces.  A direct write through an actor reference — or a
read of another actor's ``_underscore`` internals — is a latent race the
moment fibers interleave differently, the exact class of bug
tests/test_race_stress.py hunts dynamically and DeltaPath-style
dataflow analysis argues should be caught structurally.

Collection (whole-project): the transitive set of ``Actor`` subclasses
comes from the shared symbol table (``project(ctx).subclasses_of``, the
call-graph engine — no per-pass project walk), then per-module which
names/attributes are actor-typed — constructor results
(``self.spark = Spark(..)``), parameter annotations (``spark: Spark``),
and local bindings.  Rules:

* ``actor-cross-write``    — store through an actor-typed expression that
                             isn't ``self``: ``node.spark.foo = ..``,
                             ``self.kv_store._db[k] = ..``
* ``actor-private-access`` — load of a ``_private`` attribute through an
                             actor-typed expression that isn't ``self``
                             (reading internals couples to state the
                             owner mutates without synchronization)

Same-class access (``other: KvStore`` inside ``KvStore``) is exempt —
``__eq__``/merge helpers touching a peer's privates is idiomatic Python,
not a module boundary crossing.  Test harnesses and the chaos injector
cross boundaries *on purpose*; those sites carry explicit suppressions
so the transgression stays visible and audited.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from openr_tpu.analysis.astutil import (
    annotation_name,
    enclosing_class,
    resolve,
)
from openr_tpu.analysis.findings import Finding
from openr_tpu.analysis.passes.base import ParsedModule, Pass, project


class ActorIsolationPass(Pass):
    name = "actor-isolation"
    rules = {
        "actor-cross-write": "mutating another actor's state bypasses the queue/RPC contract",
        "actor-private-access": "reading another actor's _private state couples across module boundaries",
    }
    _EXAMPLE_CTX = (
        "from openr_tpu.common.runtime import Actor\n"
        "\n"
        "class Spark(Actor):\n"
        "    pass\n"
    )
    examples = {
        "actor-cross-write": {
            "trip": (
                "from ctx0 import Spark\n"
                "\n"
                "def poke(spark: Spark) -> None:\n"
                "    spark.neighbors = {}\n"
            ),
            "fix": (
                "from ctx0 import Spark\n"
                "\n"
                "async def poke(spark: Spark) -> None:\n"
                "    await spark.queue.put(('reset_neighbors',))\n"
            ),
            "context": (_EXAMPLE_CTX,),
        },
        "actor-private-access": {
            "trip": (
                "from ctx0 import Spark\n"
                "\n"
                "def peek(spark: Spark):\n"
                "    return spark._neighbors\n"
            ),
            "fix": (
                "from ctx0 import Spark\n"
                "\n"
                "def peek(spark: Spark):\n"
                "    return spark.neighbor_snapshot()\n"
            ),
            "context": (_EXAMPLE_CTX,),
        },
    }

    def run(self, mod: ParsedModule, ctx: dict) -> List[Finding]:
        if not mod.is_protocol_plane():
            return []
        # project-wide transitive Actor hierarchy, by bare class name —
        # served by the shared symbol table since the call-graph engine
        actors: Set[str] = project(ctx).subclasses_of("Actor")
        typed = _ActorTypedExprs(mod, actors)
        out: List[Finding] = []
        #: (line, base expr) already flagged as a write — the Load of
        #: `x._db` inside `x._db[k] = v` is the same transgression, not a
        #: second finding
        written: Set[Tuple[int, str]] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                for t in targets:
                    hit = typed.actor_base(t, skip_outermost=True)
                    if hit is None:
                        continue
                    expr_src, cls = hit
                    if typed.same_class_exempt(node, cls):
                        continue
                    written.add((node.lineno, expr_src))
                    out.append(
                        mod.finding(
                            "actor-cross-write",
                            node,
                            f"write through actor-typed `{expr_src}` "
                            f"(a {cls}) — modules talk only through "
                            "openr_tpu.messaging queues / registered RPC "
                            "surfaces (common/runtime.py)",
                        )
                    )
            elif isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load
            ):
                attr = node.attr
                if not attr.startswith("_") or attr.startswith("__"):
                    continue
                hit = typed.actor_base(node.value, skip_outermost=False)
                if hit is None:
                    continue
                expr_src, cls = hit
                if typed.same_class_exempt(node, cls):
                    continue
                if (node.lineno, expr_src) in written:
                    continue
                out.append(
                    mod.finding(
                        "actor-private-access",
                        node,
                        f"`{expr_src}.{attr}` reads {cls} internals across "
                        "a module boundary; use its queue or public API",
                    )
                )
        return out


class _ActorTypedExprs:
    """Which expressions in this module statically hold actor instances."""

    def __init__(self, mod: ParsedModule, actors: Set[str]) -> None:
        self.mod = mod
        self.actors = actors
        #: plain names (params / locals): name -> actor class
        self.names: Dict[str, str] = {}
        #: self attributes: (class name, attr) -> actor class
        self.self_attrs: Dict[Tuple[str, str], str] = {}
        self._index()

    def _index(self) -> None:
        for node in ast.walk(self.mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                a = node.args
                for p in a.posonlyargs + a.args + a.kwonlyargs:
                    cls = annotation_name(p.annotation)
                    if cls in self.actors:
                        self.names[p.arg] = cls
            elif isinstance(node, ast.AnnAssign):
                cls = annotation_name(node.annotation)
                if cls in self.actors:
                    self._bind_target(node.target, cls)
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                called = resolve(node.value.func, self.mod.imports)
                cls = called.split(".")[-1] if called else None
                if cls in self.actors:
                    for t in node.targets:
                        self._bind_target(t, cls)

    def _bind_target(self, target: ast.expr, cls: str) -> None:
        if isinstance(target, ast.Name):
            self.names[target.id] = cls
        elif (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
        ):
            owner = enclosing_class(target)
            if owner is not None:
                self.self_attrs[(owner.name, target.attr)] = cls

    def _base_type(self, expr: ast.expr) -> Optional[Tuple[str, str]]:
        """(source text, actor class) when `expr` is actor-typed."""
        if isinstance(expr, ast.Name):
            cls = self.names.get(expr.id)
            if cls:
                return expr.id, cls
        elif (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
        ):
            owner = enclosing_class(expr)
            if owner is not None:
                cls = self.self_attrs.get((owner.name, expr.attr))
                if cls:
                    return f"self.{expr.attr}", cls
        return None

    def actor_base(
        self, node: ast.expr, skip_outermost: bool
    ) -> Optional[Tuple[str, str]]:
        """Walk down a target/value chain (Attribute/Subscript/Starred);
        report the innermost actor-typed base.  With ``skip_outermost``
        the node itself doesn't count — rebinding a *variable* that held
        an actor (``x = ..``) is not a write *through* it."""
        first = True
        while True:
            if not (first and skip_outermost):
                hit = self._base_type(node)
                if hit is not None:
                    return hit
            first = False
            if isinstance(node, (ast.Attribute,)):
                node = node.value
            elif isinstance(node, (ast.Subscript, ast.Starred)):
                node = node.value
            else:
                return None

    def same_class_exempt(self, node: ast.AST, cls: str) -> bool:
        owner = enclosing_class(node)
        return owner is not None and owner.name == cls
