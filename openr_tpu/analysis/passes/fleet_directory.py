"""Fleet membership/directory discipline — liveness has ONE writer.

The fleet fabric's whole correctness story (docs/Fleet.md) is that
world assignment and feed ownership are PURE FUNCTIONS of the live-node
set: the coordinator re-packs and the stream router migrates exactly
when membership transitions, and the health plane pages/tickets off the
same transitions.  A stray ``node_down`` / ``drain_node`` call from an
arbitrary module would mutate the live set behind the fabric's back —
assignments silently recomputed against a set nobody else observed,
watchers migrated with no alert edge, the membership seq desynced from
the transition that caused it.

Rules:

* ``fleet-directory`` — a call to the membership mutators
  (``node_down``, ``node_up``, ``drain_node``, ``undrain_node``)
  anywhere outside ``openr_tpu/fleet/`` (the owner), ``openr_tpu/chaos/``
  and ``openr_tpu/emulation/`` (fault injection crosses the boundary on
  purpose).  Reads (``live_nodes``, ``is_live``, ``status``) are fine
  everywhere.  The generic-sounding names are matched only as attribute
  calls on a receiver whose name hints at the fleet (``membership``,
  ``fleet``, ``nodeset``) — ``x.node_up()`` on unrelated objects must
  not trip.
* ``fleet-liveness`` (ISSUE 20) — the epoch/suspicion/damping mutators
  (``bump_epoch``, ``mark_suspect``, ``clear_suspect``,
  ``set_damped_until``, ``record_incarnation``) called anywhere outside
  ``openr_tpu/fleet/`` itself.  STRICTER than fleet-directory: chaos
  and the emulation harness are NOT exempt — they perturb the heartbeat
  PLANE (stall a beacon, drop a publication, reincarnate) and the
  LivenessTracker must conclude the epoch bump or suspicion itself.  A
  harness that writes the fencing token directly is testing its own
  wiring, not the detector.
"""

from __future__ import annotations

import ast
from typing import List

from openr_tpu.analysis.findings import Finding
from openr_tpu.analysis.passes.base import ParsedModule, Pass

ALLOWED_PREFIXES = (
    "openr_tpu/fleet/",
    "openr_tpu/chaos/",
    "openr_tpu/emulation/",
)

#: the liveness tier's mutators are single-writer inside the fleet
#: package itself — even chaos/emulation only drive the heartbeat plane
LIVENESS_ALLOWED_PREFIXES = ("openr_tpu/fleet/",)

_MUTATOR_CALLS = {"node_down", "node_up", "drain_node", "undrain_node"}
_LIVENESS_MUTATORS = {
    "bump_epoch",
    "mark_suspect",
    "clear_suspect",
    "set_damped_until",
    "record_incarnation",
}
_RECEIVER_HINTS = ("membership", "fleet", "nodeset", "liveness", "tracker")


class FleetDirectoryPass(Pass):
    name = "fleet-directory"
    rules = {
        "fleet-directory": (
            "fleet membership mutator called outside openr_tpu/fleet/ "
            "(liveness is single-writer: assignment, migration and the "
            "node-loss alerts all key off the membership seq)"
        ),
        "fleet-liveness": (
            "fleet epoch/suspicion/damping mutator called outside "
            "openr_tpu/fleet/ (the fencing token and suspicion state "
            "have ONE writer — the liveness tracker; chaos perturbs "
            "the heartbeat plane, never these)"
        ),
    }
    examples = {
        "fleet-directory": {
            "trip": (
                "def evict(membership, name):\n"
                "    membership.node_down(name)\n"
            ),
            "fix": (
                "def evict(membership, name):\n"
                "    return membership.status()['live']\n"
            ),
        },
        "fleet-liveness": {
            "trip": (
                "def fence(membership):\n"
                "    membership.bump_epoch()\n"
            ),
            "fix": (
                "def fence(membership):\n"
                "    return membership.epoch\n"
            ),
        },
    }

    def run(self, mod: ParsedModule, ctx: dict) -> List[Finding]:
        dir_exempt = mod.rel.startswith(ALLOWED_PREFIXES)
        liveness_exempt = mod.rel.startswith(LIVENESS_ALLOWED_PREFIXES)
        if dir_exempt and liveness_exempt:
            return []
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not isinstance(f, ast.Attribute):
                continue
            name = f.attr
            if name in _MUTATOR_CALLS:
                if dir_exempt:
                    continue
                rule = "fleet-directory"
                msg = (
                    f"`{name}(..)` outside openr_tpu/fleet/ mutates "
                    "the live-node set behind the fabric's back; "
                    "drive membership through FleetMembership (fleet/"
                    "chaos/emulation tiers only)"
                )
            elif name in _LIVENESS_MUTATORS:
                if liveness_exempt:
                    continue
                rule = "fleet-liveness"
                msg = (
                    f"`{name}(..)` outside openr_tpu/fleet/ writes the "
                    "epoch/suspicion/damping state the LivenessTracker "
                    "single-writes; perturb the heartbeat plane (stall/"
                    "partition/reincarnate) and let the tracker conclude"
                )
            else:
                continue
            hit = True
            if isinstance(f.value, ast.Name):
                recv = f.value.id.lower()
                hit = any(h in recv for h in _RECEIVER_HINTS)
            elif isinstance(f.value, ast.Attribute):
                recv = f.value.attr.lower()
                hit = any(h in recv for h in _RECEIVER_HINTS)
            if hit:
                out.append(mod.finding(rule, node, msg))
        return out
