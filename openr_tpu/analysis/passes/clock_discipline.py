"""Clock discipline — "all protocol-plane sleeping/timing MUST go through
this" (common/runtime.py:Clock).

SimClock tests advance virtual time event-by-event; one raw
``asyncio.sleep(0.5)`` in an actor parks that fiber on the *host* loop
where virtual time never reaches it, and the test either hangs or goes
timing-dependent — exactly the nondeterminism the runtime docstring
bans.  ``time.time()``/``time.monotonic()`` reads are the same bug on
the read side: FSM timeouts computed from wall time diverge from the
virtual clock.  Rules:

* ``clock-sleep``     — ``time.sleep(..)`` / ``asyncio.sleep(x)`` for any
                        x other than the literal 0 (a bare yield is a
                        scheduling primitive, not a timed wait — SimClock
                        itself quiesces with ``asyncio.sleep(0)``)
* ``clock-now``       — ``time.time/monotonic/perf_counter[_ns]()``
* ``clock-call-later``— ``<loop>.call_later(..)`` / ``.call_at(..)``:
                        host-loop timers that SimClock cannot see

The legitimate users (WallClock itself, SystemMetrics' CPU%% sampling,
epoch timestamps for wire formats) carry line-level suppressions with
justifications — grep ``orlint: disable=clock`` for the list.
"""

from __future__ import annotations

import ast
from typing import List

from openr_tpu.analysis.astutil import const_value, resolve
from openr_tpu.analysis.findings import Finding
from openr_tpu.analysis.passes.base import ParsedModule, Pass

_SLEEPS = {"time.sleep", "asyncio.sleep"}
_NOW = {
    "time.time",
    "time.time_ns",
    "time.monotonic",
    "time.monotonic_ns",
    "time.perf_counter",
    "time.perf_counter_ns",
}
_LOOP_TIMERS = {"call_later", "call_at"}


class ClockDisciplinePass(Pass):
    name = "clock-discipline"
    rules = {
        "clock-sleep": "raw sleep bypasses the injected Clock (breaks SimClock determinism)",
        "clock-now": "raw wall-time read bypasses the injected Clock",
        "clock-call-later": "event-loop timer bypasses the injected Clock",
    }
    examples = {
        "clock-sleep": {
            "trip": (
                "import asyncio\n"
                "\n"
                "async def retry_loop():\n"
                "    await asyncio.sleep(0.5)\n"
            ),
            "fix": (
                "async def retry_loop(clock):\n"
                "    await clock.sleep(0.5)\n"
            ),
        },
        "clock-now": {
            "trip": (
                "import time\n"
                "\n"
                "def deadline():\n"
                "    return time.monotonic() + 5.0\n"
            ),
            "fix": (
                "def deadline(clock):\n"
                "    return clock.now() + 5.0\n"
            ),
        },
        "clock-call-later": {
            "trip": (
                "def arm(loop, cb):\n"
                "    loop.call_later(1.0, cb)\n"
            ),
            "fix": (
                "async def arm(clock, cb):\n"
                "    await clock.sleep(1.0)\n"
                "    cb()\n"
            ),
        },
    }

    def run(self, mod: ParsedModule, ctx: dict) -> List[Finding]:
        if not mod.is_protocol_plane():
            return []
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            target = resolve(node.func, mod.imports)
            if target in _SLEEPS:
                if (
                    target == "asyncio.sleep"
                    and len(node.args) == 1
                    and not node.keywords
                    and const_value(node.args[0]) == 0
                ):
                    continue  # bare cooperative yield, SimClock-safe
                out.append(
                    mod.finding(
                        "clock-sleep",
                        node,
                        f"`{target}` bypasses the injected Clock; use "
                        "`await clock.sleep(..)` (common/runtime.py: all "
                        "protocol-plane sleeping MUST go through Clock)",
                    )
                )
            elif target in _NOW:
                out.append(
                    mod.finding(
                        "clock-now",
                        node,
                        f"`{target}` reads host time; use `clock.now()` / "
                        "`clock.now_ms()` so SimClock tests stay "
                        "deterministic",
                    )
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _LOOP_TIMERS
            ):
                out.append(
                    mod.finding(
                        "clock-call-later",
                        node,
                        f"`.{node.func.attr}(..)` schedules on the host "
                        "event loop, invisible to SimClock; use "
                        "`Actor.schedule(..)` / `clock.sleep(..)`",
                    )
                )
        return out
