"""Slot-table discipline — the structural encode state has ONE owner.

The slot-stable encode (ISSUE 12, ``ops/csr.py``) keeps node slots and
edge rows stable across LSDB membership churn: tombstoned slots, a
free-list, and in-place row revival.  That state is only coherent as a
CHAIN — every generation must be produced by the csr patch functions
from its predecessor, and the decision backend is the only component
that drives the chain (it owns the encoding cache, the decline
accounting, and the warm-context compatibility proof).  A third party
calling the slot mutators — or fabricating tombstone metadata on an
encoding — would hand the warm kernels a layout the reset-frontier
planner never vouched for: silently wrong routes, not a crash.

Rule:

* ``slot-table`` — a call to ``patch_encoded_topology_slots`` /
  ``patch_encoded_multi_area_slots``, or an assignment to the
  ``tombstoned_nodes`` / ``tombstoned_links`` / ``slot_changed``
  attributes of an encoding, anywhere outside the owners: the encoder
  itself (``ops/csr.py``) and the decision backend
  (``decision/backend.py``).  Reads are fine — the warm planner, the
  selective-selection path and tests all inspect the metadata.
"""

from __future__ import annotations

import ast
from typing import List

from openr_tpu.analysis.findings import Finding
from openr_tpu.analysis.passes.base import ParsedModule, Pass

#: the slot chain's legitimate owners (calls + metadata writes allowed)
ALLOWED_PREFIXES = (
    "openr_tpu/ops/csr.py",
    "openr_tpu/decision/backend.py",
)

_SLOT_CALLS = {
    "patch_encoded_topology_slots",
    "patch_encoded_multi_area_slots",
}
_SLOT_ATTRS = {"tombstoned_nodes", "tombstoned_links", "slot_changed"}


class SlotTablePass(Pass):
    name = "slot-table"
    rules = {
        "slot-table": (
            "slot-table mutator used outside ops/csr + decision/backend "
            "(the structural encode chain has one owner; route "
            "membership churn through the backend's encoding cache)"
        ),
    }
    examples = {
        "slot-table": {
            "trip": (
                "def churn(enc, ls):\n"
                "    return patch_encoded_topology_slots(enc, ls, 'me')\n"
            ),
            "fix": (
                "def churn(backend, ls):\n"
                "    return backend.build_route_db(ls, warm_delta=None)\n"
            ),
        },
    }

    def run(self, mod: ParsedModule, ctx: dict) -> List[Finding]:
        if mod.rel.startswith(ALLOWED_PREFIXES):
            return []
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and t.attr in _SLOT_ATTRS
                    ):
                        out.append(
                            mod.finding(
                                "slot-table",
                                node,
                                f"write to `.{t.attr}` fabricates slot "
                                "metadata the warm planner never "
                                "vouched for; only the csr patch "
                                "functions may produce it",
                            )
                        )
            elif isinstance(node, ast.Call):
                f = node.func
                name = (
                    f.attr
                    if isinstance(f, ast.Attribute)
                    else (f.id if isinstance(f, ast.Name) else "")
                )
                if name in _SLOT_CALLS:
                    out.append(
                        mod.finding(
                            "slot-table",
                            node,
                            f"`{name}(..)` outside ops/csr + "
                            "decision/backend breaks the slot chain's "
                            "single-owner discipline; go through the "
                            "backend's encoding cache",
                        )
                    )
        return out
