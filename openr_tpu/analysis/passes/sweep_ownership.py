"""Sweep spill/checkpoint discipline — durable sweep state has ONE owner.

The capacity-sweep resume contract (docs/Developer_Guide.md) hangs on a
strict commit ordering: rows durable in the spill BEFORE the checkpoint
manifest records their shard, and the manifest only ever reset against
a matching scenario-set hash.  A stray ``commit_shard`` / ``spill_rows``
/ ``reset`` call from outside the executor would let state bypass that
ordering — a checkpoint claiming rows the spill doesn't hold, or a
manifest reset that orphans committed rows — and the failure mode is
silent until a resume replays garbage.

Rule:

* ``sweep-spill-ownership`` — a call to the spill/checkpoint mutators
  (``spill_rows``, ``commit_shard``, or ``CheckpointManifest``'s
  ``reset``) anywhere outside ``openr_tpu/sweep/``.  Reads
  (``SpillReader``, ``completed_shards``, ``matches``, ``stats``) are
  fine everywhere.  ``reset`` is matched only as an attribute call on a
  name containing ``checkpoint``/``manifest`` — plain ``x.reset()`` on
  unrelated objects must not trip.
"""

from __future__ import annotations

import ast
from typing import List

from openr_tpu.analysis.findings import Finding
from openr_tpu.analysis.passes.base import ParsedModule, Pass

ALLOWED_PREFIXES = ("openr_tpu/sweep/",)

_MUTATOR_CALLS = {"spill_rows", "commit_shard"}
_RESET_RECEIVER_HINTS = ("checkpoint", "manifest")


class SweepOwnershipPass(Pass):
    name = "sweep-ownership"
    rules = {
        "sweep-spill-ownership": (
            "sweep spill/checkpoint mutator called outside "
            "openr_tpu/sweep/ (route durable sweep state through the "
            "executor so the spill-before-checkpoint commit ordering "
            "holds)"
        ),
    }
    examples = {
        "sweep-spill-ownership": {
            "trip": (
                "def shortcut(spill, rows):\n"
                "    spill.spill_rows(rows)\n"
            ),
            "fix": (
                "def shortcut(service, spec):\n"
                "    return service.start_sweep(spec)\n"
            ),
        },
    }

    def run(self, mod: ParsedModule, ctx: dict) -> List[Finding]:
        if mod.rel.startswith(ALLOWED_PREFIXES):
            return []
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not isinstance(f, ast.Attribute):
                continue
            name = f.attr
            hit = name in _MUTATOR_CALLS
            if name == "reset" and isinstance(f.value, ast.Name):
                recv = f.value.id.lower()
                hit = any(h in recv for h in _RESET_RECEIVER_HINTS)
            if hit:
                out.append(
                    mod.finding(
                        "sweep-spill-ownership",
                        node,
                        f"`{name}(..)` outside openr_tpu/sweep/ bypasses "
                        "the executor's spill-before-checkpoint commit "
                        "ordering; drive sweeps through SweepExecutor/"
                        "SweepService instead",
                    )
                )
        return out
