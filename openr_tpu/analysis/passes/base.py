"""Pass interface + the per-file parse unit the engine hands to passes."""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List

from openr_tpu.analysis.astutil import ImportMap, attach_parents
from openr_tpu.analysis.findings import Finding
from openr_tpu.analysis.suppress import Suppressions

#: protocol-plane scoping: presentation/tooling trees where wall-clock and
#: direct state access are not protocol bugs (breeze CLI formats
#: timestamps for humans; examples are out-of-process clients; the linter
#: itself talks about forbidden calls in strings and fixtures)
NON_PROTOCOL_PREFIXES = (
    "openr_tpu/cli/",
    "openr_tpu/examples/",
    "openr_tpu/analysis/",
)


@dataclass
class ParsedModule:
    rel: str  #: repo-relative posix path
    module_name: str  #: dotted import path, "" when not under a package
    source: str
    tree: ast.Module
    imports: ImportMap
    suppressions: Suppressions
    lines: List[str] = field(default_factory=list)

    @classmethod
    def parse(cls, rel: str, source: str) -> "ParsedModule":
        tree = ast.parse(source)
        attach_parents(tree)
        module_name = ""
        if rel.endswith(".py"):
            parts = rel[:-3].split("/")
            if parts[-1] == "__init__":
                parts = parts[:-1]
            module_name = ".".join(parts)
        return cls(
            rel=rel,
            module_name=module_name,
            source=source,
            tree=tree,
            imports=ImportMap(tree),
            suppressions=Suppressions(source),
            lines=source.splitlines(),
        )

    def snippet(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=rule,
            path=self.rel,
            line=node.lineno,
            col=node.col_offset,
            message=message,
            snippet=self.snippet(node.lineno),
        )

    def is_protocol_plane(self) -> bool:
        return not self.rel.startswith(NON_PROTOCOL_PREFIXES)


class Pass:
    """One invariant family.  Two-phase: every pass sees every module in
    ``collect`` (cross-module facts: actor classes, jitted kernels), then
    ``finalize`` closes over the collected facts, then ``run`` emits
    findings per module."""

    name = "base"
    rules: Dict[str, str] = {}

    def collect(self, mod: ParsedModule, ctx: dict) -> None:
        return

    def finalize(self, ctx: dict) -> None:
        return

    def run(self, mod: ParsedModule, ctx: dict) -> List[Finding]:
        raise NotImplementedError
