"""Pass interface + the per-file parse unit the engine hands to passes.

Since the call-graph engine (analysis/callgraph.py) the contract is:

* the engine builds ONE :class:`~openr_tpu.analysis.callgraph.Project`
  (symbol table + call graph) from every module's serializable summary
  and publishes it in the shared ``ctx`` — passes query it via
  :func:`project` instead of each running its own project-wide AST walk;
* ``Pass.run(mod, ctx)`` stays per-module and may use ``mod``'s AST
  freely (a module being run is always parsed; cached modules skip
  ``run`` entirely — see cache.py).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from openr_tpu.analysis.astutil import ImportMap, attach_parents
from openr_tpu.analysis.callgraph import ModuleSummary, Project, summarize_module
from openr_tpu.analysis.findings import Finding
from openr_tpu.analysis.suppress import Suppressions

#: protocol-plane scoping: presentation/tooling trees where wall-clock and
#: direct state access are not protocol bugs (breeze CLI formats
#: timestamps for humans; examples are out-of-process clients; the linter
#: itself talks about forbidden calls in strings and fixtures)
NON_PROTOCOL_PREFIXES = (
    "openr_tpu/cli/",
    "openr_tpu/examples/",
    "openr_tpu/analysis/",
)

#: ctx key the engine publishes the Project under
CTX_PROJECT = "project"


def project(ctx: dict) -> Project:
    """The shared symbol table + call graph for this analysis run."""
    return ctx[CTX_PROJECT]


@dataclass
class ParsedModule:
    rel: str  #: repo-relative posix path
    module_name: str  #: dotted import path, "" when not under a package
    source: str
    tree: ast.Module
    imports: ImportMap
    suppressions: Suppressions
    lines: List[str] = field(default_factory=list)

    @classmethod
    def parse(cls, rel: str, source: str) -> "ParsedModule":
        tree = ast.parse(source)
        attach_parents(tree)
        module_name = ""
        if rel.endswith(".py"):
            parts = rel[:-3].split("/")
            if parts[-1] == "__init__":
                parts = parts[:-1]
            module_name = ".".join(parts)
        return cls(
            rel=rel,
            module_name=module_name,
            source=source,
            tree=tree,
            imports=ImportMap(tree),
            suppressions=Suppressions(source),
            lines=source.splitlines(),
        )

    def summary(self) -> ModuleSummary:
        """This module's serializable cross-module facts (cached)."""
        cached = getattr(self, "_orlint_summary", None)
        if cached is None:
            from openr_tpu.analysis.passes.jax_hygiene import collect_jitted

            jitted, _bodies = collect_jitted(self.tree, self.imports)
            cached = summarize_module(
                self.module_name, self.rel, self.tree, self.imports,
                jitted=jitted,
            )
            self._orlint_summary = cached
        return cached

    def string_literals(self) -> List[Tuple[ast.AST, str]]:
        """Every string constant + f-string head in the module, one walk,
        shared by the prefix-registry passes: ``(node, text)`` where an
        f-string is reported ONCE via its JoinedStr head and its inner
        constants are excluded (the f-string-head dedupe)."""
        cached = getattr(self, "_orlint_strings", None)
        if cached is not None:
            return cached
        inside_fstring = {
            id(v)
            for node in ast.walk(self.tree)
            if isinstance(node, ast.JoinedStr)
            for v in node.values
        }
        out: List[Tuple[ast.AST, str]] = []
        for node in ast.walk(self.tree):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and id(node) not in inside_fstring
            ):
                out.append((node, node.value))
            elif isinstance(node, ast.JoinedStr) and node.values:
                head = node.values[0]
                if isinstance(head, ast.Constant) and isinstance(
                    head.value, str
                ):
                    out.append((node, head.value))
        self._orlint_strings = out
        return out

    def snippet(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=rule,
            path=self.rel,
            line=node.lineno,
            col=node.col_offset,
            message=message,
            snippet=self.snippet(node.lineno),
        )

    def finding_at(self, rule: str, line: int, message: str) -> Finding:
        """Finding anchored to a line number (call-graph passes work from
        summaries whose call refs carry lines, not AST nodes)."""
        return Finding(
            rule=rule,
            path=self.rel,
            line=line,
            col=0,
            message=message,
            snippet=self.snippet(line),
        )

    def is_protocol_plane(self) -> bool:
        return not self.rel.startswith(NON_PROTOCOL_PREFIXES)


class Pass:
    """One invariant family.  ``run`` emits findings per module; every
    cross-module fact comes from the shared :func:`project` (symbol
    table + call graph) the engine built before any pass ran.

    ``examples`` powers the ``--explain <rule>`` CLI: per rule a minimal
    tripping snippet and its fixed twin (validated by a meta-test — the
    trip must trip exactly that rule, the fix must be clean)."""

    name = "base"
    rules: Dict[str, str] = {}
    #: rule -> {"trip": src, "fix": src, "context": (extra srcs,)}
    examples: Dict[str, Dict] = {}

    def run(self, mod: ParsedModule, ctx: dict) -> List[Finding]:
        raise NotImplementedError
