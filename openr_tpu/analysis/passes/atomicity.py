"""Await-point atomicity family — actor turns must be interleaving-safe
(rule family 11, docs/Developer_Guide.md).

The replay-determinism family (passes/determinism.py) proves that ONE
schedule replays byte-identically; nothing proved that the digests are
the same under a DIFFERENT legal schedule.  An actor turn that reads
``self`` state, suspends (``await``), and then acts on the pre-suspension
read is exactly such a schedule dependence: between the read and the
write any other fiber may run and update the same state, so the outcome
is decided by dispatch order, not by content.  Before EmulatedNetwork
can be sharded across workers (ROADMAP), every such window has to be
closed — this pass finds them statically; ``openr_tpu.chaos.schedule``
hunts the same class dynamically by perturbing the dispatch order.

A *suspension point* is anything that can yield control to another
fiber: a bare ``await fut``, ``async for`` / ``async with``, or an
awaited call whose callee **transitively** suspends — computed
interprocedurally over the Project symbol table
(``Project.suspension_verdicts``).  The flip side is the precision this
family needs to stay quiet: ``await self._helper()`` where the helper
never reaches a real suspension primitive is NOT a turn boundary and
does not trip anything.

Rules (scoped to ``Actor`` subclasses on the protocol plane):

* ``await-atomicity`` — read-modify-write on ``self`` state straddling
  a suspension without re-validation.  Two shapes: check-then-act (a
  guard on ``self.X`` whose dependent write lands after an ``await``
  with no re-check) and stale RMW (a local read from ``self.X`` before
  the suspension written back after it).  Sanctioned spelling:
  re-validate after the await (read ``self.X`` again), or restructure
  so the turn does not suspend between check and act.

* ``await-aliasing`` — a mutable actor-owned container (``self.X`` of
  set/dict/list type) handed BY REFERENCE to another actor or callback
  across a turn boundary: as an argument to a suspending awaited call
  on a non-``self`` receiver, or to a queue/handoff method (``push`` /
  ``put`` / ``publish``) whose consumer runs in a later turn.  The
  receiver observes future mutations, not the handoff-time state.
  Sanctioned spelling: pass a snapshot — ``dict(self.X)`` /
  ``list(self.X)`` / ``set(self.X)``.

* ``await-iteration`` — iterating an actor-owned container while the
  loop body suspends: another turn may mutate the container
  mid-iteration (``RuntimeError: dictionary changed size`` at best, a
  silently skewed traversal at worst).  Sanctioned spelling: iterate a
  snapshot — ``list(self.X)`` / ``sorted(self.X)``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from openr_tpu.analysis.callgraph import (
    CONTAINER_MARKERS,
    FunctionInfo,
    ModuleSummary,
    Project,
    call_ref_for,
)
from openr_tpu.analysis.findings import Finding
from openr_tpu.analysis.passes.base import ParsedModule, Pass, project

#: container methods that mutate their receiver (treated as writes; like
#: AugAssign they consume the pre-state unconditionally, so they do NOT
#: count as re-validation)
_MUTATORS = {
    "add", "append", "appendleft", "clear", "discard", "extend",
    "insert", "pop", "popitem", "popleft", "remove", "setdefault",
    "update",
}

#: methods that hand their arguments to another fiber even without an
#: ``await`` at the call site: queue producers and listener/callback
#: registration — the consumer runs in a later turn
_HANDOFF_METHODS = {"push", "put", "put_nowait", "publish", "add_listener"}

#: marker -> sanctioned snapshot spelling, for the aliasing message
_SNAPSHOT_SPELLING = {"dict": "dict(...)", "set": "set(...)", "list": "list(...)"}

_DICT_VIEWS = ("items", "keys", "values")


def _is_self_attr(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    )


def _names_in(expr: Optional[ast.AST]) -> Set[str]:
    if expr is None:
        return set()
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


class _TurnScan:
    """Flow-sensitive event scan of one async actor method.

    Models the body as a stream of READ / WRITE / SUSPEND events over
    ``self`` attributes, in approximate execution order.  Guard frames
    (pushed per ``if``/``while`` test) track which attributes the
    current branch's behavior was decided by; a SUSPEND marks them
    straddled, a later READ of the attribute re-validates, and a WRITE
    while straddled is the finding.  Locals bound from ``self`` state
    go stale at a SUSPEND; writing one back is the RMW shape."""

    def __init__(self, owner: "AtomicityPass", mod: ParsedModule,
                 proj: Project, summary: ModuleSummary,
                 fn_info: Optional[FunctionInfo]) -> None:
        self.owner = owner
        self.mod = mod
        self.proj = proj
        self.summary = summary
        self.fn_info = fn_info
        #: guard frames: attr -> {"guard": test line, "suspend": line|None}
        self.frames: List[Dict[str, Dict[str, Optional[int]]]] = []
        #: local var -> {"attr": source attr, "line": read line,
        #:               "stale": suspend line|None}
        self.locals_from: Dict[str, Dict[str, Optional[int]]] = {}
        self.findings: List[Finding] = []
        self._seen: Set[Tuple[str, int, str]] = set()

    # -- suspension oracle -------------------------------------------------

    def call_suspends(self, call: ast.Call) -> bool:
        ref = call_ref_for(call, self.mod.imports)
        if self.fn_info is not None:
            targets = self.proj.resolve_ref(self.summary, self.fn_info, ref)
        else:
            targets = []
        return self.proj.targets_suspend(targets) if targets else True

    # -- event stream ------------------------------------------------------

    def _expr_events(self, expr: Optional[ast.AST]) -> Iterator[Tuple]:
        """(kind, ...) events of one expression in approximate execution
        order.  Pure — applying them to the flow state is ``_emit``'s
        job, which lets guard collection reuse this walk."""
        if expr is None:
            return
        if isinstance(expr, ast.Await):
            if isinstance(expr.value, ast.Call):
                yield from self._call_events(expr.value, awaited=True)
            else:
                # bare future/task: unconditionally a turn boundary
                yield ("suspend", expr.lineno)
            return
        if isinstance(expr, ast.Call):
            yield from self._call_events(expr, awaited=False)
            return
        if _is_self_attr(expr) and isinstance(expr.ctx, ast.Load):
            yield ("read", expr.attr, expr.lineno)
            return
        if isinstance(expr, (ast.Lambda, ast.GeneratorExp)):
            return  # deferred bodies execute at an unknown time
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                yield from self._expr_events(child)
            elif isinstance(child, ast.comprehension):
                yield from self._expr_events(child.iter)
                for cond in child.ifs:
                    yield from self._expr_events(cond)

    def _call_events(self, call: ast.Call, awaited: bool) -> Iterator[Tuple]:
        f = call.func
        receiver_attr: Optional[str] = None
        if isinstance(f, ast.Attribute):
            if _is_self_attr(f.value):
                receiver_attr = f.value.attr
            elif not (isinstance(f.value, ast.Name) and f.value.id == "self"):
                yield from self._expr_events(f.value)
        elif not isinstance(f, ast.Name):
            yield from self._expr_events(f)
        for a in call.args:
            yield from self._expr_events(
                a.value if isinstance(a, ast.Starred) else a
            )
        for kw in call.keywords:
            yield from self._expr_events(kw.value)
        if receiver_attr is not None:
            if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                # like AugAssign: consumes pre-state, does not re-validate
                yield ("write", receiver_attr, call.lineno, None)
            else:
                yield ("read", receiver_attr, f.lineno)
        if awaited and self.call_suspends(call):
            yield ("suspend", call.lineno)

    def _emit(self, expr: Optional[ast.AST]) -> None:
        for ev in self._expr_events(expr):
            if ev[0] == "read":
                self._on_read(ev[1])
            elif ev[0] == "suspend":
                self._on_suspend(ev[1])
            else:
                self._on_write(ev[1], ev[2], ev[3])

    def _guard_attrs(self, test: ast.AST) -> Set[str]:
        return {ev[1] for ev in self._expr_events(test) if ev[0] == "read"}

    # -- flow state --------------------------------------------------------

    def _on_read(self, attr: str) -> None:
        for frame in self.frames:
            ent = frame.get(attr)
            if ent is not None:
                ent["suspend"] = None  # re-validated
        # NOTE: locals stay stale — re-reading self.X does not refresh a
        # variable that still holds the pre-suspension value

    def _on_suspend(self, line: int) -> None:
        for frame in self.frames:
            for ent in frame.values():
                if ent["suspend"] is None:
                    ent["suspend"] = line  # first straddling suspension
        for info in self.locals_from.values():
            if info["stale"] is None:
                info["stale"] = line

    def _on_write(self, attr: str, line: int,
                  value: Optional[ast.AST]) -> None:
        for frame in reversed(self.frames):
            ent = frame.get(attr)
            if ent is not None and ent["suspend"] is not None:
                self._add(
                    "await-atomicity", attr, line,
                    f"`self.{attr}` is checked at line {ent['guard']} and "
                    f"written at line {line}, but the turn suspends at "
                    f"line {ent['suspend']} in between — by write time the "
                    f"check is stale (another fiber may have updated "
                    f"`self.{attr}`); re-validate after the await",
                )
                break
        for name in _names_in(value):
            info = self.locals_from.get(name)
            if (
                info is not None
                and info["attr"] == attr
                and info["stale"] is not None
            ):
                self._add(
                    "await-atomicity", attr, line,
                    f"read-modify-write on `self.{attr}` straddles a "
                    f"suspension: local `{name}` was read from it at line "
                    f"{info['line']}, the turn suspends at line "
                    f"{info['stale']}, and the stale value is written back "
                    f"at line {line} — concurrent updates are lost; "
                    f"re-read `self.{attr}` after the await",
                )
                break
        for frame in self.frames:
            ent = frame.get(attr)
            if ent is not None:
                ent["suspend"] = None  # the write establishes our version

    def _add(self, rule: str, attr: str, line: int, message: str) -> None:
        key = (rule, line, attr)
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings.append(self.mod.finding_at(rule, line, message))

    # fork/merge of the mutable staleness (If branches are exclusive —
    # a suspension in the body must not straddle the orelse)

    def _snap(self):
        return (
            [{a: e["suspend"] for a, e in fr.items()} for fr in self.frames],
            {n: i["stale"] for n, i in self.locals_from.items()},
        )

    def _restore(self, snap) -> None:
        frames, stales = snap
        for fr, saved in zip(self.frames, frames):
            for a, v in saved.items():
                if a in fr:
                    fr[a]["suspend"] = v
        for n, v in stales.items():
            if n in self.locals_from:
                self.locals_from[n]["stale"] = v

    def _merge(self, snap) -> None:
        frames, stales = snap
        for fr, other in zip(self.frames, frames):
            for a, v in other.items():
                if a in fr and fr[a]["suspend"] is None:
                    fr[a]["suspend"] = v
        for n, v in stales.items():
            info = self.locals_from.get(n)
            if info is not None and info["stale"] is None:
                info["stale"] = v

    # -- statements --------------------------------------------------------

    def scan(self, stmts: Sequence[ast.stmt]) -> None:
        for st in stmts:
            self._stmt(st)

    def _stmt(self, st: ast.stmt) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # nested defs run at an unknown time
        if isinstance(st, ast.Assign):
            self._emit(st.value)
            if len(st.targets) == 1 and isinstance(st.targets[0], ast.Name):
                src = self._attr_source(st.value)
                name = st.targets[0].id
                if src is not None:
                    self.locals_from[name] = {
                        "attr": src, "line": st.lineno, "stale": None,
                    }
                else:
                    self.locals_from.pop(name, None)
            for t in st.targets:
                self._assign_target(t, st.value, st.lineno)
            return
        if isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._emit(st.value)
                self._assign_target(st.target, st.value, st.lineno)
            return
        if isinstance(st, ast.AugAssign):
            self._emit(st.value)
            t = st.target
            if _is_self_attr(t):
                self._on_write(t.attr, st.lineno, st.value)
            elif isinstance(t, ast.Subscript):
                self._emit(t.slice)
                if _is_self_attr(t.value):
                    self._on_write(t.value.attr, st.lineno, st.value)
            return
        if isinstance(st, ast.Delete):
            for t in st.targets:
                if _is_self_attr(t):
                    self._on_write(t.attr, st.lineno, None)
                elif isinstance(t, ast.Subscript):
                    self._emit(t.slice)
                    if _is_self_attr(t.value):
                        self._on_write(t.value.attr, st.lineno, None)
            return
        if isinstance(st, (ast.Expr, ast.Return, ast.Raise)):
            self._emit(getattr(st, "value", None) or getattr(st, "exc", None))
            return
        if isinstance(st, ast.Assert):
            self._emit(st.test)
            return
        if isinstance(st, ast.If):
            self._emit(st.test)
            self._push_guard(st.test)
            pre = self._snap()
            self.scan(st.body)
            after_body = self._snap()
            self._restore(pre)
            self.scan(st.orelse)
            self._merge(after_body)
            self.frames.pop()
            return
        if isinstance(st, ast.While):
            self._emit(st.test)
            self._push_guard(st.test)
            # scan twice with the test re-emitted at the back edge: the
            # second pass sees cross-iteration straddles (a suspension
            # late in iteration N is live at the top of iteration N+1)
            self.scan(st.body)
            self._emit(st.test)
            self.scan(st.body)
            self.frames.pop()
            self.scan(st.orelse)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self.owner.check_iteration(self, st)
            if isinstance(st, ast.AsyncFor):
                self._on_suspend(st.lineno)
            self._emit(st.iter)
            for name in _names_in(st.target):
                self.locals_from.pop(name, None)
            self.scan(st.body)
            self.scan(st.orelse)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            if isinstance(st, ast.AsyncWith):
                self._on_suspend(st.lineno)
            for item in st.items:
                self._emit(item.context_expr)
            self.scan(st.body)
            return
        if isinstance(st, ast.Try):
            self.scan(st.body)
            for h in st.handlers:
                self.scan(h.body)
            self.scan(st.orelse)
            self.scan(st.finalbody)
            return
        # anything else: conservatively walk child expressions/statements
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self._emit(child)
            elif isinstance(child, ast.stmt):
                self._stmt(child)

    def _push_guard(self, test: ast.AST) -> None:
        self.frames.append({
            a: {"guard": test.lineno, "suspend": None}
            for a in self._guard_attrs(test)
        })

    def _assign_target(self, t: ast.expr, value: Optional[ast.AST],
                       line: int) -> None:
        if _is_self_attr(t):
            self._on_write(t.attr, line, value)
        elif isinstance(t, ast.Attribute) and _is_self_attr(t.value):
            # self.X.field = v mutates the object held by self.X
            self._on_write(t.value.attr, line, value)
        elif isinstance(t, ast.Subscript):
            self._emit(t.slice)
            if _is_self_attr(t.value):
                self._on_write(t.value.attr, line, value)
            else:
                self._emit(t.value)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._assign_target(e, value, line)

    def _attr_source(self, v: Optional[ast.AST]) -> Optional[str]:
        """The ``self`` attribute a local's value derives from, or None.
        An awaited value is fresh by construction (it was produced after
        the suspension)."""
        if v is None or isinstance(v, ast.Await):
            return None
        if _is_self_attr(v):
            return v.attr
        if isinstance(v, ast.Attribute):
            return self._attr_source(v.value)
        if isinstance(v, ast.Subscript):
            return self._attr_source(v.value)
        if isinstance(v, ast.Call):
            f = v.func
            if (
                isinstance(f, ast.Attribute)
                and _is_self_attr(f.value)
                and f.attr not in _MUTATORS
            ):
                return f.value.attr
            return None
        if isinstance(v, ast.BinOp):
            return self._attr_source(v.left) or self._attr_source(v.right)
        return None


class AtomicityPass(Pass):
    name = "atomicity"
    rules = {
        "await-atomicity": (
            "read-modify-write on actor state straddles a suspension "
            "point without re-validation (check-then-act across an "
            "await) — the outcome depends on fiber dispatch order"
        ),
        "await-aliasing": (
            "mutable actor-owned container handed by reference to "
            "another actor/callback across a turn boundary — the "
            "receiver sees future mutations; pass a snapshot"
        ),
        "await-iteration": (
            "iteration over an actor-owned container spans a suspension "
            "that can mutate it mid-loop — iterate a snapshot "
            "(list(...)/sorted(...))"
        ),
    }

    examples = {
        "await-atomicity": {
            "trip": (
                "from openr_tpu.common.runtime import Actor\n"
                "\n"
                "class Cache(Actor):\n"
                "    async def lookup(self, key):\n"
                "        if key not in self._entries:\n"
                "            value = await self._fetch(key)\n"
                "            self._entries[key] = value\n"
                "        return self._entries[key]\n"
            ),
            "fix": (
                "from openr_tpu.common.runtime import Actor\n"
                "\n"
                "class Cache(Actor):\n"
                "    async def lookup(self, key):\n"
                "        if key not in self._entries:\n"
                "            value = await self._fetch(key)\n"
                "            if key not in self._entries:\n"
                "                self._entries[key] = value\n"
                "        return self._entries[key]\n"
            ),
        },
        "await-aliasing": {
            "trip": (
                "from openr_tpu.common.runtime import Actor\n"
                "\n"
                "class Publisher(Actor):\n"
                "    def __init__(self, updates_q):\n"
                "        self._routes = {}\n"
                "        self._q = updates_q\n"
                "\n"
                "    def publish(self):\n"
                "        self._q.push(self._routes)\n"
            ),
            "fix": (
                "from openr_tpu.common.runtime import Actor\n"
                "\n"
                "class Publisher(Actor):\n"
                "    def __init__(self, updates_q):\n"
                "        self._routes = {}\n"
                "        self._q = updates_q\n"
                "\n"
                "    def publish(self):\n"
                "        self._q.push(dict(self._routes))\n"
            ),
        },
        "await-iteration": {
            "trip": (
                "from openr_tpu.common.runtime import Actor\n"
                "\n"
                "class Flusher(Actor):\n"
                "    def __init__(self):\n"
                "        self._pending = {}\n"
                "\n"
                "    async def flush(self):\n"
                "        for key, value in self._pending.items():\n"
                "            await self._send(key, value)\n"
            ),
            "fix": (
                "from openr_tpu.common.runtime import Actor\n"
                "\n"
                "class Flusher(Actor):\n"
                "    def __init__(self):\n"
                "        self._pending = {}\n"
                "\n"
                "    async def flush(self):\n"
                "        for key, value in sorted(self._pending.items()):\n"
                "            await self._send(key, value)\n"
            ),
        },
    }

    def run(self, mod: ParsedModule, ctx: dict) -> List[Finding]:
        if not mod.is_protocol_plane():
            return []
        summary = mod.summary()
        if not summary.classes:
            return []
        proj = project(ctx)
        actors = proj.subclasses_of("Actor")
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            # the Actor base itself IS the scheduler — its bookkeeping is
            # the turn machinery, not a turn
            if node.name not in actors or node.name == "Actor":
                continue
            findings.extend(self._check_class(mod, proj, summary, node))
        findings.sort(key=lambda f: (f.line, f.col, f.rule))
        return findings

    # -- per-class ---------------------------------------------------------

    def _check_class(self, mod: ParsedModule, proj: Project,
                     summary: ModuleSummary,
                     cls: ast.ClassDef) -> List[Finding]:
        out: List[Finding] = []
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            fn_info = summary.functions.get(f"{cls.name}.{item.name}")
            scan = _TurnScan(self, mod, proj, summary, fn_info)
            if isinstance(item, ast.AsyncFunctionDef):
                scan.scan(item.body)
            out.extend(scan.findings)
            out.extend(self._check_aliasing(mod, proj, cls.name, item, scan))
        return out

    # -- await-aliasing ----------------------------------------------------

    def _check_aliasing(self, mod: ParsedModule, proj: Project,
                        cls_name: str, fn: ast.AST,
                        scan: _TurnScan) -> List[Finding]:
        out: List[Finding] = []
        awaited_calls = {
            id(n.value)
            for n in ast.walk(fn)
            if isinstance(n, ast.Await) and isinstance(n.value, ast.Call)
        }
        for call in ast.walk(fn):
            if not isinstance(call, ast.Call):
                continue
            f = call.func
            is_self_method = isinstance(f, ast.Attribute) and isinstance(
                f.value, ast.Name
            ) and f.value.id == "self"
            handoff = (
                isinstance(f, ast.Attribute)
                and f.attr in _HANDOFF_METHODS
            )
            suspending_escape = (
                id(call) in awaited_calls
                and not is_self_method
                and scan.call_suspends(call)
            )
            if not (handoff or suspending_escape):
                continue
            for arg in list(call.args) + [kw.value for kw in call.keywords]:
                if not _is_self_attr(arg):
                    continue
                marker = proj.attr_type(cls_name, arg.attr)
                if marker not in CONTAINER_MARKERS:
                    continue
                desc = ast.unparse(f) if hasattr(ast, "unparse") else "call"
                snap = _SNAPSHOT_SPELLING.get(marker, "a copy")
                verb = (
                    "handed to the queue/callback"
                    if handoff else "held across the suspension by"
                )
                out.append(mod.finding(
                    "await-aliasing", arg,
                    f"actor-owned {marker} `self.{arg.attr}` escapes by "
                    f"reference — {verb} `{desc}(...)`, whose consumer "
                    f"runs in a later turn and observes future mutations "
                    f"instead of the handoff-time state; pass a snapshot "
                    f"(`{snap.replace('...', f'self.{arg.attr}')}`)",
                ))
        return out

    # -- await-iteration ---------------------------------------------------

    def check_iteration(self, scan: _TurnScan,
                        st: "ast.For | ast.AsyncFor") -> None:
        attr = self._iterated_attr(st.iter)
        if attr is None:
            return
        marker = scan.proj.attr_type(scan.fn_info.cls if scan.fn_info
                                     else "", attr)
        if marker not in CONTAINER_MARKERS:
            return
        susp = self._first_suspension(scan, st.body)
        if susp is None:
            return
        scan._add(
            "await-iteration", attr, st.lineno,
            f"iterating actor-owned {marker} `self.{attr}` while the loop "
            f"body suspends at line {susp} — another fiber may mutate it "
            f"mid-iteration (RuntimeError, or a traversal that silently "
            f"skews); iterate a snapshot: `list(self.{attr})` / "
            f"`sorted(...)`",
        )

    @staticmethod
    def _iterated_attr(it: ast.expr) -> Optional[str]:
        if _is_self_attr(it):
            return it.attr
        if (
            isinstance(it, ast.Call)
            and isinstance(it.func, ast.Attribute)
            and it.func.attr in _DICT_VIEWS
            and _is_self_attr(it.func.value)
            and not it.args
        ):
            return it.func.value.attr
        return None

    def _first_suspension(self, scan: _TurnScan,
                          stmts: Sequence[ast.stmt]) -> Optional[int]:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, (ast.AsyncFor, ast.AsyncWith)):
                return st.lineno
            for node in ast.walk(st):
                if isinstance(node, (ast.AsyncFor, ast.AsyncWith)):
                    return node.lineno
                if isinstance(node, ast.Await):
                    if not isinstance(node.value, ast.Call):
                        return node.lineno
                    if scan.call_suspends(node.value):
                        return node.lineno
        return None
