"""Replay-determinism family — byte-identical seeded replay, enforced
statically (rule family 9, docs/Developer_Guide.md).

Every artifact this repo ships stakes its correctness claim on
byte-identical seeded replay: chaos acceptance runs compare two replays
of the same FaultPlan byte for byte, the sweep's kill-and-resume proof
compares ``summary_digest`` values, the streaming plane's chaos tests
compare emission logs, and the health plane compares alert-transition
JSONL.  One unsorted ``set`` iteration feeding any of those sinks — or
one wall-clock read on a path a replay executes — breaks the gate weeks
later, in whichever PR happens to perturb hash seeds or arrival order.
DeltaPath-style incremental engines (PAPERS.md) are only trustworthy
when delta/merge order is deterministic; these rules make the ordering
contract structural instead of tribal.

Rules (all interprocedural, riding analysis/callgraph.py):

* ``unordered-emission`` — iterating a ``set``/``dict`` (or a
  ``.items()``/``.keys()``/``.values()`` view) without an explicit
  order, where the loop body reaches a **declared determinism sink**
  (digest / spill / wire / alert-log — see ``SINK_FUNCTIONS`` /
  ``SINK_METHODS`` below).  ``sorted(...)`` around the iterable is the
  sanctioned spelling.  Python dicts iterate in insertion order, which
  is an accident of arrival, not content — two nodes merging the same
  facts in different orders emit different bytes.

* ``wallclock-reachability`` — the interprocedural upgrade of
  clock-discipline: an undisciplined ``time.*`` / ``datetime.now``
  read is flagged when the function containing it is *reachable from a
  replay-critical root* (actor run loops, the sweep reducer/spill
  plane, streaming emission, alert/metrics export), no matter how many
  helpers deep.  Calls dispatched through a ``Clock``-typed receiver
  are the sanctioned discipline and form a traversal **barrier** — the
  same read behind an injected Clock does not trip.

* ``unseeded-random`` — global-state randomness (``random.random()``,
  ``np.random.*`` module draws, unseeded ``random.Random()`` /
  ``default_rng()``) outside the seeded-Generator plumbing every
  chaos/emulation component uses (``random.Random(seed)``).

* ``unstable-sort-key`` — ordering by ``id(...)`` or runtime
  ``hash(...)``: object identity changes every process, and str hashes
  change with PYTHONHASHSEED, so the "stable" order is stable only
  within one run — exactly what a replay diff catches, eventually.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from openr_tpu.analysis.astutil import (
    enclosing_class,
    enclosing_functions,
    resolve,
)
from openr_tpu.analysis.callgraph import (
    FunctionInfo,
    ModuleSummary,
    Reach,
    call_ref_for,
)
from openr_tpu.analysis.findings import Finding
from openr_tpu.analysis.passes.base import ParsedModule, Pass, project

# ---------------------------------------------------------------------------
# the determinism SINK registry — where replayed bytes are minted.
# Each entry names a function/method whose input ORDER becomes output
# bytes: feed it from an unordered iteration and two replays disagree.
# ---------------------------------------------------------------------------

#: fully-qualified functions (internal qualnames or external dotted)
SINK_FUNCTIONS = {
    # THE canonical encoding for everything the sweep hashes or spills
    "openr_tpu.sweep.scenario.canonical_json",
    "openr_tpu.sweep.scenario.content_hash",
    # streaming wire spelling shared encodes splice fragments of
    "openr_tpu.serving.streaming.canonical_wire",
}

#: external callable families that digest their call ORDER
SINK_FUNCTION_PREFIXES = ("hashlib.",)

#: distinctive method names (receiver often untypable statically):
#: sweep spill + checkpoint commit, metrics JSONL export, streaming
#: wire delivery, alert transition log, digest finalization
SINK_METHODS = {
    "spill_rows",
    "commit_shard",
    "write_nodes",
    "to_jsonl",
    "deliver_wire",
    "summary_digest",
    "hexdigest",
    "_log_event",
}

#: bare-name callables (callback parameters by convention)
SINK_BARE = {"deliver_wire"}


def is_sink(target: str) -> bool:
    if target in SINK_FUNCTIONS or target in SINK_BARE:
        return True
    if target.startswith(SINK_FUNCTION_PREFIXES):
        return True
    if "." in target:
        return target.rsplit(".", 1)[-1] in SINK_METHODS
    return False


# ---------------------------------------------------------------------------
# replay-critical ROOTS — what a seeded replay re-executes.
# ---------------------------------------------------------------------------

#: Actor-subclass methods that are fiber entry points: ``run`` (the main
#: fiber), ``start`` (which spawns the queue loops / timer callbacks),
#: and ``__init__`` (which registers debounce/listener callbacks) —
#: callback harvesting in callgraph.py turns those registrations into
#: edges, so everything an actor wires up is replay-critical
ACTOR_LOOP_METHODS = ("run", "start", "__init__")

#: module trees that ARE emission/reduction planes: every function in
#: them must behave identically across replays
ROOT_MODULE_PREFIXES = (
    "openr_tpu.sweep.reduce.",
    "openr_tpu.sweep.spill.",
    "openr_tpu.sweep.executor.",
    "openr_tpu.serving.streaming.",
    "openr_tpu.health.alerts.",
    "openr_tpu.monitor.metrics.",
)

#: classes whose method calls are the *sanctioned* time discipline —
#: traversal stops at the barrier (subclasses resolved transitively)
BARRIER_CLASSES = ("Clock",)

#: undisciplined wall-time reads (superset of clock-now: datetime too)
WALLCLOCK_TARGETS = {
    "time.time",
    "time.time_ns",
    "time.monotonic",
    "time.monotonic_ns",
    "time.perf_counter",
    "time.perf_counter_ns",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "datetime.datetime.today",
    "datetime.date.today",
}

#: random module-level draws that touch global state
_RANDOM_GLOBAL = {
    "betavariate", "choice", "choices", "expovariate", "gauss",
    "getrandbits", "lognormvariate", "normalvariate", "paretovariate",
    "randint", "random", "randrange", "sample", "seed", "shuffle",
    "triangular", "uniform", "vonmisesvariate", "weibullvariate",
    "randbytes",
}

#: numpy.random names that are seeded-Generator plumbing, not draws
_NP_RANDOM_PLUMBING = {
    "default_rng", "Generator", "RandomState", "SeedSequence",
    "BitGenerator", "PCG64", "PCG64DXSM", "Philox", "MT19937", "SFC64",
}

_CTX_REACH = "determinism.reach"  #: qualname -> Reach, lazily built
_CTX_SINK_MEMO = "determinism.sink_memo"

_DICT_VIEWS = ("items", "keys", "values")


class DeterminismPass(Pass):
    name = "determinism"
    rules = {
        "unordered-emission": (
            "set/dict iterated without an explicit order while the loop "
            "body reaches a digest/spill/wire/alert sink (breaks "
            "byte-identical replay)"
        ),
        "wallclock-reachability": (
            "undisciplined wall-clock read reachable from a "
            "replay-critical root (actor loop / reducer / emission "
            "path) — inject a Clock"
        ),
        "unseeded-random": (
            "global-state randomness outside seeded-Generator plumbing "
            "(replays draw different values)"
        ),
        "unstable-sort-key": (
            "ordering by id()/hash() of non-content values — stable "
            "only within one process, never across replays"
        ),
    }

    examples = {
        "unordered-emission": {
            "trip": (
                "from openr_tpu.sweep.scenario import canonical_json\n"
                "\n"
                "def emit(rows: dict, out):\n"
                "    for k, v in rows.items():\n"
                "        out.append(canonical_json({k: v}))\n"
            ),
            "fix": (
                "from openr_tpu.sweep.scenario import canonical_json\n"
                "\n"
                "def emit(rows: dict, out):\n"
                "    for k, v in sorted(rows.items()):\n"
                "        out.append(canonical_json({k: v}))\n"
            ),
        },
        "wallclock-reachability": {
            "trip": (
                "from openr_tpu.common.runtime import Actor\n"
                "from datetime import datetime\n"
                "\n"
                "class Poller(Actor):\n"
                "    async def run(self):\n"
                "        self._tick()\n"
                "\n"
                "    def _tick(self):\n"
                "        return self._stamp()\n"
                "\n"
                "    def _stamp(self):\n"
                "        return datetime.now()\n"
            ),
            "fix": (
                "from openr_tpu.common.runtime import Actor, Clock\n"
                "\n"
                "class Poller(Actor):\n"
                "    def __init__(self, clock: Clock):\n"
                "        self.clock = clock\n"
                "\n"
                "    async def run(self):\n"
                "        self._tick()\n"
                "\n"
                "    def _tick(self):\n"
                "        return self._stamp()\n"
                "\n"
                "    def _stamp(self):\n"
                "        return self.clock.now()\n"
            ),
        },
        "unseeded-random": {
            "trip": (
                "import random\n"
                "\n"
                "def jitter():\n"
                "    return random.random()\n"
            ),
            "fix": (
                "import random\n"
                "\n"
                "def jitter(seed: int):\n"
                "    return random.Random(seed).random()\n"
            ),
        },
        "unstable-sort-key": {
            "trip": (
                "def order(rows):\n"
                "    return sorted(rows, key=id)\n"
            ),
            "fix": (
                "def order(rows):\n"
                "    return sorted(rows, key=lambda r: r.name)\n"
            ),
        },
    }

    # -- shared project queries (lazy, memoized in ctx) --------------------

    def _reach(self, ctx: dict) -> Dict[str, Reach]:
        reach = ctx.get(_CTX_REACH)
        if reach is None:
            proj = project(ctx)
            actors = proj.subclasses_of("Actor")
            barrier_owners: Set[str] = set()
            for b in BARRIER_CLASSES:
                barrier_owners |= proj.subclasses_of(b)
            roots = [
                qual
                for qual, fn in proj.functions.items()
                if (fn.cls in actors and fn.name in ACTOR_LOOP_METHODS)
                or qual.startswith(ROOT_MODULE_PREFIXES)
            ]
            reach = proj.reachable_from(
                roots,
                barrier=lambda q: proj.owner_class(q) in barrier_owners,
            )
            ctx[_CTX_REACH] = reach
        return reach

    def _sink_memo(self, ctx: dict) -> Dict[str, bool]:
        return ctx.setdefault(_CTX_SINK_MEMO, {})

    # -- run ---------------------------------------------------------------

    def run(self, mod: ParsedModule, ctx: dict) -> List[Finding]:
        out: List[Finding] = []
        summary = mod.summary()
        # wallclock-reachability is deliberately NOT protocol-plane
        # gated: the whole point is catching a helper in a tree the
        # per-site rules exempt, reached from a replay root.
        out.extend(self._wallclock(mod, summary, ctx))
        if mod.is_protocol_plane():
            out.extend(self._unordered_emission(mod, summary, ctx))
            out.extend(self._unseeded_random(mod))
            out.extend(self._unstable_sort_key(mod))
        out.sort(key=lambda f: (f.line, f.col, f.rule))
        return out

    # -- wallclock-reachability -------------------------------------------

    def _wallclock(
        self, mod: ParsedModule, summary: ModuleSummary, ctx: dict
    ) -> List[Finding]:
        reach = self._reach(ctx)
        out: List[Finding] = []
        for local_qual, fn in summary.functions.items():
            qual = (
                f"{summary.module}.{local_qual}"
                if summary.module
                else local_qual
            )
            r = reach.get(qual)
            if r is None:
                continue
            for ref in fn.calls:
                if ref[0] == "n" and ref[1] in WALLCLOCK_TARGETS:
                    hops = (
                        f"{r.hops} call hop{'s' if r.hops != 1 else ''}"
                    )
                    out.append(
                        mod.finding_at(
                            "wallclock-reachability",
                            ref[-1],
                            f"`{ref[1]}` is {hops} from replay-critical "
                            f"root `{r.root}`; a replay re-executes this "
                            "path — read time from the injected Clock",
                        )
                    )
        return out

    # -- unordered-emission ------------------------------------------------

    def _fn_info_for(
        self, node: ast.AST, summary: ModuleSummary
    ) -> Optional[FunctionInfo]:
        fns = enclosing_functions(node)
        if not fns:
            return summary.functions.get("<module>")
        outer = fns[-1]
        cls = enclosing_class(outer)
        key = f"{cls.name}.{outer.name}" if cls is not None else outer.name
        return summary.functions.get(key)

    def _unordered_desc(
        self,
        it: ast.expr,
        fn: Optional[FunctionInfo],
        summary: ModuleSummary,
        mod: ParsedModule,
    ) -> Optional[str]:
        """Why this iterable has no defined order, or None if it does."""
        if isinstance(it, ast.Call):
            f = it.func
            if isinstance(f, ast.Attribute) and f.attr in _DICT_VIEWS:
                return f"`.{f.attr}()` view"
            target = resolve(f, mod.imports)
            if target in ("set", "frozenset"):
                return f"`{target}(...)`"
            return None  # sorted(...), list(...) of a sorted, helpers
        if isinstance(it, (ast.Set, ast.SetComp)):
            return "set literal"
        ref: Optional[str] = None
        shown = ""
        if isinstance(it, ast.Name):
            shown = it.id
            if fn is not None:
                ref = fn.var_types.get(it.id)
        elif (
            isinstance(it, ast.Attribute)
            and isinstance(it.value, ast.Name)
            and it.value.id == "self"
        ):
            shown = f"self.{it.attr}"
            cls = enclosing_class(it)
            if cls is not None:
                cinfo = summary.classes.get(cls.name)
                if cinfo is not None:
                    ref = cinfo.attrs.get(it.attr)
        if ref == "set":
            return f"set `{shown}`"
        if ref == "dict":
            return f"dict `{shown}`"
        return None

    def _unordered_emission(
        self, mod: ParsedModule, summary: ModuleSummary, ctx: dict
    ) -> List[Finding]:
        proj = project(ctx)
        memo = self._sink_memo(ctx)
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.For, ast.AsyncFor)):
                continue
            fn = self._fn_info_for(node, summary)
            desc = self._unordered_desc(node.iter, fn, summary, mod)
            if desc is None:
                continue
            hit = self._loop_reaches_sink(node, fn, summary, proj, memo, mod)
            if hit is None:
                continue
            out.append(
                mod.finding(
                    "unordered-emission",
                    node,
                    f"iterating {desc} without an explicit order, and the "
                    f"loop body reaches determinism sink `{hit}` — wrap "
                    "the iterable in sorted(..) so two replays emit "
                    "identical bytes",
                )
            )
        return out

    def _loop_reaches_sink(
        self,
        loop: ast.AST,
        fn: Optional[FunctionInfo],
        summary: ModuleSummary,
        proj,
        memo: Dict[str, bool],
        mod: ParsedModule,
    ) -> Optional[str]:
        targets: Set[str] = set()
        fn = fn or FunctionInfo(name="<module>", cls="", line=0, end_line=0)
        for stmt in list(loop.body) + list(getattr(loop, "orelse", [])):
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call):
                    ref = call_ref_for(sub, mod.imports)
                    targets.update(proj.resolve_ref(summary, fn, ref))
        if not targets:
            return None
        return proj.targets_reach(targets, is_sink, _memo=memo)

    # -- unseeded-random ---------------------------------------------------

    def _unseeded_random(self, mod: ParsedModule) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            target = resolve(node.func, mod.imports)
            if not target:
                continue
            msg = None
            if target == "random.Random" and not node.args and not node.keywords:
                msg = (
                    "`random.Random()` without a seed draws from OS "
                    "entropy; pass an explicit seed (the chaos/emulation "
                    "pattern: `random.Random(seed)`)"
                )
            elif (
                target.startswith("random.")
                and target.split(".", 1)[1] in _RANDOM_GLOBAL
            ):
                msg = (
                    f"`{target}` mutates/draws the process-global RNG; "
                    "replays and concurrent draws interleave — use a "
                    "seeded `random.Random(seed)` instance"
                )
            elif target.startswith("numpy.random."):
                tail = target.split(".")[-1]
                if tail in ("default_rng", "RandomState"):
                    if not node.args and not node.keywords:
                        msg = (
                            f"`{target}()` without a seed; pass one so "
                            "device-side draws replay"
                        )
                elif tail not in _NP_RANDOM_PLUMBING:
                    msg = (
                        f"`{target}` draws numpy's global RNG; use a "
                        "seeded `numpy.random.default_rng(seed)` Generator"
                    )
            if msg is not None:
                out.append(mod.finding("unseeded-random", node, msg))
        return out

    # -- unstable-sort-key -------------------------------------------------

    def _unstable_sort_key(self, mod: ParsedModule) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            target = resolve(node.func, mod.imports)
            is_order_call = target in ("sorted", "min", "max") or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "sort"
            )
            if not is_order_call:
                continue
            for kw in node.keywords:
                if kw.arg != "key":
                    continue
                bad = self._identity_key(kw.value)
                if bad is not None:
                    out.append(
                        mod.finding(
                            "unstable-sort-key",
                            node,
                            f"ordering by `{bad}` — object identity / "
                            "runtime hashes differ across processes, so "
                            "the order never replays; key on content "
                            "(name, tuple of fields) instead",
                        )
                    )
        return out

    def _identity_key(self, key: ast.expr) -> Optional[str]:
        if isinstance(key, ast.Name) and key.id in ("id", "hash"):
            return key.id
        if isinstance(key, ast.Lambda):
            for sub in ast.walk(key.body):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Name)
                    and sub.func.id in ("id", "hash")
                ):
                    return f"{sub.func.id}(..)"
        return None
