"""JAX kernel hygiene — the compute-plane invariants from ops/.

Three failure modes this pass catches structurally:

* ``jit-unguarded-call`` — calling a ``jax.jit`` product directly instead
  of through ``call_jit_guarded``.  ops/jit_guard.py documents the
  jax-0.9.0 executable-cache corruption ("Execution supplied N buffers
  but compiled program expected M"): the first call of a fresh jitted
  function after *other* kernel families compiled in-process can draw a
  corrupted cache entry.  Any direct call site re-opens that
  intermittent crash.  Calls *inside* another jitted body are exempt
  (they trace inline; only the outermost dispatch touches the
  executable cache), as are warm-up/self-test sites that carry a
  suppression.

* ``jit-traced-branch`` — Python ``if``/``while`` on a traced value
  inside a jitted body.  Branching on a tracer either raises
  ``TracerBoolConversionError`` at first trace or — worse — silently
  bakes one branch into the compiled program.  Shape/dtype inspection
  (``x.ndim``, ``x.shape``, ``len(x)``, ``isinstance``) is static and
  allowed; parameters named in ``static_argnames`` are allowed.

* ``jit-host-sync`` — ``.block_until_ready()`` / ``.item()`` /
  ``.tolist()`` / ``np.asarray(..)`` / ``jax.device_get(..)`` inside a
  jitted body: a host sync inside a trace is at best a silent constant-
  fold of a tracer and at worst a ConcretizationTypeError; either way
  the kernel stops being a pure device program.

Collection is project-wide: jitted names are gathered per module
(decorator form, ``functools.partial(jax.jit, ..)`` form, and
``name = jax.jit(fn, ..)`` assignment form) by :func:`collect_jitted` —
which also feeds every module's summary, so the cross-module registry
now rides the shared symbol table (``project(ctx).jitted_registry()``)
instead of a per-pass collect walk, and an importing module's direct
call of another module's kernel is still flagged.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from openr_tpu.analysis.astutil import (
    ImportMap,
    enclosing_functions,
    resolve,
)
from openr_tpu.analysis.findings import Finding
from openr_tpu.analysis.passes.base import ParsedModule, Pass, project

_SHAPE_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "weak_type"}
_HOST_SYNC_ATTRS = {"block_until_ready", "item", "tolist"}
_HOST_SYNC_CALLS = {
    "numpy.asarray",
    "numpy.array",
    "jax.device_get",
}


def _jit_target(node: ast.expr, imports) -> Optional[ast.expr]:
    """For a decorator / assignment value, return the expression whose
    product is jitted, or None.  Handles ``jax.jit``,
    ``functools.partial(jax.jit, ..)`` and ``jax.jit(fn, ..)``."""
    target = resolve(node, imports)
    if target == "jax.jit":
        return node
    if isinstance(node, ast.Call):
        called = resolve(node.func, imports)
        if called == "jax.jit":
            return node
        if called in ("functools.partial", "partial") and node.args:
            if resolve(node.args[0], imports) == "jax.jit":
                return node
    return None


def _static_argnames(node: ast.expr) -> Set[str]:
    names: Set[str] = set()
    if not isinstance(node, ast.Call):
        return names
    for kw in node.keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            vals = (
                kw.value.elts
                if isinstance(kw.value, (ast.Tuple, ast.List))
                else [kw.value]
            )
            for v in vals:
                if isinstance(v, ast.Constant):
                    names.add(str(v.value))
    return names


def collect_jitted(
    tree: ast.Module, imports: ImportMap
) -> Tuple[Dict[str, Set[str]], Dict[ast.AST, Set[str]]]:
    """One module's jitted surface: ``{name -> static argnames}`` (what
    the project summary publishes) and ``{FunctionDef -> statics}`` for
    the traced bodies this pass inspects locally."""
    jitted: Dict[str, Set[str]] = {}
    bodies: Dict[ast.AST, Set[str]] = {}
    defs_by_name = {
        n.name: n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                jt = _jit_target(dec, imports)
                if jt is not None:
                    statics = _static_argnames(jt)
                    jitted[node.name] = statics
                    bodies[node] = statics
        elif isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Call
        ):
            jt = _jit_target(node.value, imports)
            if jt is None or resolve(node.value.func, imports) != "jax.jit":
                continue
            statics = _static_argnames(node.value)
            for t in node.targets:
                if isinstance(t, ast.Name):
                    jitted[t.id] = statics
            # `fn = jax.jit(_impl, ..)`: the traced body is _impl's
            if node.value.args:
                impl = node.value.args[0]
                if isinstance(impl, ast.Name) and impl.id in defs_by_name:
                    bodies[defs_by_name[impl.id]] = statics
    return jitted, bodies


class JaxHygienePass(Pass):
    name = "jax-hygiene"
    rules = {
        "jit-unguarded-call": "direct jitted call skips call_jit_guarded (executable-cache corruption, ops/jit_guard.py)",
        "jit-traced-branch": "Python control flow on a traced value inside a jitted body",
        "jit-host-sync": "host synchronization inside a jitted body",
    }
    _EXAMPLE_CTX = (
        "import jax\n"
        "\n"
        "@jax.jit\n"
        "def kernel(x):\n"
        "    return x * 2\n"
    )
    examples = {
        "jit-unguarded-call": {
            "trip": (
                "from ctx0 import kernel\n"
                "\n"
                "def run(v):\n"
                "    return kernel(v)\n"
            ),
            "fix": (
                "from ctx0 import kernel\n"
                "from openr_tpu.ops.jit_guard import call_jit_guarded\n"
                "\n"
                "def run(v):\n"
                "    return call_jit_guarded(kernel, v)\n"
            ),
            "context": (_EXAMPLE_CTX,),
        },
        "jit-traced-branch": {
            "trip": (
                "import jax\n"
                "\n"
                "@jax.jit\n"
                "def clamp(x):\n"
                "    if x > 0:\n"
                "        return x\n"
                "    return -x\n"
            ),
            "fix": (
                "import jax\n"
                "import jax.numpy as jnp\n"
                "\n"
                "@jax.jit\n"
                "def clamp(x):\n"
                "    return jnp.abs(x)\n"
            ),
        },
        "jit-host-sync": {
            "trip": (
                "import jax\n"
                "\n"
                "@jax.jit\n"
                "def bad(x):\n"
                "    return x.block_until_ready()\n"
            ),
            "fix": (
                "import jax\n"
                "from openr_tpu.ops.jit_guard import call_jit_guarded\n"
                "\n"
                "@jax.jit\n"
                "def good(x):\n"
                "    return x * 2\n"
                "\n"
                "def run(x):\n"
                "    return call_jit_guarded(good, x).block_until_ready()\n"
            ),
        },
    }

    def run(self, mod: ParsedModule, ctx: dict) -> List[Finding]:
        #: cross-module jitted names ride the shared symbol table
        registry: Dict[str, Dict[str, Set[str]]] = project(
            ctx
        ).jitted_registry()
        local = registry.get(mod.module_name, {})
        # names imported from other modules that are jitted there
        imported: Set[str] = set()
        for name, origin in mod.imports.names.items():
            src_mod, _, src_name = origin.rpartition(".")
            if src_name in registry.get(src_mod, {}):
                imported.add(name)
        jitted_names = set(local) | imported
        #: jitted function bodies to inspect (local to this module's AST)
        _, bodies = collect_jitted(mod.tree, mod.imports)

        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                out.extend(
                    self._check_call(mod, node, jitted_names, bodies, registry)
                )
        for body, statics in bodies.items():
            out.extend(self._check_traced_branches(mod, body, statics))
        return out

    def _in_jitted_body(self, node: ast.AST, bodies) -> bool:
        return any(fn in bodies for fn in enclosing_functions(node))

    def _check_call(
        self, mod: ParsedModule, node: ast.Call, jitted_names, bodies, registry
    ) -> List[Finding]:
        out: List[Finding] = []
        inside_jit = self._in_jitted_body(node, bodies)
        target = resolve(node.func, mod.imports)
        # host sync inside a traced body
        if inside_jit:
            attr = (
                node.func.attr
                if isinstance(node.func, ast.Attribute)
                else None
            )
            if target in _HOST_SYNC_CALLS or attr in _HOST_SYNC_ATTRS:
                what = target or f".{attr}(..)"
                out.append(
                    mod.finding(
                        "jit-host-sync",
                        node,
                        f"`{what}` inside a jitted body forces a host "
                        "sync / concretization during trace",
                    )
                )
            return out
        # direct dispatch of a jitted callable outside any trace: a bare
        # name (local or from-imported kernel) or a dotted reference into
        # a module whose registry says the attribute is jitted
        direct = (
            isinstance(node.func, ast.Name) and node.func.id in jitted_names
        )
        if not direct and target and "." in target:
            src_mod, _, src_name = target.rpartition(".")
            direct = src_name in registry.get(src_mod, {})
        if direct:
            shown = target or node.func.id  # type: ignore[union-attr]
            out.append(
                mod.finding(
                    "jit-unguarded-call",
                    node,
                    f"direct call of jitted `{shown}` — route through "
                    "call_jit_guarded (ops/jit_guard.py: executable-cache "
                    "corruption heals only under the guard)",
                )
            )
        return out

    def _check_traced_branches(
        self, mod: ParsedModule, body: ast.AST, statics: Set[str]
    ) -> List[Finding]:
        a = body.args
        traced = {
            p.arg for p in a.posonlyargs + a.args + a.kwonlyargs
        } - statics
        out: List[Finding] = []
        for node in ast.walk(body):
            if isinstance(node, (ast.If, ast.While)):
                name = _traced_name_in_test(node.test, traced)
                if name is not None:
                    kind = "if" if isinstance(node, ast.If) else "while"
                    out.append(
                        mod.finding(
                            "jit-traced-branch",
                            node,
                            f"Python `{kind}` on traced `{name}` inside a "
                            "jitted body; use jax.lax.cond/while_loop or "
                            "mark it static_argnames",
                        )
                    )
        return out


def _traced_name_in_test(test: ast.expr, traced: Set[str]) -> Optional[str]:
    """First traced param referenced *as a value* (not via static
    shape/dtype inspection) in a branch test."""
    for node in ast.walk(test):
        if not (isinstance(node, ast.Name) and node.id in traced):
            continue
        parent = getattr(node, "orlint_parent", None)
        if (
            isinstance(parent, ast.Attribute)
            and parent.attr in _SHAPE_ATTRS
        ):
            continue
        if isinstance(parent, ast.Call) and resolve(
            parent.func, _no_imports()
        ) in ("len", "isinstance"):
            continue
        return node.id
    return None


_NO_IMPORTS = None


def _no_imports():
    global _NO_IMPORTS
    if _NO_IMPORTS is None:
        from openr_tpu.analysis.astutil import ImportMap

        _NO_IMPORTS = ImportMap(ast.parse(""))
    return _NO_IMPORTS
