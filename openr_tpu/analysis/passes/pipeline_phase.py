"""Pipeline-phase registry discipline — ``pipeline.*`` names have ONE home.

The pipeline attribution plane (``openr_tpu/tracing/pipeline.py``) is
only useful if every phase sample lands under a name the dashboards,
the bench schema gate, and the Prometheus exposition all agree on.  A
free-spelled ``"pipeline.decod.ms"`` in some dispatch loop would record
forever and alarm never.  So the registry module is the single place
the ``pipeline.`` prefix may be spelled; everything else imports the
constants (``pipeline.DECODE``, ``hist_key(...)``, ``span_name(...)``).

Rule:

* ``pipeline-phase-registry`` — a string literal (or f-string head)
  beginning with ``pipeline.`` anywhere outside the registry module.
  Reads through the constants are invisible to this pass by
  construction — that is the point.
"""

from __future__ import annotations

import ast
from typing import List

from openr_tpu.analysis.findings import Finding
from openr_tpu.analysis.passes.base import ParsedModule, Pass

#: the registry itself (the only module allowed to spell the prefix) —
#: and this pass, which must spell it to detect it
ALLOWED_PREFIXES = (
    "openr_tpu/tracing/pipeline.py",
    "openr_tpu/analysis/passes/pipeline_phase.py",
)

_PREFIX = "pipeline."


class PipelinePhasePass(Pass):
    name = "pipeline-phase"
    rules = {
        "pipeline-phase-registry": (
            "pipeline.* metric/span name spelled as a free string "
            "(import the registry constants from "
            "openr_tpu.tracing.pipeline so every phase sample lands "
            "under a schema-known name)"
        ),
    }

    def run(self, mod: ParsedModule, ctx: dict) -> List[Finding]:
        if mod.rel.startswith(ALLOWED_PREFIXES):
            return []
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            value = None
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                value = node.value
            elif isinstance(node, ast.JoinedStr) and node.values:
                head = node.values[0]
                if isinstance(head, ast.Constant) and isinstance(
                    head.value, str
                ):
                    value = head.value
            if value is None or not value.startswith(_PREFIX):
                continue
            out.append(
                mod.finding(
                    "pipeline-phase-registry",
                    node,
                    f"free-string pipeline name {value!r}; use the "
                    "openr_tpu.tracing.pipeline registry constants "
                    "(PHASES / hist_key / span_name)",
                )
            )
        return out
