"""Pipeline-phase registry discipline — ``pipeline.*`` names have ONE home.

The pipeline attribution plane (``openr_tpu/tracing/pipeline.py``) is
only useful if every phase sample lands under a name the dashboards,
the bench schema gate, and the Prometheus exposition all agree on.  A
free-spelled ``"pipeline.decod.ms"`` in some dispatch loop would record
forever and alarm never.  So the registry module is the single place
the ``pipeline.`` prefix may be spelled; everything else imports the
constants (``pipeline.DECODE``, ``hist_key(...)``, ``span_name(...)``).

Rule:

* ``pipeline-phase-registry`` — a string literal (or f-string head)
  beginning with ``pipeline.`` anywhere outside the registry module.
  Reads through the constants are invisible to this pass by
  construction — that is the point.

Implementation rides the shared string-literal index + declarative base
in registry_strings.py (one walk serves every prefix-registry rule).
"""

from __future__ import annotations

from openr_tpu.analysis.passes.registry_strings import StringPrefixRegistryPass

#: the registry itself (the only module allowed to spell the prefix) —
#: and this pass, which must spell it to detect it
ALLOWED_PREFIXES = (
    "openr_tpu/tracing/pipeline.py",
    "openr_tpu/analysis/passes/pipeline_phase.py",
)

_PREFIX = "pipeline."


class PipelinePhasePass(StringPrefixRegistryPass):
    name = "pipeline-phase"
    rule = "pipeline-phase-registry"
    rules = {
        "pipeline-phase-registry": (
            "pipeline.* metric/span name spelled as a free string "
            "(import the registry constants from "
            "openr_tpu.tracing.pipeline so every phase sample lands "
            "under a schema-known name)"
        ),
    }
    prefix = _PREFIX
    allowed_prefixes = ALLOWED_PREFIXES
    what = "pipeline name"
    hint = (
        "use the openr_tpu.tracing.pipeline registry constants "
        "(PHASES / hist_key / span_name)"
    )
    examples = {
        "pipeline-phase-registry": {
            "trip": (
                "def record(counters):\n"
                '    counters.observe("pipeline.decode.ms", 1.0)\n'
            ),
            "fix": (
                "from openr_tpu.tracing import pipeline\n"
                "\n"
                "def record(counters):\n"
                "    counters.observe(pipeline.hist_key(pipeline.DECODE), 1.0)\n"
            ),
        },
    }
