"""Shared machinery for the name-registry passes.

Two rule families (``pipeline-phase-registry``, ``alert-name-registry``)
enforce the same law: a dotted metric/span/alert prefix has ONE home
module; everywhere else must import the registry constants instead of
free-spelling a name no dashboard or fidelity test knows about.  Both
used to run their own full-AST string scan; they now share ONE per-module
string-literal index (``ParsedModule.string_literals()`` — constants plus
f-string heads with the inner-constant dedupe) and this declarative base.
"""

from __future__ import annotations

from typing import List, Tuple

from openr_tpu.analysis.findings import Finding
from openr_tpu.analysis.passes.base import ParsedModule, Pass


class StringPrefixRegistryPass(Pass):
    """Flag any string literal (or f-string head) starting with
    ``prefix`` outside ``allowed_prefixes`` (the registry module itself,
    plus the pass module that must spell the prefix to police it)."""

    prefix = ""
    allowed_prefixes: Tuple[str, ...] = ()
    rule = ""
    what = "name"  # e.g. "pipeline name" / "alert name"
    hint = ""  # "use the ... registry (...)" tail of the message

    def run(self, mod: ParsedModule, ctx: dict) -> List[Finding]:
        if mod.rel.startswith(self.allowed_prefixes):
            return []
        out: List[Finding] = []
        for node, value in mod.string_literals():
            if not value.startswith(self.prefix):
                continue
            out.append(
                mod.finding(
                    self.rule,
                    node,
                    f"free-string {self.what} {value!r}; {self.hint}",
                )
            )
        return out
