"""Resilience-latch discipline — the device-health latch has ONE owner.

``TpuBackend.device_failed`` used to be a free-for-all boolean: chaos
flipped it, operators flipped it, and nothing guaranteed the flip was
probed, counted, or even noticed by the serving plane.  PR 5 made the
:class:`~openr_tpu.resilience.governor.BackendHealthGovernor` the single
health authority: it is the only component allowed to write the latch
(quarantine on shadow-verification mismatch / repeated dispatch failure,
restore only after a passing probe), and everything else must go through
its API (``force_quarantine`` / ``request_probe`` / ``force_restore``)
so transitions are counted under ``resilience.*`` and recoveries are
verified.

Rule:

* ``resilience-latch`` — assignment to a ``device_failed`` attribute, or
  a call to ``inject_device_failure`` / ``inject_silent_corruption``, or
  a call to the per-device quarantine-mask mutators
  ``quarantine_device`` / ``restore_device`` (``DevicePool`` — ISSUE 6:
  per-chip health is governor-owned exactly like the whole-backend
  latch; everything else goes through ``force_quarantine_device`` /
  ``request_probe_device`` so transitions are counted and recoveries
  probed), anywhere outside the allowed owners: the backend itself
  (``decision/backend.py``), the pool (``parallel/mesh.py``), the
  governor tree (``resilience/``), and chaos fault handlers
  (``chaos/``).  Reads are fine — ``Decision.device_available()`` and
  ``DevicePool.healthy_indices()`` exist precisely to read the state.
"""

from __future__ import annotations

import ast
from typing import List

from openr_tpu.analysis.findings import Finding
from openr_tpu.analysis.passes.base import ParsedModule, Pass

#: the latch's legitimate owners (writes allowed)
ALLOWED_PREFIXES = (
    "openr_tpu/decision/backend.py",
    "openr_tpu/parallel/mesh.py",
    "openr_tpu/resilience/",
    "openr_tpu/chaos/",
)

_LATCH_ATTRS = {"device_failed"}
_LATCH_CALLS = {
    "inject_device_failure",
    "inject_silent_corruption",
    # DevicePool per-chip quarantine-mask mutators
    "quarantine_device",
    "restore_device",
}


class ResilienceLatchPass(Pass):
    name = "resilience-latch"
    rules = {
        "resilience-latch": (
            "device-health latch written outside backend/governor/chaos "
            "(route through BackendHealthGovernor so the transition is "
            "counted and recovery is probed)"
        ),
    }
    examples = {
        "resilience-latch": {
            "trip": (
                "def drain(backend):\n"
                "    backend.device_failed = True\n"
            ),
            "fix": (
                "def drain(governor):\n"
                "    governor.force_quarantine(reason='drain')\n"
            ),
        },
    }

    def run(self, mod: ParsedModule, ctx: dict) -> List[Finding]:
        if mod.rel.startswith(ALLOWED_PREFIXES):
            return []
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and t.attr in _LATCH_ATTRS
                    ):
                        out.append(
                            mod.finding(
                                "resilience-latch",
                                node,
                                f"direct write to `.{t.attr}` bypasses the "
                                "BackendHealthGovernor; use "
                                "force_quarantine/request_probe/"
                                "force_restore so the transition is "
                                "counted and recovery is probed",
                            )
                        )
            elif isinstance(node, ast.Call):
                f = node.func
                name = (
                    f.attr
                    if isinstance(f, ast.Attribute)
                    else (f.id if isinstance(f, ast.Name) else "")
                )
                if name in _LATCH_CALLS:
                    out.append(
                        mod.finding(
                            "resilience-latch",
                            node,
                            f"`{name}(..)` outside backend/governor/chaos "
                            "bypasses the health governor; route the "
                            "fault through its API instead",
                        )
                    )
        return out
