"""orlint pass registry.

Each pass encodes one family of repo invariants (see the module
docstrings for the law being enforced and where it's written down).
``ALL_PASSES`` is the canonical ordering used by the engine and the CLI's
``--list-rules``.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from openr_tpu.analysis.passes.actor_isolation import ActorIsolationPass
from openr_tpu.analysis.passes.alert_registry import AlertRegistryPass
from openr_tpu.analysis.passes.async_blocking import AsyncBlockingPass
from openr_tpu.analysis.passes.atomicity import AtomicityPass
from openr_tpu.analysis.passes.base import Pass
from openr_tpu.analysis.passes.clock_discipline import ClockDisciplinePass
from openr_tpu.analysis.passes.determinism import DeterminismPass
from openr_tpu.analysis.passes.fleet_directory import FleetDirectoryPass
from openr_tpu.analysis.passes.jax_hygiene import JaxHygienePass
from openr_tpu.analysis.passes.pipeline_phase import PipelinePhasePass
from openr_tpu.analysis.passes.protection_table import ProtectionTablePass
from openr_tpu.analysis.passes.resilience_latch import ResilienceLatchPass
from openr_tpu.analysis.passes.slot_table import SlotTablePass
from openr_tpu.analysis.passes.sweep_ownership import SweepOwnershipPass


def make_passes():
    return [
        ClockDisciplinePass(),
        ActorIsolationPass(),
        JaxHygienePass(),
        AsyncBlockingPass(),
        ResilienceLatchPass(),
        SlotTablePass(),
        PipelinePhasePass(),
        AlertRegistryPass(),
        SweepOwnershipPass(),
        FleetDirectoryPass(),
        ProtectionTablePass(),
        DeterminismPass(),
        AtomicityPass(),
    ]


def all_rules() -> Dict[str, str]:
    out = {}
    for p in make_passes():
        out.update(p.rules)
    return out


def rule_families() -> Dict[str, str]:
    """rule id -> pass (family) name, for ``--list-rules``."""
    out = {}
    for p in make_passes():
        for rule in p.rules:
            out[rule] = p.name
    return out


def rule_example(rule: str) -> Optional[Tuple[str, Dict]]:
    """(family, {"trip","fix","context"?}) for ``--explain <rule>``."""
    for p in make_passes():
        if rule in p.examples:
            return p.name, p.examples[rule]
    return None


__all__ = [
    "Pass",
    "all_rules",
    "make_passes",
    "rule_example",
    "rule_families",
]
