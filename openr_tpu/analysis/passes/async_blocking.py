"""Blocking-in-event-loop — every actor fiber shares ONE asyncio loop.

A synchronous socket read, subprocess wait, or file open inside an
``async def`` stalls every other module's fibers for its full duration:
Spark misses hello deadlines, the Watchdog sees stalled heartbeats and
fires, SimClock tests deadlock (virtual time can't advance while the
host loop is blocked).  The reference gives each module its own thread +
EventBase so a blocking call only hurts its own module; our asyncio port
loses that isolation, which makes this rule load-bearing rather than
stylistic.

Rule ``async-blocking`` flags, inside any ``async def`` (nested sync
``def``s are skipped — they're commonly handed to ``run_in_executor``):

* ``subprocess.*`` / ``os.system`` / ``os.popen`` / ``os.wait*``
* raw-socket verbs: ``.recv/.recvfrom/.recv_into/.accept/.connect/
  .sendall(..)`` when not awaited (awaited forms are custom async
  transports, e.g. an IoProvider's ``recv`` coroutine)
* builtin ``open(..)`` and ``pathlib``'s ``.read_text/.write_text/
  .read_bytes/.write_bytes``
* ``requests.*`` / ``urllib.request.*`` HTTP clients

Startup-path reads that are genuinely one-shot (config load before the
loop is busy) carry line suppressions with a justification.
"""

from __future__ import annotations

import ast
from typing import List

from openr_tpu.analysis.astutil import is_awaited, resolve
from openr_tpu.analysis.findings import Finding
from openr_tpu.analysis.passes.base import ParsedModule, Pass

_SOCKET_VERBS = {
    "recv",
    "recvfrom",
    "recv_into",
    "accept",
    "connect",
    "sendall",
}
_FILE_VERBS = {"read_text", "write_text", "read_bytes", "write_bytes"}
_BLOCKING_ROOTS = ("subprocess.", "requests.", "urllib.request.")
_BLOCKING_EXACT = {"os.system", "os.popen", "os.wait", "os.waitpid", "open"}


class AsyncBlockingPass(Pass):
    name = "async-blocking"
    rules = {
        "async-blocking": "synchronous I/O inside async def stalls every actor on the shared loop",
    }
    examples = {
        "async-blocking": {
            "trip": (
                "class Loader:\n"
                "    async def load(self, path):\n"
                "        return open(path).read()\n"
            ),
            "fix": (
                "class Loader:\n"
                "    async def load(self, loop, path):\n"
                "        def _read():\n"
                "            return open(path).read()\n"
                "        return await loop.run_in_executor(None, _read)\n"
            ),
        },
    }

    def run(self, mod: ParsedModule, ctx: dict) -> List[Finding]:
        if not mod.is_protocol_plane():
            return []
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                self._scan_async_body(mod, node, out)
        return out

    def _scan_async_body(
        self, mod: ParsedModule, fn: ast.AsyncFunctionDef, out: List[Finding]
    ) -> None:
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # sync helpers may be executor-bound; nested
                # async defs get their own top-level scan
            stack.extend(ast.iter_child_nodes(node))
            if isinstance(node, ast.Call):
                self._check_call(mod, node, out)

    def _check_call(
        self, mod: ParsedModule, node: ast.Call, out: List[Finding]
    ) -> None:
        if is_awaited(node):
            return
        target = resolve(node.func, mod.imports) or ""
        attr = node.func.attr if isinstance(node.func, ast.Attribute) else None
        blocking = (
            target in _BLOCKING_EXACT
            or target.startswith(_BLOCKING_ROOTS)
            or attr in _SOCKET_VERBS
            or attr in _FILE_VERBS
        )
        if not blocking:
            return
        what = target if target and "." in target else (
            f".{attr}(..)" if attr else target
        )
        out.append(
            mod.finding(
                "async-blocking",
                node,
                f"`{what or 'open'}` blocks the shared event loop inside "
                "`async def`; use the async transport, clock, or "
                "run_in_executor",
            )
        )
