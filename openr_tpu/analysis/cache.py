"""Per-file content-hash result cache — keeps the lint gate sub-linear.

``--check`` over the whole repo parses every file and resolves a
project-wide call graph; as the repo grows that cost grows with it, and
the tier-1 gate pays it on every run.  The cache makes the warm path
cheap with a sound invalidation story in three keys:

* **file hash** — each entry is keyed by the sha256 of the file's
  source.  Content change ⇒ that entry is dead.
* **rule-set signature** — a hash over every registered rule id + its
  rationale text + the cache format version.  Adding/renaming/bumping
  any rule invalidates EVERYTHING (findings were computed under a
  different law).
* **project facts digest** — a hash over every module's serializable
  summary (analysis/callgraph.py).  Findings are interprocedural, so a
  file's cached findings are only valid while the cross-module facts
  they were computed under are byte-identical.  Same digest ⇒ a file
  whose content did not change cannot have different findings; changed
  digest ⇒ full re-run (sound, and still one edit away from warm).

What a warm hit skips: ``ast.parse``, the summary walk, and every pass —
the entry carries the file's raw findings and its parsed suppression
spec, so the engine only replays filtering/baseline bookkeeping.  The
acceptance bar (tests/test_orlint.py): a warm ``--cache`` check
re-parses ZERO unchanged files.

The cache lives at ``<repo_root>/.orlint_cache.json`` (gitignored),
written atomically (tmp + rename) so concurrent runs never read torn
state — a torn/alien file is treated as empty, never an error.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, Optional

CACHE_FORMAT = 1
DEFAULT_CACHE_NAME = ".orlint_cache.json"


def ruleset_signature() -> str:
    """Hash of the active rule set: ids + rationale text + format.  Any
    rule addition/removal/rewording produces a new signature, which is
    the ``--cache`` invalidation contract for rule-set bumps."""
    from openr_tpu.analysis.passes import all_rules

    doc = {"format": CACHE_FORMAT, "rules": dict(sorted(all_rules().items()))}
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()


def source_hash(source: str) -> str:
    return hashlib.sha256(source.encode()).hexdigest()


class ResultCache:
    """The on-disk document plus lookup/store bookkeeping."""

    def __init__(self, path: str, doc: Optional[dict] = None) -> None:
        self.path = path
        self.doc = doc if isinstance(doc, dict) else {}

    @classmethod
    def load(cls, path) -> "ResultCache":
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            doc = {}
        if not isinstance(doc, dict):
            doc = {}
        return cls(str(path), doc)

    @property
    def valid(self) -> bool:
        return (
            self.doc.get("format") == CACHE_FORMAT
            and self.doc.get("ruleset") == ruleset_signature()
        )

    @property
    def project_digest(self) -> str:
        return self.doc.get("project_digest", "") if self.valid else ""

    def entry(self, rel: str, content_hash: str) -> Optional[dict]:
        """The stored entry for ``rel`` iff it matches ``content_hash``
        under the current rule set."""
        if not self.valid:
            return None
        e = self.doc.get("files", {}).get(rel)
        if isinstance(e, dict) and e.get("hash") == content_hash:
            return e
        return None

    def replace(self, project_digest: str, files: Dict[str, dict]) -> None:
        self.doc = {
            "format": CACHE_FORMAT,
            "ruleset": ruleset_signature(),
            "project_digest": project_digest,
            "files": files,
        }

    def save(self) -> None:
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(self.doc, f, sort_keys=True)
                f.write("\n")
            os.replace(tmp, self.path)
        except OSError:
            # a read-only checkout must not fail the lint run
            try:
                os.unlink(tmp)
            except OSError:
                pass
