"""orlint engine — discover files, run passes, filter, report.

Two-phase execution (see passes/base.py): every pass collects
cross-module facts over the whole file set before any pass runs, so the
actor registry and the jitted-kernel registry see the full project no
matter the file ordering.  Findings are then filtered through in-source
suppressions (suppress.py) and the checked-in baseline (baseline.py);
only what survives fails ``--check``.
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterable, List, Optional, Sequence

from openr_tpu.analysis.baseline import Baseline
from openr_tpu.analysis.findings import Finding, Report
from openr_tpu.analysis.passes import make_passes
from openr_tpu.analysis.passes.base import ParsedModule

DEFAULT_BASELINE_NAME = "baseline.json"


def repo_root() -> Path:
    """Directory containing the ``openr_tpu`` package."""
    import openr_tpu

    return Path(openr_tpu.__file__).resolve().parent.parent


def default_baseline_path() -> Path:
    return Path(__file__).resolve().parent / DEFAULT_BASELINE_NAME


def iter_python_files(root: Path) -> Iterable[Path]:
    if root.is_file():
        yield root
        return
    for p in sorted(root.rglob("*.py")):
        if "__pycache__" in p.parts:
            continue
        yield p


def load_modules(
    paths: Sequence[Path], base: Optional[Path] = None
) -> List[ParsedModule]:
    base = base or repo_root()
    mods: List[ParsedModule] = []
    for root in paths:
        for path in iter_python_files(Path(root)):
            try:
                rel = path.resolve().relative_to(base).as_posix()
            except ValueError:
                rel = path.as_posix()
            try:
                source = path.read_text()
            except (OSError, UnicodeDecodeError):
                continue
            try:
                mods.append(ParsedModule.parse(rel, source))
            except SyntaxError:
                # not ours to judge; python itself will complain louder
                continue
    return mods


def analyze_modules(
    mods: Sequence[ParsedModule],
    baseline: Optional[Baseline] = None,
    rules: Optional[Sequence[str]] = None,
) -> Report:
    passes = make_passes()
    ctx: dict = {}
    for p in passes:
        for mod in mods:
            p.collect(mod, ctx)
        p.finalize(ctx)
    report = Report(files_scanned=len(mods))
    raw: List[Finding] = []
    for p in passes:
        for mod in mods:
            raw.extend(p.run(mod, ctx))
    if rules:
        wanted = set(rules)
        raw = [f for f in raw if f.rule in wanted]
    raw.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    for f in raw:
        sup = next(
            (m.suppressions for m in mods if m.rel == f.path), None
        )
        if sup is not None and sup.is_suppressed(f.rule, f.line):
            report.suppressed.append(f)
        else:
            report.findings.append(f)
    if baseline is not None:
        baseline.apply(report)
    return report


def analyze_paths(
    paths: Optional[Sequence[Path]] = None,
    baseline_path: Optional[Path] = None,
    use_baseline: bool = True,
    rules: Optional[Sequence[str]] = None,
) -> Report:
    base = repo_root()
    if not paths:
        paths = [base / "openr_tpu"]
    baseline = None
    if use_baseline:
        baseline = Baseline.load(baseline_path or default_baseline_path())
    return analyze_modules(load_modules(paths, base), baseline, rules)


def analyze_source(
    source: str, rel: str = "snippet.py", context: Optional[Sequence[str]] = None
) -> List[Finding]:
    """Analyze an in-memory snippet (test fixtures), optionally alongside
    extra context sources.  Returns unsuppressed findings for ``rel``."""
    mods = [ParsedModule.parse(rel, source)]
    for i, ctx_src in enumerate(context or ()):
        mods.append(ParsedModule.parse(f"ctx{i}.py", ctx_src))
    report = analyze_modules(mods, baseline=None)
    return [f for f in report.findings if f.path == rel]
