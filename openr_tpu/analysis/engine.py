"""orlint engine — discover files, build the project, run passes, report.

Since the call-graph engine the execution shape is:

1. every module parses into a :class:`ParsedModule` and contributes a
   serializable :class:`~openr_tpu.analysis.callgraph.ModuleSummary`;
2. ONE :class:`~openr_tpu.analysis.callgraph.Project` (symbol table +
   call graph) is assembled from the summaries and published to every
   pass through ``ctx`` (passes/base.py) — no pass runs its own
   project-wide walk;
3. passes run per module; findings filter through in-source
   suppressions (suppress.py) and the checked-in baseline (baseline.py);
   only what survives fails ``--check``.

With ``cache_path`` set (the ``--cache`` flag), step 1 is served from
the per-file content-hash result cache (cache.py): a file whose hash,
rule-set signature and project-facts digest all match skips parse,
summary AND passes — its findings replay from the cache.  A content
change whose summary is byte-identical re-runs just that file; a summary
change re-runs everything (cross-module facts moved).
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from openr_tpu.analysis.baseline import Baseline
from openr_tpu.analysis.cache import ResultCache, source_hash
from openr_tpu.analysis.callgraph import (
    ModuleSummary,
    Project,
    project_digest,
)
from openr_tpu.analysis.findings import Finding, Report, StaleSuppression
from openr_tpu.analysis.passes import make_passes
from openr_tpu.analysis.passes.base import CTX_PROJECT, ParsedModule
from openr_tpu.analysis.suppress import ALL, Suppressions

DEFAULT_BASELINE_NAME = "baseline.json"


def repo_root() -> Path:
    """Directory containing the ``openr_tpu`` package."""
    import openr_tpu

    return Path(openr_tpu.__file__).resolve().parent.parent


def default_baseline_path() -> Path:
    return Path(__file__).resolve().parent / DEFAULT_BASELINE_NAME


def default_cache_path() -> Path:
    from openr_tpu.analysis.cache import DEFAULT_CACHE_NAME

    return repo_root() / DEFAULT_CACHE_NAME


def iter_python_files(root: Path) -> Iterable[Path]:
    if root.is_file():
        yield root
        return
    for p in sorted(root.rglob("*.py")):
        if "__pycache__" in p.parts:
            continue
        yield p


def _iter_sources(
    paths: Sequence[Path], base: Path
) -> Iterable[Tuple[str, str]]:
    for root in paths:
        for path in iter_python_files(Path(root)):
            try:
                rel = path.resolve().relative_to(base).as_posix()
            except ValueError:
                rel = path.as_posix()
            try:
                yield rel, path.read_text()
            except (OSError, UnicodeDecodeError):
                continue


def load_modules(
    paths: Sequence[Path], base: Optional[Path] = None
) -> List[ParsedModule]:
    base = base or repo_root()
    mods: List[ParsedModule] = []
    for rel, source in _iter_sources(paths, base):
        try:
            mods.append(ParsedModule.parse(rel, source))
        except SyntaxError:
            # not ours to judge; python itself will complain louder
            continue
    return mods


def build_project(mods: Sequence[ParsedModule]) -> Project:
    return Project([m.summary() for m in mods])


def _run_passes(
    passes, mod: ParsedModule, ctx: dict
) -> List[Finding]:
    out: List[Finding] = []
    for p in passes:
        out.extend(p.run(mod, ctx))
    return out


def _stale_suppressions_for(
    rel: str, findings: List[Finding], sup: Suppressions
) -> List[StaleSuppression]:
    """Suppression rules in ``rel`` that no RAW finding matches any more.
    Computed from the pre-suppression finding list: a marker is live iff
    removing it would surface something.  Only meaningful on a full run
    (every pass executed) — callers must skip this under a --rule filter."""
    by_line: Dict[int, set] = {}
    fired: set = set()
    for f in findings:
        by_line.setdefault(f.line, set()).add(f.rule)
        fired.add(f.rule)

    def _dead(rule: str, hit: set) -> bool:
        # disable=all is live while ANY finding hits its scope
        return not hit if rule == ALL else rule not in hit

    out: List[StaleSuppression] = []
    for line, marked in sorted(sup.line_rules.items()):
        hit = by_line.get(line, set())
        stale = tuple(sorted(r for r in marked if _dead(r, hit)))
        if stale:
            out.append(StaleSuppression(path=rel, line=line, rules=stale))
    stale_file = tuple(sorted(r for r in sup.file_rules if _dead(r, fired)))
    if stale_file:
        out.append(StaleSuppression(path=rel, line=0, rules=stale_file))
    return out


def _assemble_report(
    per_file: Dict[str, Tuple[List[Finding], Suppressions]],
    files_scanned: int,
    files_parsed: int,
    baseline: Optional[Baseline],
    rules: Optional[Sequence[str]],
) -> Report:
    report = Report(
        files_scanned=files_scanned, files_parsed=files_parsed
    )
    raw: List[Finding] = []
    sup_by_rel: Dict[str, Suppressions] = {}
    for rel, (findings, sup) in per_file.items():
        raw.extend(findings)
        sup_by_rel[rel] = sup
        if not rules:
            report.stale_suppressions.extend(
                _stale_suppressions_for(rel, findings, sup)
            )
    report.stale_suppressions.sort(key=lambda s: (s.path, s.line))
    if rules:
        wanted = set(rules)
        raw = [f for f in raw if f.rule in wanted]
    raw.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    for f in raw:
        sup = sup_by_rel.get(f.path)
        if sup is not None and sup.is_suppressed(f.rule, f.line):
            report.suppressed.append(f)
        else:
            report.findings.append(f)
    if baseline is not None:
        baseline.apply(report)
    return report


def analyze_modules(
    mods: Sequence[ParsedModule],
    baseline: Optional[Baseline] = None,
    rules: Optional[Sequence[str]] = None,
) -> Report:
    passes = make_passes()
    ctx: dict = {CTX_PROJECT: build_project(mods)}
    per_file = {
        mod.rel: (_run_passes(passes, mod, ctx), mod.suppressions)
        for mod in mods
    }
    return _assemble_report(
        per_file, len(mods), len(mods), baseline, rules
    )


def analyze_paths(
    paths: Optional[Sequence[Path]] = None,
    baseline_path: Optional[Path] = None,
    use_baseline: bool = True,
    rules: Optional[Sequence[str]] = None,
    cache_path: Optional[Path] = None,
) -> Report:
    base = repo_root()
    if not paths:
        paths = [base / "openr_tpu"]
    baseline = None
    if use_baseline:
        baseline = Baseline.load(baseline_path or default_baseline_path())
    if cache_path is None:
        return analyze_modules(load_modules(paths, base), baseline, rules)
    return _analyze_cached(paths, base, baseline, rules, cache_path)


# ---------------------------------------------------------------------------
# the --cache path (see cache.py for the invalidation contract)
# ---------------------------------------------------------------------------


def _analyze_cached(
    paths: Sequence[Path],
    base: Path,
    baseline: Optional[Baseline],
    rules: Optional[Sequence[str]],
    cache_path: Path,
) -> Report:
    cache = ResultCache.load(cache_path)
    sources = list(_iter_sources(paths, base))
    hashes: Dict[str, str] = {}
    summaries: Dict[str, ModuleSummary] = {}
    parsed: Dict[str, ParsedModule] = {}
    cached_entries: Dict[str, dict] = {}
    files_parsed = 0

    def _parse(rel: str, source: str) -> Optional[ParsedModule]:
        nonlocal files_parsed
        pm = parsed.get(rel)
        if pm is None:
            try:
                pm = ParsedModule.parse(rel, source)
            except SyntaxError:
                return None
            files_parsed += 1
            parsed[rel] = pm
        return pm

    ordered: List[Tuple[str, str]] = []
    for rel, source in sources:
        h = source_hash(source)
        hashes[rel] = h
        entry = cache.entry(rel, h)
        if entry is not None:
            cached_entries[rel] = entry
            summaries[rel] = ModuleSummary.from_json(entry["summary"])
            ordered.append((rel, source))
        else:
            pm = _parse(rel, source)
            if pm is None:
                continue  # syntax error: skipped exactly like load_modules
            summaries[rel] = pm.summary()
            ordered.append((rel, source))

    digest = project_digest(summaries.values())
    per_file: Dict[str, Tuple[List[Finding], Suppressions]] = {}
    new_files: Dict[str, dict] = {}
    passes = None
    ctx: Optional[dict] = None

    def _ensure_ctx():
        nonlocal passes, ctx
        if ctx is None:
            passes = make_passes()
            ctx = {CTX_PROJECT: Project(list(summaries.values()))}
        return passes, ctx

    facts_unchanged = digest == cache.project_digest
    for rel, source in ordered:
        entry = cached_entries.get(rel)
        if facts_unchanged and entry is not None and "findings" in entry:
            findings = [Finding.from_json(d) for d in entry["findings"]]
            sup = Suppressions.from_spec(entry.get("suppressions", {}))
        else:
            # either this file changed, or the project facts moved under
            # everyone — both require a live run for this module
            ps, c = _ensure_ctx()
            pm = _parse(rel, source)
            if pm is None:
                continue
            findings = _run_passes(ps, pm, c)
            sup = pm.suppressions
        per_file[rel] = (findings, sup)
        new_files[rel] = {
            "hash": hashes[rel],
            "summary": summaries[rel].to_json(),
            "findings": [f.to_json() for f in findings],
            "suppressions": sup.to_spec(),
        }

    cache.replace(digest, new_files)
    cache.save()
    return _assemble_report(
        per_file, len(per_file), files_parsed, baseline, rules
    )


def analyze_source(
    source: str, rel: str = "snippet.py", context: Optional[Sequence[str]] = None
) -> List[Finding]:
    """Analyze an in-memory snippet (test fixtures), optionally alongside
    extra context sources.  Returns unsuppressed findings for ``rel``."""
    mods = [ParsedModule.parse(rel, source)]
    for i, ctx_src in enumerate(context or ()):
        mods.append(ParsedModule.parse(f"ctx{i}.py", ctx_src))
    report = analyze_modules(mods, baseline=None)
    return [f for f in report.findings if f.path == rel]
