"""Finding model for orlint.

A :class:`Finding` is one rule violation at one source location.  Findings
carry the *stripped text of the offending line* (``snippet``) so the
baseline can match them content-first: line numbers drift every edit, the
offending code mostly does not (see baseline.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Tuple


@dataclass(frozen=True)
class Finding:
    rule: str  #: rule id, e.g. "clock-sleep"
    path: str  #: repo-relative posix path
    line: int  #: 1-based line of the offending AST node
    col: int  #: 0-based column
    message: str  #: human explanation, names the invariant violated
    snippet: str = ""  #: stripped source text of `line`, for baseline matching

    def key(self):
        """Identity used for baseline matching — content-based, no column
        (editor reformatting must not un-baseline a grandfathered hit)."""
        return (self.rule, self.path, self.snippet)

    def to_json(self) -> Dict[str, Any]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "snippet": self.snippet,
        }

    @classmethod
    def from_json(cls, doc: Dict[str, Any]) -> "Finding":
        return cls(
            rule=doc["rule"],
            path=doc["path"],
            line=int(doc.get("line", 0)),
            col=int(doc.get("col", 0)),
            message=doc.get("message", ""),
            snippet=doc.get("snippet", ""),
        )

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def render_github(self) -> str:
        """GitHub Actions workflow-command annotation (``--format=github``).
        Newlines/percents URL-escape per the workflow-command grammar."""

        def esc(s: str, *, prop: bool = False) -> str:
            s = s.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")
            if prop:
                s = s.replace(":", "%3A").replace(",", "%2C")
            return s

        return (
            f"::error file={esc(self.path, prop=True)},"
            f"line={self.line},col={self.col},"
            f"title={esc('orlint ' + self.rule, prop=True)}"
            f"::{esc(self.message)}"
        )


@dataclass(frozen=True)
class StaleSuppression:
    """A suppression comment no raw finding uses any more.

    ``line`` is the marker's own physical line for the line-level form,
    0 for the file-level ``disable-file`` form.  ``rules`` lists only
    the STALE subset of the marker's rule list — a marker naming two
    rules of which one still fires is reported (and rewritten) for the
    dead rule alone."""

    path: str
    line: int
    rules: Tuple[str, ...]

    def to_json(self) -> Dict[str, Any]:
        return {"path": self.path, "line": self.line, "rules": list(self.rules)}

    def render(self) -> str:
        where = f"{self.path}:{self.line}" if self.line else self.path
        scope = "suppression" if self.line else "file-level suppression"
        return (
            f"{where}: [stale-suppression] {scope} for "
            f"{', '.join(self.rules)} no longer matches any finding — "
            "remove it (--fix-stale-suppressions)"
        )


@dataclass
class Report:
    """One analysis run: active findings plus what was filtered and why."""

    findings: list = field(default_factory=list)  #: unsuppressed, unbaselined
    suppressed: list = field(default_factory=list)
    baselined: list = field(default_factory=list)
    stale_baseline: list = field(default_factory=list)  #: entries no finding matched
    #: suppression comments whose rules no longer fire (audited only on
    #: full runs — a --rule filter proves nothing about absent findings)
    stale_suppressions: List[StaleSuppression] = field(default_factory=list)
    files_scanned: int = 0
    #: how many files were actually ast.parse'd this run (< files_scanned
    #: when the ``--cache`` result cache serves warm entries)
    files_parsed: int = 0

    @property
    def clean(self) -> bool:
        return not self.findings

    def counts_by_rule(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return dict(sorted(out.items()))

    def to_json(self) -> Dict[str, Any]:
        return {
            "files_scanned": self.files_scanned,
            "files_parsed": self.files_parsed,
            "counts": self.counts_by_rule(),
            "findings": [f.to_json() for f in self.findings],
            "suppressed": len(self.suppressed),
            "baselined": len(self.baselined),
            "stale_baseline": [e.to_json() for e in self.stale_baseline],
            "stale_suppressions": [
                s.to_json() for s in self.stale_suppressions
            ],
        }


# ---------------------------------------------------------------------------
# SARIF 2.1.0 (--format=sarif): the interchange format code-scanning UIs
# ingest.  The emitter keeps full Finding fidelity (snippet rides in the
# region) so findings_from_sarif() round-trips byte-exactly — the
# contract tests/test_orlint.py pins.
# ---------------------------------------------------------------------------

SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def render_sarif(report: Report, rule_meta: Dict[str, str]) -> Dict[str, Any]:
    """One SARIF run for this report.  ``rule_meta`` maps rule id to its
    one-line rationale (passes.all_rules()); only rules that actually
    fired are listed in the driver, keeping the document proportional to
    the findings."""
    fired = sorted({f.rule for f in report.findings})
    rule_index = {rule: i for i, rule in enumerate(fired)}
    results = []
    for f in report.findings:
        results.append(
            {
                "ruleId": f.rule,
                "ruleIndex": rule_index[f.rule],
                "level": "error",
                "message": {"text": f.message},
                "locations": [
                    {
                        "physicalLocation": {
                            "artifactLocation": {
                                "uri": f.path,
                                "uriBaseId": "SRCROOT",
                            },
                            "region": {
                                "startLine": f.line,
                                # SARIF columns are 1-based; Finding.col
                                # is the AST's 0-based offset
                                "startColumn": f.col + 1,
                                "snippet": {"text": f.snippet},
                            },
                        }
                    }
                ],
            }
        )
    return {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "orlint",
                        "rules": [
                            {
                                "id": rule,
                                "shortDescription": {
                                    "text": rule_meta.get(rule, "")
                                },
                            }
                            for rule in fired
                        ],
                    }
                },
                "results": results,
            }
        ],
    }


def findings_from_sarif(doc: Dict[str, Any]) -> List[Finding]:
    """Inverse of :func:`render_sarif` — used by the round-trip test and
    by tooling that diffs finding sets across SARIF uploads."""
    out: List[Finding] = []
    for run in doc.get("runs", ()):
        for res in run.get("results", ()):
            loc = (res.get("locations") or [{}])[0].get(
                "physicalLocation", {}
            )
            region = loc.get("region", {})
            out.append(
                Finding(
                    rule=res.get("ruleId", ""),
                    path=loc.get("artifactLocation", {}).get("uri", ""),
                    line=int(region.get("startLine", 0)),
                    col=int(region.get("startColumn", 1)) - 1,
                    message=res.get("message", {}).get("text", ""),
                    snippet=region.get("snippet", {}).get("text", ""),
                )
            )
    return out
