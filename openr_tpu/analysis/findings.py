"""Finding model for orlint.

A :class:`Finding` is one rule violation at one source location.  Findings
carry the *stripped text of the offending line* (``snippet``) so the
baseline can match them content-first: line numbers drift every edit, the
offending code mostly does not (see baseline.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict


@dataclass(frozen=True)
class Finding:
    rule: str  #: rule id, e.g. "clock-sleep"
    path: str  #: repo-relative posix path
    line: int  #: 1-based line of the offending AST node
    col: int  #: 0-based column
    message: str  #: human explanation, names the invariant violated
    snippet: str = ""  #: stripped source text of `line`, for baseline matching

    def key(self):
        """Identity used for baseline matching — content-based, no column
        (editor reformatting must not un-baseline a grandfathered hit)."""
        return (self.rule, self.path, self.snippet)

    def to_json(self) -> Dict[str, Any]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "snippet": self.snippet,
        }

    @classmethod
    def from_json(cls, doc: Dict[str, Any]) -> "Finding":
        return cls(
            rule=doc["rule"],
            path=doc["path"],
            line=int(doc.get("line", 0)),
            col=int(doc.get("col", 0)),
            message=doc.get("message", ""),
            snippet=doc.get("snippet", ""),
        )

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def render_github(self) -> str:
        """GitHub Actions workflow-command annotation (``--format=github``).
        Newlines/percents URL-escape per the workflow-command grammar."""

        def esc(s: str, *, prop: bool = False) -> str:
            s = s.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")
            if prop:
                s = s.replace(":", "%3A").replace(",", "%2C")
            return s

        return (
            f"::error file={esc(self.path, prop=True)},"
            f"line={self.line},col={self.col},"
            f"title={esc('orlint ' + self.rule, prop=True)}"
            f"::{esc(self.message)}"
        )


@dataclass
class Report:
    """One analysis run: active findings plus what was filtered and why."""

    findings: list = field(default_factory=list)  #: unsuppressed, unbaselined
    suppressed: list = field(default_factory=list)
    baselined: list = field(default_factory=list)
    stale_baseline: list = field(default_factory=list)  #: entries no finding matched
    files_scanned: int = 0
    #: how many files were actually ast.parse'd this run (< files_scanned
    #: when the ``--cache`` result cache serves warm entries)
    files_parsed: int = 0

    @property
    def clean(self) -> bool:
        return not self.findings

    def counts_by_rule(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return dict(sorted(out.items()))

    def to_json(self) -> Dict[str, Any]:
        return {
            "files_scanned": self.files_scanned,
            "files_parsed": self.files_parsed,
            "counts": self.counts_by_rule(),
            "findings": [f.to_json() for f in self.findings],
            "suppressed": len(self.suppressed),
            "baselined": len(self.baselined),
            "stale_baseline": [e.to_json() for e in self.stale_baseline],
        }
