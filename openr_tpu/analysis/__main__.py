"""orlint CLI — ``python -m openr_tpu.analysis``.

Modes:

* (default)            — report findings, exit 0 regardless
* ``--check``          — exit 1 when any unsuppressed, unbaselined
                         finding survives (the tier-1 gate,
                         tests/test_orlint.py)
* ``--update-baseline``— rewrite analysis/baseline.json from the current
                         findings (the ratchet: run after FIXING things,
                         not instead of fixing them)
* ``--format=json``    — machine-readable report (finding list + per-rule
                         counts) so BENCH/verdict tooling can diff
                         finding counts across PRs
* ``--list-rules``     — every rule id with its one-line rationale
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from openr_tpu.analysis.baseline import Baseline
from openr_tpu.analysis.engine import (
    analyze_paths,
    default_baseline_path,
)
from openr_tpu.analysis.passes import all_rules


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m openr_tpu.analysis",
        description="orlint: static invariant checks for openr-tpu "
        "(clock discipline, actor isolation, JAX kernel hygiene, "
        "blocking-in-event-loop)",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        type=Path,
        help="files/dirs to scan (default: the openr_tpu package)",
    )
    ap.add_argument(
        "--check",
        action="store_true",
        help="exit non-zero when unbaselined findings remain",
    )
    ap.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        dest="fmt",
    )
    ap.add_argument(
        "--baseline",
        type=Path,
        default=None,
        help=f"baseline file (default: {default_baseline_path()})",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline (show grandfathered findings too)",
    )
    ap.add_argument(
        "--update-baseline",
        action="store_true",
        help="rewrite the baseline from current findings and exit",
    )
    ap.add_argument(
        "--rule",
        action="append",
        dest="rules",
        metavar="RULE",
        help="restrict to specific rule id(s)",
    )
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, why in all_rules().items():
            print(f"{rule:22s} {why}")
        return 0

    baseline_path = args.baseline or default_baseline_path()

    if args.update_baseline:
        report = analyze_paths(
            args.paths, baseline_path, use_baseline=False, rules=args.rules
        )
        Baseline.from_findings(report.findings).dump(baseline_path)
        print(
            f"orlint: baseline written to {baseline_path} "
            f"({len(report.findings)} findings)"
        )
        return 0

    report = analyze_paths(
        args.paths,
        baseline_path,
        use_baseline=not args.no_baseline,
        rules=args.rules,
    )

    if args.fmt == "json":
        print(json.dumps(report.to_json(), indent=2))
    else:
        for f in report.findings:
            print(f.render())
        for e in report.stale_baseline:
            print(
                f"{e.path}:{e.line}: [stale-baseline] entry no longer "
                f"matches any {e.rule} finding — remove it "
                "(--update-baseline)"
            )
        counts = report.counts_by_rule()
        summary = (
            f"orlint: {len(report.findings)} finding(s) across "
            f"{report.files_scanned} file(s)"
            f" ({len(report.baselined)} baselined, "
            f"{len(report.suppressed)} suppressed"
            + (
                f", {len(report.stale_baseline)} stale baseline entr"
                + ("y" if len(report.stale_baseline) == 1 else "ies")
                if report.stale_baseline
                else ""
            )
            + ")"
        )
        if counts:
            summary += " — " + ", ".join(
                f"{r}: {n}" for r, n in counts.items()
            )
        print(summary)

    if args.check and not report.clean:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
