"""orlint CLI — ``python -m openr_tpu.analysis``.

Modes:

* (default)            — report findings, exit 0 regardless
* ``--check``          — exit 1 when any unsuppressed, unbaselined
                         finding survives (the tier-1 gate,
                         tests/test_orlint.py; canonical invocation:
                         ``python -m openr_tpu.analysis --check --cache``)
* ``--cache``          — serve unchanged files from the content-hash
                         result cache (cache.py; warm runs re-parse
                         zero files)
* ``--update-baseline``— rewrite analysis/baseline.json from the current
                         findings (the ratchet: run after FIXING things,
                         not instead of fixing them)
* ``--format=json``    — machine-readable report (finding list + per-rule
                         counts) so BENCH/verdict tooling can diff
                         finding counts across PRs
* ``--format=github``  — GitHub Actions ``::error file=..,line=..``
                         annotations, one per finding
* ``--format=sarif``   — SARIF 2.1.0 (code-scanning upload format;
                         round-trips through findings_from_sarif)
* ``--fix-stale-suppressions`` — rewrite source files removing
                         suppression comments whose rules no longer
                         fire (``--check`` reports them as warnings)
* ``--list-rules``     — every rule id with its pass family and one-line
                         rationale
* ``--explain RULE``   — the rule's rationale plus a minimal tripping
                         snippet and its fixed twin
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from openr_tpu.analysis.baseline import Baseline
from openr_tpu.analysis.engine import (
    analyze_paths,
    default_baseline_path,
    default_cache_path,
    repo_root,
)
from openr_tpu.analysis.findings import render_sarif
from openr_tpu.analysis.passes import (
    all_rules,
    make_passes,
    rule_example,
)
from openr_tpu.analysis.suppress import strip_stale


def _explain(rule: str) -> int:
    rules = all_rules()
    if rule not in rules:
        print(f"orlint: unknown rule {rule!r} (see --list-rules)")
        return 2
    found = rule_example(rule)
    print(f"{rule} [{found[0] if found else '?'}]")
    print(f"  {rules[rule]}")
    if found is None:  # pragma: no cover - meta-test enforces coverage
        print("  (no example registered)")
        return 0
    _, ex = found
    print("\ntrips:\n")
    for ln in ex["trip"].rstrip("\n").splitlines():
        print(f"    {ln}")
    print("\nfixed:\n")
    for ln in ex["fix"].rstrip("\n").splitlines():
        print(f"    {ln}")
    print(
        "\nsuppress (only with a written justification):\n"
        f"    ... # orlint: disable={rule} (<why this site is legitimate>)"
    )
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m openr_tpu.analysis",
        description="orlint: static invariant checks for openr-tpu "
        "(clock discipline, actor isolation, JAX kernel hygiene, "
        "blocking-in-event-loop, replay determinism)",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        type=Path,
        help="files/dirs to scan (default: the openr_tpu package)",
    )
    ap.add_argument(
        "--check",
        action="store_true",
        help="exit non-zero when unbaselined findings remain",
    )
    ap.add_argument(
        "--format",
        choices=("text", "json", "github", "sarif"),
        default="text",
        dest="fmt",
    )
    ap.add_argument(
        "--fix-stale-suppressions",
        action="store_true",
        help="rewrite files removing suppression comments whose rules "
        "no longer fire, then exit",
    )
    ap.add_argument(
        "--baseline",
        type=Path,
        default=None,
        help=f"baseline file (default: {default_baseline_path()})",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline (show grandfathered findings too)",
    )
    ap.add_argument(
        "--update-baseline",
        action="store_true",
        help="rewrite the baseline from current findings and exit",
    )
    ap.add_argument(
        "--rule",
        action="append",
        dest="rules",
        metavar="RULE",
        help="restrict to specific rule id(s)",
    )
    ap.add_argument(
        "--cache",
        action="store_true",
        help="use the per-file result cache (warm runs re-parse zero "
        "unchanged files; invalidated by file hash, rule-set version, "
        "and the project facts digest)",
    )
    ap.add_argument(
        "--cache-path",
        type=Path,
        default=None,
        help=f"cache file (default: {default_cache_path()})",
    )
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument(
        "--explain",
        metavar="RULE",
        default=None,
        help="show a rule's rationale with a minimal trip/fix example",
    )
    args = ap.parse_args(argv)

    if args.explain:
        return _explain(args.explain)

    if args.list_rules:
        for p in make_passes():
            for rule, why in p.rules.items():
                print(f"{rule:24s} [{p.name}] {why}")
        return 0

    baseline_path = args.baseline or default_baseline_path()
    cache_path = None
    if args.cache or args.cache_path is not None:
        cache_path = args.cache_path or default_cache_path()

    if args.update_baseline:
        report = analyze_paths(
            args.paths, baseline_path, use_baseline=False, rules=args.rules
        )
        Baseline.from_findings(report.findings).dump(baseline_path)
        print(
            f"orlint: baseline written to {baseline_path} "
            f"({len(report.findings)} findings)"
        )
        return 0

    if args.fix_stale_suppressions and args.rules:
        print(
            "orlint: --fix-stale-suppressions needs a full run — a "
            "--rule filter proves nothing about absent findings"
        )
        return 2

    report = analyze_paths(
        args.paths,
        baseline_path,
        use_baseline=not args.no_baseline,
        rules=args.rules,
        cache_path=cache_path,
    )

    if args.fix_stale_suppressions:
        by_path: dict = {}
        for s in report.stale_suppressions:
            by_path.setdefault(s.path, []).append((s.line, s.rules))
        edited_files = 0
        base = repo_root()
        for rel, entries in sorted(by_path.items()):
            path = Path(rel)
            if not path.is_absolute():
                path = base / rel
            new_text, edits = strip_stale(path.read_text(), entries)
            if edits:
                path.write_text(new_text)
                edited_files += 1
                print(f"orlint: {rel}: removed {edits} stale marker(s)")
        print(
            f"orlint: {len(report.stale_suppressions)} stale "
            f"suppression(s) across {edited_files} file(s) fixed"
        )
        return 0

    if args.fmt == "json":
        print(json.dumps(report.to_json(), indent=2))
    elif args.fmt == "sarif":
        print(json.dumps(render_sarif(report, all_rules()), indent=2))
    elif args.fmt == "github":
        for f in report.findings:
            print(f.render_github())
    else:
        for f in report.findings:
            print(f.render())
        for e in report.stale_baseline:
            print(
                f"{e.path}:{e.line}: [stale-baseline] entry no longer "
                f"matches any {e.rule} finding — remove it "
                "(--update-baseline)"
            )
        for s in report.stale_suppressions:
            print(f"warning: {s.render()}")
        counts = report.counts_by_rule()
        summary = (
            f"orlint: {len(report.findings)} finding(s) across "
            f"{report.files_scanned} file(s)"
        )
        if cache_path is not None:
            summary += f" ({report.files_parsed} parsed)"
        summary += (
            f" ({len(report.baselined)} baselined, "
            f"{len(report.suppressed)} suppressed"
            + (
                f", {len(report.stale_baseline)} stale baseline entr"
                + ("y" if len(report.stale_baseline) == 1 else "ies")
                if report.stale_baseline
                else ""
            )
            + (
                f", {len(report.stale_suppressions)} stale "
                "suppression(s)"
                if report.stale_suppressions
                else ""
            )
            + ")"
        )
        if counts:
            summary += " — " + ", ".join(
                f"{r}: {n}" for r, n in counts.items()
            )
        print(summary)

    if args.check and not report.clean:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
