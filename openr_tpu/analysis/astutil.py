"""Shared AST plumbing for orlint passes.

Passes reason about *dotted origins*: ``import time as _time`` followed by
``_time.monotonic()`` must trip the same rule as ``time.monotonic()``, and
``from jax import jit`` must count as ``jax.jit``.  :class:`ImportMap`
normalizes every locally-bound name to the dotted path it was imported
from; :func:`resolve` folds an expression's attribute chain down onto
that.

Everything here is deliberately scope-naive — one namespace per module,
names matched textually.  That trades a sliver of precision (a local
variable shadowing an import) for passes that stay ~50 lines each; the
suppression mechanism absorbs the rare false positive.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional


class ImportMap:
    """local name -> dotted origin, from a module's import statements."""

    def __init__(self, tree: ast.Module) -> None:
        self.names: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    # `import a.b` binds `a`; `import a.b as c` binds c->a.b
                    self.names[local] = alias.name if alias.asname else local
            elif isinstance(node, ast.ImportFrom):
                if node.level:  # relative import: origin unknown, skip
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    self.names[local] = f"{node.module}.{alias.name}"

    def origin(self, name: str) -> str:
        return self.names.get(name, name)


def resolve(node: ast.expr, imports: ImportMap) -> Optional[str]:
    """Dotted origin of an expression: Name or Attribute chain rooted at a
    Name.  ``_time.monotonic`` -> ``time.monotonic``; ``self.clock.sleep``
    -> ``self.clock.sleep`` (roots that aren't imports pass through).
    Returns None for anything else (calls, subscripts, literals)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(imports.origin(node.id))
    return ".".join(reversed(parts))


def attach_parents(tree: ast.Module) -> None:
    """Annotate every node with ``.orlint_parent`` (None at the root)."""
    tree.orlint_parent = None  # type: ignore[attr-defined]
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.orlint_parent = node  # type: ignore[attr-defined]


def parent_chain(node: ast.AST) -> Iterator[ast.AST]:
    cur = getattr(node, "orlint_parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "orlint_parent", None)


def enclosing_functions(node: ast.AST) -> List[ast.AST]:
    """Innermost-first chain of enclosing (async) function defs."""
    return [
        p
        for p in parent_chain(node)
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]


def enclosing_class(node: ast.AST) -> Optional[ast.ClassDef]:
    for p in parent_chain(node):
        if isinstance(p, ast.ClassDef):
            return p
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # keep climbing: methods live inside the class
            continue
    return None


def is_awaited(call: ast.Call) -> bool:
    parent = getattr(call, "orlint_parent", None)
    return isinstance(parent, ast.Await)


def const_value(node: ast.expr):
    """Constant's value, else a sentinel that equals nothing."""
    if isinstance(node, ast.Constant):
        return node.value
    return _NOT_CONST


class _NotConst:
    def __eq__(self, other) -> bool:  # pragma: no cover - sentinel
        return False

    def __hash__(self) -> int:  # pragma: no cover - sentinel
        return 0


_NOT_CONST = _NotConst()


def all_param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def annotation_name(node: Optional[ast.expr]) -> Optional[str]:
    """Bare class name of an annotation: ``Spark``, ``runtime.Actor`` ->
    ``Actor``, ``Optional[KvStore]`` -> ``KvStore`` (single-arg generics
    only; unions stay None)."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            node = ast.parse(node.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(node, ast.Subscript):
        base = node.value
        if (
            resolve(base, _EMPTY_IMPORTS) or ""
        ).split(".")[-1] in ("Optional",):
            return annotation_name(node.slice)
        return None
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


_EMPTY_IMPORTS = ImportMap(ast.parse(""))
