"""orlint — AST-based static analysis for openr-tpu's load-bearing
invariants.

The repo's discipline rules are written down as docstring law
(common/runtime.py: queues-only actor isolation, Clock-only timing;
ops/jit_guard.py: guarded jit dispatch) but were previously enforced
only by stress tests.  This package enforces them structurally:

* ``python -m openr_tpu.analysis --check`` — the tier-1 gate
* ``python -m openr_tpu.analysis --format=json`` — for tooling diffs
* ``# orlint: disable=<rule> (<why>)`` — per-line escape hatch
* ``analysis/baseline.json`` — grandfathered findings; ratchets down

See docs/Developer_Guide.md §"Static invariants (orlint)" for each rule
and its rationale.
"""

from openr_tpu.analysis.baseline import Baseline, BaselineEntry
from openr_tpu.analysis.callgraph import ModuleSummary, Project
from openr_tpu.analysis.engine import (
    analyze_modules,
    analyze_paths,
    analyze_source,
    build_project,
    default_baseline_path,
    default_cache_path,
    load_modules,
    repo_root,
)
from openr_tpu.analysis.findings import (
    Finding,
    Report,
    StaleSuppression,
    findings_from_sarif,
    render_sarif,
)
from openr_tpu.analysis.passes import all_rules, make_passes

__all__ = [
    "Baseline",
    "BaselineEntry",
    "Finding",
    "ModuleSummary",
    "Project",
    "Report",
    "StaleSuppression",
    "findings_from_sarif",
    "render_sarif",
    "all_rules",
    "analyze_modules",
    "analyze_paths",
    "analyze_source",
    "build_project",
    "default_baseline_path",
    "default_cache_path",
    "load_modules",
    "make_passes",
    "repo_root",
]
