"""Grandfathered-finding baseline for orlint.

The gate must start green on day one without blessing new violations, so
pre-existing findings live in a checked-in ``baseline.json`` and are
filtered out of ``--check``.  The contract is a *ratchet*: the baseline
only shrinks — fix a finding, regenerate with ``--update-baseline``, and
the meta-test (tests/test_orlint.py) fails if an entry goes stale (its
file vanished or the offending line text no longer appears), forcing the
dead weight out.

Matching is content-based: an entry is ``(rule, path, snippet)`` where
``snippet`` is the stripped source text of the offending line, stored
with a count (the same line text can trip the same rule several times in
one file).  Line numbers are recorded for humans but ignored for
matching, so unrelated edits above a grandfathered hit don't resurrect
it.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Dict, List, Tuple

from openr_tpu.analysis.findings import Finding, Report

VERSION = 1


@dataclass
class BaselineEntry:
    rule: str
    path: str
    snippet: str
    line: int  # advisory only; matching is by (rule, path, snippet)

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.snippet)

    def to_json(self) -> Dict[str, Any]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "snippet": self.snippet,
        }


class Baseline:
    def __init__(self, entries: List[BaselineEntry]) -> None:
        self.entries = entries

    @classmethod
    def load(cls, path) -> "Baseline":
        try:
            with open(path) as f:
                doc = json.load(f)
        except FileNotFoundError:
            return cls([])
        entries = [
            BaselineEntry(
                rule=e["rule"],
                path=e["path"],
                snippet=e.get("snippet", ""),
                line=int(e.get("line", 0)),
            )
            for e in doc.get("findings", [])
        ]
        return cls(entries)

    @classmethod
    def from_findings(cls, findings: List[Finding]) -> "Baseline":
        return cls(
            [
                BaselineEntry(
                    rule=f.rule, path=f.path, snippet=f.snippet, line=f.line
                )
                for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
            ]
        )

    def dump(self, path) -> None:
        doc = {
            "version": VERSION,
            "findings": [e.to_json() for e in self.entries],
        }
        with open(path, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=False)
            f.write("\n")

    def apply(self, report: Report) -> None:
        """Move baselined findings out of ``report.findings``; record
        entries that matched nothing as stale."""
        budget: Dict[Tuple[str, str, str], int] = {}
        for e in self.entries:
            budget[e.key()] = budget.get(e.key(), 0) + 1
        active: List[Finding] = []
        for f in report.findings:
            k = f.key()
            if budget.get(k, 0) > 0:
                budget[k] -= 1
                report.baselined.append(f)
            else:
                active.append(f)
        report.findings = active
        for e in self.entries:
            if budget.get(e.key(), 0) > 0:
                budget[e.key()] -= 1
                report.stale_baseline.append(e)
