"""Project-wide symbol table + call graph — orlint's interprocedural spine.

Per-file passes (PR 2) can only judge a call site by what the site says.
The determinism family (passes/determinism.py) needs more: a raw
``datetime.now()`` three helpers away from an actor run loop breaks
byte-identical replay just as surely as one written inside the loop, and
an unsorted ``set`` iteration is only a replay bug when the loop body
eventually *reaches* a digest/spill/wire sink.  Both are reachability
questions over the whole project, so this module grows orlint from a
per-file linter into a project-wide engine:

* :class:`ModuleSummary` — the serializable cross-module facts of ONE
  file: class defs (bases, methods, constructor-assignment attribute
  types), function defs, per-function call references, jitted kernel
  names.  Summaries are pure data (canonical-JSON round-trip), which is
  what makes the ``--cache`` result cache sound: a file whose summary is
  byte-identical cannot have changed what any OTHER file's findings
  depend on (see cache.py).

* :class:`Project` — the symbol table + call graph assembled from every
  summary: bare-name class hierarchy (``subclasses_of`` — the actor
  registry generalized), a qualname function index, resolved call
  edges, and BFS reachability with *barrier classes* (calls dispatched
  through an injected ``Clock`` receiver are the sanctioned discipline,
  so traversal stops at the barrier — that is exactly why a wall-clock
  read behind ``self.clock.now()`` does not trip
  ``wallclock-reachability``).

Resolution is deliberately bare-name / single-namespace, same trade as
astutil.py: a sliver of precision for an engine that stays small and a
suppression mechanism that absorbs the rare false positive.  Over- and
under-approximation are both possible; every edge the graph *does* draw
comes from an explicit syntactic pattern listed in ``_CallIndexer``.
"""

from __future__ import annotations

import ast
import hashlib
import json
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from openr_tpu.analysis.astutil import ImportMap, annotation_name, resolve

# call-ref kinds (compact, serialization-stable):
#   ["n", target, line]        plain/dotted call: helper(..), time.monotonic(..)
#   ["s", method, line]        self.method(..)
#   ["a", attr, method, line]  self.attr.method(..)
#   ["v", var, method, line]   var.method(..) — var may be locally typed
#   ["m", method, line]        method call on an untypable receiver
CallRef = List  # [kind, *parts, line]

#: pseudo-function holding a module's top-level calls
MODULE_BODY = "<module>"

#: builtin container constructors that bind an "unordered" local type
_SET_CTORS = {"set", "frozenset"}
_DICT_CTORS = {"dict", "collections.defaultdict", "collections.Counter"}
_LIST_CTORS = {"list", "collections.deque"}
_ORDERED_ANNOTATIONS = {"OrderedDict"}

#: the builtin mutable-container markers (attr/var types that are not a
#: class name) — the atomicity family treats exactly these as
#: "actor-owned container" (passes/atomicity.py)
CONTAINER_MARKERS = ("set", "dict", "list")


@dataclass
class ClassInfo:
    name: str
    bases: List[str] = field(default_factory=list)  # bare base names
    #: constructor-assignment attribute types: attr -> class ref (bare
    #: internal name, dotted external like "hashlib.sha256", or the
    #: builtin markers "set"/"dict")
    attrs: Dict[str, str] = field(default_factory=dict)
    methods: Dict[str, str] = field(default_factory=dict)  # name -> local qual

    def to_json(self) -> dict:
        return {
            "bases": self.bases,
            "attrs": self.attrs,
            "methods": self.methods,
        }

    @classmethod
    def from_json(cls, name: str, doc: dict) -> "ClassInfo":
        return cls(
            name=name,
            bases=list(doc.get("bases", [])),
            attrs=dict(doc.get("attrs", {})),
            methods=dict(doc.get("methods", {})),
        )


@dataclass
class FunctionInfo:
    name: str  # bare function/method name
    cls: str  # enclosing class bare name, "" for module functions
    line: int
    end_line: int
    calls: List[CallRef] = field(default_factory=list)
    #: locally-typed names: var -> class ref (annotations + ctor bindings)
    var_types: Dict[str, str] = field(default_factory=dict)
    #: suspension facts (passes/atomicity.py): whether this is an async
    #: def, the call refs that appear under an ``await``, and whether the
    #: body suspends unconditionally of any callee (awaiting a bare
    #: future/task, ``async for``, ``async with``).  Serialized so the
    #: interprocedural suspends-fixpoint is a pure function of summaries
    #: — which is what keeps the result cache's project_digest sound.
    is_async: bool = False
    awaited: List[CallRef] = field(default_factory=list)
    suspends: bool = False

    def to_json(self) -> dict:
        out = {
            "cls": self.cls,
            "line": self.line,
            "end_line": self.end_line,
            "calls": self.calls,
            "var_types": self.var_types,
        }
        # truthy-only keys keep summaries compact and byte-stable for the
        # (majority) sync functions
        if self.is_async:
            out["is_async"] = True
        if self.awaited:
            out["awaited"] = self.awaited
        if self.suspends:
            out["suspends"] = True
        return out

    @classmethod
    def from_json(cls, local_qual: str, doc: dict) -> "FunctionInfo":
        # the summary dict key is the LOCAL qualname ("Cls.meth" / "fn" /
        # "<module>"); the bare name is its last segment — reconstructing
        # it wrong silently empties the (cls, method) index, which is why
        # test_orlint_determinism pins full Project-edge round-trip equality
        return cls(
            name=local_qual.rsplit(".", 1)[-1],
            cls=doc.get("cls", ""),
            line=int(doc.get("line", 0)),
            end_line=int(doc.get("end_line", 0)),
            calls=[list(c) for c in doc.get("calls", [])],
            var_types=dict(doc.get("var_types", {})),
            is_async=bool(doc.get("is_async", False)),
            awaited=[list(c) for c in doc.get("awaited", [])],
            suspends=bool(doc.get("suspends", False)),
        )


@dataclass
class ModuleSummary:
    """The cross-module facts of one file — everything any pass may read
    about a module it did not parse.  Keep this complete: the cache's
    soundness argument is "same summaries ⇒ same cross-module context"."""

    module: str  # dotted import path ("" outside a package)
    rel: str
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    #: "Cls.meth" / "fn" / "<module>" -> FunctionInfo
    functions: Dict[str, FunctionInfo] = field(default_factory=dict)
    #: jitted kernel names -> sorted static argnames (jax_hygiene registry)
    jitted: Dict[str, List[str]] = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "module": self.module,
            "rel": self.rel,
            "classes": {k: c.to_json() for k, c in sorted(self.classes.items())},
            "functions": {
                k: f.to_json() for k, f in sorted(self.functions.items())
            },
            "jitted": {k: sorted(v) for k, v in sorted(self.jitted.items())},
        }

    @classmethod
    def from_json(cls, doc: dict) -> "ModuleSummary":
        return cls(
            module=doc.get("module", ""),
            rel=doc.get("rel", ""),
            classes={
                k: ClassInfo.from_json(k, v)
                for k, v in doc.get("classes", {}).items()
            },
            functions={
                k: FunctionInfo.from_json(k, v)
                for k, v in doc.get("functions", {}).items()
            },
            jitted={k: list(v) for k, v in doc.get("jitted", {}).items()},
        )

    def content_hash(self) -> str:
        return hashlib.sha256(
            json.dumps(self.to_json(), sort_keys=True, separators=(",", ":"))
            .encode()
        ).hexdigest()


# ---------------------------------------------------------------------------
# building a summary from a parsed module
# ---------------------------------------------------------------------------


def _class_ref(node: ast.expr, imports: ImportMap) -> Optional[str]:
    """What a constructor call binds: bare internal class name, dotted
    external ("hashlib.sha256"), or the builtin set/dict markers."""
    target = resolve(node, imports)
    if not target:
        return None
    if target in _SET_CTORS:
        return "set"
    if target in _DICT_CTORS:
        return "dict"
    if target in _LIST_CTORS:
        return "list"
    if "." in target:
        head = target.split(".", 1)[0]
        # imported/external dotted reference: keep the dots so sink
        # matching can see "hashlib.sha256"; internal classes resolve by
        # their bare tail at graph time
        return target if head not in ("self",) else None
    return target


def _annotation_type(node: Optional[ast.expr]) -> Optional[str]:
    """Class ref for a parameter/variable annotation, with set/dict
    container annotations folded to the builtin markers."""
    name = annotation_name(node)
    if name is None and isinstance(node, ast.Subscript):
        name = annotation_name(node.value)
    if name is None:
        return None
    low = name.lower()
    if name in ("Set", "FrozenSet", "AbstractSet", "MutableSet") or low == "set":
        return "set"
    if name in ("Dict", "Mapping", "MutableMapping", "DefaultDict", "Counter") or low == "dict":
        return "dict"
    if name in ("List", "MutableSequence", "Deque") or low == "list":
        return "list"
    return name


class _CallIndexer(ast.NodeVisitor):
    """One walk of a module: classes, functions-of-record, call refs.

    Nested defs and lambdas are *flattened* into their enclosing
    function-of-record — defining a closure is treated as (potentially)
    calling it, which over-approximates reachability in exactly the
    conservative direction the determinism rules want."""

    def __init__(self, module_name: str, rel: str, tree: ast.Module,
                 imports: ImportMap) -> None:
        self.summary = ModuleSummary(module=module_name, rel=rel)
        self.imports = imports
        self._class_stack: List[ClassInfo] = []
        self._func_stack: List[FunctionInfo] = []
        mod_fn = FunctionInfo(name=MODULE_BODY, cls="", line=0, end_line=0)
        self.summary.functions[MODULE_BODY] = mod_fn
        self._module_fn = mod_fn
        self.visit(tree)

    # -- scopes ------------------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        info = ClassInfo(name=node.name)
        for b in node.bases:
            name = annotation_name(b)
            if name:
                info.bases.append(name)
        prev = self.summary.classes.get(node.name)
        if prev is None:
            self.summary.classes[node.name] = info
        else:  # same-name class redefinition: merge conservatively
            prev.bases.extend(b for b in info.bases if b not in prev.bases)
            info = prev
        self._class_stack.append(info)
        self.generic_visit(node)
        self._class_stack.pop()

    def _enter_function(self, node) -> None:
        if self._func_stack:  # nested def: flatten into the outer record
            self._record_param_types(node, self._func_stack[-1])
            self.generic_visit(node)
            return
        cls = self._class_stack[-1] if self._class_stack else None
        qual = f"{cls.name}.{node.name}" if cls else node.name
        info = FunctionInfo(
            name=node.name,
            cls=cls.name if cls else "",
            line=node.lineno,
            end_line=getattr(node, "end_lineno", node.lineno) or node.lineno,
            is_async=isinstance(node, ast.AsyncFunctionDef),
        )
        self._record_param_types(node, info)
        if cls is not None:
            cls.methods.setdefault(node.name, qual)
        # first definition wins (same-name redefinitions are rare and the
        # first is what most callers bound at import time)
        self.summary.functions.setdefault(qual, info)
        self._func_stack.append(info)
        self.generic_visit(node)
        self._func_stack.pop()

    def _record_param_types(self, node, info: FunctionInfo) -> None:
        a = node.args
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            t = _annotation_type(p.annotation)
            if t:
                info.var_types.setdefault(p.arg, t)

    visit_FunctionDef = _enter_function
    visit_AsyncFunctionDef = _enter_function

    # -- bindings ----------------------------------------------------------

    @property
    def _fn(self) -> FunctionInfo:
        return self._func_stack[-1] if self._func_stack else self._module_fn

    def visit_Assign(self, node: ast.Assign) -> None:
        self._bind(node.targets, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        t = _annotation_type(node.annotation)
        if t:
            self._bind_ref([node.target], t)
        self.generic_visit(node)

    def _bind(self, targets: Sequence[ast.expr], value: ast.expr) -> None:
        # `x = given or Default(..)` / `x = a if cond else b`: any branch
        # that resolves to a class binds (first resolvable wins — the
        # branches of real fallback chains construct the same family)
        if isinstance(value, ast.BoolOp):
            for v in value.values:
                self._bind(targets, v)
            return
        if isinstance(value, ast.IfExp):
            self._bind(targets, value.body)
            self._bind(targets, value.orelse)
            return
        ref: Optional[str] = None
        if isinstance(value, ast.Call):
            ref = _class_ref(value.func, self.imports)
            if ref is not None and "." not in ref and ref not in CONTAINER_MARKERS:
                # plain-name call: only a Title-case name plausibly
                # constructs; helper() results stay untyped
                if not ref[:1].isupper():
                    ref = None
        elif isinstance(value, ast.SetComp) or (
            isinstance(value, ast.Set)
        ):
            ref = "set"
        elif isinstance(value, (ast.Dict, ast.DictComp)):
            ref = "dict"
        elif isinstance(value, (ast.List, ast.ListComp)):
            ref = "list"
        elif isinstance(value, ast.Name):
            # alias of an already-typed local (incl. annotated params):
            # `clock = self._clock or fallback` is NOT this shape — only a
            # plain name copy propagates
            ref = self._fn.var_types.get(value.id)
        if ref is not None:
            self._bind_ref(targets, ref)

    def _bind_ref(self, targets: Sequence[ast.expr], ref: str) -> None:
        for t in targets:
            if isinstance(t, ast.Name):
                self._fn.var_types.setdefault(t.id, ref)
            elif (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
                and self._class_stack
            ):
                self._class_stack[-1].attrs.setdefault(t.attr, ref)

    # -- parameter-to-attribute propagation happens via _bind: in
    #    `self.clock = clock`, the RHS Name's type comes from var_types
    #    (annotated params are registered there at function entry).

    # -- calls -------------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        self._fn.calls.append(self._call_ref(node))
        # callback harvesting: a function/method REFERENCE passed as an
        # argument is treated as potentially called by the receiver —
        # that is how every actor fiber is born (`spawn_queue_loop(q,
        # self._process)`, `schedule(5.0, self._sample)`, listener
        # registration) and the conservative direction reachability wants
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            ref = callable_ref_for(arg, self.imports)
            if ref is not None:
                self._fn.calls.append(ref)
        self.generic_visit(node)

    def _call_ref(self, node: ast.Call) -> CallRef:
        return call_ref_for(node, self.imports)

    # -- suspension facts --------------------------------------------------

    def visit_Await(self, node: ast.Await) -> None:
        if isinstance(node.value, ast.Call):
            self._fn.awaited.append(call_ref_for(node.value, self.imports))
        else:
            # awaiting a bare future/task/gather-result: suspension is not
            # attributable to a callee — the function suspends, period
            self._fn.suspends = True
        self.generic_visit(node)

    def visit_AsyncFor(self, node: ast.AsyncFor) -> None:
        self._fn.suspends = True
        self.generic_visit(node)

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        self._fn.suspends = True
        self.generic_visit(node)


def call_ref_for(node: ast.Call, imports: ImportMap) -> CallRef:
    """Classify one call site into a serializable CallRef (shared with
    passes that resolve individual sites, e.g. unordered-emission)."""
    line = node.lineno
    target = resolve(node.func, imports)
    if target is not None:
        parts = target.split(".")
        if parts[0] == "self":
            if len(parts) == 2:
                return ["s", parts[1], line]
            if len(parts) == 3:
                return ["a", parts[1], parts[2], line]
            return ["m", parts[-1], line]
        if len(parts) == 1:
            return ["n", target, line]
        # `var.method()` where var is a plain (non-imported) local name is
        # a typed-receiver candidate; imported roots stay dotted targets
        root = node.func
        chain: List[str] = []
        while isinstance(root, ast.Attribute):
            chain.append(root.attr)
            root = root.value
        if (
            isinstance(root, ast.Name)
            and root.id not in imports.names
            and len(chain) == 1
        ):
            return ["v", root.id, chain[0], line]
        return ["n", target, line]
    if isinstance(node.func, ast.Attribute):
        return ["m", node.func.attr, line]
    return ["n", "<dynamic>", line]


def callable_ref_for(expr: ast.expr, imports: ImportMap) -> Optional[CallRef]:
    """CallRef for a bare callable *reference* (an uncalled Name or
    attribute handed to a spawner/listener), or None.  Data arguments
    resolve to targets no sink or function index matches, so the
    over-approximation stays cheap."""
    if isinstance(expr, ast.Name):
        return ["n", imports.origin(expr.id), expr.lineno]
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        root = expr.value.id
        if root == "self":
            return ["s", expr.attr, expr.lineno]
        if root in imports.names:  # `module.fn` reference
            return ["n", f"{imports.origin(root)}.{expr.attr}", expr.lineno]
        return ["v", root, expr.attr, expr.lineno]
    return None


def summarize_module(
    module_name: str, rel: str, tree: ast.Module, imports: ImportMap,
    jitted: Optional[Dict[str, Iterable[str]]] = None,
) -> ModuleSummary:
    idx = _CallIndexer(module_name, rel, tree, imports)
    if jitted:
        idx.summary.jitted = {k: sorted(v) for k, v in jitted.items()}
    return idx.summary


# ---------------------------------------------------------------------------
# the project: symbol table + call graph
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Reach:
    """Why a function is reachable: the root and the hop count."""

    root: str
    hops: int


class Project:
    """Symbol table + call graph over every module summary."""

    def __init__(self, summaries: Sequence[ModuleSummary]) -> None:
        self.summaries: Dict[str, ModuleSummary] = {s.rel: s for s in summaries}
        self._by_module: Dict[str, ModuleSummary] = {
            s.module: s for s in summaries if s.module
        }
        #: bare class name -> [(module, ClassInfo)]
        self.classes: Dict[str, List[Tuple[str, ClassInfo]]] = {}
        #: function qualname ("module.Cls.fn" / "module.fn") -> FunctionInfo
        self.functions: Dict[str, FunctionInfo] = {}
        #: (bare class, method) -> [qualname]
        self.methods: Dict[Tuple[str, str], List[str]] = {}
        self._subclass_cache: Dict[str, Set[str]] = {}
        self._untyped_cache: Dict[str, List[str]] = {}
        self._suspends_cache: Optional[Dict[str, bool]] = None
        for s in summaries:
            for cname, cinfo in s.classes.items():
                self.classes.setdefault(cname, []).append((s.module, cinfo))
            for local_qual, finfo in s.functions.items():
                qual = f"{s.module}.{local_qual}" if s.module else local_qual
                self.functions[qual] = finfo
                if finfo.cls:
                    self.methods.setdefault(
                        (finfo.cls, finfo.name), []
                    ).append(qual)
        #: resolved adjacency: qualname -> {target} where target is an
        #: internal qualname or an external dotted/bare string
        self.edges: Dict[str, Set[str]] = {}
        for s in summaries:
            for local_qual, finfo in s.functions.items():
                qual = f"{s.module}.{local_qual}" if s.module else local_qual
                self.edges[qual] = {
                    t
                    for ref in finfo.calls
                    for t in self.resolve_ref(s, finfo, ref)
                }

    # -- symbol table ------------------------------------------------------

    def subclasses_of(self, base: str) -> Set[str]:
        """Transitive subclasses by bare name, including ``base`` itself —
        the generalized actor-registry query."""
        cached = self._subclass_cache.get(base)
        if cached is not None:
            return cached
        out: Set[str] = {base}
        changed = True
        while changed:
            changed = False
            for name, infos in self.classes.items():
                if name in out:
                    continue
                if any(set(i.bases) & out for _, i in infos):
                    out.add(name)
                    changed = True
        self._subclass_cache[base] = out
        return out

    def jitted_registry(self) -> Dict[str, Dict[str, Set[str]]]:
        """module name -> {jitted fn -> static argnames} (jax_hygiene)."""
        return {
            s.module: {k: set(v) for k, v in s.jitted.items()}
            for s in self.summaries.values()
        }

    def attr_type(self, cls: str, attr: str) -> Optional[str]:
        for _, info in self.classes.get(cls, ()):  # first binding wins
            ref = info.attrs.get(attr)
            if ref:
                return ref
        return None

    def _method_quals(self, cls: str, method: str) -> List[str]:
        """Resolve ``cls.method`` through the bare-name base chain (the
        statically-declared class only — overrides in subclasses are NOT
        edges; that asymmetry is what makes Clock a real barrier)."""
        seen: Set[str] = set()
        frontier = [cls]
        while frontier:
            cur = frontier.pop(0)
            if cur in seen:
                continue
            seen.add(cur)
            quals = self.methods.get((cur, method))
            if quals:
                return quals
            for _, info in self.classes.get(cur, ()):
                frontier.extend(info.bases)
        return []

    # -- edge resolution ---------------------------------------------------

    def resolve_ref(
        self, s: ModuleSummary, fn: FunctionInfo, ref: CallRef
    ) -> List[str]:
        """CallRef -> graph targets.  Internal functions resolve to their
        qualname; anything else stays a dotted/bare external string (still
        matchable by sink registries); untypable method calls become
        ``?.method``."""
        kind = ref[0]
        if kind == "n":
            target = ref[1]
            if "." not in target:
                local = s.functions.get(target)
                if local is not None and target != MODULE_BODY:
                    return [f"{s.module}.{target}" if s.module else target]
                if target in s.classes:
                    return self._ctor_targets(s.module, target)
                return [target]
            # dotted: exact function? class ctor? external.
            if target in self.functions:
                return [target]
            mod, _, tail = target.rpartition(".")
            src = self._summary_for_module(mod)
            if src is not None:
                if tail in src.functions:
                    return [target]
                if tail in src.classes:
                    return self._ctor_targets(mod, tail)
            # bare-tail class ctor via from-import: `Foo()` resolved to
            # "pkg.mod.Foo" lands here when pkg.mod defines class Foo
            if self.classes.get(tail):
                return self._ctor_targets_by_name(tail)
            return [target]
        if kind == "s":
            if fn.cls:
                quals = self._method_quals(fn.cls, ref[1])
                if quals:
                    return quals
                # an attribute of self holding a callable (debounce /
                # throttle objects) — fall through to the attr type
                cls_ref = self.attr_type(fn.cls, ref[1])
                if cls_ref is not None:
                    return self._typed_method(cls_ref, "__call__")
            return self._untyped_method(ref[1])
        if kind == "a":
            attr, method = ref[1], ref[2]
            cls_ref = self.attr_type(fn.cls, attr) if fn.cls else None
            return self._typed_method(cls_ref, method)
        if kind == "v":
            var, method = ref[1], ref[2]
            return self._typed_method(fn.var_types.get(var), method)
        if kind == "m":
            return self._untyped_method(ref[1])
        return []

    #: by-name dispatch cap: an untypable receiver's method call edges to
    #: every project class defining that name, but only while the name
    #: stays distinctive — ubiquitous names (get, items, append..) would
    #: otherwise wire the whole graph together
    NAME_DISPATCH_CAP = 6

    def _untyped_method(self, method: str) -> List[str]:
        cached = self._untyped_cache.get(method)
        if cached is not None:
            return cached
        owners = [
            quals
            for (_cls, m), quals in self.methods.items()
            if m == method
        ]
        if owners and len(owners) <= self.NAME_DISPATCH_CAP:
            out = sorted({q for quals in owners for q in quals})
            out.append(f"?.{method}")  # keep the sink-matchable marker
        else:
            out = [f"?.{method}"]
        self._untyped_cache[method] = out
        return out

    def _typed_method(self, cls_ref: Optional[str], method: str) -> List[str]:
        if cls_ref is None or cls_ref in CONTAINER_MARKERS:
            return self._untyped_method(method)
        if "." in cls_ref:  # external dotted type: keep dotted for sinks
            return [f"{cls_ref}.{method}"]
        quals = self._method_quals(cls_ref, method)
        if quals:
            return quals
        return [f"{cls_ref}.{method}" if cls_ref[:1].isupper() else f"?.{method}"]

    def _ctor_targets(self, module: str, cls: str) -> List[str]:
        quals = self._method_quals(cls, "__init__")
        return quals or [f"{module}.{cls}.__init__" if module else f"{cls}.__init__"]

    def _ctor_targets_by_name(self, cls: str) -> List[str]:
        return self._method_quals(cls, "__init__") or [f"{cls}.__init__"]

    def _summary_for_module(self, module: str) -> Optional[ModuleSummary]:
        return self._by_module.get(module)

    # -- suspension analysis -----------------------------------------------

    def _override_expand(self, targets: Iterable[str]) -> Set[str]:
        """Widen internal method targets with their subclass overrides.
        ``await self.clock.sleep(..)`` statically resolves to the Clock
        base (whose stub body never suspends) — but at runtime a
        SimClock/WallClock override runs, and THOSE suspend.  Suspension
        is a may-property, so dynamic dispatch must widen; contrast the
        determinism barrier, where the same asymmetry is deliberate."""
        out = set(targets)
        for t in targets:
            fn = self.functions.get(t)
            if fn is None or not fn.cls:
                continue
            for sub in self.subclasses_of(fn.cls):
                out.update(self.methods.get((sub, fn.name), ()))
        return out

    def suspension_verdicts(self) -> Dict[str, bool]:
        """qualname -> "awaiting this internal function can yield control
        to another fiber".  Least fixpoint over the awaited-call edges: a
        function suspends iff its body suspends unconditionally (bare
        future, ``async for``/``async with``) or some awaited call
        resolves to an external target (unknown callee ⇒ conservatively
        suspends) or to an internal suspender.  The complement is the
        precision the atomicity family buys: ``await self._helper()``
        where the helper never reaches a real suspension primitive is NOT
        a turn boundary."""
        if self._suspends_cache is not None:
            return self._suspends_cache
        sus: Dict[str, bool] = {}
        awaited_tgts: Dict[str, Set[str]] = {}
        for s in self.summaries.values():
            for local_qual, fn in s.functions.items():
                qual = f"{s.module}.{local_qual}" if s.module else local_qual
                sus[qual] = bool(fn.suspends)
                if fn.awaited:
                    awaited_tgts[qual] = self._override_expand({
                        t
                        for ref in fn.awaited
                        for t in self.resolve_ref(s, fn, ref)
                    })
        changed = True
        while changed:
            changed = False
            for qual, tgts in awaited_tgts.items():
                if sus.get(qual):
                    continue
                for t in tgts:
                    if t not in self.functions or sus.get(t):
                        sus[qual] = True
                        changed = True
                        break
        self._suspends_cache = sus
        return sus

    def targets_suspend(self, targets: Iterable[str]) -> bool:
        """Would awaiting a call that resolves to ``targets`` suspend?
        External/unresolved targets conservatively do; internal method
        targets are widened with their subclass overrides."""
        sus = self.suspension_verdicts()
        return any(
            t not in self.functions or sus.get(t)
            for t in self._override_expand(set(targets))
        )

    # -- reachability ------------------------------------------------------

    def owner_class(self, qual: str) -> str:
        fn = self.functions.get(qual)
        return fn.cls if fn is not None else ""

    def reachable_from(
        self,
        roots: Iterable[str],
        barrier: Optional[Callable[[str], bool]] = None,
    ) -> Dict[str, Reach]:
        """BFS over resolved edges from ``roots`` (function qualnames).
        Returns every reachable *internal* function with its closest root
        and hop count.  ``barrier(qual)`` stops traversal INTO a node
        (the node is neither reported nor expanded)."""
        out: Dict[str, Reach] = {}
        frontier: List[Tuple[str, str, int]] = []
        for r in sorted(set(roots)):
            if r in self.functions and r not in out:
                out[r] = Reach(root=r, hops=0)
                frontier.append((r, r, 0))
        while frontier:
            cur, root, hops = frontier.pop(0)
            for t in sorted(self.edges.get(cur, ())):
                if t not in self.functions or t in out:
                    continue
                if barrier is not None and barrier(t):
                    continue
                out[t] = Reach(root=root, hops=hops + 1)
                frontier.append((t, root, hops + 1))
        return out

    def targets_reach(
        self,
        targets: Iterable[str],
        goal: Callable[[str], bool],
        _memo: Optional[Dict[str, bool]] = None,
    ) -> Optional[str]:
        """Does any of ``targets`` (graph target strings) reach a target
        satisfying ``goal``?  Returns the first matched goal target (for
        the finding message) or None.  ``_memo`` caches per-node verdicts
        across queries within one analysis run."""
        memo = _memo if _memo is not None else {}
        for t in sorted(set(targets)):
            hit = self._reaches_goal(t, goal, memo, set())
            if hit is not None:
                return hit
        return None

    def _reaches_goal(
        self,
        node: str,
        goal: Callable[[str], bool],
        memo: Dict[str, bool],
        on_path: Set[str],
    ) -> Optional[str]:
        if goal(node):
            return node
        if node not in self.functions:
            return None
        if node in memo:
            return memo[node] if isinstance(memo[node], str) else None
        if node in on_path:  # recursion cycle
            return None
        on_path.add(node)
        for t in sorted(self.edges.get(node, ())):
            hit = self._reaches_goal(t, goal, memo, on_path)
            if hit is not None:
                memo[node] = hit
                on_path.discard(node)
                return hit
        on_path.discard(node)
        memo[node] = False
        return None


def project_digest(summaries: Iterable[ModuleSummary]) -> str:
    """One hash over every module's facts — the cache's cross-module
    validity token (cache.py): findings computed under a digest are
    reusable only under the same digest."""
    doc = {s.rel: s.content_hash() for s in summaries}
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()
