"""FleetSweepCoordinator — one capacity sweep across N nodes' pools.

The single-node SweepService already packs scenarios by (world, hash)
into committed, resumable shards; the coordinator lifts that one tier:
it enumerates the FULL scenario set once, content-derives the
world→node assignment (assignment.py: pure function of
(scenario_set_hash, live set)), drives each node's SweepService with a
``world_filter`` sub-sweep solved from ONE shared vantage, and merges
every node's spill stream through the feed-order-independent
SweepReducer — so the merged summary digest is byte-equal to a
single-node run of the same set, whatever the node count or feed
interleaving.

Failure domains compose: a dead CHIP re-packs its shard inside one
node's executor (PR-8); a dead NODE is the domain above it — the
coordinator discards the dead node's *unmerged* spill entirely,
re-packs ALL its incomplete worlds onto the survivors as the next
assignment round, and keeps merged work untouched.  ISSUE 20 hardens
the three trust boundaries that remained:

* **RPC discipline** — every cross-node ctrl touch (state polls,
  launches, cancels, even the local spill read for a remote task)
  rides a per-member PR-5 ``CircuitBreaker``: a raising ctrl surface
  costs a failure + a gray strike and is retried under exponential
  backoff, never propagated into the pump (the PR-19 merge loop died
  on the first member exception).
* **Epoch fencing** — every assignment round is stamped with the
  membership epoch it was derived under and dispatched as
  ``fleet_epoch``; the receiving SweepService rejects stale-epoch work
  (``fleet.fenced.sweep``, returned not raised) and the coordinator
  re-derives those worlds under the current epoch.  A coordinator
  acting on a stale view can therefore never start work the current
  composition didn't derive.
* **Stragglers + gray failure** — a member that holds a round past
  ``straggler_deadline_s`` has its unfinished worlds re-packed onto
  the OTHER survivors *without* being declared dead; whichever copy
  commits a world first wins (first-committed-wins by world key — the
  loser's rows are dropped at merge, so the digest is byte-identical
  whether the straggler finishes late, never, or twice).  Strikes from
  failed/timed-out/raising sub-sweeps accumulate per member; at
  ``gray_strike_threshold`` the member — heartbeating, answering,
  failing — is demoted to drained (``fleet_gray_failure`` ticket).

The fleet manifest is pure content — (set hash, completed worlds,
totals) in canonical JSON — so at completion its bytes are identical
to an uninterrupted run's, whatever the kill/straggler history; the
operational world→spill routing that replay needs lives in a separate
sidecar (now carrying the worlds actually MERGED from each spill, so a
resume after a partial first-committed merge replays exactly those),
explicitly NOT part of the byte-identity contract.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from openr_tpu.common.runtime import Actor, Clock, CounterMap
from openr_tpu.fleet.assignment import assign_worlds
from openr_tpu.fleet.membership import FleetMembership
from openr_tpu.resilience.breaker import CircuitBreaker
from openr_tpu.sweep import (
    ScenarioSpec,
    SpillReader,
    SweepError,
    SweepReducer,
    enumerate_scenarios,
    scenario_set_hash,
)
from openr_tpu.sweep.scenario import canonical_json

MANIFEST_NAME = "fleet_manifest.json"
ROUTING_NAME = "fleet_routing.json"

#: sentinel: a ctrl call that was short-circuited or failed (breaker
#: bookkeeping already done) — callers skip and retry next pump
_CTRL_UNAVAILABLE = object()


class _Task:
    """One (node, round, world set) sub-sweep assignment."""

    __slots__ = (
        "node", "round", "worlds", "scenarios", "state", "spill_dir",
        "epoch", "launched_at", "merged_worlds", "straggled",
    )

    def __init__(self, node, rnd, worlds, scenarios, spill_dir, epoch=0):
        self.node = node
        self.round = rnd
        self.worlds: Tuple[str, ...] = worlds
        self.scenarios = scenarios
        #: pending|running|merged|lost|fenced|duplicate
        self.state = "pending"
        self.spill_dir = spill_dir
        #: membership epoch this assignment was derived under — the
        #: fencing stamp dispatched as ``fleet_epoch``
        self.epoch = epoch
        self.launched_at = 0.0
        #: the worlds actually fed from this spill (first-committed-
        #: wins may merge a strict subset); the routing sidecar records
        #: these so resume replays exactly what was merged
        self.merged_worlds: Tuple[str, ...] = ()
        self.straggled = False


class FleetSweepCoordinator(Actor):
    """Drives one fleet sweep over the member nodes' SweepServices.

    ``services`` maps fleet node name -> that node's SweepService.
    ``prepare`` enumerates + assigns (resuming from the fleet manifest
    when it matches); ``run`` pumps until every world is merged or the
    sweep is cancelled.  Everything the coordinator touches on a
    SweepService is its public ctrl surface — start_sweep /
    get_sweep_status / state — so a real deployment swaps the direct
    references for ctrl RPC without changing this logic; the per-member
    breaker is exactly where that RPC's timeout/backoff would live.
    """

    def __init__(
        self,
        clock: Clock,
        membership: FleetMembership,
        services: Dict[str, object],
        spill_root: str,
        counters: Optional[CounterMap] = None,
        top_k: int = 64,
        poll_interval_s: float = 0.02,
        straggler_deadline_s: float = 0.0,
        gray_strike_threshold: int = 3,
        ctrl_failure_threshold: int = 3,
        ctrl_backoff_initial_s: float = 0.5,
        ctrl_backoff_max_s: float = 8.0,
        ctrl_seed: int = 0,
    ) -> None:
        super().__init__("fleet", clock, counters)
        self.membership = membership
        self.services = dict(services)
        self.spill_root = spill_root
        self.top_k = top_k
        self.poll_interval_s = poll_interval_s
        #: 0 disables the straggler policy (a deadline must be chosen
        #: against the grammar size; config.py carries the knob)
        self.straggler_deadline_s = straggler_deadline_s
        self.gray_strike_threshold = gray_strike_threshold
        self.ctrl_failure_threshold = ctrl_failure_threshold
        self.ctrl_backoff_initial_s = ctrl_backoff_initial_s
        self.ctrl_backoff_max_s = ctrl_backoff_max_s
        self.ctrl_seed = ctrl_seed
        self.state = "idle"  # idle|running|done|cancelled|failed
        self.error = ""
        self.fleet_id = ""
        self.set_hash = ""
        self.params: dict = {}
        self.vantage = ""
        self.worlds_total = 0
        self.scenarios_total = 0
        self.world_scenarios: Dict[str, int] = {}
        self.completed_worlds: set = set()
        self.tasks: List[_Task] = []
        self.rounds = 0
        self.repacked_worlds = 0
        self.fenced_worlds = 0
        self.straggler_repacks = 0
        self.straggler_repacked_worlds = 0
        self.duplicate_completions = 0
        self.duplicate_rows_dropped = 0
        self.reducer = SweepReducer(top_k=top_k)
        self._cancelled = False
        #: node -> the task currently running on it
        self._running: Dict[str, _Task] = {}
        #: per-member ctrl breakers (lazy — a member may join late)
        self._breakers: Dict[str, CircuitBreaker] = {}
        #: per-member per-capability gray-failure strikes
        self._strikes: Dict[str, Dict[str, int]] = {}

    # -- ctrl discipline ---------------------------------------------------

    def _breaker(self, node: str) -> CircuitBreaker:
        br = self._breakers.get(node)
        if br is None:
            br = self._breakers[node] = CircuitBreaker(
                f"fleet.ctrl.{node}",
                self.clock,
                failure_threshold=self.ctrl_failure_threshold,
                backoff_initial_s=self.ctrl_backoff_initial_s,
                backoff_max_s=self.ctrl_backoff_max_s,
                seed=self.ctrl_seed,
                counters=self.counters,
            )
        return br

    def _member_call(self, node: str, what: str, fn):
        """One breaker-gated ctrl touch.  A raising member costs a
        breaker failure + a gray strike and returns the unavailable
        sentinel — the pump skips and retries under backoff; nothing a
        member does can take the coordinator fiber down."""
        br = self._breaker(node)
        if not br.allow_request():
            self.counters.bump("fleet.ctrl.short_circuits")
            return _CTRL_UNAVAILABLE
        try:
            out = fn()
        except Exception as exc:  # noqa: BLE001 — the trust boundary
            br.record_failure()
            self.counters.bump("fleet.ctrl.errors")
            self.error = f"{node}.{what}: {exc}"
            self._strike(node, "ctrl")
            return _CTRL_UNAVAILABLE
        br.record_success()
        return out

    def _strike(self, node: str, capability: str) -> None:
        """Gray-failure accounting: a member that answers (or at least
        heartbeats) but keeps failing its work accrues strikes; at the
        threshold it is demoted to drained — serves streams, owns no
        worlds — and the ``fleet_gray_failure`` ticket fires via
        membership.health_firing()."""
        per = self._strikes.setdefault(node, {})
        per[capability] = per.get(capability, 0) + 1
        self.counters.bump("fleet.gray.strikes")
        total = sum(per.values())
        if total >= self.gray_strike_threshold and self.membership.is_live(
            node
        ):
            self.membership.drain_node(node, reason="gray_failure")
            self.counters.bump("fleet.gray.demotions")

    # -- manifest ----------------------------------------------------------

    def _dir(self) -> str:
        return os.path.join(self.spill_root, self.fleet_id)

    def _manifest_path(self) -> str:
        return os.path.join(self._dir(), MANIFEST_NAME)

    def _routing_path(self) -> str:
        return os.path.join(self._dir(), ROUTING_NAME)

    def _atomic_write(self, path: str, text: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def manifest_doc(self) -> dict:
        """Pure content: identical bytes for identical progress,
        whatever the node count or kill history."""
        return {
            "fleet_set_hash": self.set_hash,
            "scenarios_total": self.scenarios_total,
            "worlds_total": self.worlds_total,
            "completed_worlds": sorted(self.completed_worlds),
        }

    def manifest_bytes(self) -> bytes:
        return canonical_json(self.manifest_doc()).encode()

    def _write_manifest(self) -> None:
        self._atomic_write(
            self._manifest_path(), canonical_json(self.manifest_doc())
        )

    def _write_routing(self) -> None:
        # operational sidecar (NOT content): which spill dir replays
        # which MERGED worlds on resume — merged_worlds, not the
        # assignment, because first-committed-wins may have dropped a
        # straggler's duplicate subset
        doc = {
            "fleet_set_hash": self.set_hash,
            "merged": [
                {
                    "node": t.node,
                    "round": t.round,
                    "spill_dir": t.spill_dir,
                    "worlds": list(t.merged_worlds or t.worlds),
                }
                for t in self.tasks
                if t.state == "merged"
            ],
        }
        self._atomic_write(
            self._routing_path(),
            json.dumps(doc, indent=1, sort_keys=True),
        )

    # -- preparation -------------------------------------------------------

    def prepare(self, params: Optional[dict] = None, resume: bool = True) -> dict:
        """Enumerate the full set, derive the assignment, and (when the
        fleet manifest matches) resume: merged worlds replay from their
        recorded spills, everything else re-packs over the CURRENT live
        set."""
        params = dict(params or {})
        params.pop("world_filter", None)  # the coordinator owns filters
        params.pop("fleet_epoch", None)  # ... and fencing stamps
        live = self.membership.live_nodes()
        if not live:
            raise SweepError("fleet sweep: no live nodes")
        lead = self.services[live[0]]
        spec = ScenarioSpec.from_params(lead.config, params)
        pairs = lead.enumeration_pairs()
        scenarios = enumerate_scenarios(spec, pairs)
        if not scenarios:
            raise SweepError("fleet sweep: grammar enumerates zero scenarios")
        self.params = params
        self.vantage = str(
            params.get("root")
            or lead.decision.capacity_sweep_inputs()["root"]
        )
        self.set_hash = scenario_set_hash(spec, scenarios)
        self.fleet_id = self.set_hash[:16]
        self.world_scenarios = {}
        for s in scenarios:
            wk = s.world.key()
            self.world_scenarios[wk] = self.world_scenarios.get(wk, 0) + 1
        self.worlds_total = len(self.world_scenarios)
        self.scenarios_total = len(scenarios)
        self.completed_worlds = set()
        self.tasks = []
        self.rounds = 0
        self.repacked_worlds = 0
        self.fenced_worlds = 0
        self.straggler_repacks = 0
        self.straggler_repacked_worlds = 0
        self.duplicate_completions = 0
        self.duplicate_rows_dropped = 0
        self.reducer = SweepReducer(top_k=self.top_k)
        self._cancelled = False
        self._running = {}
        self._strikes = {}
        os.makedirs(self._dir(), exist_ok=True)
        resumed_worlds = 0
        if resume:
            resumed_worlds = self._resume_from_manifest()
        pending = [
            wk
            for wk in sorted(self.world_scenarios)
            if wk not in self.completed_worlds
        ]
        if pending:
            self._assign_round(pending, live)
        self.state = "running" if pending else "done"
        for svc in self.services.values():
            svc.attach_fleet(self.status, epoch_fn=self._current_epoch)
        self._write_manifest()
        self.counters.bump("fleet.sweeps_prepared")
        return {
            "fleet_id": self.fleet_id,
            "set_hash": self.set_hash,
            "scenarios": self.scenarios_total,
            "worlds": self.worlds_total,
            "nodes": len(live),
            "resumed_worlds": resumed_worlds,
            "state": self.state,
        }

    def _current_epoch(self) -> int:
        return self.membership.epoch

    def _resume_from_manifest(self) -> int:
        try:
            with open(self._manifest_path(), encoding="utf-8") as f:
                man = json.load(f)
            with open(self._routing_path(), encoding="utf-8") as f:
                routing = json.load(f)
        except (OSError, ValueError):
            return 0
        if man.get("fleet_set_hash") != self.set_hash:
            return 0
        if routing.get("fleet_set_hash") != self.set_hash:
            return 0
        completed = set(man.get("completed_worlds", ()))
        replayed: set = set()
        max_round = -1
        for entry in routing.get("merged", ()):
            worlds = tuple(entry.get("worlds", ()))
            if not worlds or not set(worlds) <= completed:
                continue
            want = set(worlds)
            try:
                rows = [
                    r
                    for r in SpillReader(entry["spill_dir"]).rows()
                    # the sidecar's worlds are what was MERGED from
                    # this spill; a straggler's duplicate rows for
                    # worlds committed elsewhere must not replay
                    if r.get("world") in want
                ]
            except OSError:
                continue
            self.reducer.feed(rows)
            t = _Task(
                entry.get("node", "?"),
                int(entry.get("round", 0)),
                worlds,
                sum(self.world_scenarios.get(w, 0) for w in worlds),
                entry["spill_dir"],
            )
            t.state = "merged"
            t.merged_worlds = worlds
            self.tasks.append(t)
            replayed |= set(worlds)
            max_round = max(max_round, t.round)
        self.completed_worlds = replayed
        self.rounds = max_round + 1
        if replayed:
            self.counters.bump("fleet.resumed_worlds", len(replayed))
        return len(replayed)

    def _assign_round(
        self, worlds: List[str], live: Tuple[str, ...]
    ) -> None:
        rnd = self.rounds
        self.rounds += 1
        epoch = self.membership.epoch
        for node, wks in assign_worlds(
            self.set_hash, worlds, live
        ).items():
            self.tasks.append(
                _Task(
                    node,
                    rnd,
                    wks,
                    sum(self.world_scenarios[w] for w in wks),
                    os.path.join(self._dir(), f"{node}.r{rnd}"),
                    epoch=epoch,
                )
            )

    # -- the pump ----------------------------------------------------------

    def _pump(self) -> None:
        """One scheduling pass: repack lost work, merge finished work
        (first-committed-wins), repack stragglers, launch pending work
        on idle live nodes."""
        # 1. a running task on a node that left the live set is LOST:
        #    its spill is discarded (never merged) and every one of its
        #    worlds re-packs over the survivors as a fresh round
        lost_worlds: List[str] = []
        for t in self.tasks:
            if t.state == "running" and not self.membership.is_live(t.node):
                t.state = "lost"
                self._running.pop(t.node, None)
                lost_worlds.extend(t.worlds)
        # pending tasks stranded on dead nodes re-pack the same way
        for t in self.tasks:
            if t.state == "pending" and not self.membership.is_live(t.node):
                t.state = "lost"
                lost_worlds.extend(t.worlds)
        if lost_worlds:
            lost_fresh = sorted(
                set(lost_worlds) - self.completed_worlds
            )
            live = self.membership.live_nodes()
            if not live:
                self.state = "failed"
                self.error = "fleet sweep: no survivors to re-pack onto"
                return
            if lost_fresh:
                self.repacked_worlds += len(lost_fresh)
                self.counters.bump(
                    "fleet.repacked_worlds", len(lost_fresh)
                )
                self._assign_round(lost_fresh, live)
        # 2. merge every finished sub-sweep (order never matters: the
        #    reducer is feed-order-independent; duplicates are dropped
        #    world-granularly — first committed wins)
        for node, t in list(self._running.items()):
            if not self.membership.is_live(node):
                continue  # handled as lost next pass
            state = self._member_call(
                node, "state", lambda s=self.services[node]: s.state
            )
            if state is _CTRL_UNAVAILABLE:
                continue
            if state == "done":
                fresh = [
                    w for w in t.worlds if w not in self.completed_worlds
                ]
                if not fresh:
                    # a straggler whose every world was already
                    # committed by its re-pack: nothing to merge
                    t.state = "duplicate"
                    self._running.pop(node)
                    self.duplicate_completions += 1
                    self.counters.bump("fleet.duplicate_completions")
                    continue
                want = set(fresh)
                rows = self._member_call(
                    node,
                    "spill",
                    lambda d=t.spill_dir: list(SpillReader(d).rows()),
                )
                if rows is _CTRL_UNAVAILABLE:
                    continue
                kept = [r for r in rows if r.get("world") in want]
                dropped = len(rows) - len(kept)
                if dropped:
                    self.duplicate_rows_dropped += dropped
                    self.counters.bump(
                        "fleet.duplicate_rows_dropped", dropped
                    )
                self.reducer.feed(kept)
                t.state = "merged"
                t.merged_worlds = tuple(fresh)
                self.completed_worlds |= want
                self._running.pop(node)
                self._write_manifest()
                self._write_routing()
                self.counters.bump("fleet.merged_worlds", len(fresh))
            elif state in ("failed", "cancelled"):
                # gray signal: the member is alive (we just asked it)
                # but its sweep died — strike it, re-solve elsewhere
                t.state = "lost"
                self._running.pop(node)
                if state == "failed":
                    self._strike(node, "sweep")
                live = [
                    n
                    for n in self.membership.live_nodes()
                    if n != node
                ] or list(self.membership.live_nodes())
                redo = sorted(set(t.worlds) - self.completed_worlds)
                if redo:
                    self.repacked_worlds += len(redo)
                    self._assign_round(redo, tuple(live))
        # 2b. stragglers: a live member holding a round past the
        #     deadline has its unfinished worlds re-packed onto the
        #     OTHER survivors — without waiting for it to die; the
        #     merge step's first-committed-wins reconciles whichever
        #     copy lands first
        if self.straggler_deadline_s > 0:
            now = self.clock.now()
            for node, t in list(self._running.items()):
                if t.straggled:
                    continue
                if now - t.launched_at <= self.straggler_deadline_s:
                    continue
                others = tuple(
                    n
                    for n in self.membership.live_nodes()
                    if n != node
                )
                unfinished = sorted(
                    set(t.worlds) - self.completed_worlds
                )
                if not others or not unfinished:
                    continue
                t.straggled = True
                self.straggler_repacks += 1
                self.straggler_repacked_worlds += len(unfinished)
                self.counters.bump(
                    "fleet.straggler_repacked_worlds", len(unfinished)
                )
                self._strike(node, "straggler")
                self._assign_round(unfinished, others)
        # 3. launch pending tasks on idle live nodes, earliest round
        #    first (a node's repack work queues behind its current
        #    task).  Launches are epoch-stamped; the RECEIVER fences
        #    stale ones (counted, returned, never raised) and the
        #    coordinator re-derives those worlds under the current
        #    epoch.
        fenced: List[str] = []
        for t in list(self.tasks):
            if t.state != "pending":
                continue
            if not self.membership.is_live(t.node):
                continue
            if t.node in self._running:
                continue
            svc = self.services[t.node]
            state = self._member_call(
                t.node, "state", lambda s=svc: s.state
            )
            if state is _CTRL_UNAVAILABLE or state == "running":
                continue
            res = self._member_call(
                t.node,
                "start_sweep",
                lambda s=svc, task=t: s.start_sweep(
                    {
                        **self.params,
                        "world_filter": list(task.worlds),
                        "spill_dir": task.spill_dir,
                        "root": self.vantage,
                        "resume": True,
                        "fleet_epoch": task.epoch,
                    }
                ),
            )
            if res is _CTRL_UNAVAILABLE:
                continue
            if isinstance(res, dict) and res.get("fenced"):
                t.state = "fenced"
                self.fenced_worlds += len(t.worlds)
                self.counters.bump("fleet.fenced.sweep")
                fenced.extend(t.worlds)
                continue
            t.state = "running"
            t.launched_at = self.clock.now()
            self._running[t.node] = t
            self.counters.bump("fleet.subsweeps_started")
        if fenced:
            redo = sorted(set(fenced) - self.completed_worlds)
            live = self.membership.live_nodes()
            if redo and live:
                self._assign_round(redo, live)

    def _cancel_leftovers(self) -> None:
        """The set completed while stragglers still run their (now
        fully duplicate) copies: cancel them — their committed shards
        stay durable, their rows are never fed."""
        for node, t in list(self._running.items()):
            self._member_call(
                node,
                "cancel_sweep",
                lambda s=self.services[node]: s.cancel_sweep(),
            )
            t.state = "duplicate"
            self._running.pop(node, None)
            self.duplicate_completions += 1
            self.counters.bump("fleet.duplicate_completions")

    async def run(self) -> None:
        """Pump until the whole set is merged (or cancel/failure)."""
        while self.state == "running" and not self._cancelled:
            self._pump()
            if len(self.completed_worlds) == self.worlds_total:
                self.state = "done"
                self._cancel_leftovers()
                self._write_manifest()
                break
            if self.state == "failed":
                break
            self.touch()
            await self.clock.sleep(self.poll_interval_s)
        if self._cancelled and self.state == "running":
            self.state = "cancelled"
        self.counters.bump(f"fleet.sweeps_{self.state}")

    def cancel(self) -> dict:
        self._cancelled = True
        for node, _t in self._running.items():
            self._member_call(
                node,
                "cancel_sweep",
                lambda s=self.services[node]: s.cancel_sweep(),
            )
        return {"state": self.state}

    # -- observability -----------------------------------------------------

    def status(self) -> dict:
        live = self.membership.live_nodes()
        return {
            "fleet_id": self.fleet_id,
            "set_hash": self.set_hash,
            "state": self.state,
            "epoch": self.membership.epoch,
            "nodes_live": len(live),
            "nodes_total": len(self.membership.names),
            "worlds_total": self.worlds_total,
            "worlds_merged": len(self.completed_worlds),
            "scenarios_total": self.scenarios_total,
            "scenarios_merged": self.reducer.scenarios,
            "repacked_worlds": self.repacked_worlds,
            "fenced_worlds": self.fenced_worlds,
            "straggler_repacks": self.straggler_repacks,
            "straggler_repacked_worlds": self.straggler_repacked_worlds,
            "duplicate_completions": self.duplicate_completions,
            "duplicate_rows_dropped": self.duplicate_rows_dropped,
            "rounds": self.rounds,
            "strikes": {
                n: dict(sorted(per.items()))
                for n, per in sorted(self._strikes.items())
            },
            "breakers": {
                n: br.state for n, br in sorted(self._breakers.items())
            },
            "assignments": [
                {
                    "node": t.node,
                    "round": t.round,
                    "worlds": len(t.worlds),
                    "scenarios": t.scenarios,
                    "state": t.state,
                    "epoch": t.epoch,
                }
                for t in self.tasks
            ],
        }

    def summary(self) -> dict:
        complete = self.state == "done"
        return {
            "fleet_id": self.fleet_id,
            "set_hash": self.set_hash,
            "state": self.state,
            "complete": complete,
            "summary": self.reducer.summary() if complete else None,
            "summary_digest": (
                self.reducer.summary_digest() if complete else ""
            ),
        }

    def gauges(self) -> Dict[str, float]:
        return {
            "fleet.running": 1.0 if self.state == "running" else 0.0,
            "fleet.worlds_total": float(self.worlds_total),
            "fleet.worlds_merged": float(len(self.completed_worlds)),
            "fleet.repacked_worlds": float(self.repacked_worlds),
            "fleet.fenced_worlds": float(self.fenced_worlds),
            "fleet.straggler_repacks": float(self.straggler_repacks),
            "fleet.duplicate_completions": float(
                self.duplicate_completions
            ),
            "fleet.rounds": float(self.rounds),
        }
