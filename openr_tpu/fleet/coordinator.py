"""FleetSweepCoordinator — one capacity sweep across N nodes' pools.

The single-node SweepService already packs scenarios by (world, hash)
into committed, resumable shards; the coordinator lifts that one tier:
it enumerates the FULL scenario set once, content-derives the
world→node assignment (assignment.py: pure function of
(scenario_set_hash, live set)), drives each node's SweepService with a
``world_filter`` sub-sweep solved from ONE shared vantage, and merges
every node's spill stream through the feed-order-independent
SweepReducer — so the merged summary digest is byte-equal to a
single-node run of the same set, whatever the node count or feed
interleaving.

Failure domains compose: a dead CHIP re-packs its shard inside one
node's executor (PR-8); a dead NODE is the domain above it — the
coordinator discards the dead node's *unmerged* spill entirely (a
partial spill would force row-level dedup; world-granular re-solve is
deterministic and duplicate-free), re-packs ALL its incomplete worlds
onto the survivors as the next assignment round, and keeps merged work
untouched.  The fleet manifest is pure content — (set hash, completed
worlds, totals) in canonical JSON — so at completion its bytes are
identical to an uninterrupted run's, whatever the kill history; the
operational world→spill routing that replay needs lives in a separate
sidecar, explicitly NOT part of the byte-identity contract.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from openr_tpu.common.runtime import Actor, Clock, CounterMap
from openr_tpu.fleet.assignment import assign_worlds
from openr_tpu.fleet.membership import FleetMembership
from openr_tpu.sweep import (
    ScenarioSpec,
    SpillReader,
    SweepError,
    SweepReducer,
    enumerate_scenarios,
    scenario_set_hash,
)
from openr_tpu.sweep.scenario import canonical_json

MANIFEST_NAME = "fleet_manifest.json"
ROUTING_NAME = "fleet_routing.json"


class _Task:
    """One (node, round, world set) sub-sweep assignment."""

    __slots__ = (
        "node", "round", "worlds", "scenarios", "state", "spill_dir",
    )

    def __init__(self, node, rnd, worlds, scenarios, spill_dir) -> None:
        self.node = node
        self.round = rnd
        self.worlds: Tuple[str, ...] = worlds
        self.scenarios = scenarios
        #: pending|running|merged|lost
        self.state = "pending"
        self.spill_dir = spill_dir


class FleetSweepCoordinator(Actor):
    """Drives one fleet sweep over the member nodes' SweepServices.

    ``services`` maps fleet node name -> that node's SweepService.
    ``prepare`` enumerates + assigns (resuming from the fleet manifest
    when it matches); ``run`` pumps until every world is merged or the
    sweep is cancelled.  Everything the coordinator touches on a
    SweepService is its public ctrl surface — start_sweep /
    get_sweep_status / state — so a real deployment swaps the direct
    references for ctrl RPC without changing this logic.
    """

    def __init__(
        self,
        clock: Clock,
        membership: FleetMembership,
        services: Dict[str, object],
        spill_root: str,
        counters: Optional[CounterMap] = None,
        top_k: int = 64,
        poll_interval_s: float = 0.02,
    ) -> None:
        super().__init__("fleet", clock, counters)
        self.membership = membership
        self.services = dict(services)
        self.spill_root = spill_root
        self.top_k = top_k
        self.poll_interval_s = poll_interval_s
        self.state = "idle"  # idle|running|done|cancelled|failed
        self.error = ""
        self.fleet_id = ""
        self.set_hash = ""
        self.params: dict = {}
        self.vantage = ""
        self.worlds_total = 0
        self.scenarios_total = 0
        self.world_scenarios: Dict[str, int] = {}
        self.completed_worlds: set = set()
        self.tasks: List[_Task] = []
        self.rounds = 0
        self.repacked_worlds = 0
        self.reducer = SweepReducer(top_k=top_k)
        self._cancelled = False
        #: node -> the task currently running on it
        self._running: Dict[str, _Task] = {}

    # -- manifest ----------------------------------------------------------

    def _dir(self) -> str:
        return os.path.join(self.spill_root, self.fleet_id)

    def _manifest_path(self) -> str:
        return os.path.join(self._dir(), MANIFEST_NAME)

    def _routing_path(self) -> str:
        return os.path.join(self._dir(), ROUTING_NAME)

    def _atomic_write(self, path: str, text: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def manifest_doc(self) -> dict:
        """Pure content: identical bytes for identical progress,
        whatever the node count or kill history."""
        return {
            "fleet_set_hash": self.set_hash,
            "scenarios_total": self.scenarios_total,
            "worlds_total": self.worlds_total,
            "completed_worlds": sorted(self.completed_worlds),
        }

    def manifest_bytes(self) -> bytes:
        return canonical_json(self.manifest_doc()).encode()

    def _write_manifest(self) -> None:
        self._atomic_write(
            self._manifest_path(), canonical_json(self.manifest_doc())
        )

    def _write_routing(self) -> None:
        # operational sidecar (NOT content): which spill dir replays
        # which merged worlds on resume
        doc = {
            "fleet_set_hash": self.set_hash,
            "merged": [
                {
                    "node": t.node,
                    "round": t.round,
                    "spill_dir": t.spill_dir,
                    "worlds": list(t.worlds),
                }
                for t in self.tasks
                if t.state == "merged"
            ],
        }
        self._atomic_write(
            self._routing_path(),
            json.dumps(doc, indent=1, sort_keys=True),
        )

    # -- preparation -------------------------------------------------------

    def prepare(self, params: Optional[dict] = None, resume: bool = True) -> dict:
        """Enumerate the full set, derive the assignment, and (when the
        fleet manifest matches) resume: merged worlds replay from their
        recorded spills, everything else re-packs over the CURRENT live
        set."""
        params = dict(params or {})
        params.pop("world_filter", None)  # the coordinator owns filters
        live = self.membership.live_nodes()
        if not live:
            raise SweepError("fleet sweep: no live nodes")
        lead = self.services[live[0]]
        spec = ScenarioSpec.from_params(lead.config, params)
        pairs = lead.enumeration_pairs()
        scenarios = enumerate_scenarios(spec, pairs)
        if not scenarios:
            raise SweepError("fleet sweep: grammar enumerates zero scenarios")
        self.params = params
        self.vantage = str(
            params.get("root")
            or lead.decision.capacity_sweep_inputs()["root"]
        )
        self.set_hash = scenario_set_hash(spec, scenarios)
        self.fleet_id = self.set_hash[:16]
        self.world_scenarios = {}
        for s in scenarios:
            wk = s.world.key()
            self.world_scenarios[wk] = self.world_scenarios.get(wk, 0) + 1
        self.worlds_total = len(self.world_scenarios)
        self.scenarios_total = len(scenarios)
        self.completed_worlds = set()
        self.tasks = []
        self.rounds = 0
        self.repacked_worlds = 0
        self.reducer = SweepReducer(top_k=self.top_k)
        self._cancelled = False
        self._running = {}
        os.makedirs(self._dir(), exist_ok=True)
        resumed_worlds = 0
        if resume:
            resumed_worlds = self._resume_from_manifest()
        pending = [
            wk
            for wk in sorted(self.world_scenarios)
            if wk not in self.completed_worlds
        ]
        if pending:
            self._assign_round(pending, live)
        self.state = "running" if pending else "done"
        for svc in self.services.values():
            svc.attach_fleet(self.status)
        self._write_manifest()
        self.counters.bump("fleet.sweeps_prepared")
        return {
            "fleet_id": self.fleet_id,
            "set_hash": self.set_hash,
            "scenarios": self.scenarios_total,
            "worlds": self.worlds_total,
            "nodes": len(live),
            "resumed_worlds": resumed_worlds,
            "state": self.state,
        }

    def _resume_from_manifest(self) -> int:
        try:
            with open(self._manifest_path(), encoding="utf-8") as f:
                man = json.load(f)
            with open(self._routing_path(), encoding="utf-8") as f:
                routing = json.load(f)
        except (OSError, ValueError):
            return 0
        if man.get("fleet_set_hash") != self.set_hash:
            return 0
        if routing.get("fleet_set_hash") != self.set_hash:
            return 0
        completed = set(man.get("completed_worlds", ()))
        replayed: set = set()
        max_round = -1
        for entry in routing.get("merged", ()):
            worlds = tuple(entry.get("worlds", ()))
            if not worlds or not set(worlds) <= completed:
                continue
            try:
                rows = list(SpillReader(entry["spill_dir"]).rows())
            except OSError:
                continue
            self.reducer.feed(rows)
            t = _Task(
                entry.get("node", "?"),
                int(entry.get("round", 0)),
                worlds,
                sum(self.world_scenarios.get(w, 0) for w in worlds),
                entry["spill_dir"],
            )
            t.state = "merged"
            self.tasks.append(t)
            replayed |= set(worlds)
            max_round = max(max_round, t.round)
        self.completed_worlds = replayed
        self.rounds = max_round + 1
        if replayed:
            self.counters.bump("fleet.resumed_worlds", len(replayed))
        return len(replayed)

    def _assign_round(
        self, worlds: List[str], live: Tuple[str, ...]
    ) -> None:
        rnd = self.rounds
        self.rounds += 1
        for node, wks in assign_worlds(
            self.set_hash, worlds, live
        ).items():
            self.tasks.append(
                _Task(
                    node,
                    rnd,
                    wks,
                    sum(self.world_scenarios[w] for w in wks),
                    os.path.join(self._dir(), f"{node}.r{rnd}"),
                )
            )

    # -- the pump ----------------------------------------------------------

    def _pump(self) -> None:
        """One scheduling pass: repack lost work, merge finished work,
        launch pending work on idle live nodes."""
        # 1. a running task on a node that left the live set is LOST:
        #    its spill is discarded (never merged) and every one of its
        #    worlds re-packs over the survivors as a fresh round
        lost_worlds: List[str] = []
        for t in self.tasks:
            if t.state == "running" and not self.membership.is_live(t.node):
                t.state = "lost"
                self._running.pop(t.node, None)
                lost_worlds.extend(t.worlds)
        # pending tasks stranded on dead nodes re-pack the same way
        for t in self.tasks:
            if t.state == "pending" and not self.membership.is_live(t.node):
                t.state = "lost"
                lost_worlds.extend(t.worlds)
        if lost_worlds:
            live = self.membership.live_nodes()
            if not live:
                self.state = "failed"
                self.error = "fleet sweep: no survivors to re-pack onto"
                return
            self.repacked_worlds += len(set(lost_worlds))
            self.counters.bump(
                "fleet.repacked_worlds", len(set(lost_worlds))
            )
            self._assign_round(sorted(set(lost_worlds)), live)
        # 2. merge every finished sub-sweep (order never matters: the
        #    reducer is feed-order-independent)
        for node, t in list(self._running.items()):
            svc = self.services[node]
            if not self.membership.is_live(node):
                continue  # handled as lost next pass
            if svc.state == "done":
                rows = list(SpillReader(t.spill_dir).rows())
                self.reducer.feed(rows)
                t.state = "merged"
                self.completed_worlds |= set(t.worlds)
                self._running.pop(node)
                self._write_manifest()
                self._write_routing()
                self.counters.bump("fleet.merged_worlds", len(t.worlds))
            elif svc.state in ("failed", "cancelled"):
                # treat like a lost node: re-solve its worlds elsewhere
                t.state = "lost"
                self._running.pop(node)
                live = [
                    n
                    for n in self.membership.live_nodes()
                    if n != node
                ] or list(self.membership.live_nodes())
                self.repacked_worlds += len(t.worlds)
                self._assign_round(sorted(t.worlds), tuple(live))
        # 3. launch pending tasks on idle live nodes, earliest round
        #    first (a node's repack work queues behind its current task)
        for t in self.tasks:
            if t.state != "pending":
                continue
            if not self.membership.is_live(t.node):
                continue
            if t.node in self._running:
                continue
            svc = self.services[t.node]
            if svc.state == "running":
                continue
            svc.start_sweep(
                {
                    **self.params,
                    "world_filter": list(t.worlds),
                    "spill_dir": t.spill_dir,
                    "root": self.vantage,
                    "resume": True,
                }
            )
            t.state = "running"
            self._running[t.node] = t
            self.counters.bump("fleet.subsweeps_started")

    async def run(self) -> None:
        """Pump until the whole set is merged (or cancel/failure)."""
        while self.state == "running" and not self._cancelled:
            self._pump()
            if len(self.completed_worlds) == self.worlds_total:
                self.state = "done"
                self._write_manifest()
                break
            if self.state == "failed":
                break
            self.touch()
            await self.clock.sleep(self.poll_interval_s)
        if self._cancelled and self.state == "running":
            self.state = "cancelled"
        self.counters.bump(f"fleet.sweeps_{self.state}")

    def cancel(self) -> dict:
        self._cancelled = True
        for node, _t in self._running.items():
            self.services[node].cancel_sweep()
        return {"state": self.state}

    # -- observability -----------------------------------------------------

    def status(self) -> dict:
        live = self.membership.live_nodes()
        return {
            "fleet_id": self.fleet_id,
            "set_hash": self.set_hash,
            "state": self.state,
            "nodes_live": len(live),
            "nodes_total": len(self.membership.names),
            "worlds_total": self.worlds_total,
            "worlds_merged": len(self.completed_worlds),
            "scenarios_total": self.scenarios_total,
            "scenarios_merged": self.reducer.scenarios,
            "repacked_worlds": self.repacked_worlds,
            "rounds": self.rounds,
            "assignments": [
                {
                    "node": t.node,
                    "round": t.round,
                    "worlds": len(t.worlds),
                    "scenarios": t.scenarios,
                    "state": t.state,
                }
                for t in self.tasks
            ],
        }

    def summary(self) -> dict:
        complete = self.state == "done"
        return {
            "fleet_id": self.fleet_id,
            "set_hash": self.set_hash,
            "state": self.state,
            "complete": complete,
            "summary": self.reducer.summary() if complete else None,
            "summary_digest": (
                self.reducer.summary_digest() if complete else ""
            ),
        }

    def gauges(self) -> Dict[str, float]:
        return {
            "fleet.running": 1.0 if self.state == "running" else 0.0,
            "fleet.worlds_total": float(self.worlds_total),
            "fleet.worlds_merged": float(len(self.completed_worlds)),
            "fleet.repacked_worlds": float(self.repacked_worlds),
            "fleet.rounds": float(self.rounds),
        }
