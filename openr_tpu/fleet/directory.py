"""FeedDirectory + FleetStreamRouter — the consistent-hash watch plane.

A watcher of vantage X should be served by ANY node holding the fleet
tables, not the one node it happened to dial.  ``FeedDirectory`` maps
each canonical feed key to its owner by rendezvous hash over the LIVE
serving nodes (assignment.py's one law: pure function of (key, live
set)).  ``FleetStreamRouter`` holds the fleet's watchers, subscribes
each to its owner's StreamingService (PR-13 push transport), and on
every membership EPOCH BUMP re-derives ownership: a watcher whose
serving node died or drained migrates to the hash successor, who pushes
a fresh generation-stamped snapshot and then deltas — resync riding the
existing snapshot+delta machinery.

**Epoch fencing (ISSUE 20).**  Every subscription's deliver path is
stamped with the membership epoch it was derived under; once the router
re-derives at a newer epoch, anything the OLD subscription still pushes
is rejected at the watcher's door (``fleet.fenced.stream``, counted
never raised).  This closes the split-brain window structurally: a
partitioned-but-alive old owner — one the fleet declared down, whose
daemon never heard the unsubscribe — can push forever and never lands a
double delivery.  (PR 19 closed one instance of this bug class with an
``is_up``-vs-``is_live`` predicate at detach time; the fence makes the
whole class unreachable.)  Subscriptions on unreachable daemons are
remembered and garbage-collected with a real unsubscribe when the node
is next reachable.

The migration invariant (checked per watcher, per emission): the
monotone-generation contract HOLDS ACROSS the migration — a delta's seq
is strictly above the cursor, a snapshot's at or above it, and no
generation older than the migration floor (the cursor at hand-off) is
ever re-emitted.  The chaos tier proves zero violations under node
kills; the fleet bench ratchets it.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from openr_tpu.common.runtime import CounterMap
from openr_tpu.fleet.assignment import owner_of, rank_members
from openr_tpu.fleet.membership import FleetMembership
from openr_tpu.serving import apply_emission, canonical_query

#: salt namespacing feed-key hashes away from sweep-world hashes
DIRECTORY_SALT = "fleet.feeds"


def feed_key(kind: str, params: dict) -> str:
    """The directory's content address for one feed: the serving
    plane's canonical query (order-normalized), stringified so it can
    salt a rendezvous hash."""
    return str(canonical_query(kind, dict(params or {})))


class FeedDirectory:
    """Who serves which feed, derived — never stored.

    Ownership is recomputed from the live set on every lookup, so the
    directory cannot drift from membership: a dead node stops owning
    its feeds the instant membership marks it down.
    """

    def __init__(self, membership: FleetMembership) -> None:
        self.membership = membership

    def owner(self, kind: str, params: dict) -> Optional[str]:
        """The live node serving this feed (None when nothing is
        live)."""
        live = self.membership.live_nodes()
        if not live:
            return None
        return owner_of(DIRECTORY_SALT, feed_key(kind, params), live)

    def owners(self, kind: str, params: dict, k: int = 2) -> Tuple[str, ...]:
        """The first ``k`` ranked live nodes — index 0 serves, index 1
        is the migration successor the runbook points operators at."""
        live = self.membership.live_nodes()
        return tuple(
            rank_members(DIRECTORY_SALT, feed_key(kind, params), live)[:k]
        )


class FleetWatcher:
    """One fleet-level subscriber: a push transport recording every
    emission, the applied client state, and the migration-invariant
    bookkeeping.  Violations are COUNTED, never raised — raising inside
    a deliver callback would poison the publisher's fan-out fiber."""

    def __init__(self, watcher_id: int, kind: str, params: dict,
                 client_id: str) -> None:
        self.watcher_id = watcher_id
        self.kind = kind
        self.params = dict(params or {})
        self.client_id = client_id
        self.emissions: List[dict] = []
        self.state: Dict[tuple, object] = {}
        #: last generation seq applied; -1 = nothing yet
        self.cursor_seq = -1
        #: cursor at the most recent hand-off — nothing older than this
        #: may ever be emitted again
        self.migration_floor = -1
        self.migrations = 0
        self.invariant_violations = 0
        self.pre_migration_re_emissions = 0
        self.serving_node: Optional[str] = None
        self.sub_id: Optional[int] = None
        #: the epoch the CURRENT placement was derived under — the
        #: fencing token each subscription's deliver closure compares
        self.fence_epoch = -1
        #: stale-epoch deliveries rejected at this watcher's door
        self.fenced = 0
        #: subscriptions left behind on daemons that were unreachable
        #: at hand-off: (node, sub_id) — GC'd when the node is next up
        self.stale_subs: List[Tuple[str, int]] = []

    def deliver(self, emission: dict) -> None:
        seq = int(emission["seq"])
        snapshot = emission.get("type") == "snapshot"
        ok = (
            seq >= self.cursor_seq if snapshot else seq > self.cursor_seq
        )
        if not ok:
            self.invariant_violations += 1
        if seq < self.migration_floor:
            self.pre_migration_re_emissions += 1
        self.emissions.append(emission)
        self.state = apply_emission(self.state, emission)
        self.cursor_seq = max(self.cursor_seq, seq)

    def note_migration(self) -> None:
        self.migration_floor = max(self.migration_floor, self.cursor_seq)
        self.migrations += 1

    def seqs(self) -> List[int]:
        return [int(e["seq"]) for e in self.emissions]

    def log_bytes(self) -> bytes:
        import json

        return b"\n".join(
            json.dumps(e, sort_keys=True, default=str).encode()
            for e in self.emissions
        )


class FleetStreamRouter:
    """Places fleet watchers on their directory owners and migrates
    them when membership moves.

    ``streaming_services`` maps fleet node name -> that node's
    StreamingService (all holding the same fleet tables — the shared
    decision is what makes generation seqs comparable across nodes, so
    the monotone invariant is meaningful across a migration).
    """

    def __init__(
        self,
        directory: FeedDirectory,
        streaming_services: Dict[str, object],
        counters: Optional[CounterMap] = None,
    ) -> None:
        self.directory = directory
        self.services = dict(streaming_services)
        self.counters = counters if counters is not None else CounterMap()
        self.watchers: List[FleetWatcher] = []
        self._next_id = 0
        self.num_migrations = 0
        self.num_orphaned = 0
        #: the last epoch a re-derivation pass ran under — membership
        #: may fire several listener events per migration (suspicion
        #: edges, multi-transition verbs); placement is re-derived once
        #: per EPOCH, not once per firing
        self._resync_epoch = self.directory.membership.epoch
        #: owner_of evaluations performed by membership-driven resyncs
        #: (the coalescing regression gauge: one per watcher per epoch)
        self.owner_derivations = 0
        self.directory.membership.add_listener(self._on_membership)

    # -- watch surface -----------------------------------------------------

    def watch(
        self,
        kind: str,
        params: Optional[dict] = None,
        client_id: str = "",
        prefix_filters: Tuple[str, ...] = (),
    ) -> FleetWatcher:
        """Create a fleet watcher and attach it to its directory owner
        (snapshot pushes synchronously on subscribe)."""
        w = FleetWatcher(
            self._next_id, kind, dict(params or {}),
            client_id or f"fleet-w{self._next_id}",
        )
        w.prefix_filters = tuple(prefix_filters)
        self._next_id += 1
        self.watchers.append(w)
        self.counters.bump("fleet.directory.watches")
        self._attach(w)
        return w

    def unwatch(self, w: FleetWatcher) -> None:
        self._detach(w, unsubscribe=True)
        if w in self.watchers:
            self.watchers.remove(w)

    # -- placement ---------------------------------------------------------

    def _fenced_deliver(self, w: FleetWatcher, epoch: int):
        """Wrap the watcher's deliver with the epoch stamp the
        subscription was derived under.  Once the watcher moves to a
        newer epoch, anything this closure still receives — a
        partitioned old owner that never heard the unsubscribe — is
        rejected and counted, never raised and never applied."""

        def deliver(emission: dict) -> None:
            if w.fence_epoch != epoch:
                w.fenced += 1
                self.counters.bump("fleet.fenced.stream")
                return
            w.deliver(emission)

        return deliver

    def _attach(self, w: FleetWatcher) -> None:
        owner = self.directory.owner(w.kind, w.params)
        if owner is None:
            w.serving_node = None
            w.sub_id = None
            w.fence_epoch = self.directory.membership.epoch
            self.num_orphaned += 1
            self.counters.bump("fleet.directory.orphaned")
            return
        svc = self.services[owner]
        epoch = self.directory.membership.epoch
        w.fence_epoch = epoch
        w.sub_id = svc.subscribe(
            w.kind,
            dict(w.params),
            client_id=w.client_id,
            prefix_filters=getattr(w, "prefix_filters", ()),
            deliver=self._fenced_deliver(w, epoch),
        )
        w.serving_node = owner

    def _detach(self, w: FleetWatcher, unsubscribe: bool) -> None:
        if w.serving_node is not None and w.sub_id is not None:
            if unsubscribe:
                self.services[w.serving_node].unsubscribe(w.sub_id)
            else:
                # the daemon was unreachable at hand-off: its
                # subscription may well still exist (partition, not
                # crash) — remember it for GC; the fence keeps its
                # pushes out in the meantime
                w.stale_subs.append((w.serving_node, w.sub_id))
        w.serving_node = None
        w.sub_id = None

    def _gc_stale_subs(self, w: FleetWatcher) -> None:
        """Unsubscribe leftovers on daemons that are reachable again
        (a partition healed, a drained node re-admitted)."""
        keep: List[Tuple[str, int]] = []
        for node, sub_id in w.stale_subs:
            if self.directory.membership.is_up(node):
                self.services[node].unsubscribe(sub_id)
                self.counters.bump("fleet.directory.stale_unsubscribed")
            else:
                keep.append((node, sub_id))
        w.stale_subs = keep

    def _on_membership(self, event: dict) -> None:
        """Re-derive placement — once per epoch bump.  Suspicion edges
        and duplicate listener firings arrive at an unchanged epoch and
        are coalesced away (the live set they would re-derive against
        is identical); one migration therefore produces exactly one
        resync per affected watcher, however many events it threw."""
        epoch = int(event.get("epoch", self.directory.membership.epoch))
        if epoch == self._resync_epoch:
            return
        self._resync_epoch = epoch
        for w in list(self.watchers):
            self._gc_stale_subs(w)
            owner = self.directory.owner(w.kind, w.params)
            self.owner_derivations += 1
            if owner == w.serving_node:
                # placement unchanged: the fence stamp stays with the
                # surviving subscription (its closure compares against
                # w.fence_epoch, both still the derivation epoch), so
                # it keeps delivering without a resync
                continue
            old = w.serving_node
            # up, not live: a DRAINED node's daemon still answers, so
            # its subscription must be detached (or it keeps pushing
            # alongside the successor); a crashed or partitioned one
            # can't hear us — the fence holds it off until GC
            clean = old is not None and self.directory.membership.is_up(
                old
            )
            self._detach(w, unsubscribe=clean)
            if owner is None:
                self.num_orphaned += 1
                self.counters.bump("fleet.directory.orphaned")
                continue
            if old is not None:
                # a real hand-off: pin the floor BEFORE the successor's
                # snapshot pushes, so the re-emission audit sees it
                w.note_migration()
                self.num_migrations += 1
                self.counters.bump("fleet.directory.migrations")
            self._attach(w)

    # -- observability -----------------------------------------------------

    def invariant_violations(self) -> int:
        return sum(w.invariant_violations for w in self.watchers)

    def pre_migration_re_emissions(self) -> int:
        return sum(w.pre_migration_re_emissions for w in self.watchers)

    def fenced_deliveries(self) -> int:
        return sum(w.fenced for w in self.watchers)

    def status(self) -> dict:
        placement: Dict[str, int] = {}
        for w in self.watchers:
            placement[w.serving_node or "-"] = (
                placement.get(w.serving_node or "-", 0) + 1
            )
        return {
            "watchers": len(self.watchers),
            "placement": dict(sorted(placement.items())),
            "migrations": self.num_migrations,
            "orphaned": self.num_orphaned,
            "epoch": self._resync_epoch,
            "fenced_deliveries": self.fenced_deliveries(),
            "stale_subscriptions": sum(
                len(w.stale_subs) for w in self.watchers
            ),
            "invariant_violations": self.invariant_violations(),
            "pre_migration_re_emissions": (
                self.pre_migration_re_emissions()
            ),
        }
