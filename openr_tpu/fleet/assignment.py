"""Content-derived world assignment — rendezvous hashing, pure functions.

The fleet's one assignment law: who owns what is a PURE FUNCTION of
``(content key, live-node set)``.  No arrival order, no coordinator
state, no rebalance history — two coordinators (or one coordinator
before and after a crash) looking at the same scenario-set hash and the
same live set compute byte-identical assignments.  Rendezvous (highest
random weight) hashing gives that plus minimal reshuffle: when a node
dies, ONLY the keys it owned move (each to its second-ranked member);
everything else stays put, which is what keeps a mid-sweep node kill
from perturbing the surviving nodes' work.

Both fleet halves consume the same primitives: the sweep coordinator
assigns ``World.key()`` strings salted by the scenario-set hash, the
feed directory assigns canonical feed keys salted by the directory
namespace.  See docs/Fleet.md §"The assignment function".
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Sequence, Tuple


def rendezvous_score(salt: str, key: str, member: str) -> int:
    """The HRW weight of ``member`` for ``key`` under ``salt`` — the
    integer value of the first 16 bytes of
    ``sha256(f"{salt}|{key}|{member}")``.  128 bits: collisions are
    not a practical concern, but ties still break by member name so
    the function stays total."""
    h = hashlib.sha256(
        f"{salt}|{key}|{member}".encode("utf-8")
    ).digest()
    return int.from_bytes(h[:16], "big")


def rank_members(
    salt: str, key: str, members: Sequence[str]
) -> List[str]:
    """Members ordered by descending rendezvous score (name-ascending
    on the astronomically unlikely tie).  Index 0 is the owner; index
    1 is where the key migrates when the owner dies."""
    return sorted(
        members,
        key=lambda m: (-rendezvous_score(salt, key, m), m),
    )


def owner_of(salt: str, key: str, members: Sequence[str]) -> str:
    """The highest-ranked member for ``key`` (raises on an empty
    member set — callers decide what "nobody is live" means)."""
    ranked = rank_members(salt, key, members)
    if not ranked:
        raise ValueError(f"owner_of({key!r}): no live members")
    return ranked[0]


def assign_worlds(
    set_hash: str,
    world_keys: Sequence[str],
    live_nodes: Sequence[str],
) -> Dict[str, Tuple[str, ...]]:
    """Pack sweep worlds onto live nodes: ``{node: (world_key, ...)}``,
    worlds in canonical (sorted) order per node, nodes with no worlds
    omitted.  Salted by the scenario-set hash so two different sweeps
    over the same topology shuffle independently."""
    if not live_nodes:
        raise ValueError("assign_worlds: no live nodes")
    out: Dict[str, List[str]] = {}
    for wk in sorted(set(world_keys)):
        node = owner_of(set_hash, wk, live_nodes)
        out.setdefault(node, []).append(wk)
    return {n: tuple(ws) for n, ws in sorted(out.items())}
