"""Fleet compute fabric (ISSUE 19/20) — the tier above one node.

Every earlier plane stops at a single daemon: a capacity sweep runs on
ONE node's DevicePool, a watcher must dial the node that holds its
feed.  This package is the cross-node tier both were designed for, two
halves over one membership/directory core:

* :mod:`openr_tpu.fleet.assignment` — rendezvous hashing: ownership is
  a pure function of (content key, live-node set), so reassignment on
  membership change is content-derived and minimal, never
  arrival-ordered;
* :mod:`openr_tpu.fleet.membership` — ``FleetMembership``, the single
  writer of node liveness/drain state (NodeSet underneath — the
  node-level DevicePool), feeding listeners and the health plane
  (``fleet_node_loss`` pages, ``fleet_drain_migration`` /
  ``fleet_gray_failure`` tickets), and minting the monotone **epoch**
  every ownership derivation is fenced against;
* :mod:`openr_tpu.fleet.liveness` — ``MemberBeacon`` +
  ``LivenessTracker`` (ISSUE 20): heartbeat-derived membership over the
  TTL-bearing ``fleet:member:<name>`` key family — suspicion state
  machine (up → suspect → down at TTL expiry), incarnation-monotone
  rejoin, deterministic flap damping.  The fleet detects death itself
  instead of being told;
* :mod:`openr_tpu.fleet.directory` — ``FeedDirectory`` +
  ``FleetStreamRouter``: any live node serves a watcher's feed; node
  death/drain migrates subscribers to the hash successor, who resyncs
  with a fresh generation-stamped snapshot then deltas, the monotone-
  generation invariant checked ACROSS the migration; deliveries are
  epoch-fenced, resyncs coalesce per epoch bump;
* :mod:`openr_tpu.fleet.coordinator` — ``FleetSweepCoordinator``:
  world-granular sweep sharding across N nodes' pools, merged through
  the feed-order-independent reducer (merged digest byte-equal to a
  single-node run), dead-node worlds re-packed onto survivors with a
  pure-content fleet manifest that stays byte-identical to an
  uninterrupted run's; per-member ctrl breakers, epoch-stamped
  dispatches, straggler re-packs with first-committed-wins duplicate
  reconciliation, gray-failure strike demotion.

Failure-domain hierarchy: chip < node.  A dead chip re-packs one shard
inside its node's executor; a dead node re-packs whole worlds across
the fleet and migrates its watchers.  See docs/Fleet.md.
"""

from openr_tpu.fleet.assignment import (
    assign_worlds,
    owner_of,
    rank_members,
    rendezvous_score,
)
from openr_tpu.fleet.coordinator import FleetSweepCoordinator
from openr_tpu.fleet.directory import (
    FeedDirectory,
    FleetStreamRouter,
    FleetWatcher,
    feed_key,
)
from openr_tpu.fleet.liveness import (
    LivenessTracker,
    MemberBeacon,
    heartbeat_value,
    parse_heartbeat,
)
from openr_tpu.fleet.membership import FleetMembership, MembershipView

__all__ = [
    "FeedDirectory",
    "FleetMembership",
    "FleetStreamRouter",
    "FleetSweepCoordinator",
    "FleetWatcher",
    "LivenessTracker",
    "MemberBeacon",
    "MembershipView",
    "assign_worlds",
    "feed_key",
    "heartbeat_value",
    "owner_of",
    "parse_heartbeat",
    "rank_members",
    "rendezvous_score",
]
