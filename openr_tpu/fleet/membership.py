"""FleetMembership — the fleet's single writer of node liveness.

Wraps ``openr_tpu.parallel.nodes.NodeSet`` (the node-level DevicePool
analogue) behind the mutator surface orlint's ``fleet-directory`` rule
owns: ONLY the fleet/chaos/emulation tiers may call ``node_down`` /
``node_up`` / ``drain_node`` / ``undrain_node``.  Every transition
bumps the membership seq, notifies registered listeners (the sweep
coordinator re-packs, the stream router migrates), and feeds the
health plane: an unexpected down is a PAGE (``fleet_node_loss``), a
drain is a TICKET (``fleet_drain_migration`` — the migration is the
expected behaviour, the ticket just audits it).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

from openr_tpu.common.runtime import CounterMap
from openr_tpu.parallel.nodes import NodeSet


class FleetMembership:
    """Liveness + drain state for the fleet's member nodes.

    The read surface (``live_nodes`` above all) is what the
    content-derived assignment and directory hashes consume; the write
    surface is orlint-owned.  Listeners fire synchronously AFTER the
    transition commits, in registration order, with an event dict —
    consumers that need async work schedule it themselves.
    """

    def __init__(
        self,
        names: Sequence[str],
        counters: Optional[CounterMap] = None,
    ) -> None:
        self.nodes = NodeSet(names)
        self.counters = counters if counters is not None else CounterMap()
        self._listeners: List[Callable[[dict], None]] = []

    # -- read surface ------------------------------------------------------

    @property
    def names(self) -> Tuple[str, ...]:
        return self.nodes.names

    @property
    def membership_seq(self) -> int:
        return self.nodes.membership_seq

    def live_nodes(self) -> Tuple[str, ...]:
        return self.nodes.live_nodes()

    def is_live(self, name: str) -> bool:
        return self.nodes.is_live(name)

    def is_up(self, name: str) -> bool:
        """Up ≠ live: a drained node is up (its daemon answers — clean
        subscription hand-off) but not live (it owns nothing)."""
        return self.nodes.is_up(name)

    def add_listener(self, cb: Callable[[dict], None]) -> None:
        self._listeners.append(cb)

    # -- transitions (fleet-directory rule: fleet/chaos/emulation only) ----

    def node_down(self, name: str, reason: str = "crash") -> bool:
        if not self.nodes.mark_down(name):
            return False
        self.counters.bump("fleet.membership.node_down")
        self._notify("node_down", name, reason)
        return True

    def node_up(self, name: str, reason: str = "restart") -> bool:
        if not self.nodes.mark_up(name):
            return False
        self.counters.bump("fleet.membership.node_up")
        self._notify("node_up", name, reason)
        return True

    def drain_node(self, name: str, reason: str = "maintenance") -> bool:
        if not self.nodes.mark_drained(name):
            return False
        self.counters.bump("fleet.membership.drain")
        self._notify("node_drained", name, reason)
        return True

    def undrain_node(self, name: str, reason: str = "maintenance") -> bool:
        if not self.nodes.clear_drained(name):
            return False
        self.counters.bump("fleet.membership.undrain")
        self._notify("node_undrained", name, reason)
        return True

    def _notify(self, event: str, name: str, reason: str) -> None:
        ev = {
            "event": event,
            "node": name,
            "reason": reason,
            "membership_seq": self.nodes.membership_seq,
            "live": list(self.nodes.live_nodes()),
        }
        for cb in list(self._listeners):
            cb(ev)

    # -- health plane ------------------------------------------------------

    def health_firing(self) -> Dict[str, dict]:
        """The fleet's contribution to the AlertSink firing set: a PAGE
        while any member is down (node-loss is the failure domain above
        the chip — see health/alerts.py), a TICKET while any member is
        drained (the watcher/world migration is EXPECTED; the ticket
        audits that it completed)."""
        firing: Dict[str, dict] = {}
        down = self.nodes.down_nodes()
        if down:
            firing["fleet_node_loss"] = {
                "nodes": list(down),
                "live": len(self.nodes.live_nodes()),
            }
        drained = self.nodes.drained_nodes()
        if drained:
            firing["fleet_drain_migration"] = {
                "nodes": list(drained),
            }
        return firing

    # -- observability -----------------------------------------------------

    def status(self) -> dict:
        return self.nodes.status()

    def counter_snapshot(self) -> dict:
        return self.nodes.counter_snapshot(prefix="fleet.membership")
