"""FleetMembership — the fleet's single writer of node liveness.

Wraps ``openr_tpu.parallel.nodes.NodeSet`` (the node-level DevicePool
analogue) behind the mutator surface orlint's ``fleet-directory`` rule
owns: ONLY the fleet/chaos/emulation tiers may call ``node_down`` /
``node_up`` / ``drain_node`` / ``undrain_node``.  Every transition
bumps the membership seq, notifies registered listeners (the sweep
coordinator re-packs, the stream router migrates), and feeds the
health plane: an unexpected down is a PAGE (``fleet_node_loss``), a
drain is a TICKET (``fleet_drain_migration`` — the migration is the
expected behaviour, the ticket just audits it), and a gray-failure
demotion is its own TICKET (``fleet_gray_failure``).

ISSUE 20 adds the **epoch**: a monotone counter bumped exactly when the
live-node COMPOSITION changes (down/up/drain/undrain — not suspicion,
which is bookkeeping over an unchanged live set).  Everything ownership
is derived from — stream subscriptions, sweep ``world_filter``
dispatches — is stamped with the epoch it was derived under, and
receivers reject stale-epoch work (``fleet.fenced.*``): the split-brain
window where a partitioned-but-alive old owner works alongside its
successor is fenced structurally, not predicate-by-predicate.  The
epoch/suspicion mutators (``bump_epoch`` / ``mark_suspect`` /
``clear_suspect``) are single-writer inside ``openr_tpu/fleet/`` —
orlint's ``fleet-liveness`` rule; even chaos drives them only through
the heartbeat plane.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from openr_tpu.common.runtime import CounterMap
from openr_tpu.parallel.nodes import NodeSet


@dataclass(frozen=True)
class MembershipView:
    """One consistent read of the fleet's composition, epoch-stamped.

    ``epoch`` is the fencing token: any ownership derivation (watcher
    placement, world assignment) made from this view carries it, and is
    rejected by receivers once a newer epoch exists."""

    epoch: int
    live: Tuple[str, ...]
    suspects: Tuple[str, ...]
    down: Tuple[str, ...]
    drained: Tuple[str, ...]


class FleetMembership:
    """Liveness + drain state for the fleet's member nodes.

    The read surface (``live_nodes`` above all) is what the
    content-derived assignment and directory hashes consume; the write
    surface is orlint-owned.  Listeners fire synchronously AFTER the
    transition commits, in registration order, with an event dict —
    consumers that need async work schedule it themselves.
    """

    def __init__(
        self,
        names: Sequence[str],
        counters: Optional[CounterMap] = None,
    ) -> None:
        self.nodes = NodeSet(names)
        self.counters = counters if counters is not None else CounterMap()
        self._listeners: List[Callable[[dict], None]] = []
        #: monotone composition-change counter (the fencing token)
        self._epoch = 0
        self._last_live: Tuple[str, ...] = self.nodes.live_nodes()
        #: suspicion bookkeeping (liveness tracker writes) — suspects
        #: STAY live: suspicion is a warning, only TTL expiry demotes
        self._suspects: set = set()
        #: node -> reason for the current drain (gray demotions fire
        #: their own ticket via health_firing)
        self._drain_reasons: Dict[str, str] = {}

    # -- read surface ------------------------------------------------------

    @property
    def names(self) -> Tuple[str, ...]:
        return self.nodes.names

    @property
    def membership_seq(self) -> int:
        return self.nodes.membership_seq

    @property
    def epoch(self) -> int:
        return self._epoch

    def live_nodes(self) -> Tuple[str, ...]:
        return self.nodes.live_nodes()

    def is_live(self, name: str) -> bool:
        return self.nodes.is_live(name)

    def is_up(self, name: str) -> bool:
        """Up ≠ live: a drained node is up (its daemon answers — clean
        subscription hand-off) but not live (it owns nothing)."""
        return self.nodes.is_up(name)

    def suspects(self) -> Tuple[str, ...]:
        return tuple(sorted(self._suspects))

    def view(self) -> MembershipView:
        return MembershipView(
            epoch=self._epoch,
            live=self.nodes.live_nodes(),
            suspects=self.suspects(),
            down=self.nodes.down_nodes(),
            drained=self.nodes.drained_nodes(),
        )

    def add_listener(self, cb: Callable[[dict], None]) -> None:
        self._listeners.append(cb)

    # -- transitions (fleet-directory rule: fleet/chaos/emulation only) ----

    def node_down(self, name: str, reason: str = "crash") -> bool:
        if not self.nodes.mark_down(name):
            return False
        self._suspects.discard(name)
        self.counters.bump("fleet.membership.node_down")
        self._notify("node_down", name, reason)
        return True

    def node_up(self, name: str, reason: str = "restart") -> bool:
        if not self.nodes.mark_up(name):
            return False
        self._suspects.discard(name)
        self._drain_reasons.pop(name, None)
        self.counters.bump("fleet.membership.node_up")
        self._notify("node_up", name, reason)
        return True

    def drain_node(self, name: str, reason: str = "maintenance") -> bool:
        if not self.nodes.mark_drained(name):
            return False
        self._drain_reasons[name] = reason
        self.counters.bump("fleet.membership.drain")
        self._notify("node_drained", name, reason)
        return True

    def undrain_node(self, name: str, reason: str = "maintenance") -> bool:
        if not self.nodes.clear_drained(name):
            return False
        self._drain_reasons.pop(name, None)
        self.counters.bump("fleet.membership.undrain")
        self._notify("node_undrained", name, reason)
        return True

    # -- epoch + suspicion (fleet-liveness rule: openr_tpu/fleet/ ONLY) ----

    def bump_epoch(self) -> int:
        """Advance the fencing token.  Called internally on every
        composition change; single-writer inside openr_tpu/fleet/."""
        self._epoch += 1
        self.counters.set("fleet.membership.epoch", float(self._epoch))
        return self._epoch

    def mark_suspect(self, name: str, reason: str = "missed_refresh") -> bool:
        """Suspicion bookkeeping (LivenessTracker writes): the node
        missed heartbeat refreshes but its TTL has not expired.  The
        live set — and therefore the epoch — is unchanged."""
        if name in self._suspects or not self.nodes.is_live(name):
            return False
        self._suspects.add(name)
        self.counters.bump("fleet.membership.suspect")
        self._notify("node_suspect", name, reason)
        return True

    def clear_suspect(self, name: str, reason: str = "refreshed") -> bool:
        if name not in self._suspects:
            return False
        self._suspects.discard(name)
        self.counters.bump("fleet.membership.unsuspect")
        self._notify("node_unsuspect", name, reason)
        return True

    def _notify(self, event: str, name: str, reason: str) -> None:
        live = self.nodes.live_nodes()
        if live != self._last_live:
            self._last_live = live
            self.bump_epoch()
        ev = {
            "event": event,
            "node": name,
            "reason": reason,
            "membership_seq": self.nodes.membership_seq,
            "epoch": self._epoch,
            "live": list(live),
        }
        for cb in list(self._listeners):
            cb(ev)

    # -- health plane ------------------------------------------------------

    def health_firing(self) -> Dict[str, dict]:
        """The fleet's contribution to the AlertSink firing set: a PAGE
        while any member is down (node-loss is the failure domain above
        the chip — see health/alerts.py), a TICKET while any member is
        drained (the watcher/world migration is EXPECTED; the ticket
        audits that it completed), and a separate TICKET while any
        drain was a gray-failure demotion (heartbeats fine, work
        failing — the runbook's "fleet disagrees" case)."""
        firing: Dict[str, dict] = {}
        down = self.nodes.down_nodes()
        if down:
            firing["fleet_node_loss"] = {
                "nodes": list(down),
                "live": len(self.nodes.live_nodes()),
            }
        drained = self.nodes.drained_nodes()
        if drained:
            firing["fleet_drain_migration"] = {
                "nodes": list(drained),
            }
        gray = sorted(
            n for n, r in self._drain_reasons.items()
            if r == "gray_failure" and n in drained
        )
        if gray:
            firing["fleet_gray_failure"] = {"nodes": gray}
        return firing

    # -- observability -----------------------------------------------------

    def status(self) -> dict:
        out = self.nodes.status()
        out["epoch"] = self._epoch
        out["suspects"] = list(self.suspects())
        out["drain_reasons"] = dict(sorted(self._drain_reasons.items()))
        return out

    def counter_snapshot(self) -> dict:
        return self.nodes.counter_snapshot(prefix="fleet.membership")
