"""Heartbeat-derived fleet membership — liveness without an oracle.

PR 19's membership plane was injected: the harness *told* the fleet who
died.  This module is the detection tier, dogfooding the protocol the
fleet already routes with: every member advertises a TTL-bearing
``fleet:member:<name>`` key (types.py key family; KvStore grows the
matching ``advertise_fleet_heartbeat`` origination surface), stamped
with its incarnation via the PR-12 ``node.start_ms`` discipline and
refreshed on the injected Clock at ``heartbeat_interval_s``.  The
``LivenessTracker`` folds key arrival / TTL expiry into
``FleetMembership`` transitions through a suspicion state machine:

    up ──(suspect_after_s without a refresh)──► suspect
    suspect ──(refresh arrives)──► up
    suspect ──(heartbeat_ttl_s without a refresh)──► down

Rejoin from ``down`` requires a STRICTLY higher incarnation — a zombie
instance replaying its old incarnation's heartbeats is counted
(``fleet.liveness.stale_incarnation``) and ignored, exactly the
self-originated-key guard the KvStore applies to restarted daemons.
A node that bounces repeatedly is **flap-damped**: an exponentially
growing hold (deterministic seeded jitter, breaker-style name-salted
rng) keeps it out of the live set while its heartbeats keep arriving
(``fleet.flap_damped``), so assignment churn is hysteresis-bounded.

Suspicion and damping are bookkeeping over an UNCHANGED live set; only
the up/down/drain transitions bump the membership epoch (the fencing
token every ownership derivation is stamped with — see membership.py).
The tracker is the single writer of suspicion state and damping clocks
(orlint ``fleet-liveness``): chaos never mutates them directly, it
perturbs the heartbeat PLANE (stall, partition, reincarnate) and the
tracker must conclude the rest.
"""

from __future__ import annotations

import json
import random
import zlib
from typing import Callable, Dict, List, Optional

from openr_tpu.common.runtime import Actor, Clock, CounterMap
from openr_tpu.fleet.membership import FleetMembership
from openr_tpu.types import (
    Publication,
    Value,
    fleet_member_key,
    parse_fleet_member_key,
)


def heartbeat_value(
    node: str, incarnation: int, seq: int, ttl_ms: int
) -> Value:
    """One heartbeat as a KvStore value: version carries the refresh
    seq (monotone per incarnation), the payload the incarnation stamp."""
    return Value(
        version=int(seq),
        originator_id=node,
        value=json.dumps(
            {"incarnation": int(incarnation), "node": node, "seq": int(seq)},
            sort_keys=True,
        ).encode(),
        ttl=int(ttl_ms),
    )


def parse_heartbeat(value: Value) -> Optional[dict]:
    """Decode a ``fleet:member:*`` value; None when malformed (a
    malformed heartbeat must never poison the tracker fiber)."""
    if value.value is None:
        return None
    try:
        body = json.loads(value.value.decode())
    except (ValueError, UnicodeDecodeError):
        return None
    if "incarnation" not in body:
        return None
    return {
        "incarnation": int(body["incarnation"]),
        "seq": int(body.get("seq", value.version)),
    }


class MemberBeacon(Actor):
    """One member's heartbeat publisher: refreshes its
    ``fleet:member:<name>`` key every ``heartbeat_interval_s`` on the
    injected Clock, incarnation-stamped (``node.start_ms`` discipline —
    minted from the clock at start, strictly advanced on reincarnate).

    Chaos drives the failure modes: ``stall()`` keeps the daemon alive
    but stops refreshes (the unannounced-kill / gray-network signal);
    ``reincarnate()`` models the supervisor restarting the process (the
    only way back in once the fleet declared this incarnation dead).
    """

    def __init__(
        self,
        name: str,
        clock: Clock,
        publish: Callable[[Publication], None],
        heartbeat_interval_s: float = 0.5,
        heartbeat_ttl_s: float = 2.5,
        incarnation: Optional[int] = None,
        counters: Optional[CounterMap] = None,
    ) -> None:
        super().__init__(f"fleet.beacon.{name}", clock, counters)
        self.member = name
        self.publish = publish
        self.heartbeat_interval_s = heartbeat_interval_s
        self.ttl_ms = max(int(heartbeat_ttl_s * 1000.0), 1)
        #: the node.start_ms incarnation stamp
        self.incarnation = (
            int(clock.now_ms()) if incarnation is None else int(incarnation)
        )
        self.seq = 0
        self.stalled = False

    def stall(self) -> None:
        """Stop refreshing (daemon alive, heartbeats gone — what an
        unannounced kill, a wedged fiber or a dead NIC all look like)."""
        self.stalled = True
        self.counters.bump("fleet.beacon.stalls")

    def resume(self) -> None:
        self.stalled = False

    def reincarnate(self) -> int:
        """Supervisor restart: a strictly higher incarnation (the fleet
        will not readmit the old one once it was declared down)."""
        self.incarnation = max(int(self.clock.now_ms()), self.incarnation + 1)
        self.seq = 0
        self.stalled = False
        self.counters.bump("fleet.beacon.reincarnations")
        return self.incarnation

    def beat_now(self) -> None:
        """Publish one refresh immediately (also the first beat at
        start, so a fresh member is visible within one dispatch)."""
        self.seq += 1
        self.publish(
            Publication(
                key_vals={
                    fleet_member_key(self.member): heartbeat_value(
                        self.member, self.incarnation, self.seq, self.ttl_ms
                    )
                },
                area="0",
            )
        )
        self.counters.bump("fleet.beacon.beats")

    async def run(self) -> None:
        while True:
            if not self.stalled:
                self.beat_now()
            self.touch()
            await self.clock.sleep(self.heartbeat_interval_s)


class _MemberLiveness:
    """Tracker-side bookkeeping for one member."""

    __slots__ = (
        "name", "incarnation", "seq", "last_hb", "damped_until", "flaps",
    )

    def __init__(self, name: str, now: float) -> None:
        self.name = name
        #: last ACCEPTED incarnation (-1 = never heard)
        self.incarnation = -1
        self.seq = -1
        #: start-time grace: a member that never beats is detected via
        #: the same suspect→down path as one that stopped
        self.last_hb = now
        self.damped_until = 0.0
        #: accepted-rejoin times inside the flap window
        self.flaps: List[float] = []


class LivenessTracker(Actor):
    """Folds heartbeat arrival/expiry into membership transitions.

    Consumes ``fleet:member:*`` publications (``on_publication`` — the
    fabric's heartbeat bus, or a KvStore drain loop in a real
    deployment) and runs a periodic suspicion tick.  All membership
    writes happen HERE (single-writer): announced chaos verbs still
    mutate membership directly — the tracker reconciles by reading
    membership state before acting, so an announced kill and a detected
    one converge on the same transitions.
    """

    def __init__(
        self,
        clock: Clock,
        membership: FleetMembership,
        heartbeat_interval_s: float = 0.5,
        suspect_after_s: float = 1.25,
        heartbeat_ttl_s: float = 2.5,
        flap_window_s: float = 30.0,
        flap_hold_base_s: float = 2.0,
        flap_hold_max_s: float = 60.0,
        jitter_pct: float = 0.1,
        seed: int = 0,
        tick_s: float = 0.25,
        counters: Optional[CounterMap] = None,
    ) -> None:
        super().__init__("fleet.liveness", clock, counters)
        assert heartbeat_interval_s < suspect_after_s < heartbeat_ttl_s, (
            "liveness needs heartbeat_interval < suspect_after < ttl"
        )
        self.membership = membership
        self.heartbeat_interval_s = heartbeat_interval_s
        self.suspect_after_s = suspect_after_s
        self.heartbeat_ttl_s = heartbeat_ttl_s
        self.flap_window_s = flap_window_s
        self.flap_hold_base_s = flap_hold_base_s
        self.flap_hold_max_s = flap_hold_max_s
        self.jitter_pct = jitter_pct
        self.seed = seed
        self.tick_s = tick_s
        self._m: Dict[str, _MemberLiveness] = {}
        #: per-member damping jitter rng, breaker-style name-salted so
        #: a fleet sharing one seed still de-syncs deterministically
        self._rngs: Dict[str, random.Random] = {}

    # -- bookkeeping -------------------------------------------------------

    def _ensure(self, name: str) -> _MemberLiveness:
        m = self._m.get(name)
        if m is None:
            m = self._m[name] = _MemberLiveness(name, self.clock.now())
        return m

    def _rng(self, name: str) -> random.Random:
        rng = self._rngs.get(name)
        if rng is None:
            rng = self._rngs[name] = random.Random(
                (self.seed << 32) ^ zlib.crc32(name.encode())
            )
        return rng

    def record_incarnation(self, name: str, incarnation: int) -> None:
        """Adopt an accepted incarnation (single-writer: tracker only)."""
        self._ensure(name).incarnation = int(incarnation)

    def set_damped_until(self, name: str, until: float) -> None:
        """Arm/clear one member's damping hold (single-writer: tracker
        only — chaos perturbs the heartbeat plane, never this clock)."""
        self._ensure(name).damped_until = float(until)

    # -- heartbeat ingress -------------------------------------------------

    def on_publication(self, pub: Publication) -> None:
        for key, value in (pub.key_vals or {}).items():
            node = parse_fleet_member_key(key)
            if node is None:
                continue
            hb = parse_heartbeat(value)
            if hb is None:
                self.counters.bump("fleet.liveness.malformed")
                continue
            self.on_heartbeat(node, hb["incarnation"], hb["seq"])
        for key in pub.expired_keys or ():
            node = parse_fleet_member_key(key)
            if node is not None:
                self._expire(node, reason="heartbeat_key_expired")

    def on_heartbeat(self, node: str, incarnation: int, seq: int) -> None:
        if node not in self.membership.names:
            return
        now = self.clock.now()
        m = self._ensure(node)
        if incarnation < m.incarnation:
            # a zombie instance replaying an old incarnation — never a
            # refresh, whatever the membership state
            self.counters.bump("fleet.liveness.stale_incarnation")
            return
        if self.membership.is_live(node):
            if incarnation > m.incarnation:
                self.record_incarnation(node, incarnation)
            m.last_hb = now
            m.seq = seq
            if node in self.membership.suspects():
                self.membership.clear_suspect(node)
                self.counters.bump("fleet.liveness.recoveries")
            return
        if self.membership.is_up(node):
            # drained: deliberate demotion — refresh bookkeeping only,
            # heartbeats must not undrain a node the operator (or the
            # gray-failure policy) took out of rotation
            if incarnation > m.incarnation:
                self.record_incarnation(node, incarnation)
            m.last_hb = now
            m.seq = seq
            return
        # down.  While a damping hold is armed, refreshes keep the
        # bookkeeping warm but do NOT readmit (the tick does, once the
        # hold expires and the node is still beating).
        if m.damped_until > now:
            if incarnation > m.incarnation:
                self.record_incarnation(node, incarnation)
            m.last_hb = now
            m.seq = seq
            return
        # rejoin: strictly higher incarnation than the one the fleet
        # declared dead (same discipline as the KvStore ttl clock)
        if incarnation <= m.incarnation:
            self.counters.bump("fleet.liveness.stale_incarnation")
            return
        self.record_incarnation(node, incarnation)
        m.last_hb = now
        m.seq = seq
        m.flaps = [
            t for t in m.flaps if now - t <= self.flap_window_s
        ] + [now]
        if len(m.flaps) >= 2:
            # flapping: exponential hold before re-entering the live
            # set, deterministic seeded jitter (one draw per damping)
            hold = min(
                self.flap_hold_base_s * (2.0 ** (len(m.flaps) - 2)),
                self.flap_hold_max_s,
            )
            if self.jitter_pct:
                hold *= 1.0 + self.jitter_pct * self._rng(node).uniform(
                    -1.0, 1.0
                )
            self.set_damped_until(node, now + hold)
            self.counters.bump("fleet.flap_damped")
            return
        self._readmit(node, reason="heartbeat_rejoin")

    # -- suspicion tick ----------------------------------------------------

    def _expire(self, node: str, reason: str) -> None:
        if self.membership.is_live(node) or self.membership.is_up(node):
            self.membership.node_down(node, reason=reason)
            self.counters.bump("fleet.liveness.expiries")

    def _readmit(self, node: str, reason: str) -> None:
        self.membership.node_up(node, reason=reason)
        self.counters.bump("fleet.liveness.rejoins")

    def tick(self) -> None:
        now = self.clock.now()
        for name in self.membership.names:
            m = self._ensure(name)
            if self.membership.is_live(name):
                age = now - m.last_hb
                if age > self.heartbeat_ttl_s:
                    self._expire(name, reason="heartbeat_expired")
                elif age > self.suspect_after_s:
                    self.membership.mark_suspect(name)
            elif self.membership.is_up(name):
                # drained: death-while-drained still detected
                if now - m.last_hb > self.heartbeat_ttl_s:
                    self._expire(name, reason="heartbeat_expired")
            elif m.damped_until > 0.0:
                if now >= m.damped_until:
                    self.set_damped_until(name, 0.0)
                    if now - m.last_hb <= self.suspect_after_s:
                        self._readmit(name, reason="damping_hold_expired")
                    # else: stopped beating during the hold — stays
                    # down, the next valid rejoin starts over

    async def run(self) -> None:
        while True:
            self.tick()
            self.touch()
            await self.clock.sleep(self.tick_s)

    # -- observability -----------------------------------------------------

    def member_state(self, name: str) -> str:
        now = self.clock.now()
        if self.membership.is_live(name):
            return (
                "suspect" if name in self.membership.suspects() else "live"
            )
        if self.membership.is_up(name):
            return "drained"
        m = self._m.get(name)
        if m is not None and m.damped_until > now:
            return "damped"
        return "down"

    def status(self) -> dict:
        """The ``breeze fleet status`` liveness columns: per-member
        state / incarnation / heartbeat age / damping clock, plus the
        epoch every ownership derivation is fenced against."""
        now = self.clock.now()
        members = {}
        for name in self.membership.names:
            m = self._ensure(name)
            members[name] = {
                "state": self.member_state(name),
                "incarnation": m.incarnation,
                "seq": m.seq,
                "heartbeat_age_s": round(now - m.last_hb, 6),
                "damped_for_s": round(max(m.damped_until - now, 0.0), 6),
                "flaps_in_window": len(
                    [t for t in m.flaps if now - t <= self.flap_window_s]
                ),
            }
        return {
            "epoch": self.membership.epoch,
            "suspect_after_s": self.suspect_after_s,
            "heartbeat_ttl_s": self.heartbeat_ttl_s,
            "members": members,
        }
