"""Monitor plane: event-log ring + process metrics (`monitor`) and the
point-in-time metrics-export tier (`metrics`: `MetricsSnapshot`,
Prometheus text exposition, deterministic JSONL writer)."""

from openr_tpu.monitor.metrics import (
    NONDETERMINISTIC_PREFIXES,
    MetricsJsonlWriter,
    MetricsSnapshot,
    parse_prometheus,
    render_prometheus,
)
from openr_tpu.monitor.monitor import Monitor, SystemMetrics

__all__ = [
    "MetricsJsonlWriter",
    "MetricsSnapshot",
    "Monitor",
    "NONDETERMINISTIC_PREFIXES",
    "SystemMetrics",
    "parse_prometheus",
    "render_prometheus",
]
