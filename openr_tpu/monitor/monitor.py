"""Monitor — structured event-log sink + process metrics.

TPU-native re-design of the reference monitor service
(openr/monitor/{MonitorBase,Monitor,LogSample,SystemMetrics}.{h,cpp}):

  * drains ``logSampleQueue`` (any module pushes ``LogSample`` records,
    reference MonitorBase.h:32-51);
  * every sample is stamped, normalized to JSON, kept in a bounded recent-log
    ring (``max_event_log_size``) queryable via the ctrl API ``getEventLogs``
    (if/OpenrCtrl.thrift:702);
  * periodically samples process CPU / RSS into counters
    (monitor/SystemMetrics.h:24-36 via /proc, no psutil dependency);
  * counts received/dropped samples like the reference
    (``monitor.log_sample_received`` etc.).

Forwarding to an external log pipeline (Scuba in Meta's deployment) is a
pluggable callback here, defaulting to a no-op — the OSS reference does the
same (Monitor.cpp processes but does not export).
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional

from openr_tpu.common.runtime import Actor, Clock, CounterMap
from openr_tpu.messaging.queue import RQueue
from openr_tpu.types import LogSample


class SystemMetrics:
    """CPU/RSS sampling from /proc (reference monitor/SystemMetrics.h:24-36
    reads getrusage + /proc/self/statm)."""

    def __init__(self) -> None:
        self._last_cpu: Optional[float] = None
        self._last_wall: Optional[float] = None
        self._page_size = os.sysconf("SC_PAGE_SIZE")

    def rss_bytes(self) -> Optional[int]:
        try:
            with open("/proc/self/statm") as f:
                fields = f.read().split()
            return int(fields[1]) * self._page_size
        except (OSError, IndexError, ValueError):
            return None

    def cpu_pct(self) -> Optional[float]:
        """Process CPU% since the previous call (first call returns None)."""
        try:
            cpu = sum(os.times()[:2])  # user + system
        except OSError:
            return None
        wall = time.monotonic()  # orlint: disable=clock-now,wallclock-reachability (CPU%% is a real-time rate; virtual time would skew it, and the value feeds gauges, never replay-compared bytes)
        pct = None
        if self._last_cpu is not None and wall > self._last_wall:
            pct = 100.0 * (cpu - self._last_cpu) / (wall - self._last_wall)
        self._last_cpu, self._last_wall = cpu, wall
        return pct


class Monitor(Actor):
    """Event-log ring + metrics sampler (reference monitor/Monitor.h)."""

    def __init__(
        self,
        node_name: str,
        clock: Clock,
        log_sample_reader: RQueue,
        counters: Optional[CounterMap] = None,
        max_event_log_size: int = 100,
        enable_event_log_submission: bool = True,
        metrics_interval_s: float = 60.0,
        forward_fn: Optional[Callable[[Dict[str, Any]], None]] = None,
    ) -> None:
        super().__init__("monitor", clock, counters)
        self.node_name = node_name
        self._reader = log_sample_reader
        self._ring: Deque[str] = deque(maxlen=max_event_log_size)
        self._submit = enable_event_log_submission
        self._metrics_interval = metrics_interval_s
        self._forward = forward_fn
        self.system_metrics = SystemMetrics()
        self._start_time = clock.now()
        #: gauge providers sampled each metrics sweep: modules whose
        #: internal state isn't naturally counter-shaped (Fib retry/backoff,
        #: decision-backend build/fallback tallies) register a callable
        #: returning {counter_key: value} so the ctrl API / breeze surface
        #: them without the modules knowing about sampling cadence
        self._providers: List[Callable[[], Dict[str, float]]] = []

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self.spawn_queue_loop(self._reader, self.process_log_sample, "monitor.logs")
        self.spawn(self._metrics_fiber(), "monitor.metrics")

    async def _metrics_fiber(self) -> None:
        while True:
            self.sample_system_metrics()
            await self.clock.sleep(self._metrics_interval)

    # -- log samples (Monitor.cpp processEventLog) -------------------------

    def process_log_sample(self, sample: LogSample) -> None:
        self.counters.bump("monitor.log.sample_received")
        if not self._submit:
            self.counters.bump("monitor.log.sample_dropped")
            return
        record = {
            "event": sample.event,
            "node_name": self.node_name,
            "timestamp_ms": sample.timestamp_ms or self.clock.now_ms(),
            **sample.attributes,
        }
        if len(self._ring) == self._ring.maxlen:
            # the bounded ring is about to silently drop its oldest
            # sample; count it — `sample_dropped` only covers
            # disabled-submission drops, so before this counter evictions
            # were invisible to getEventLogs consumers
            self.counters.bump("monitor.log.sample_evicted")
        self._ring.append(json.dumps(record, sort_keys=True, default=str))
        if self._forward is not None:
            self._forward(record)

    def get_event_logs(self) -> List[str]:
        """ctrl API getEventLogs (if/OpenrCtrl.thrift:702)."""
        return list(self._ring)

    # -- system metrics ----------------------------------------------------

    def add_counter_provider(
        self, provider: Callable[[], Dict[str, float]]
    ) -> None:
        """Register a gauge provider; sampled every metrics sweep."""
        self._providers.append(provider)

    def sample_providers(self) -> None:
        """Sweep ONLY the registered gauge providers (no process.*
        sampling).  The metrics-export tier calls this at snapshot
        capture so provider-backed gauges are current at the captured
        instant — and stays deterministic under SimClock, which the
        wall-clock process metrics are not."""
        for provider in self._providers:
            try:
                for key, value in provider().items():
                    self.counters.set(key, value)
            except Exception:  # noqa: BLE001 - a sick provider must not
                self.counters.bump("monitor.provider_errors")  # kill sampling

    def sample_system_metrics(self) -> None:
        rss = self.system_metrics.rss_bytes()
        if rss is not None:
            self.counters.set("process.memory.rss", rss)
        cpu = self.system_metrics.cpu_pct()
        if cpu is not None:
            self.counters.set("process.cpu.pct", cpu)
        self.counters.set(
            "process.uptime.seconds", self.clock.now() - self._start_time
        )
        self.sample_providers()
        self.touch()
