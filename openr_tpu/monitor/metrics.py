"""Metrics export tier — point-in-time snapshots off the node.

The ctrl API's ``getCounters`` answers a pull from one operator; fleet
monitoring needs the whole metric surface (counters + gauge providers +
histogram BUCKETS, not just percentiles) in a form external systems
ingest.  Two renderings of one `MetricsSnapshot`:

  * **Prometheus text exposition** (`render_prometheus`): counters and
    gauges as ``gauge`` samples, fixed-bucket histograms as classic
    Prometheus ``histogram`` families (cumulative ``_bucket{le=..}`` +
    ``_sum`` + ``_count``), every sample labeled ``node="..."`` so one
    scrape of an emulation covers all nodes.  `parse_prometheus` is the
    inverse used by the round-trip test — the exposition this module
    emits must survive its own parser exactly.
  * **JSONL** (`MetricsJsonlWriter`): one snapshot per line, sorted
    keys, driven by the injected Clock (``--metrics-export PATH`` in
    ``--emulate`` mode) — under SimClock two identical seeded runs
    write byte-identical files, which is what makes snapshot diffs a
    usable regression instrument.

Every snapshot is generation-stamped (Decision's content-address key,
so a sample is attributable to the exact LSDB/policy state it measured)
and env-stamped (python/jax identity; deliberately NOT loadavg or RSS —
the stamp must be stable across replays of one seed).
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")

#: counter prefixes excluded from DETERMINISTIC exports (flight-recorder
#: dumps, seeded-replay JSONL): process CPU/RSS and wall-clock rates
#: differ across replays of the same seed and would break byte-diffing
NONDETERMINISTIC_PREFIXES = ("process.",)


def env_stamp() -> Dict[str, Any]:
    """Replay-stable environment identity: interpreter + jax version.
    jax attributes are read only when jax is ALREADY imported — a
    metrics sweep must never be the thing that boots an accelerator
    platform (same rule as the backend's pool gauges)."""
    import platform
    import sys

    jax_mod = sys.modules.get("jax")
    return {
        "python": platform.python_version(),
        "jax": getattr(jax_mod, "__version__", "") if jax_mod else "",
    }


class MetricsSnapshot:
    """One node's full metric surface at one instant."""

    def __init__(
        self,
        node: str,
        ts_ms: int,
        generation: Any,
        env: Dict[str, Any],
        counters: Dict[str, float],
        histograms: Dict[str, Dict[str, Any]],
    ) -> None:
        self.node = node
        self.ts_ms = ts_ms
        self.generation = generation
        self.env = env
        self.counters = counters
        self.histograms = histograms

    @classmethod
    def capture(
        cls,
        node=None,
        *,
        counters=None,
        node_name: str = "",
        clock=None,
        generation: Any = None,
        exclude: Tuple[str, ...] = (),
    ) -> "MetricsSnapshot":
        """Snapshot an OpenrNode (or a bare CounterMap).

        Given a full node, the Monitor's gauge providers are swept first
        so provider-backed gauges (backend tallies, pool health, tracer
        drop counts, pipeline busy gauges) are current at capture time
        instead of stale from the last periodic sweep.  ``exclude``
        drops counter-key prefixes — deterministic exports pass
        :data:`NONDETERMINISTIC_PREFIXES`."""
        if node is not None:
            counters = node.counters
            node_name = node.name
            clock = node.clock
            monitor = getattr(node, "monitor", None)
            if monitor is not None:
                monitor.sample_providers()
            if generation is None:
                decision = getattr(node, "decision", None)
                if decision is not None:
                    generation = list(decision.generation_key())
        if counters is None:
            raise ValueError("capture needs a node or a CounterMap")
        counter_vals = {
            k: v
            for k, v in sorted(counters.dump().items())
            if not exclude or not k.startswith(exclude)
        }
        hists: Dict[str, Dict[str, Any]] = {}
        for key in counters.histogram_keys():
            h = counters.histogram(key)
            snap = dict(h.config())
            snap.update(
                count=h.count,
                sum=h.total,
                min=h.vmin,
                max=h.vmax,
                buckets=[[edge, c] for edge, c in h.bucket_items()],
            )
            hists[key] = snap
        return cls(
            node=node_name,
            ts_ms=int(clock.now_ms()) if clock is not None else 0,
            generation=generation,
            env=env_stamp(),
            counters=counter_vals,
            histograms=hists,
        )

    def to_wire(self) -> Dict[str, Any]:
        return {
            "node": self.node,
            "ts_ms": self.ts_ms,
            "generation": self.generation,
            "env": self.env,
            "counters": self.counters,
            "histograms": self.histograms,
        }

    def to_jsonl(self) -> str:
        """One deterministic line: sorted keys, no float repr games
        (json round-trips doubles exactly)."""
        return json.dumps(
            self.to_wire(), sort_keys=True, separators=(",", ":")
        )


# -- Prometheus text exposition --------------------------------------------


def _metric_name(key: str) -> str:
    return "openr_" + _NAME_RE.sub("_", key)


#: per-device gauge keys (``<head>.dev<N>.<tail>``) are promoted to ONE
#: labeled family per (head, tail) with a ``device="N"`` label — a fleet
#: dashboard graphs `openr_pipeline_device_busy_ms` across chips instead
#: of discovering `_dev0_`/`_dev1_`/... families one by one.  Internal
#: dotted counter names are UNCHANGED; this is a rendering-layer mapping.
_DEV_RE = re.compile(r"^(?P<head>.+?)\.dev(?P<idx>\d+)\.(?P<tail>.+)$")


def _device_family(key: str) -> Optional[Tuple[str, str]]:
    """(family_internal_key, device_index_str) for a per-device gauge
    key, else None.  The family key spells the device segment as
    ``.device.`` — e.g. ``pipeline.dev3.busy_ms`` ->
    (``pipeline.device.busy_ms``, "3")."""
    m = _DEV_RE.match(key)
    if m is None:
        return None
    return f"{m.group('head')}.device.{m.group('tail')}", m.group("idx")


_DESCRIPTIONS: Optional[Dict[str, str]] = None


def _build_descriptions() -> Dict[str, str]:
    """The metric-description registry behind ``# HELP`` emission:
    known counter/histogram families only — an undocumented counter
    renders without HELP rather than with a made-up one.  Names are
    derived through the owning registries (pipeline phases, alert
    names), never re-spelled."""
    from openr_tpu.health.alerts import ALERTS, alert_counter_key
    from openr_tpu.tracing import pipeline as _pl

    d = {
        "convergence.event_to_fib_ms": (
            "end-to-end convergence latency: origin event to FIB ack"
        ),
        "decision.spf_ms": "one SPF solve inside a Decision rebuild",
        "serving.queue_wait_ms": (
            "serving-plane queue wait before a query joins a batch"
        ),
        "serving.batch_solve_ms": "one micro-batched device solve",
        "streaming.staleness_ms": (
            "delta age at delivery: oldest merged generation's mint to "
            "its emission to the subscriber"
        ),
        "streaming.subscribers": "attached watch-plane subscribers",
        "streaming.num_resyncs": (
            "snapshot resyncs (queue overflow / transport failure "
            "escalations)"
        ),
        "streaming.num_invariant_violations": (
            "emissions refused by the monotone-generation check "
            "(must stay 0)"
        ),
        "trace.dropped_spans": (
            "open spans dropped at the open-span cap (trace blind spots)"
        ),
        "trace.spans_evicted": (
            "completed spans evicted from the bounded ring"
        ),
        "monitor.log.sample_received": "log samples drained by Monitor",
        "watchdog.crashes": "crashes fired by the watchdog",
        "resilience.backend.quarantined": (
            "1 while the whole device backend is quarantined"
        ),
        "resilience.backend.shadow_checks": (
            "device builds shadow-verified against the scalar oracle"
        ),
        "resilience.backend.shadow_mismatches": (
            "shadow checks that caught wrong device output"
        ),
        "decision.backend.pool.size": "chips in the device pool",
        "decision.backend.pool.healthy": "healthy chips in the pool",
        "health.sweeps": "fleet health aggregator sweeps",
        "health.alerts.active": "currently-firing fleet health alerts",
    }
    for phase in _pl.PHASES:
        d[_pl.hist_key(phase)] = (
            f"milliseconds in the {phase} pipeline phase per dispatch"
        )
    busy_fam = _device_family(_pl.device_busy_key(0))
    util_fam = _device_family(_pl.device_utilization_key(0))
    if busy_fam is not None:
        d[busy_fam[0]] = "cumulative committed-dispatch busy ms per chip"
    if util_fam is not None:
        d[util_fam[0]] = "busy fraction of the probe lifetime per chip"
    d["decision.backend.pool.device.dispatches"] = (
        "committed dispatches per chip"
    )
    d["resilience.backend.device.state"] = (
        "per-chip breaker state (0 closed, 1 open, 2 half-open)"
    )
    for name in ALERTS:
        d[alert_counter_key(name)] = (
            "firing-sweep counter for fleet health alert: "
            + ALERTS[name][1]
        )
    return d


def metric_description(key: str) -> Optional[str]:
    """One-line HELP text for a known family's INTERNAL dotted key
    (device families use the ``.device.`` spelling); None when the
    family is not in the registry."""
    global _DESCRIPTIONS
    if _DESCRIPTIONS is None:
        _DESCRIPTIONS = _build_descriptions()
    return _DESCRIPTIONS.get(key)


def _fmt(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if float(v) == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def render_prometheus(snapshots: Iterable[MetricsSnapshot]) -> str:
    """All nodes' snapshots as one text-exposition document.  Samples of
    one metric family are contiguous under a single ``# TYPE`` header
    (the format's grouping requirement), labeled per node; per-device
    gauges collapse into one family per (head, tail) with a
    ``device="N"`` label; families in the description registry get a
    ``# HELP`` line the strict parser preserves."""
    snaps = list(snapshots)
    # family internal key -> [(label items, value)]; labels beyond
    # node= come from the per-device promotion
    gauge_keys: Dict[str, List[Tuple[Tuple[Tuple[str, str], ...], float]]] = {}
    for s in snaps:
        for k, v in s.counters.items():
            fam = _device_family(k)
            if fam is not None:
                key, labels = fam[0], (("node", s.node), ("device", fam[1]))
            else:
                key, labels = k, (("node", s.node),)
            gauge_keys.setdefault(key, []).append((labels, float(v)))
    hist_keys: Dict[str, List[Tuple[str, Dict[str, Any]]]] = {}
    for s in snaps:
        for k, h in s.histograms.items():
            hist_keys.setdefault(k, []).append((s.node, h))
    lines: List[str] = []

    def _header(key: str, mtype: str) -> str:
        name = _metric_name(key)
        help_text = metric_description(key)
        if help_text is not None:
            lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")
        return name

    def _labels(items: Tuple[Tuple[str, str], ...]) -> str:
        return ",".join(f'{k}="{v}"' for k, v in items)

    for key in sorted(gauge_keys):
        name = _header(key, "gauge")
        for labels, v in gauge_keys[key]:
            lines.append(f"{name}{{{_labels(labels)}}} {_fmt(v)}")
    for key in sorted(hist_keys):
        name = _header(key, "histogram")
        for node, h in hist_keys[key]:
            cum = 0
            for edge, c in h["buckets"]:
                cum += c
                le = _fmt(float(edge))
                lines.append(
                    f'{name}_bucket{{node="{node}",le="{le}"}} {cum}'
                )
            if not h["buckets"] or h["buckets"][-1][0] != float("inf"):
                lines.append(
                    f'{name}_bucket{{node="{node}",le="+Inf"}} {h["count"]}'
                )
            lines.append(f'{name}_sum{{node="{node}"}} {_fmt(h["sum"])}')
            lines.append(f'{name}_count{{node="{node}"}} {h["count"]}')
    return "\n".join(lines) + "\n"


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$"
)
_LABEL_RE = re.compile(r'(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>[^"]*)"')


def parse_prometheus(text: str) -> Dict[str, Dict[str, Any]]:
    """Parse a text exposition back into
    ``{metric: {"type": t, "samples": {(label items): value}, ...}}``
    (families with a ``# HELP`` line carry its text under ``"help"``) —
    strict enough that a malformed document (bad label syntax, sample
    before its TYPE header, malformed HELP, non-float value) raises
    ValueError, which is the property the round-trip test leans on."""
    metrics: Dict[str, Dict[str, Any]] = {}
    current_family = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) != 4:
                raise ValueError(f"line {lineno}: malformed HELP line")
            _, _, name, help_text = parts
            fam = metrics.setdefault(name, {"type": None, "samples": {}})
            fam["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4:
                raise ValueError(f"line {lineno}: malformed TYPE header")
            _, _, name, mtype = parts
            fam = metrics.setdefault(name, {"type": None, "samples": {}})
            fam["type"] = mtype
            current_family = name
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: unparseable sample {line!r}")
        name = m.group("name")
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if (
                name.endswith(suffix)
                and current_family is not None
                and name == current_family + suffix
            ):
                base = current_family
                break
        fam = metrics.get(base) or metrics.get(name)
        if fam is None or fam.get("type") is None:
            # a bare HELP line does not open a family for samples
            raise ValueError(
                f"line {lineno}: sample {name} before its TYPE header"
            )
        labels_raw = m.group("labels") or ""
        labels = tuple(
            (lm.group("k"), lm.group("v"))
            for lm in _LABEL_RE.finditer(labels_raw)
        )
        consumed = "".join(f'{k}="{v}",' for k, v in labels).rstrip(",")
        if labels_raw and consumed != labels_raw.rstrip(","):
            raise ValueError(f"line {lineno}: malformed labels {labels_raw!r}")
        try:
            value = float(m.group("value"))
        except ValueError as e:
            raise ValueError(f"line {lineno}: bad value") from e
        fam["samples"][(name,) + labels] = value
    return metrics


# -- JSONL periodic writer -------------------------------------------------


class MetricsJsonlWriter:
    """Append-structured snapshot log: one JSON line per node per sweep.
    The caller owns cadence (an emulation fiber sleeping on the injected
    Clock); this class owns only deterministic serialization."""

    def __init__(self, path: str, exclude: Tuple[str, ...] = ()) -> None:
        self.path = path
        self.exclude = exclude
        self.num_lines = 0
        # truncate: an export file is one run's record, not an append log
        with open(path, "w"):
            pass

    def write_nodes(self, nodes: Iterable) -> int:
        """Capture + append one line per node (sorted by name for a
        stable inter-node order)."""
        snaps = [
            MetricsSnapshot.capture(node, exclude=self.exclude)
            for node in sorted(nodes, key=lambda n: n.name)
        ]
        with open(self.path, "a") as f:
            for s in snaps:
                f.write(s.to_jsonl() + "\n")
        self.num_lines += len(snaps)
        return len(snaps)
