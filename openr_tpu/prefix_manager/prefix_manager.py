"""PrefixManager — owns everything this node advertises.

Reference: openr/prefix-manager/PrefixManager.{h,cpp}:
  * receives PrefixEvents (plugins/API/config) per origination type and
    keeps the authoritative advertised set
  * advertises per-prefix keys ``prefix:<node>:[<prefix>]`` into KvStore
    via the kvRequestQueue (key format common/LsdbTypes.h:437-458)
  * config-originated prefixes with `minimum_supporting_routes`
    aggregation (OpenrConfig.thrift:345-441): the aggregate is advertised
    only while enough more-specific routes are present in the FIB view,
    and optionally installed locally via the static-routes channel
  * area redistribution (PrefixManager.cpp:1507, 1584): routes the FIB
    confirmed programming whose best entry came from area A are
    re-advertised into every other configured area with `area_stack`
    extended and distance accumulated — with loop prevention (never
    redistribute into an area already on the stack)
  * PREFIX_DB_SYNCED initialization event after the first KvStore sync
"""

from __future__ import annotations

import copy
import ipaddress
from typing import Callable, Dict, List, Optional, Set, Tuple

from openr_tpu import constants as C
from openr_tpu.common.runtime import Actor, Clock, CounterMap
from openr_tpu.config import OriginatedPrefix
from openr_tpu.decision.rib import DecisionRouteUpdate, RibUnicastEntry
from openr_tpu.messaging.queue import RQueue, ReplicateQueue
from openr_tpu.types import (
    InitializationEvent,
    KeyValueRequest,
    KvRequestType,
    NextHop,
    PrefixDatabase,
    PrefixEntry,
    PrefixEvent,
    PrefixEventType,
    PrefixMetrics,
    PrefixType,
    prefix_key,
)


def serialize_prefix_db(db: PrefixDatabase, fmt: str = "json") -> bytes:
    from openr_tpu.lsdb_codec import serialize_prefix_db as _ser

    return _ser(db, fmt)


def deserialize_prefix_db(data: bytes) -> PrefixDatabase:
    """Format-sniffing: JSON or the reference's thrift-compact bytes."""
    from openr_tpu.lsdb_codec import deserialize_prefix_db as _de

    return _de(data)


class PrefixManager(Actor):
    def __init__(
        self,
        node_name: str,
        clock: Clock,
        kv_request_queue: ReplicateQueue,
        static_route_updates_queue: Optional[ReplicateQueue] = None,
        prefix_updates_reader: Optional[RQueue] = None,
        fib_route_updates_reader: Optional[RQueue] = None,
        areas: Optional[List[str]] = None,
        originated_prefixes: Optional[List[OriginatedPrefix]] = None,
        initialization_cb: Optional[Callable[[InitializationEvent], None]] = None,
        counters: Optional[CounterMap] = None,
        policy_manager=None,
        area_import_policies: Optional[Dict[str, str]] = None,
        lsdb_wire_format: str = "json",
    ) -> None:
        super().__init__("prefix_manager", clock, counters)
        self.node_name = node_name
        #: flood-payload encoding ("json" | "thrift-compact") — see
        #: openr_tpu.lsdb_codec
        self.lsdb_wire_format = lsdb_wire_format
        self.kv_request_queue = kv_request_queue
        self.static_route_updates_queue = static_route_updates_queue
        self.prefix_updates_reader = prefix_updates_reader
        self.fib_route_updates_reader = fib_route_updates_reader
        self.areas = areas or [C.DEFAULT_AREA]
        self.originated = {p.prefix: p for p in (originated_prefixes or [])}
        self.initialization_cb = initialization_cb
        #: routing-policy engine (openr/policy/PolicyManager): applied at
        #: origination (per-OriginatedPrefix policy) and at area import
        #: during redistribution (AreaConfig.import_policy)
        self.policy_manager = policy_manager
        self.area_import_policies = area_import_policies or {}
        #: type -> prefix -> (entry, dst_areas)
        self.advertised: Dict[
            PrefixType, Dict[str, Tuple[PrefixEntry, Set[str]]]
        ] = {}
        #: originated prefix -> set of supporting more-specific prefixes
        self._supporting: Dict[str, Set[str]] = {
            p: set() for p in self.originated
        }
        self._originated_advertised: Set[str] = set()
        #: redistribution state: prefix -> (src_area, {dst_area: entry})
        #: (per-area entries: each destination's import policy may rewrite
        #: or reject the entry differently)
        self._redistributed: Dict[str, Tuple[str, Dict[str, PrefixEntry]]] = {}
        #: (area, key) currently present in kvstore
        self._advertised_keys: Set[Tuple[str, str]] = set()
        self._synced_signaled = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self.prefix_updates_reader is not None:
            self.spawn_queue_loop(
                self.prefix_updates_reader, self._on_prefix_event, "pm.events"
            )
        if self.fib_route_updates_reader is not None:
            self.spawn_queue_loop(
                self.fib_route_updates_reader, self._on_fib_update, "pm.fib"
            )
        # initial sync (possibly empty) then signal
        self.schedule(0.0, self._initial_sync)

    def _initial_sync(self) -> None:
        # aggregates whose support threshold is already met (notably
        # minimum_supporting_routes=0) advertise immediately — they must
        # not wait for a FIB update to touch them
        for agg, op in self.originated.items():
            self._refresh_originated(agg, op)
        self._sync_kv_store()
        if not self._synced_signaled:
            self._synced_signaled = True
            if self.initialization_cb is not None:
                self.initialization_cb(InitializationEvent.PREFIX_DB_SYNCED)

    # -- prefix events (PrefixManager.h:217 advertisePrefixesImpl) ---------

    def _on_prefix_event(self, ev: PrefixEvent) -> None:
        by_type = self.advertised.setdefault(ev.type, {})
        dst = set(ev.dst_areas) if ev.dst_areas else set(self.areas)
        if ev.event_type == PrefixEventType.ADD_PREFIXES:
            for entry in ev.prefixes:
                by_type[entry.prefix] = (entry, dst)
        elif ev.event_type == PrefixEventType.WITHDRAW_PREFIXES:
            for entry in ev.prefixes:
                by_type.pop(entry.prefix, None)
        elif ev.event_type == PrefixEventType.WITHDRAW_PREFIXES_BY_TYPE:
            by_type.clear()
        elif ev.event_type == PrefixEventType.SYNC_PREFIXES_BY_TYPE:
            by_type.clear()
            for entry in ev.prefixes:
                by_type[entry.prefix] = (entry, dst)
        self._sync_kv_store()

    # -- fib feedback: originated support + redistribution -----------------

    def _on_fib_update(self, update: DecisionRouteUpdate) -> None:
        changed = False
        for prefix, entry in update.unicast_routes_to_update.items():
            changed |= self._update_supporting(prefix, present=True)
            changed |= self._maybe_redistribute(prefix, entry)
        for prefix in update.unicast_routes_to_delete:
            changed |= self._update_supporting(prefix, present=False)
            changed |= self._withdraw_redistribution(prefix)
        if changed:
            self._sync_kv_store()

    # -- originated prefix aggregation (PrefixManager.h:325-346) -----------

    def _update_supporting(self, prefix: str, present: bool) -> bool:
        changed = False
        net = ipaddress.ip_network(prefix)
        for agg, op in self.originated.items():
            agg_net = ipaddress.ip_network(agg)
            if net.version != agg_net.version or net == agg_net:
                continue
            if not net.subnet_of(agg_net):
                continue
            before = len(self._supporting[agg])
            if present:
                self._supporting[agg].add(prefix)
            else:
                self._supporting[agg].discard(prefix)
            if len(self._supporting[agg]) != before:
                changed |= self._refresh_originated(agg, op)
        return changed

    def _refresh_originated(self, agg: str, op: OriginatedPrefix) -> bool:
        should = len(self._supporting[agg]) >= op.minimum_supporting_routes
        if should and agg not in self._originated_advertised:
            self._originated_advertised.add(agg)
            if op.install_to_fib and self.static_route_updates_queue is not None:
                self.static_route_updates_queue.push(
                    DecisionRouteUpdate(
                        unicast_routes_to_update={
                            agg: RibUnicastEntry(
                                prefix=agg,
                                nexthops={
                                    NextHop(address=C.LOCAL_ROUTE_NEXTHOP_V6)
                                },
                                do_not_install=False,
                            )
                        }
                    )
                )
            return True
        if not should and agg in self._originated_advertised:
            self._originated_advertised.discard(agg)
            if op.install_to_fib and self.static_route_updates_queue is not None:
                self.static_route_updates_queue.push(
                    DecisionRouteUpdate(unicast_routes_to_delete=[agg])
                )
            return True
        return False

    def _originated_entries(self) -> Dict[str, Tuple[PrefixEntry, Set[str]]]:
        out = {}
        for agg in self._originated_advertised:
            op = self.originated[agg]
            entry = PrefixEntry(
                prefix=agg,
                type=PrefixType.CONFIG,
                forwarding_type=op.forwarding_type,
                forwarding_algorithm=op.forwarding_algorithm,
                metrics=PrefixMetrics(
                    path_preference=op.path_preference,
                    source_preference=op.source_preference,
                ),
                tags=set(op.tags),
                min_nexthop=op.min_nexthop,
            )
            # origination policy (PrefixManager.cpp:480: applyPolicy at
            # origination; rejected => not advertised at all)
            policy = getattr(op, "origination_policy", None)
            if policy and self.policy_manager is not None:
                entry, _hit = self.policy_manager.apply_policy(policy, entry)
                if entry is None:
                    self.counters.bump("prefix_manager.policy.origination_rejects")
                    continue
            out[agg] = (entry, set(self.areas))
        return out

    # -- area redistribution (redistributePrefixesAcrossAreas) -------------

    def _maybe_redistribute(self, prefix: str, entry: RibUnicastEntry) -> bool:
        if len(self.areas) < 2:
            return False
        best = entry.best_prefix_entry
        src_area = entry.best_area
        if not src_area:
            return False
        # never re-advertise something we originate ourselves
        if any(prefix in by_type for by_type in self.advertised.values()):
            return False
        if prefix in self.originated:
            return False
        # loop prevention: target areas not on the path already
        stack = list(best.area_stack) + [src_area]
        dst = {a for a in self.areas if a != src_area and a not in stack}
        if not dst:
            return self._withdraw_redistribution(prefix)
        re_entry = copy.deepcopy(best)
        re_entry.area_stack = stack
        re_entry.metrics = PrefixMetrics(
            version=best.metrics.version,
            drain_metric=best.metrics.drain_metric,
            path_preference=best.metrics.path_preference,
            source_preference=best.metrics.source_preference,
            # accumulate the igp cost to reach the originator
            distance=best.metrics.distance + int(entry.igp_cost),
        )
        # per-destination import policy (AreaConfig.import_policy,
        # OpenrConfig.thrift:456-458; applyPolicy at area import,
        # PrefixManager.cpp:1135): rejected areas are simply not targeted
        per_area: Dict[str, PrefixEntry] = {}
        for area in dst:
            area_entry = re_entry
            policy = self.area_import_policies.get(area)
            if policy and self.policy_manager is not None:
                area_entry, _hit = self.policy_manager.apply_policy(
                    policy, re_entry, igp_cost=int(entry.igp_cost)
                )
                if area_entry is None:
                    self.counters.bump("prefix_manager.policy.import_rejects")
                    continue
            per_area[area] = area_entry
        if not per_area:
            return self._withdraw_redistribution(prefix)
        prior = self._redistributed.get(prefix)
        if prior is not None and prior == (src_area, per_area):
            return False
        self._redistributed[prefix] = (src_area, per_area)
        self.counters.bump("prefix_manager.redistributed")
        return True

    def _withdraw_redistribution(self, prefix: str) -> bool:
        return self._redistributed.pop(prefix, None) is not None

    # -- KvStore sync (syncKvStore, PrefixManager.cpp:617) -----------------

    def desired_advertisements(self) -> Dict[Tuple[str, str], PrefixEntry]:
        """(area, prefix) → entry: everything this node advertises into
        each area right now — API/plugin advertisements (best-per-prefix
        across types), config-originated aggregates, and cross-area
        redistribution.  Single source of truth for BOTH the KvStore sync
        and the ctrl area-view (so the operator surface can never drift
        from what is actually advertised)."""
        desired: Dict[Tuple[str, str], PrefixEntry] = {}
        # API/plugin advertisements; if the same prefix is advertised under
        # several types, resolve deterministically by best metrics (the
        # reference's per-prefix tie-break), ties by lower type value
        best_per_prefix: Dict[str, Tuple[tuple, PrefixEntry, Set[str]]] = {}
        for ptype in sorted(self.advertised):
            for prefix, (entry, dst_areas) in self.advertised[ptype].items():
                rank = (entry.metrics.sort_key(), int(ptype))
                prior = best_per_prefix.get(prefix)
                if prior is None or rank < prior[0]:
                    best_per_prefix[prefix] = (rank, entry, dst_areas)
        for prefix, (_rank, entry, dst_areas) in best_per_prefix.items():
            for area in dst_areas:
                desired[(area, prefix)] = entry
        # config-originated aggregates
        for prefix, (entry, dst_areas) in self._originated_entries().items():
            for area in dst_areas:
                desired[(area, prefix)] = entry
        # cross-area redistribution
        for prefix, (_src, per_area) in self._redistributed.items():
            for area, entry in per_area.items():
                desired[(area, prefix)] = entry
        return desired

    def _sync_kv_store(self) -> None:
        desired = {
            (area, prefix_key(self.node_name, prefix)): entry
            for (area, prefix), entry in self.desired_advertisements().items()
        }

        for (area, key), entry in desired.items():
            db = PrefixDatabase(
                this_node_name=self.node_name,
                prefix_entries=[entry],
                area=area,
            )
            self.kv_request_queue.push(
                KeyValueRequest(
                    request_type=KvRequestType.PERSIST_KEY,
                    area=area,
                    key=key,
                    value=serialize_prefix_db(db, self.lsdb_wire_format),
                )
            )
        # withdraw keys no longer desired: stop refreshing AND flood an
        # explicit deletePrefix tombstone so withdrawal propagates now
        # instead of at TTL expiry (reference withdraws via PrefixDatabase
        # deletePrefix=true, Types.thrift:436-439)
        for area, key in self._advertised_keys - set(desired):
            self.kv_request_queue.push(
                KeyValueRequest(
                    request_type=KvRequestType.CLEAR_KEY, area=area, key=key
                )
            )
            tombstone = PrefixDatabase(
                this_node_name=self.node_name,
                prefix_entries=[],
                delete_prefix=True,
                area=area,
            )
            self.kv_request_queue.push(
                KeyValueRequest(
                    request_type=KvRequestType.SET_KEY,
                    area=area,
                    key=key,
                    value=serialize_prefix_db(tombstone, self.lsdb_wire_format),
                )
            )
        self._advertised_keys = set(desired)
        self.counters.set(
            "prefix_manager.advertised_keys", len(self._advertised_keys)
        )

    # -- API (ctrl surface) ------------------------------------------------

    def advertise(
        self,
        entries: List[PrefixEntry],
        type: PrefixType = PrefixType.BREEZE,
        dst_areas: Optional[Set[str]] = None,
    ) -> None:
        self._on_prefix_event(
            PrefixEvent(PrefixEventType.ADD_PREFIXES, type, entries, dst_areas)
        )

    def withdraw(
        self, entries: List[PrefixEntry], type: PrefixType = PrefixType.BREEZE
    ) -> None:
        self._on_prefix_event(
            PrefixEvent(PrefixEventType.WITHDRAW_PREFIXES, type, entries)
        )

    def withdraw_by_type(self, type: PrefixType) -> None:
        """Drop every advertisement of one source type
        (withdrawPrefixesByType)."""
        self._on_prefix_event(
            PrefixEvent(PrefixEventType.WITHDRAW_PREFIXES_BY_TYPE, type, [])
        )

    def sync_by_type(
        self,
        type: PrefixType,
        entries: List[PrefixEntry],
        dst_areas: Optional[Set[str]] = None,
    ) -> None:
        """Replace one type's advertised set wholesale
        (syncPrefixesByType)."""
        self._on_prefix_event(
            PrefixEvent(
                PrefixEventType.SYNC_PREFIXES_BY_TYPE, type, entries, dst_areas
            )
        )

    def get_by_type(self, type: PrefixType) -> List[PrefixEntry]:
        """Advertised entries of one source type (getPrefixesByType)."""
        return [e for e, _ in self.advertised.get(type, {}).values()]

    def get_advertised_routes(self) -> List[PrefixEntry]:
        out = []
        for by_type in self.advertised.values():
            out.extend(e for e, _ in by_type.values())
        for prefix, (e, _) in self._originated_entries().items():
            out.append(e)
        return out

    def get_originated_prefixes(self) -> Dict[str, dict]:
        return {
            p: {
                "supporting_count": len(self._supporting[p]),
                "advertised": p in self._originated_advertised,
                "minimum_supporting_routes": op.minimum_supporting_routes,
            }
            for p, op in self.originated.items()
        }
