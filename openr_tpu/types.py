"""Core data model for openr-tpu.

These are idiomatic Python dataclasses carrying the same information as the
reference's thrift IDL (see /root/reference/openr/if/Types.thrift,
KvStore.thrift, Network.thrift, OpenrConfig.thrift).  They are the wire/type
contract (layer L0) shared by every module: the KvStore replicates serialized
`AdjacencyDatabase` / `PrefixDatabase` objects, Decision consumes them, Fib
programs `UnicastRoute`s derived from them.

Design notes (TPU build):
  * IP prefixes are canonical strings ("10.0.0.0/24", "::/0") rather than
    packed binary — the host protocol plane never does per-packet work, and
    strings keep the KvStore payloads debuggable.  The device compute plane
    never sees prefixes as strings; they are interned to dense int ids by
    ``openr_tpu.ops.csr`` before hitting the TPU.
  * Everything is msgpack/JSON-serializable via ``to_wire``/``from_wire`` so
    the RPC plane needs no IDL compiler.
"""

from __future__ import annotations

import dataclasses
import enum
import functools
import ipaddress
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple


# ---------------------------------------------------------------------------
# Enums (reference: openr/if/Types.thrift, OpenrConfig.thrift)
# ---------------------------------------------------------------------------


class DrainState(enum.IntEnum):
    """Node drain state (Types.thrift:30-34)."""

    UNDRAINED = 0
    HARD_DRAINED = 1
    SOFT_DRAINED = 2


class SparkNeighState(enum.IntEnum):
    """Spark neighbor FSM states (Types.thrift:51-57)."""

    IDLE = 0
    WARM = 1
    NEGOTIATE = 2
    ESTABLISHED = 3
    RESTART = 4


class SparkNeighEvent(enum.IntEnum):
    """Spark neighbor FSM events (Types.thrift:59-69)."""

    HELLO_RCVD_INFO = 0
    HELLO_RCVD_NO_INFO = 1
    HELLO_RCVD_RESTART = 2
    HEARTBEAT_RCVD = 3
    HANDSHAKE_RCVD = 4
    HEARTBEAT_TIMER_EXPIRE = 5
    NEGOTIATE_TIMER_EXPIRE = 6
    GR_TIMER_EXPIRE = 7
    NEGOTIATION_FAILURE = 8


class PrefixForwardingType(enum.IntEnum):
    """IP vs SR_MPLS forwarding (OpenrConfig.thrift:19-26)."""

    IP = 0
    SR_MPLS = 1


class PrefixForwardingAlgorithm(enum.IntEnum):
    """Route computation algorithm (OpenrConfig.thrift:28-41)."""

    SP_ECMP = 0
    KSP2_ED_ECMP = 1


class RouteComputationRules(enum.IntEnum):
    """Best-route selection algorithm (OpenrConfig.thrift:82-100)."""

    SHORTEST_DISTANCE = 0
    PER_AREA_SHORTEST_DISTANCE = 1


class PrefixType(enum.IntEnum):
    """Origin of a prefix advertisement (Network.thrift PrefixType)."""

    LOOPBACK = 1
    DEFAULT = 2
    BGP = 3
    PREFIX_ALLOCATOR = 4
    BREEZE = 5
    RIB = 6
    CONFIG = 7
    VIP = 8


class KvStorePeerState(enum.IntEnum):
    """KvStore peer FSM (KvStore.thrift:291-295)."""

    IDLE = 0
    SYNCING = 1
    INITIALIZED = 2


class KvStoreNoMergeReason(enum.IntEnum):
    """Why an incoming (key, value) was not merged (KvStore.thrift:176-184)."""

    UNKNOWN = 0
    NO_MATCHED_KEY = 1
    INVALID_TTL = 2
    OLD_VERSION = 3
    NO_NEED_TO_UPDATE = 4
    LOOP_DETECTED = 5
    INCONSISTENCY_DETECTED = 6


class InitializationEvent(enum.IntEnum):
    """Cold-start initialization sequence signals (KvStore.thrift:25-62,
    docs/Protocol_Guide/Initialization_Process.md)."""

    INITIALIZING = 0
    AGENT_CONFIGURED = 1
    LINK_DISCOVERED = 2
    NEIGHBOR_DISCOVERED = 3
    KVSTORE_SYNCED = 4
    RIB_COMPUTED = 5
    FIB_SYNCED = 6
    PREFIX_DB_SYNCED = 7
    INITIALIZED = 8


class LinkStatusEnum(enum.IntEnum):
    DOWN = 0
    UP = 1


# ---------------------------------------------------------------------------
# Wire helpers
# ---------------------------------------------------------------------------


#: exact-type fast path for the overwhelmingly common leaf values; an
#: IntEnum is an int subclass so `type(v) is int` stays correct for it
#: only via the explicit enum branch below (exact-type check excludes it)
_WIRE_PRIMITIVES = frozenset((str, int, float, bool, bytes, type(None)))


def _to_wire_value(v: Any) -> Any:
    # serialization runs per route per RPC: at serving-plane rates the
    # generic dataclass walk below is the ctrl plane's hottest loop, and
    # nearly every value is a primitive — test its exact type first
    if type(v) in _WIRE_PRIMITIVES:
        return v
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return v.to_wire()  # type: ignore[union-attr]
    if isinstance(v, enum.Enum):
        return int(v.value)
    if isinstance(v, dict):
        return {k: _to_wire_value(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_to_wire_value(x) for x in v]
    if isinstance(v, (set, frozenset)):
        return sorted(_to_wire_value(x) for x in v)
    return v


#: per-class codec cache: dataclasses.fields()/annotation resolution cost
#: real time when (de)serialization runs per prefix at benchmark scale
_CODEC_CACHE: Dict[type, tuple] = {}


@functools.lru_cache(maxsize=None)
def _cached_fields(cls) -> tuple:
    return tuple(dataclasses.fields(cls))


class Wire:
    """Mixin: flat dict serialization for RPC payloads and golden tests."""

    def to_wire(self) -> Dict[str, Any]:
        return {
            f.name: _to_wire_value(getattr(self, f.name))
            for f in _cached_fields(type(self))
        }

    @classmethod
    def from_wire(cls, d: Dict[str, Any]):
        codec = _CODEC_CACHE.get(cls)
        if codec is None:
            # built lazily at first use — by then every @wire_type class
            # and the enum registry are fully populated
            codec = _CODEC_CACHE[cls] = tuple(
                (f.name, _make_converter(str(f.type)))
                for f in _cached_fields(cls)
            )
        kwargs = {}
        for name, conv in codec:
            if name in d:
                v = d[name]
                kwargs[name] = None if v is None else conv(v)
        return cls(**kwargs)  # type: ignore[call-arg]


_WIRE_REGISTRY: Dict[str, type] = {}


def _make_converter(s: str):
    """Resolve one field annotation to a converter ONCE (the string scans
    over the registries used to run per field per message)."""
    for name, klass in _WIRE_REGISTRY.items():
        if s == name or s == f"Optional[{name}]":
            return lambda v, k=klass: (
                k.from_wire(v) if isinstance(v, dict) else v
            )
        if s in (f"List[{name}]", f"list[{name}]"):
            return lambda v, k=klass: (
                [k.from_wire(x) if isinstance(x, dict) else x for x in v]
                if isinstance(v, list)
                else v
            )
        if (
            s.startswith("Dict[str, ") or s.startswith("dict[str, ")
        ) and s.endswith(f"{name}]"):
            return lambda v, k=klass: (
                {
                    key: k.from_wire(x) if isinstance(x, dict) else x
                    for key, x in v.items()
                }
                if isinstance(v, dict)
                else v
            )
    if s.startswith("Set[") or s.startswith("set["):
        return set
    if s.startswith("Tuple[") or s.startswith("tuple["):
        return lambda v: tuple(v) if isinstance(v, list) else v
    if "Tuple[" in s:
        # e.g. Dict[str, Tuple[int, int]] — rebuild tuple values
        return lambda v: (
            {k: tuple(x) if isinstance(x, list) else x for k, x in v.items()}
            if isinstance(v, dict)
            else v
        )
    for e in _ENUM_REGISTRY:
        if s == e.__name__ or s == f"Optional[{e.__name__}]":
            return e
    return lambda v: v


def _all_enums() -> List[type]:
    import sys

    mod = sys.modules[__name__]
    return [
        obj
        for obj in vars(mod).values()
        if isinstance(obj, type) and issubclass(obj, enum.Enum) and obj is not enum.Enum
    ]


# Populated at end of module import (after all enums are defined).
_ENUM_REGISTRY: List[type] = []


def wire_type(cls):
    """Register a dataclass for nested from_wire reconstruction."""
    _WIRE_REGISTRY[cls.__name__] = cls
    return cls


def prefix_is_v4(prefix: str) -> bool:
    """Address family of a normalized prefix without the full ipaddress
    parse (the per-prefix ip_network() call was ~40% of route decode at
    10k prefixes; normalized v6 always contains ':')."""
    return ":" not in prefix


#: generation-swapped memo for normalize_prefix: two dicts, the active
#: one swapped out when it exceeds the cap.  The stable prefix table
#: stays hot (every pass re-sees it, re-inserting into the fresh dict
#: before the next swap) while churn of distinct prefixes — including a
#: buggy/hostile peer flooding unique prefixes forever (ADVICE r3) — can
#: retain at most 2 * _NORM_CACHE_MAX entries instead of growing
#: monotonically the way an unbounded lru_cache did.  An LRU bound would
#: instead flood to ~0% hits: each pass re-visits the whole table in
#: roughly the same order.
_NORM_CACHE_MAX = 1_000_000
_norm_active: dict = {}
_norm_stale: dict = {}


def normalize_prefix(prefix: str) -> str:
    """Canonicalize an IP prefix string (host bits zeroed)."""
    global _norm_active, _norm_stale
    v = _norm_active.get(prefix)
    if v is not None:
        return v
    v = _norm_stale.get(prefix)
    if v is None:
        v = str(ipaddress.ip_network(prefix, strict=False))
    if len(_norm_active) >= _NORM_CACHE_MAX:
        _norm_stale = _norm_active
        _norm_active = {}
    _norm_active[prefix] = v
    return v


# ---------------------------------------------------------------------------
# Performance-event breadcrumbs (Types.thrift:80-96) + causal trace context
# ---------------------------------------------------------------------------


@wire_type
@dataclass
class TraceContext(Wire):
    """Causal-trace propagation handle (openr_tpu.tracing).

    Minted by a Tracer at an event origin (Spark neighbor up/down,
    LinkMonitor interface event, KvStore key arrival) and carried through
    queue items, KvStore flooding metadata (Publication.trace_ctx) and
    flooded LSDB payloads (PerfEvents.trace_context) so every stage's
    span — on every node the event reaches — shares one ``trace_id``.
    ``span_id`` names the nearest upstream span (the parent for the next
    stage); origin fields stay pinned to the minting event so the closing
    stage (Fib programming ack) can compute end-to-end latency from
    ``t0_ms`` without walking the tree.
    """

    trace_id: str = ""
    span_id: str = ""
    origin_node: str = ""
    origin_event: str = ""
    t0_ms: int = 0


@wire_type
@dataclass
class PerfEvent(Wire):
    node_name: str
    event_descr: str
    unix_ts_ms: int = 0


@wire_type
@dataclass
class PerfEvents(Wire):
    """Ordered breadcrumb list for convergence-latency measurement; newest
    event appended at the back (Types.thrift:88-96)."""

    events: List[PerfEvent] = field(default_factory=list)
    #: causal-trace handle riding the flooded LSDB payload: survives
    #: KvStore storage, so even keys delivered later via full sync keep
    #: their origin trace (openr_tpu.tracing)
    trace_context: Optional[TraceContext] = None

    def add(self, node: str, descr: str, ts_ms: int) -> None:
        self.events.append(PerfEvent(node, descr, ts_ms))

    def total_duration_ms(self) -> int:
        if len(self.events) < 2:
            return 0
        return self.events[-1].unix_ts_ms - self.events[0].unix_ts_ms


# ---------------------------------------------------------------------------
# Link-state types (Types.thrift:145-270)
# ---------------------------------------------------------------------------


@wire_type
@dataclass
class Adjacency(Wire):
    """One established adjacency (Types.thrift:145-213)."""

    other_node_name: str
    if_name: str
    metric: int = 1
    #: SR adjacency-segment label; node-local, 0 = invalid (Types.thrift:174-179)
    adj_label: int = 0
    #: drain bit: adjacency unavailable for transit (Types.thrift:181-185)
    is_overloaded: bool = False
    #: round-trip time to neighbor, microseconds
    rtt: int = 0
    #: adjacency establishment time (s since epoch)
    timestamp: int = 0
    #: weighted-ECMP weight (unused by routing, carried for parity)
    weight: int = 1
    other_if_name: str = ""
    #: if true, only the neighbor may use this adj for routing
    #: (Types.thrift:206-212, used for initialization warm-up)
    adj_only_used_by_other_node: bool = False
    #: IPv6 link-local / IPv4 nexthop addresses of neighbor over if_name
    next_hop_v6: str = ""
    next_hop_v4: str = ""


@wire_type
@dataclass
class LinkStatusRecords(Wire):
    """if_name -> (LinkStatusEnum, unix_ts) (Types.thrift:99-133)."""

    link_status_map: Dict[str, Tuple[int, int]] = field(default_factory=dict)


@wire_type
@dataclass
class AdjacencyDatabase(Wire):
    """Per-(node, area) link state, flooded under key ``adj:<node>``
    (Types.thrift:223-270)."""

    this_node_name: str
    is_overloaded: bool = False  # hard drain: no transit through this node
    adjacencies: List[Adjacency] = field(default_factory=list)
    #: SR nodal segment label, globally unique, 0 = invalid
    node_label: int = 0
    perf_events: Optional[PerfEvents] = None
    area: str = "0"
    #: soft drain: added to every link metric through this node
    node_metric_increment_val: int = 0
    link_status_records: Optional[LinkStatusRecords] = None


# ---------------------------------------------------------------------------
# Prefix types (Types.thrift:287-430)
# ---------------------------------------------------------------------------


@wire_type
@dataclass(frozen=True)
class PrefixMetrics(Wire):
    """Best-prefix-selection metric chain (Types.thrift:287-347).

    Tie-break order (openr/decision/PrefixState + RibEntry semantics):
      1. drain_metric       prefer LOWER
      2. path_preference    prefer HIGHER
      3. source_preference  prefer HIGHER
      4. distance           prefer LOWER
    """

    version: int = 1
    drain_metric: int = 0
    path_preference: int = 0
    source_preference: int = 0
    distance: int = 0

    def sort_key(self) -> Tuple[int, int, int, int]:
        """Lower sorts better."""
        return (
            self.drain_metric,
            -self.path_preference,
            -self.source_preference,
            self.distance,
        )


@wire_type
@dataclass
class PrefixEntry(Wire):
    """One advertised route (Types.thrift:349-413)."""

    prefix: str
    type: PrefixType = PrefixType.LOOPBACK
    forwarding_type: PrefixForwardingType = PrefixForwardingType.IP
    forwarding_algorithm: PrefixForwardingAlgorithm = (
        PrefixForwardingAlgorithm.SP_ECMP
    )
    #: if set, Decision withholds the route unless >= this many nexthops
    min_nexthop: Optional[int] = None
    metrics: PrefixMetrics = field(default_factory=PrefixMetrics)
    tags: Set[str] = field(default_factory=set)
    #: areas traversed; [0] = originating area, appended on redistribution;
    #: used for inter-area loop prevention (Decision.cpp:762-773)
    area_stack: List[str] = field(default_factory=list)
    weight: Optional[int] = None

    def __post_init__(self) -> None:
        self.prefix = normalize_prefix(self.prefix)


@wire_type
@dataclass
class PrefixDatabase(Wire):
    """Route advertisement flooded under ``prefix:<node>:[<prefix>]``
    (Types.thrift:415-440)."""

    this_node_name: str
    prefix_entries: List[PrefixEntry] = field(default_factory=list)
    perf_events: Optional[PerfEvents] = None
    #: per-prefix-key deletion marker (reference advertises deletion by
    #: flooding a PrefixDatabase with deletePrefix=true)
    delete_prefix: bool = False
    area: str = "0"


# ---------------------------------------------------------------------------
# KvStore types (KvStore.thrift:100-420)
# ---------------------------------------------------------------------------


@wire_type
@dataclass
class Value(Wire):
    """Replicated KV value with eventual-consistency attributes
    (KvStore.thrift:100-151).

    Conflict resolution (KvStoreUtil.cpp:470 compareValues): higher
    ``version`` wins; then higher ``originator_id``; then larger ``value``;
    then higher ``ttl_version``.  Version 0 is undefined/uninitialized.
    """

    version: int = 0
    originator_id: str = ""
    #: opaque application payload; None = TTL-refresh-only update
    value: Optional[bytes] = None
    ttl: int = -1  # milliseconds; Constants.kTtlInfinity == INT32_MIN
    ttl_version: int = 0
    hash: Optional[int] = None

    def to_wire(self) -> Dict[str, Any]:
        d = super().to_wire()
        if isinstance(d.get("value"), bytes):
            d["value"] = d["value"].hex()
            d["_value_hex"] = True
        return d

    @classmethod
    def from_wire(cls, d: Dict[str, Any]) -> "Value":
        d = dict(d)
        if d.pop("_value_hex", False) and d.get("value") is not None:
            d["value"] = bytes.fromhex(d["value"])
        return super().from_wire(d)  # type: ignore[return-value]


KeyVals = Dict[str, Value]


@wire_type
@dataclass
class Publication(Wire):
    """KvStore delta / dump / sync response (KvStore.thrift:347-400)."""

    key_vals: Dict[str, Value] = field(default_factory=dict)
    expired_keys: List[str] = field(default_factory=list)
    #: flood-loop prevention: node ids this publication traversed
    node_ids: Optional[List[str]] = None
    #: full-sync response: keys the responder wants back from the initiator
    tobe_updated_keys: Optional[List[str]] = None
    area: str = "0"
    timestamp_ms: Optional[int] = None
    #: flooding metadata: causal-trace handle carried hop by hop with the
    #: publication (openr_tpu.tracing); None when tracing is disabled
    trace_ctx: Optional[TraceContext] = None


@wire_type
@dataclass
class PeerSpec(Wire):
    """How to reach a KvStore peer (KvStore.thrift PeerSpec)."""

    peer_addr: str = ""
    ctrl_port: int = 0
    state: KvStorePeerState = KvStorePeerState.IDLE
    flaps: int = 0
    num_thrift_failures: int = 0
    #: peer advertised DUAL support in the Spark handshake; non-supporting
    #: peers keep receiving full floods even when an SPT is converged
    supports_flood_optimization: bool = False


@wire_type
@dataclass
class KvStoreAreaSummary(Wire):
    area: str = "0"
    peers_map: Dict[str, PeerSpec] = field(default_factory=dict)
    key_vals_count: int = 0
    key_vals_bytes: int = 0


# ---------------------------------------------------------------------------
# Routes (Network.thrift UnicastRoute/MplsRoute, fib/)
# ---------------------------------------------------------------------------


class MplsActionCode(enum.IntEnum):
    """MPLS label actions (Network.thrift MplsActionCode)."""

    PUSH = 0
    SWAP = 1
    PHP = 2  # Penultimate hop popping: implicit-null
    POP_AND_LOOKUP = 3


@wire_type
@dataclass(frozen=True)
class MplsAction(Wire):
    action: MplsActionCode = MplsActionCode.PHP
    swap_label: Optional[int] = None
    push_labels: Optional[Tuple[int, ...]] = None

    def __post_init__(self):
        if self.push_labels is not None and not isinstance(self.push_labels, tuple):
            object.__setattr__(self, "push_labels", tuple(self.push_labels))


@wire_type
@dataclass(frozen=True)
class NextHop(Wire):
    """A route nexthop (Network.thrift NextHopThrift): address + interface,
    weight (0 = ECMP), optional MPLS action, and the metric/area it came
    from."""

    address: str = ""
    if_name: str = ""
    metric: int = 0
    weight: int = 0
    area: str = ""
    neighbor_node_name: str = ""
    mpls_action: Optional[MplsAction] = None


@wire_type
@dataclass
class UnicastRoute(Wire):
    dest: str = ""
    next_hops: List[NextHop] = field(default_factory=list)


@wire_type
@dataclass
class MplsRoute(Wire):
    top_label: int = 0
    next_hops: List[NextHop] = field(default_factory=list)


@wire_type
@dataclass
class RouteDatabase(Wire):
    this_node_name: str = ""
    unicast_routes: List[UnicastRoute] = field(default_factory=list)
    mpls_routes: List[MplsRoute] = field(default_factory=list)
    perf_events: Optional[PerfEvents] = None


@wire_type
@dataclass
class RouteDatabaseDelta(Wire):
    unicast_routes_to_update: List[UnicastRoute] = field(default_factory=list)
    unicast_routes_to_delete: List[str] = field(default_factory=list)
    mpls_routes_to_update: List[MplsRoute] = field(default_factory=list)
    mpls_routes_to_delete: List[int] = field(default_factory=list)
    perf_events: Optional[PerfEvents] = None


# ---------------------------------------------------------------------------
# Module event types (queue payloads; common/LsdbTypes.h equivalents)
# ---------------------------------------------------------------------------


class NeighborEventType(enum.IntEnum):
    """Spark -> LinkMonitor events (common/NeighborEvents in LsdbTypes.h)."""

    NEIGHBOR_UP = 0
    NEIGHBOR_DOWN = 1
    NEIGHBOR_RESTARTED = 2
    NEIGHBOR_RTT_CHANGE = 3
    NEIGHBOR_RESTARTING = 4
    NEIGHBOR_ADJ_SYNCED = 5


@wire_type
@dataclass
class NeighborEvent(Wire):
    event_type: NeighborEventType
    node_name: str
    area: str = "0"
    local_if_name: str = ""
    remote_if_name: str = ""
    neighbor_addr_v6: str = ""
    neighbor_addr_v4: str = ""
    ctrl_port: int = 0
    rtt_us: int = 0
    kv_label: int = 0
    adj_only_used_by_other_node: bool = False
    enable_flood_optimization: bool = False
    #: causal-trace handle minted by Spark at the FSM transition
    trace_ctx: Optional[TraceContext] = None


class PeerEventType(enum.IntEnum):
    ADD = 0
    DEL = 1


@dataclass
class PeerEvent:
    """LinkMonitor -> KvStore/Decision peer changes, per area."""

    area: str
    peers_to_add: Dict[str, PeerSpec] = field(default_factory=dict)
    peers_to_del: List[str] = field(default_factory=list)


@dataclass
class InterfaceInfo:
    """Kernel view of one interface (Types.thrift:123-139)."""

    if_name: str
    is_up: bool = False
    if_index: int = -1
    networks: List[str] = field(default_factory=list)

    def v6_link_local(self) -> Optional[str]:
        for n in self.networks:
            addr = ipaddress.ip_interface(n)
            if addr.version == 6 and addr.is_link_local:
                return str(addr.ip)
        return None

    def v4_addr(self) -> Optional[str]:
        for n in self.networks:
            addr = ipaddress.ip_interface(n)
            if addr.version == 4:
                return str(addr.ip)
        return None


@dataclass
class InterfaceDatabase:
    """LinkMonitor -> Spark interface snapshot."""

    interfaces: Dict[str, InterfaceInfo] = field(default_factory=dict)


class PrefixEventType(enum.IntEnum):
    ADD_PREFIXES = 0
    WITHDRAW_PREFIXES = 1
    WITHDRAW_PREFIXES_BY_TYPE = 2
    SYNC_PREFIXES_BY_TYPE = 3


@dataclass
class PrefixEvent:
    """API/plugins -> PrefixManager advertisement requests."""

    event_type: PrefixEventType
    type: PrefixType = PrefixType.DEFAULT
    prefixes: List[PrefixEntry] = field(default_factory=list)
    dst_areas: Optional[Set[str]] = None


class KvRequestType(enum.IntEnum):
    PERSIST_KEY = 0
    SET_KEY = 1
    CLEAR_KEY = 2


@dataclass
class KeyValueRequest:
    """PrefixManager/LinkMonitor -> KvStore self-originated key ops
    (kvstore self-originated key API, KvStore.h:196-215)."""

    request_type: KvRequestType
    area: str
    key: str
    value: bytes = b""
    version: Optional[int] = None
    #: causal-trace handle from the requesting module (LinkMonitor adj
    #: advertisement, PrefixManager) — KvStore attaches it to the
    #: resulting local publication + flood
    trace_ctx: Optional[TraceContext] = None


@dataclass
class AddressEvent:
    """NeighborMonitor -> Spark (LAG down detection etc.)."""

    address: str
    is_reachable: bool


@dataclass
class LogSample:
    """Structured event-log record -> Monitor (monitor/LogSample.h)."""

    event: str
    attributes: Dict[str, Any] = field(default_factory=dict)
    timestamp_ms: int = 0


# ---------------------------------------------------------------------------
# Key naming (common/Constants + LsdbTypes key formats)
# ---------------------------------------------------------------------------

ADJ_DB_MARKER = "adj:"
PREFIX_DB_MARKER = "prefix:"


def adj_key(node: str) -> str:
    return f"{ADJ_DB_MARKER}{node}"


def prefix_key(node: str, prefix: str) -> str:
    """Per-prefix key format ``prefix:<node>:[<prefix>]``
    (common/LsdbTypes.h:437-458)."""
    return f"{PREFIX_DB_MARKER}{node}:[{normalize_prefix(prefix)}]"


def parse_adj_key(key: str) -> Optional[str]:
    if not key.startswith(ADJ_DB_MARKER):
        return None
    return key[len(ADJ_DB_MARKER):]


def parse_prefix_key(key: str) -> Optional[Tuple[str, str]]:
    """Return (node, prefix) or None."""
    if not key.startswith(PREFIX_DB_MARKER):
        return None
    body = key[len(PREFIX_DB_MARKER):]
    if not body.endswith("]") or ":[" not in body:
        return None
    node, _, rest = body.partition(":[")
    return node, rest[:-1]


#: fleet-liveness heartbeat key family (openr_tpu.fleet.liveness): each
#: member advertises ``fleet:member:<name>`` as a TTL-bearing key whose
#: value carries its incarnation (the PR-12 ``node.start_ms`` stamp) and
#: a per-incarnation heartbeat seq — membership is DERIVED from key
#: arrival/TTL-expiry, the same eventually-consistent machinery the
#: fleet routes with
FLEET_MEMBER_MARKER = "fleet:member:"


def fleet_member_key(node: str) -> str:
    return f"{FLEET_MEMBER_MARKER}{node}"


def parse_fleet_member_key(key: str) -> Optional[str]:
    if not key.startswith(FLEET_MEMBER_MARKER):
        return None
    return key[len(FLEET_MEMBER_MARKER):]


_ENUM_REGISTRY.extend(_all_enums())
