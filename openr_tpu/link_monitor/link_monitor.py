"""LinkMonitor — interface tracking, adjacency maintenance, drain ops.

Reference: openr/link-monitor/LinkMonitor.{h,cpp}.  Responsibilities:
  * track kernel interfaces (platform events + periodic sync) with
    link-flap exponential backoff (OpenrConfig.thrift:119-146)
  * publish the interface snapshot to Spark (interfaceUpdatesQueue)
  * consume Spark NeighborEvents → per-area AdjacencyDatabase; advertise
    ``adj:<node>`` into KvStore via kvRequestQueue (LinkMonitor.cpp:741)
  * emit KvStore peer add/del on peerUpdatesQueue (restarting peers are
    removed from flooding but their adjacency is held)
  * drain operations: node overload (hard), node metric increment (soft),
    per-link overload / metric override (LinkMonitor.h:107-150), persisted
    across restarts via the config store
  * RTT-based adjacency metric option (OpenrConfig.thrift:142-146)
  * LINK_DISCOVERED initialization event after the first interface sync
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Set, Tuple

from openr_tpu import constants as C
from openr_tpu.common.runtime import Actor, Clock, CounterMap
from openr_tpu.common.utils import AsyncThrottle, ExponentialBackoff
from openr_tpu.config import LinkMonitorConfig
from openr_tpu.messaging.queue import RQueue, ReplicateQueue
from openr_tpu.types import (
    Adjacency,
    AdjacencyDatabase,
    InitializationEvent,
    InterfaceDatabase,
    InterfaceInfo,
    KeyValueRequest,
    KvRequestType,
    NeighborEvent,
    NeighborEventType,
    PeerEvent,
    PeerSpec,
    PerfEvents,
    adj_key,
)


def rtt_to_metric(rtt_us: int) -> int:
    """RTT-proportional metric, 100us granularity (reference getRttMetric)."""
    return max(1, rtt_us // 100)


@dataclasses.dataclass
class AdjacencyEntry:
    """One established adjacency (link-monitor/AdjacencyEntry.h)."""

    neighbor: str
    area: str
    local_if: str
    remote_if: str
    addr_v6: str = ""
    addr_v4: str = ""
    ctrl_port: int = 0
    rtt_us: int = 0
    metric_override: Optional[int] = None  # set-link-metric drain op
    is_overloaded: bool = False  # link hard-drain
    is_restarting: bool = False
    adj_only_used_by_other_node: bool = False
    timestamp: int = 0
    adj_label: int = 0


@dataclasses.dataclass
class InterfaceEntry:
    """Tracked interface w/ flap damping (link-monitor/InterfaceEntry.h)."""

    info: InterfaceInfo
    backoff: ExponentialBackoff = None  # type: ignore[assignment]
    #: advertised to Spark only when up AND backoff inactive
    active: bool = False
    #: pending activation timer; re-flaps must cancel it or the stale timer
    #: defeats the doubled damping window
    activate_task: object = None


class LinkMonitor(Actor):
    def __init__(
        self,
        node_name: str,
        clock: Clock,
        config: LinkMonitorConfig,
        interface_updates_queue: ReplicateQueue,
        peer_updates_queue: ReplicateQueue,
        kv_request_queue: ReplicateQueue,
        neighbor_updates_reader: Optional[RQueue] = None,
        netlink_events_reader: Optional[RQueue] = None,
        area_ids: Optional[List[str]] = None,
        node_labels: Optional[Dict[str, int]] = None,  # area -> SR label
        initialization_cb: Optional[Callable[[InitializationEvent], None]] = None,
        counters: Optional[CounterMap] = None,
        serialize_adj_db: Optional[Callable[[AdjacencyDatabase], bytes]] = None,
        tracer=None,
    ) -> None:
        super().__init__("link_monitor", clock, counters)
        from openr_tpu.tracing import disabled_tracer

        self.tracer = tracer if tracer is not None else disabled_tracer()
        #: context of the EARLIEST traced event awaiting the throttled
        #: adjacency advertisement (the advertisement is the span that
        #: hands the trace to KvStore).  Earliest — not most recent: when
        #: several events coalesce into one advertisement, last-writer-
        #: wins would embed whichever event's fiber happened to run last
        #: into the flooded value bytes (schedule-dependent LSDB hash);
        #: picking min (t0_ms, trace_id) is order-free, and the earliest
        #: cause is the right start for the convergence clock anyway.
        self._pending_trace_ctx = None
        self.node_name = node_name
        self.config = config
        self.interface_updates_queue = interface_updates_queue
        self.peer_updates_queue = peer_updates_queue
        self.kv_request_queue = kv_request_queue
        self.neighbor_updates_reader = neighbor_updates_reader
        self.netlink_events_reader = netlink_events_reader
        self.area_ids = area_ids or [C.DEFAULT_AREA]
        self.node_labels = node_labels or {}
        self.initialization_cb = initialization_cb
        self.serialize_adj_db = serialize_adj_db or (
            lambda db: __import__("json").dumps(db.to_wire()).encode()
        )
        import re as _re

        self._include_if_res = [
            _re.compile(p)
            for p in getattr(config, "include_interface_regexes", [".*"])
        ]
        self._exclude_if_res = [
            _re.compile(p)
            for p in getattr(config, "exclude_interface_regexes", [])
        ]
        self.interfaces: Dict[str, InterfaceEntry] = {}
        #: (area, neighbor, local_if) -> AdjacencyEntry
        self.adjacencies: Dict[Tuple[str, str, str], AdjacencyEntry] = {}
        # drain state (persisted via config-store by the daemon wrapper)
        self.node_overloaded = False
        self.node_metric_increment = 0
        self.link_overloads: Set[str] = set()  # if_names
        self.link_metric_overrides: Dict[str, int] = {}
        #: per-adjacency metric override, keyed (local_if, neighbor node)
        #: — more specific than a link override (setAdjacencyMetric,
        #: LinkMonitor.h:118-124)
        self.adj_metric_overrides: Dict[Tuple[str, str], int] = {}
        #: per-interface soft-drain increment added on top of the computed
        #: metric (setInterfaceMetricIncrement, LinkMonitor.h:135-146)
        self.link_metric_increments: Dict[str, int] = {}
        self._link_discovered_signaled = False
        # throttles (Constants.h:95-100)
        self._advertise_ifaces_throttle = AsyncThrottle(
            self, C.LINK_THROTTLE_TIMEOUT_S, self._advertise_interfaces
        )
        self._advertise_adjs_throttle = AsyncThrottle(
            self, C.ADJACENCY_THROTTLE_TIMEOUT_S, self._advertise_adjacencies
        )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self.neighbor_updates_reader is not None:
            self.spawn_queue_loop(
                self.neighbor_updates_reader, self._on_neighbor_event, "lm.neighbors"
            )
        if self.netlink_events_reader is not None:
            self.spawn_queue_loop(
                self.netlink_events_reader, self._on_interface_event, "lm.netlink"
            )

    # -- interface tracking ------------------------------------------------

    def set_interfaces(self, infos: List[InterfaceInfo]) -> None:
        """Full interface sync (platform getAllLinks); first call signals
        LINK_DISCOVERED."""
        seen = set()
        for info in infos:
            seen.add(info.if_name)
            self._apply_interface(info)
        for if_name in list(self.interfaces):
            if if_name not in seen:
                self._apply_interface(
                    InterfaceInfo(if_name=if_name, is_up=False)
                )
        if not self._link_discovered_signaled:
            self._link_discovered_signaled = True
            if self.initialization_cb is not None:
                self.initialization_cb(InitializationEvent.LINK_DISCOVERED)
        self._advertise_ifaces_throttle()

    def _on_interface_event(self, info: InterfaceInfo) -> None:
        """Incremental netlink event."""
        self._apply_interface(info)
        self._advertise_ifaces_throttle()

    def _interface_allowed(self, if_name: str) -> bool:
        """Config regex gate (OpenrConfig.thrift include/exclude interface
        regexes): exclusion wins, then inclusion must match."""
        for pat in self._exclude_if_res:
            if pat.fullmatch(if_name):
                return False
        return any(pat.fullmatch(if_name) for pat in self._include_if_res)

    def _apply_interface(self, info: InterfaceInfo) -> None:
        if not self._interface_allowed(info.if_name):
            return
        entry = self.interfaces.get(info.if_name)
        if (
            self.tracer.enabled
            and entry is not None
            and entry.info.is_up != info.is_up
        ):
            # trace origin: an interface state change (netlink event or
            # platform sync delta) starts a convergence clock
            self._note_pending_ctx(self.tracer.start_trace(
                f"link_monitor.interface_{'up' if info.is_up else 'down'}",
                module="link_monitor",
                if_name=info.if_name,
            ))
        if entry is None:
            entry = InterfaceEntry(
                info=info,
                backoff=ExponentialBackoff(
                    self.config.linkflap_initial_backoff_ms / 1000.0,
                    self.config.linkflap_max_backoff_ms / 1000.0,
                    self.clock,
                ),
            )
            self.interfaces[info.if_name] = entry
            entry.active = info.is_up
            return
        was_up = entry.info.is_up
        entry.info = info
        if info.is_up and not was_up:
            # flap damping: delay activation by current backoff
            entry.backoff.report_error()
            delay = entry.backoff.get_current_backoff()
            self.counters.bump("link_monitor.link_flaps")
            if entry.activate_task is not None:
                entry.activate_task.cancel()
            entry.activate_task = self.schedule(
                delay, lambda e=entry: self._activate_interface(e)
            )
            entry.active = False
        elif not info.is_up and was_up:
            if entry.activate_task is not None:
                entry.activate_task.cancel()
                entry.activate_task = None
            entry.active = False
            # tear down adjacencies on this interface
            for key, adj in list(self.adjacencies.items()):
                if adj.local_if == info.if_name:
                    self._remove_adjacency(key)

    def _activate_interface(self, entry: InterfaceEntry) -> None:
        if entry.info.is_up:
            entry.active = True
            self._advertise_ifaces_throttle()

    def _advertise_interfaces(self) -> None:
        db = InterfaceDatabase(
            interfaces={
                n: e.info for n, e in self.interfaces.items() if e.active
            }
        )
        self.interface_updates_queue.push(db)

    # -- neighbor events (LinkMonitor.h:176) -------------------------------

    def _note_pending_ctx(self, ctx) -> None:
        """Fold one traced cause into the pending advertisement's context
        by min (t0_ms, trace_id) — deterministic under any processing
        order of same-instant events."""
        if ctx is None:
            return
        cur = self._pending_trace_ctx
        if cur is None or (ctx.t0_ms, ctx.trace_id) < (cur.t0_ms, cur.trace_id):
            self._pending_trace_ctx = ctx

    def _on_neighbor_event(self, ev: NeighborEvent) -> None:
        if ev.trace_ctx is not None:
            span = self.tracer.instant(
                "link_monitor.neighbor_event",
                ev.trace_ctx,
                module="link_monitor",
                event=ev.event_type.name,
                neighbor=ev.node_name,
            )
            self._note_pending_ctx(self.tracer.child_ctx(
                span, ev.trace_ctx
            ))
        key = (ev.area, ev.node_name, ev.local_if_name)
        if ev.event_type == NeighborEventType.NEIGHBOR_UP:
            self.adjacencies[key] = AdjacencyEntry(
                neighbor=ev.node_name,
                area=ev.area,
                local_if=ev.local_if_name,
                remote_if=ev.remote_if_name,
                addr_v6=ev.neighbor_addr_v6,
                addr_v4=ev.neighbor_addr_v4,
                ctrl_port=ev.ctrl_port,
                rtt_us=ev.rtt_us,
                adj_only_used_by_other_node=ev.adj_only_used_by_other_node,
                timestamp=int(self.clock.now()),
            )
            self._peer_up(ev)
            self._advertise_adjs_throttle()
        elif ev.event_type == NeighborEventType.NEIGHBOR_DOWN:
            self._remove_adjacency(key)
        elif ev.event_type == NeighborEventType.NEIGHBOR_RESTARTING:
            adj = self.adjacencies.get(key)
            if adj is not None:
                adj.is_restarting = True
            # remove from flooding topology while it restarts
            self.peer_updates_queue.push(
                PeerEvent(area=ev.area, peers_to_del=[ev.node_name])
            )
        elif ev.event_type == NeighborEventType.NEIGHBOR_RESTARTED:
            adj = self.adjacencies.get(key)
            if adj is not None:
                adj.is_restarting = False
            self._peer_up(ev)
            self._advertise_adjs_throttle()
        elif ev.event_type == NeighborEventType.NEIGHBOR_RTT_CHANGE:
            adj = self.adjacencies.get(key)
            if adj is not None:
                adj.rtt_us = ev.rtt_us
                if self.config.use_rtt_metric:
                    self._advertise_adjs_throttle()
        elif ev.event_type == NeighborEventType.NEIGHBOR_ADJ_SYNCED:
            adj = self.adjacencies.get(key)
            if adj is not None:
                adj.adj_only_used_by_other_node = False
                self._advertise_adjs_throttle()

    def _peer_up(self, ev: NeighborEvent) -> None:
        # a bare fe80:: address is unroutable without a scope; qualify it
        # with the local interface the neighbor was heard on so the KvStore
        # transport can actually dial it (the reference carries the scope
        # the same way in its thrift peer addr)
        peer_addr = ev.neighbor_addr_v6 or ev.node_name
        if peer_addr.startswith("fe80:") and "%" not in peer_addr:
            peer_addr = f"{peer_addr}%{ev.local_if_name}"
        self.peer_updates_queue.push(
            PeerEvent(
                area=ev.area,
                peers_to_add={
                    ev.node_name: PeerSpec(
                        peer_addr=peer_addr,
                        ctrl_port=ev.ctrl_port,
                        supports_flood_optimization=ev.enable_flood_optimization,
                    )
                },
            )
        )

    def _remove_adjacency(self, key: Tuple[str, str, str]) -> None:
        adj = self.adjacencies.pop(key, None)
        if adj is None:
            return
        # only delete the kvstore peer if no other adjacency to that node
        # remains in the area
        if not any(
            a.neighbor == adj.neighbor and a.area == adj.area
            for a in self.adjacencies.values()
        ):
            self.peer_updates_queue.push(
                PeerEvent(area=adj.area, peers_to_del=[adj.neighbor])
            )
        self._advertise_adjs_throttle()

    # -- adjacency advertisement (advertiseAdjacencies) --------------------

    def _adjacency_metric(self, adj: AdjacencyEntry) -> int:
        inc = self.link_metric_increments.get(adj.local_if, 0)
        ov = self.adj_metric_overrides.get((adj.local_if, adj.neighbor))
        if ov is not None:  # most-specific override wins
            return ov + inc
        if adj.local_if in self.link_metric_overrides:
            return self.link_metric_overrides[adj.local_if] + inc
        if adj.metric_override is not None:
            return adj.metric_override + inc
        if self.config.use_rtt_metric and adj.rtt_us > 0:
            return rtt_to_metric(adj.rtt_us) + inc
        return 1 + inc

    def build_adjacency_database(self, area: str) -> AdjacencyDatabase:
        adjacencies = []
        for adj in self.adjacencies.values():
            if adj.area != area:
                continue
            adjacencies.append(
                Adjacency(
                    other_node_name=adj.neighbor,
                    if_name=adj.local_if,
                    other_if_name=adj.remote_if,
                    metric=self._adjacency_metric(adj),
                    adj_label=adj.adj_label,
                    is_overloaded=adj.is_overloaded
                    or adj.local_if in self.link_overloads,
                    rtt=adj.rtt_us,
                    timestamp=adj.timestamp,
                    next_hop_v6=adj.addr_v6,
                    next_hop_v4=adj.addr_v4,
                    adj_only_used_by_other_node=adj.adj_only_used_by_other_node,
                )
            )
        adjacencies.sort(key=lambda a: (a.other_node_name, a.if_name))
        db = AdjacencyDatabase(
            this_node_name=self.node_name,
            is_overloaded=self.node_overloaded,
            adjacencies=adjacencies,
            node_label=self.node_labels.get(area, 0),
            area=area,
            node_metric_increment_val=self.node_metric_increment,
        )
        pe = PerfEvents()
        pe.add(self.node_name, "ADJ_DB_UPDATED", self.clock.now_ms())
        db.perf_events = pe
        return db

    def _advertise_adjacencies(self) -> None:
        ctx, self._pending_trace_ctx = self._pending_trace_ctx, None
        if ctx is not None:
            span = self.tracer.instant(
                "link_monitor.advertise_adj",
                ctx,
                module="link_monitor",
                areas=len(self.area_ids),
            )
            ctx = self.tracer.child_ctx(span, ctx)
        for area in self.area_ids:
            db = self.build_adjacency_database(area)
            if db.perf_events is not None:
                # the trace rides the flooded payload itself so remote
                # Decisions join the SAME trace even when the key reaches
                # them via full sync instead of an incremental flood
                db.perf_events.trace_context = ctx
            self.kv_request_queue.push(
                KeyValueRequest(
                    request_type=KvRequestType.PERSIST_KEY,
                    area=area,
                    key=adj_key(self.node_name),
                    value=self.serialize_adj_db(db),
                    trace_ctx=ctx,
                )
            )
        self.counters.bump("link_monitor.advertise_adj_db")

    # -- drain / maintenance API (LinkMonitor.h:107-150) -------------------

    def set_node_overload(self, overloaded: bool) -> None:
        if self.node_overloaded != overloaded:
            self.node_overloaded = overloaded
            self._advertise_adjacencies()  # drain ops advertise immediately

    def set_node_metric_increment(self, increment: int) -> None:
        if self.node_metric_increment != increment:
            self.node_metric_increment = increment
            self._advertise_adjacencies()

    def set_link_overload(self, if_name: str, overloaded: bool) -> None:
        changed = (
            if_name in self.link_overloads) != overloaded
        if changed:
            if overloaded:
                self.link_overloads.add(if_name)
            else:
                self.link_overloads.discard(if_name)
            self._advertise_adjacencies()

    def set_link_metric(self, if_name: str, metric: Optional[int]) -> None:
        if metric is None:
            if self.link_metric_overrides.pop(if_name, None) is not None:
                self._advertise_adjacencies()
        elif self.link_metric_overrides.get(if_name) != metric:
            self.link_metric_overrides[if_name] = metric
            self._advertise_adjacencies()

    def set_adjacency_metric(
        self, if_name: str, node: str, metric: Optional[int]
    ) -> None:
        """Pin (or with None, clear) one adjacency's metric
        (setAdjacencyMetric/unsetAdjacencyMetric)."""
        key = (if_name, node)
        if metric is None:
            if self.adj_metric_overrides.pop(key, None) is not None:
                self._advertise_adjacencies()
        elif metric < 1:
            # SPF requires strictly positive metrics (the device kernel's
            # DAG-equality propagation rejects <= 0 at the bridge too)
            raise ValueError(f"adjacency metric must be >= 1, got {metric}")
        elif self.adj_metric_overrides.get(key) != metric:
            self.adj_metric_overrides[key] = metric
            self._advertise_adjacencies()

    def set_link_metric_increment(
        self, if_name: str, increment: int
    ) -> None:
        """Per-interface soft-drain increment; 0 clears
        (setInterfaceMetricIncrement/unset)."""
        if increment == 0:
            if self.link_metric_increments.pop(if_name, None) is not None:
                self._advertise_adjacencies()
        elif increment < 0:
            # a negative increment could push advertised metrics <= 0 and
            # break SPF (the reference rejects non-positive increments)
            raise ValueError(
                f"metric increment must be >= 0, got {increment}"
            )
        elif self.link_metric_increments.get(if_name) != increment:
            self.link_metric_increments[if_name] = increment
            self._advertise_adjacencies()

    def get_drain_state(self) -> dict:
        return {
            "node_overloaded": self.node_overloaded,
            "node_metric_increment": self.node_metric_increment,
            "link_overloads": sorted(self.link_overloads),
            "link_metric_overrides": dict(self.link_metric_overrides),
            # list-of-[if_name, node, metric] triples: interface names
            # are free-form, so a joined-string key could collide with a
            # separator character and round-trip wrongly (ADVICE r3)
            "adj_metric_overrides": [
                [i, n, m]
                for (i, n), m in sorted(self.adj_metric_overrides.items())
            ],
            "link_metric_increments": dict(self.link_metric_increments),
        }

    def restore_drain_state(self, state: dict) -> None:
        """Reload persisted drain config (config-store on restart)."""
        self.node_overloaded = state.get("node_overloaded", False)
        self.node_metric_increment = state.get("node_metric_increment", 0)
        self.link_overloads = set(state.get("link_overloads", []))
        self.link_metric_overrides = dict(
            state.get("link_metric_overrides", {})
        )
        raw = state.get("adj_metric_overrides", [])
        if isinstance(raw, dict):
            # pre-r4 persisted form: '|'-joined keys (best-effort parse)
            self.adj_metric_overrides = {
                tuple(k.split("|", 1)): m for k, m in raw.items()
            }
        else:
            self.adj_metric_overrides = {
                (i, n): m for i, n, m in raw
            }
        self.link_metric_increments = dict(
            state.get("link_metric_increments", {})
        )
