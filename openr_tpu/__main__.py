"""`python -m openr_tpu` → daemon runner (reference: openr/Main.cpp)."""

from openr_tpu.daemon import main

main()
