"""Plugin boundary — external route-origination extensions.

Reference parity: openr/plugin/Plugin.{h,cpp}: `pluginStart(PluginArgs)` /
`vipPluginStart(VipPluginArgs)` hooks, no-ops in OSS, where PluginArgs
hands the extension the prefixUpdatesQueue (to advertise/withdraw
prefixes into PrefixManager) and a route-updates reader (to observe the
computed RIB).  This is the seam BASELINE.json names for out-of-tree
integrations.

Here a plugin is any object with `async start(args)` / `async stop()`;
the PluginManager instantiates them from dotted-path names in config
(`plugin_modules`) or from directly registered factories, and owns their
lifecycle alongside the daemon's.
"""

from __future__ import annotations

import importlib
from dataclasses import dataclass
from typing import Any, Callable, List, Optional

from openr_tpu.messaging.queue import RQueue, ReplicateQueue


@dataclass
class PluginArgs:
    """What a plugin gets to touch (Plugin.h:20-27)."""

    node_name: str
    config: Any
    #: push PrefixEvents here to advertise/withdraw (PrefixManager input)
    prefix_updates_queue: ReplicateQueue
    #: observe computed route updates (Decision output)
    route_updates_reader: Optional[RQueue] = None
    counters: Any = None
    clock: Any = None


class Plugin:
    """Base plugin: override start/stop."""

    async def start(self, args: PluginArgs) -> None:  # pragma: no cover
        pass

    async def stop(self) -> None:  # pragma: no cover
        pass


class PluginManager:
    """Loads + runs plugins (pluginStart/pluginStop lifecycle)."""

    def __init__(self) -> None:
        self._factories: List[Callable[[], Plugin]] = []
        self._active: List[Plugin] = []

    def register(self, factory: Callable[[], Plugin]) -> None:
        self._factories.append(factory)

    def has_plugins(self) -> bool:
        return bool(self._factories)

    def load(self, dotted_path: str) -> None:
        """Load `pkg.module:FactoryOrClass` (or `pkg.module.Factory`)."""
        if ":" in dotted_path:
            mod_name, attr = dotted_path.split(":", 1)
        else:
            mod_name, _, attr = dotted_path.rpartition(".")
        module = importlib.import_module(mod_name)
        self.register(getattr(module, attr))

    async def start_all(self, args: PluginArgs) -> None:
        for factory in self._factories:
            plugin = factory()
            try:
                await plugin.start(args)
            except BaseException:
                # failed or cancelled mid-start: the plugin may have opened
                # resources already — stop it rather than strand it outside
                # _active where stop_all can't see it
                try:
                    await plugin.stop()
                except Exception:  # noqa: BLE001 - original error wins
                    pass
                raise
            self._active.append(plugin)

    async def stop_all(self) -> None:
        for plugin in reversed(self._active):
            await plugin.stop()
        self._active.clear()
