from openr_tpu.plugin.plugin import (  # noqa: F401
    Plugin,
    PluginArgs,
    PluginManager,
)
