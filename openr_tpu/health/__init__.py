"""openr_tpu.health — the fleet health plane.

Turns the PR-7 telemetry surface (MetricsSnapshots: counters +
histogram buckets from every node) into fleet-wide health verdicts:

  * :mod:`openr_tpu.health.slo` — declarative :class:`SloSpec`s over
    histogram percentiles / counter deltas, evaluated by the
    multi-window **burn-rate** engine (fast window catches onset, slow
    window filters blips; all windows on the injected Clock);
  * :mod:`openr_tpu.health.aggregator` — the
    :class:`FleetHealthAggregator` sweep: cross-node histogram merge
    (PR-7 widen-on-merge semantics), generation-skew/staleness,
    quarantined-chip and open-breaker rollups, queue-watermark
    saturation, per-chip utilization spread, crash latching;
  * :mod:`openr_tpu.health.alerts` — the alert-name registry (the ONLY
    spelling of ``health.alert.*``, orlint-enforced) and the
    :class:`AlertSink`: firing counters, deterministic JSONL transition
    log, detection-time flight-recorder dumps for page severity.

Operator surface: ctrl ``get_health_status`` / ``get_active_alerts``,
``breeze health status|alerts|slo``, ``--emulate ... --health-export``.
Every alert rule is chaos-verified (tests/test_health_chaos.py): a
seeded fault family fires exactly its expected alert set, a clean run
fires none, and replays are byte-identical.  See docs/Observability.md
§"Fleet health plane".
"""

from __future__ import annotations

from openr_tpu.health.aggregator import (
    FleetHealthAggregator,
    HealthMonitor,
    generation_hash,
    histogram_from_snapshot,
    merge_fleet_histograms,
)
from openr_tpu.health.alerts import (
    ALERTS,
    AlertSink,
    alert_counter_key,
    alert_description,
    alert_severity,
)
from openr_tpu.health.slo import (
    BurnRateEvaluator,
    SloSpec,
    default_slos,
    slos_for_topology_class,
)

__all__ = [
    "ALERTS",
    "AlertSink",
    "BurnRateEvaluator",
    "FleetHealthAggregator",
    "HealthMonitor",
    "SloSpec",
    "alert_counter_key",
    "alert_description",
    "alert_severity",
    "default_slos",
    "generation_hash",
    "histogram_from_snapshot",
    "merge_fleet_histograms",
    "slos_for_topology_class",
]
