"""SLO specs + the multi-window burn-rate engine.

An :class:`SloSpec` declares one service-level objective over the
metric surface PR 7 exports: either a histogram percentile ("p99 of
``convergence.event_to_fib_ms`` stays under 30s") or a counter delta
("no more than N watchdog crashes per interval").  The
:class:`BurnRateEvaluator` turns the fleet-merged metric stream into
the SRE-book multi-window burn-rate signal:

  * each aggregator sweep contributes one **interval sample**: how many
    SLI events landed since the previous sweep and how many of them
    were bad (for histogram SLIs, bucket-delta counting above the
    threshold edge; for counter SLIs, 0/1 on the delta exceeding the
    threshold);
  * a window's **burn rate** is (bad/total over the window) divided by
    the objective's error budget — burn 1.0 means "spending budget
    exactly as fast as allowed", 10 means "budget gone in a tenth of
    the window";
  * the alert fires only when BOTH the fast and the slow window exceed
    ``burn_threshold`` — the fast window catches onset quickly, the
    slow window keeps a single bad blip from paging (Google SRE
    workbook's multiwindow, multi-burn-rate alerts).

Every timestamp comes from the injected Clock, and windows are plain
deques of ``(ts, bad, total)`` — a SimClock replay reproduces the exact
same burn trajectory byte for byte.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, Optional, Tuple

from openr_tpu.health.alerts import ALERTS, SEV_PAGE, SEV_TICKET

KIND_HISTOGRAM = "histogram_percentile"
KIND_COUNTER = "counter_threshold"

SLO_KINDS = (KIND_HISTOGRAM, KIND_COUNTER)


@dataclass(frozen=True)
class SloSpec:
    """One declarative objective.  ``name`` doubles as the alert name
    and must be registered in :data:`openr_tpu.health.alerts.ALERTS`."""

    name: str
    metric: str
    kind: str = KIND_HISTOGRAM
    #: reported-value percentile (status surface) for histogram SLIs
    percentile: float = 99.0
    #: an SLI event is BAD when its value exceeds this
    threshold: float = 0.0
    #: error budget: allowed bad fraction over the objective window
    objective: float = 0.01
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    #: fire when BOTH windows burn at >= this multiple of budget
    burn_threshold: float = 2.0

    def __post_init__(self) -> None:
        if self.name not in ALERTS:
            raise ValueError(
                f"SLO name {self.name!r} is not a registered alert "
                "(openr_tpu.health.alerts.ALERTS)"
            )
        if self.kind not in SLO_KINDS:
            raise ValueError(f"SLO kind must be one of {SLO_KINDS}")
        if not (0.0 < self.objective <= 1.0):
            raise ValueError("objective must be in (0, 1]")
        if not (0.0 < self.fast_window_s <= self.slow_window_s):
            raise ValueError("need 0 < fast_window_s <= slow_window_s")
        if self.burn_threshold <= 0.0:
            raise ValueError("burn_threshold must be > 0")

    @property
    def severity(self) -> str:
        return ALERTS[self.name][0]


def default_slos(convergence_threshold_ms: float = 30_000.0) -> Tuple[SloSpec, ...]:
    """The built-in objective catalog (docs/Observability.md §fleet
    health lists the rationale for each threshold).
    ``convergence_threshold_ms`` lets a topology-class-aware deployment
    tighten the convergence objective (see
    :func:`slos_for_topology_class`)."""
    return (
        SloSpec(
            name="slo_convergence_p99",
            metric="convergence.event_to_fib_ms",
            kind=KIND_HISTOGRAM,
            percentile=99.0,
            # PAPER §1: sub-30s event->FIB even at WAN scale is the
            # catalog ceiling; per-class defaults are tighter
            threshold=convergence_threshold_ms,
            objective=0.05,
            fast_window_s=60.0,
            slow_window_s=300.0,
            burn_threshold=2.0,
        ),
        SloSpec(
            name="slo_serving_queue_wait_p95",
            metric="serving.queue_wait_ms",
            kind=KIND_HISTOGRAM,
            percentile=95.0,
            threshold=2_000.0,
            objective=0.05,
            fast_window_s=60.0,
            slow_window_s=300.0,
            burn_threshold=2.0,
        ),
    )


def slos_for_topology_class(topology_class: str) -> Tuple[SloSpec, ...]:
    """The default catalog with the convergence objective tightened to
    the topology class's registered publication→FIB SLO
    (emulation.topology.TOPOLOGY_CLASSES) — a low-diameter fabric is
    held to a tighter event→FIB bound than a long-haul WAN hierarchy.
    Unknown class names keep the 30s catalog ceiling."""
    from openr_tpu.emulation.topology import TOPOLOGY_CLASSES

    row = TOPOLOGY_CLASSES.get(topology_class)
    if row is None:
        return default_slos()
    return default_slos(convergence_threshold_ms=row.convergence_slo_ms)


@dataclass
class _SloState:
    """Mutable evaluation state for one spec."""

    #: previous sweep's cumulative (bad_count, total_count) baseline
    last_cum: Optional[Tuple[float, float]] = None
    #: interval samples (ts, bad, total), bounded by the slow window
    samples: Deque[Tuple[float, float, float]] = field(default_factory=deque)
    firing: bool = False
    last_value: Optional[float] = None


def _bad_total_from_histogram(hist: Optional[dict], threshold: float):
    """Cumulative (bad, total) from one merged histogram snapshot dict:
    bad = samples in buckets whose UPPER edge exceeds the threshold
    (bucket-granular, conservative in the operator's favor by at most
    one ~15%-wide bucket)."""
    if hist is None:
        return 0.0, 0.0
    bad = 0.0
    for edge, count in hist.get("buckets", []):
        if float(edge) > threshold:
            bad += count
    return bad, float(hist.get("count", 0))


class BurnRateEvaluator:
    """Evaluates every spec once per sweep against the merged fleet
    metric surface; owns nothing but deterministic window state."""

    def __init__(self, clock, specs) -> None:
        self.clock = clock
        self.specs = tuple(specs)
        names = [s.name for s in self.specs]
        if len(names) != len(set(names)):
            raise ValueError(f"duplicate SLO names: {names}")
        self._state: Dict[str, _SloState] = {
            s.name: _SloState() for s in self.specs
        }

    # -- one sweep ---------------------------------------------------------

    def evaluate(
        self,
        merged_histograms: Dict[str, dict],
        merged_counters: Dict[str, float],
    ) -> Dict[str, Dict[str, Any]]:
        """Returns {slo_name: detail} for the specs firing NOW."""
        now = self.clock.now()
        firing: Dict[str, Dict[str, Any]] = {}
        for spec in self.specs:
            st = self._state[spec.name]
            if spec.kind == KIND_HISTOGRAM:
                hist = merged_histograms.get(spec.metric)
                cum = _bad_total_from_histogram(hist, spec.threshold)
                st.last_value = (hist or {}).get(f"p{spec.percentile:g}")
            else:
                value = merged_counters.get(spec.metric, 0.0)
                st.last_value = value
                cum = (value, 1.0)  # delta-thresholded below
            if st.last_cum is None:
                # first sweep establishes the baseline; deltas start next
                st.last_cum = cum
                continue
            if spec.kind == KIND_HISTOGRAM:
                d_bad = max(cum[0] - st.last_cum[0], 0.0)
                d_total = max(cum[1] - st.last_cum[1], 0.0)
            else:
                delta = cum[0] - st.last_cum[0]
                d_total, d_bad = 1.0, (1.0 if delta > spec.threshold else 0.0)
            st.last_cum = cum
            st.samples.append((now, d_bad, d_total))
            while st.samples and st.samples[0][0] < now - spec.slow_window_s:
                st.samples.popleft()
            fast = self._burn(st, spec, now, spec.fast_window_s)
            slow = self._burn(st, spec, now, spec.slow_window_s)
            st.firing = (
                fast >= spec.burn_threshold and slow >= spec.burn_threshold
            )
            if st.firing:
                firing[spec.name] = {
                    "metric": spec.metric,
                    "value": st.last_value,
                    "threshold": spec.threshold,
                    "fast_burn": round(fast, 4),
                    "slow_burn": round(slow, 4),
                }
        return firing

    @staticmethod
    def _burn(st: _SloState, spec: SloSpec, now: float, window_s: float):
        bad = total = 0.0
        for ts, b, t in st.samples:
            if ts >= now - window_s:
                bad += b
                total += t
        if total <= 0.0:
            return 0.0
        return (bad / total) / spec.objective

    # -- status surface ----------------------------------------------------

    def status(self) -> list:
        now = self.clock.now()
        out = []
        for spec in self.specs:
            st = self._state[spec.name]
            out.append(
                {
                    "name": spec.name,
                    "metric": spec.metric,
                    "kind": spec.kind,
                    "percentile": spec.percentile,
                    "threshold": spec.threshold,
                    "objective": spec.objective,
                    "severity": spec.severity,
                    "value": st.last_value,
                    "fast_burn": round(
                        self._burn(st, spec, now, spec.fast_window_s), 4
                    ),
                    "slow_burn": round(
                        self._burn(st, spec, now, spec.slow_window_s), 4
                    ),
                    "firing": st.firing,
                }
            )
        return out


__all__ = [
    "SloSpec",
    "BurnRateEvaluator",
    "default_slos",
    "slos_for_topology_class",
    "KIND_HISTOGRAM",
    "KIND_COUNTER",
    "SLO_KINDS",
    "SEV_PAGE",
    "SEV_TICKET",
]
