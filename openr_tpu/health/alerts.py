"""Alert registry + sink — every fleet health alert has ONE name and
one delivery path.

The registry half mirrors ``openr_tpu/tracing/pipeline.py``: this module
is the only place a ``health.alert.*`` counter name may be spelled
(enforced by orlint's ``alert-name-registry`` rule).  An alert name that
is not in :data:`ALERTS` does not exist — the aggregator refuses to fire
it, the chaos fidelity suite cannot accidentally assert on a typo, and
dashboards can enumerate the complete alert surface from one dict.

The sink half turns per-sweep firing sets into operator surfaces:

  * ``health.alert.{name}`` counters — bumped once per sweep while the
    alert is firing, so the counter's growth rate IS the firing
    duration in sweeps (fb303-style: watchable, rateable, diffable);
  * a structured JSONL alert log — one line per transition (``fired`` /
    ``resolved``), deterministic bytes under SimClock (sorted keys,
    clock timestamps, a monotonic seq — two seeded replays must produce
    byte-identical logs, which the chaos suite asserts);
  * page-severity escalation: a rising page alert freezes the node's
    flight recorder at detection time (rate-limited, and deduped to at
    most one dump per sweep even when several page alerts rise
    together) so the post-mortem window is captured before it rolls.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

#: severity levels, mildest first
SEV_TICKET = "ticket"
SEV_PAGE = "page"

#: the ONLY spelling of the alert counter namespace
ALERT_COUNTER_PREFIX = "health.alert."

#: name -> (default severity, one-line description).  Adding an alert
#: means adding it HERE (plus a chaos scenario proving it fires —
#: tests/test_health_chaos.py is the fidelity gate).
ALERTS: Dict[str, tuple] = {
    "generation_skew": (
        SEV_TICKET,
        "a node stopped advancing Decision generations while the rest "
        "of the fleet churned (partitioned / wedged / stale LSDB)",
    ),
    "chip_quarantine": (
        SEV_PAGE,
        "one or more accelerator chips are quarantined fleet-wide "
        "(shadow-verification mismatch or chaos/operator drain)",
    ),
    "backend_quarantine": (
        SEV_PAGE,
        "a node's whole device backend is quarantined; its route "
        "builds and serving degraded to the scalar engines",
    ),
    "breaker_open": (
        SEV_TICKET,
        "a circuit breaker (FIB agent, KvStore peer, device backend) "
        "is open or probing somewhere in the fleet",
    ),
    "queue_saturation": (
        SEV_TICKET,
        "an inter-module queue's backlog exceeds the saturation "
        "threshold (consumer wedged or overloaded)",
    ),
    "utilization_spread": (
        SEV_TICKET,
        "per-chip busy-time spread on one node exceeds the bound "
        "(shard imbalance or a silently slow chip)",
    ),
    "node_crash": (
        SEV_PAGE,
        "a watchdog fired a crash somewhere in the fleet (module "
        "fiber death, stall, queue overflow, or chaos kill)",
    ),
    "protection_mismatch": (
        SEV_PAGE,
        "a fast-reroute patch a node applied to its FIB diverged from "
        "the confirming warm solve (the table was purged and the RIB "
        "full-synced, but a wrong route was briefly installed)",
    ),
    "fleet_node_loss": (
        SEV_PAGE,
        "a fleet member node is DOWN (the failure domain above the "
        "chip): its sweep worlds re-pack onto survivors and its "
        "watchers migrate to hash successors, but capacity is lost "
        "until the node returns",
    ),
    "fleet_drain_migration": (
        SEV_TICKET,
        "a fleet member node is drained for maintenance — its "
        "watchers/worlds migrated by design; the ticket audits that "
        "the hand-off completed and the drain is not forgotten",
    ),
    "fleet_gray_failure": (
        SEV_TICKET,
        "a fleet member was demoted to drained by the gray-failure "
        "strike policy: its heartbeats (and often its ctrl surface) "
        "still answer but its sweep work keeps failing or timing out "
        "— the 'fleet disagrees about who is alive' runbook case; "
        "worlds re-packed onto survivors, node needs investigation "
        "before undrain",
    ),
    "slo_convergence_p99": (
        SEV_PAGE,
        "publication->FIB convergence p99 is burning its error "
        "budget on both burn-rate windows",
    ),
    "slo_serving_queue_wait_p95": (
        SEV_TICKET,
        "serving-plane queue wait p95 is burning its error budget "
        "on both burn-rate windows",
    ),
}


def alert_severity(name: str) -> str:
    return ALERTS[name][0]


def alert_description(name: str) -> str:
    return ALERTS[name][1]


def alert_counter_key(name: str) -> str:
    """``health.alert.{name}`` — the firing counter for one alert."""
    if name not in ALERTS:
        raise ValueError(f"unknown alert name {name!r}")
    return ALERT_COUNTER_PREFIX + name


class AlertSink:
    """Transition-edge alert delivery for one aggregator.

    ``report(firing)`` is called once per sweep with the complete
    firing set; the sink diffs it against the previous sweep's to log
    transitions, bumps the per-alert counters, and (for rising page
    alerts) triggers at most one flight-recorder dump per sweep,
    rate-limited by ``page_dump_min_s`` on the injected clock.
    """

    def __init__(
        self,
        node_name: str,
        clock,
        counters,
        flight_recorder=None,
        log_path: str = "",
        max_log_entries: int = 4096,
        page_dump_min_s: float = 30.0,
    ) -> None:
        self.node_name = node_name
        self.clock = clock
        self.counters = counters
        self.flight_recorder = flight_recorder
        self.log_path = log_path
        self.max_log_entries = max_log_entries
        self.page_dump_min_s = page_dump_min_s
        #: name -> detail dict of the rising edge (the active set)
        self.active: Dict[str, Dict[str, Any]] = {}
        #: JSONL transition log (deterministic bytes under SimClock)
        self.log: List[str] = []
        self.num_fired = 0
        self.num_resolved = 0
        self.num_page_dumps = 0
        self.num_page_dumps_suppressed = 0
        self._seq = 0
        self._last_page_dump_ts: Optional[float] = None
        if log_path:
            # one run's record, not an append log (MetricsJsonlWriter rule)
            with open(log_path, "w"):
                pass

    # -- delivery ----------------------------------------------------------

    def report(self, firing: Dict[str, Dict[str, Any]]) -> None:
        """One sweep's complete firing set: {alert_name: detail}."""
        now_ms = int(self.clock.now_ms())
        rising_pages: List[str] = []
        for name in sorted(firing):
            if name not in ALERTS:
                raise ValueError(f"unregistered alert name {name!r}")
            self.counters.bump(alert_counter_key(name))
            if name not in self.active:
                self.num_fired += 1
                self._log_event("fired", name, now_ms, firing[name])
                if alert_severity(name) == SEV_PAGE:
                    rising_pages.append(name)
            self.active[name] = dict(firing[name])
        for name in sorted(set(self.active) - set(firing)):
            detail = self.active.pop(name)
            self.num_resolved += 1
            self._log_event("resolved", name, now_ms, detail)
        if rising_pages:
            self._page_dump(rising_pages, now_ms)

    def _log_event(
        self, event: str, name: str, now_ms: int, detail: Dict[str, Any]
    ) -> None:
        line = json.dumps(
            {
                "event": event,
                "name": name,
                "severity": alert_severity(name),
                "node": self.node_name,
                "seq": self._seq,
                "ts_ms": now_ms,
                "detail": detail,
            },
            sort_keys=True,
            separators=(",", ":"),
            default=str,
        )
        self._seq += 1
        self.log.append(line)
        if len(self.log) > self.max_log_entries:
            del self.log[: len(self.log) - self.max_log_entries]
        if self.log_path:
            try:
                with open(self.log_path, "a") as f:
                    f.write(line + "\n")
            except OSError:
                # a full disk must not take the health plane down with it
                self.counters.bump("health.alert_log_write_errors")

    def _page_dump(self, names: List[str], now_ms: int) -> None:
        """One detection-time post-mortem for this sweep's rising page
        alerts (deduped: several simultaneous pages share one dump),
        rate-limited so a flapping page can't churn the dump ring."""
        if self.flight_recorder is None:
            return
        now = self.clock.now()
        if (
            self._last_page_dump_ts is not None
            and now - self._last_page_dump_ts < self.page_dump_min_s
        ):
            self.num_page_dumps_suppressed += 1
            self.counters.bump("health.page_dumps_suppressed")
            return
        self._last_page_dump_ts = now
        self.num_page_dumps += 1
        self.flight_recorder.dump(
            "health_page_alert",
            extra={"alerts": names, "detected_ts_ms": now_ms},
        )

    # -- query surface -----------------------------------------------------

    def active_alerts(self) -> List[Dict[str, Any]]:
        return [
            {
                "name": name,
                "severity": alert_severity(name),
                "description": alert_description(name),
                "detail": dict(detail),
            }
            for name, detail in sorted(self.active.items())
        ]

    def log_bytes(self) -> bytes:
        """The whole transition log as JSONL bytes — what the chaos
        suite byte-compares across seeded replays."""
        return ("".join(line + "\n" for line in self.log)).encode()

    def gauges(self) -> Dict[str, float]:
        return {
            "health.alerts.active": float(len(self.active)),
            "health.alerts.fired": float(self.num_fired),
            "health.alerts.resolved": float(self.num_resolved),
            "health.page_dumps": float(self.num_page_dumps),
        }
