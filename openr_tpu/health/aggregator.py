"""FleetHealthAggregator — cross-node health verdicts from raw telemetry.

PR 7 put every counter and histogram bucket of every node one
`MetricsSnapshot` away; this module is the layer that *evaluates* them
the way Open/R's operators watch fb303 counters across the Express
Backbone (PAPER §1): one sweep pulls every node's snapshot (the
emulation hands over ``EmulatedNetwork.metrics_snapshots()``; real
deployments poll ctrl ``get_metrics_snapshot``), merges cross-node
histograms with the PR-7 merge semantics (identical bucket grids add
positionally; narrower grids widen), and derives the signals no single
node can see:

  * **generation skew / staleness** — each snapshot's Decision
    ``generation`` stamp is normalized to a stable hash (the raw stamp
    mixes node-local sequence counters, so only *change* is comparable,
    never order).  A node whose hash stays frozen across K sweeps in
    which other nodes advanced, for at least ``skew_hold_s``, is STALE:
    partitioned, wedged, or serving an old LSDB.
  * **chip / backend quarantine rollup** — fleet totals of quarantined
    chips (``decision.backend.pool.*``) and whole-backend latches
    (``resilience.backend.quarantined``).
  * **breaker rollup** — every ``resilience.*.state`` gauge that is not
    closed, named per node and edge.
  * **queue saturation** — any ``messaging.queue.*.depth`` beyond the
    threshold (backlog growth the Watchdog would only crash on later).
  * **per-chip utilization spread** — ``pipeline.devN.utilization``
    imbalance on any node's pool (a silently slow chip skews its own
    busy fraction long before it fails a shadow check).
  * **crash latch** — ``watchdog.crashes`` deltas, latched across node
    restarts (a restart resets counters; the fleet must still remember
    the crash happened).

SLO specs ride the same sweep through the multi-window burn-rate
engine (:mod:`openr_tpu.health.slo`), and everything that fires lands
in the :class:`~openr_tpu.health.alerts.AlertSink` — counters, a
deterministic JSONL transition log, and detection-time flight-recorder
dumps for page severity.  All timing comes from the injected Clock, so
two seeded SimClock replays produce byte-identical alert logs (the
chaos fidelity suite's contract).
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Callable, Dict, List, Optional

from openr_tpu.common.runtime import Actor, Clock, CounterMap, Histogram
from openr_tpu.health.alerts import AlertSink
from openr_tpu.health.slo import BurnRateEvaluator, SloSpec, default_slos


def generation_hash(generation: Any) -> str:
    """Stable 12-hex digest of a Decision generation stamp.  The stamp's
    components are node-local counters — two nodes' stamps are not
    ordered, and a restart resets them — so the only fleet-comparable
    signal is *did this node's stamp change*, which a content hash
    answers exactly."""
    blob = json.dumps(generation, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def histogram_from_snapshot(snap: Dict[str, Any]) -> Histogram:
    """Rebuild a mergeable Histogram from a MetricsSnapshot histogram
    dict (bucket grid config + sparse bucket pairs) — the bridge that
    lets the fleet rollup reuse the PR-7 ``Histogram.merge`` semantics
    (positional add, widen-on-merge) instead of re-implementing them."""
    h = Histogram(
        min_bound=snap["min_bound"],
        growth=snap["growth"],
        num_buckets=snap["num_buckets"],
    )
    overflow_edge = h.edges[-1] if h.edges else 0.0
    for edge, count in snap.get("buckets", []):
        edge = float(edge)
        if edge > overflow_edge:  # the serialized inf overflow bucket
            h.counts[-1] += count
        else:
            h.counts[h.bucket_index(edge)] += count
    h.count = int(snap.get("count", 0))
    h.total = float(snap.get("sum", 0.0))
    h.vmin = snap.get("min")
    h.vmax = snap.get("max")
    return h


def merge_fleet_histograms(
    snaps: List[Dict[str, Any]]
) -> Dict[str, Dict[str, Any]]:
    """Cross-node merge of every histogram key present in any snapshot.
    Returns snapshot-shaped dicts (grid config + buckets + percentiles)
    so downstream consumers never see the mutable Histogram objects."""
    merged: Dict[str, Histogram] = {}
    for s in snaps:
        for key, hsnap in s.get("histograms", {}).items():
            h = histogram_from_snapshot(hsnap)
            if key in merged:
                a, b = merged[key], h
                # PR-7 widen-on-merge only grows the RECEIVER; merge
                # into whichever histogram has the wider grid
                if len(b.counts) > len(a.counts):
                    a, b = b, a
                merged[key] = a.merge(b)
            else:
                merged[key] = h
    out: Dict[str, Dict[str, Any]] = {}
    for key, h in merged.items():
        d = dict(h.config())
        d.update(
            count=h.count,
            sum=h.total,
            min=h.vmin,
            max=h.vmax,
            buckets=[[edge, c] for edge, c in h.bucket_items()],
        )
        d.update(h.percentiles((50, 95, 99)))
        out[key] = d
    return out


class _NodeGenState:
    __slots__ = (
        "gen_hash",
        "last_advance_ts",
        "missed",
        "last_crashes",
        "start_ms",
    )

    def __init__(self, gen_hash: str, now: float) -> None:
        self.gen_hash = gen_hash
        self.last_advance_ts = now
        self.missed = 0
        self.last_crashes = 0.0
        self.start_ms: Optional[float] = None


class FleetHealthAggregator:
    """One sweep loop over the fleet's snapshots; owns the derived
    signal state, the burn-rate evaluator, and the alert sink."""

    def __init__(
        self,
        node_name: str,
        clock: Clock,
        source: Callable[[], List[Any]],
        sink: AlertSink,
        counters: Optional[CounterMap] = None,
        slos: Optional[List[SloSpec]] = None,
        skew_min_generations: int = 3,
        skew_hold_s: float = 30.0,
        queue_depth_threshold: float = 10_000.0,
        utilization_spread_threshold: float = 0.5,
        utilization_spread_floor: float = 0.2,
    ) -> None:
        self.node_name = node_name
        self.clock = clock
        self._source = source
        self.sink = sink
        self.counters = counters if counters is not None else CounterMap()
        self.slos = BurnRateEvaluator(
            clock, slos if slos is not None else default_slos()
        )
        self.skew_min_generations = skew_min_generations
        self.skew_hold_s = skew_hold_s
        self.queue_depth_threshold = queue_depth_threshold
        self.utilization_spread_threshold = utilization_spread_threshold
        self.utilization_spread_floor = utilization_spread_floor
        self._gen_state: Dict[str, _NodeGenState] = {}
        self._crashes_latched = 0.0
        self._restarts_latched = 0.0
        #: supervisor-stamped (operator-requested) incarnation bumps:
        #: remembered and surfaced, never alert-worthy
        self._expected_restarts_latched = 0.0
        self.num_sweeps = 0
        self._last_status: Dict[str, Any] = {}

    def set_source(self, source: Callable[[], List[Any]]) -> None:
        """Re-point the snapshot source (the emulation swaps the
        node-local default for the fleet-wide one)."""
        self._source = source

    # -- the sweep ---------------------------------------------------------

    def sweep(self) -> Dict[str, Any]:
        """Pull snapshots, derive signals, evaluate SLOs, deliver
        alerts; returns the refreshed status rollup."""
        self.num_sweeps += 1
        now = self.clock.now()
        snaps = [
            s.to_wire() if hasattr(s, "to_wire") else dict(s)
            for s in self._source()
        ]
        snaps.sort(key=lambda s: s.get("node", ""))
        merged_counters: Dict[str, float] = {}
        for s in snaps:
            for k, v in s.get("counters", {}).items():
                merged_counters[k] = merged_counters.get(k, 0.0) + float(v)
        merged_hists = merge_fleet_histograms(snaps)

        firing: Dict[str, Dict[str, Any]] = {}
        node_rows = self._generation_signal(snaps, now, firing)
        self._quarantine_signal(snaps, firing)
        self._breaker_signal(snaps, firing)
        self._queue_signal(snaps, firing)
        self._utilization_signal(snaps, firing)
        self._crash_signal(snaps, firing)
        self._protection_signal(snaps, firing)
        firing.update(self.slos.evaluate(merged_hists, merged_counters))
        self.sink.report(firing)

        self._last_status = {
            "node": self.node_name,
            "ts_ms": int(self.clock.now_ms()),
            "sweeps": self.num_sweeps,
            "nodes": node_rows,
            "chips": self._chip_rollup(snaps),
            "breakers": self._breaker_rollup(snaps),
            "queues": self._queue_rollup(snaps),
            "crashes_seen": self._crashes_latched,
            "restarts_seen": self._restarts_latched,
            "expected_restarts_seen": self._expected_restarts_latched,
            "slos": self.slos.status(),
            "active_alerts": self.sink.active_alerts(),
        }
        return self._last_status

    # -- fleet signals -----------------------------------------------------

    def _generation_signal(self, snaps, now, firing) -> List[Dict[str, Any]]:
        advanced: List[str] = []
        seen: List[str] = []
        for s in snaps:
            name = s.get("node", "")
            seen.append(name)
            gh = generation_hash(s.get("generation"))
            st = self._gen_state.get(name)
            if st is None:
                self._gen_state[name] = _NodeGenState(gh, now)
                advanced.append(name)
            elif st.gen_hash != gh:
                st.gen_hash = gh
                advanced.append(name)
        stale: List[str] = []
        rows: List[Dict[str, Any]] = []
        for name in seen:
            st = self._gen_state[name]
            if name in advanced:
                st.missed = 0
                st.last_advance_ts = now
            elif any(a != name for a in advanced):
                # at least one OTHER node advanced a generation while
                # this one sat still: one missed generation (at least)
                st.missed += 1
            is_stale = (
                st.missed >= self.skew_min_generations
                and now - st.last_advance_ts >= self.skew_hold_s
            )
            if is_stale:
                stale.append(name)
            rows.append(
                {
                    "node": name,
                    "generation_hash": st.gen_hash,
                    "missed_generations": st.missed,
                    "stale_for_s": round(now - st.last_advance_ts, 3),
                    "stale": is_stale,
                }
            )
        # forget nodes that left the fleet (decommission); a restart
        # re-registers under the same name with a fresh hash (= advance)
        for name in list(self._gen_state):
            if name not in seen:
                del self._gen_state[name]
        if stale:
            firing["generation_skew"] = {
                "stale_nodes": stale,
                "min_generations": self.skew_min_generations,
                "hold_s": self.skew_hold_s,
            }
        return rows

    def _chip_rollup(self, snaps) -> Dict[str, Any]:
        total = healthy = 0
        per_node = {}
        for s in snaps:
            c = s.get("counters", {})
            size = int(c.get("decision.backend.pool.size", 0))
            ok = int(c.get("decision.backend.pool.healthy", 0))
            if size:
                per_node[s["node"]] = {"size": size, "healthy": ok}
                total += size
                healthy += ok
        return {
            "total": total,
            "healthy": healthy,
            "quarantined": total - healthy,
            "per_node": per_node,
        }

    def _quarantine_signal(self, snaps, firing) -> None:
        chips = self._chip_rollup(snaps)
        if chips["quarantined"] > 0:
            firing["chip_quarantine"] = {
                "quarantined": chips["quarantined"],
                "nodes": sorted(
                    n
                    for n, row in chips["per_node"].items()
                    if row["healthy"] < row["size"]
                ),
            }
        latched = sorted(
            s["node"]
            for s in snaps
            if s.get("counters", {}).get("resilience.backend.quarantined", 0)
        )
        if latched:
            firing["backend_quarantine"] = {"nodes": latched}

    _CHIP_BREAKER_RE = None  # compiled lazily below

    def _breaker_rollup(self, snaps) -> List[Dict[str, Any]]:
        """Non-closed breakers fleet-wide, EXCLUDING the device backend's
        own breaker and its per-chip breakers — those states already
        surface as the dedicated backend/chip quarantine alerts, and an
        alert that fires twice under two names pages twice for one
        incident."""
        import re

        if FleetHealthAggregator._CHIP_BREAKER_RE is None:
            FleetHealthAggregator._CHIP_BREAKER_RE = re.compile(
                r"^resilience\.backend(\.dev\d+)?\.state$"
            )
        chip_re = FleetHealthAggregator._CHIP_BREAKER_RE
        out = []
        for s in snaps:
            for k, v in s.get("counters", {}).items():
                if (
                    k.startswith("resilience.")
                    and k.endswith(".state")
                    and v > 0.0
                    and chip_re.match(k) is None
                ):
                    out.append(
                        {
                            "node": s["node"],
                            "edge": k[len("resilience."):-len(".state")],
                            "state": "open" if v == 1.0 else "half_open",
                        }
                    )
        return out

    def _breaker_signal(self, snaps, firing) -> None:
        open_breakers = self._breaker_rollup(snaps)
        if open_breakers:
            firing["breaker_open"] = {
                "count": len(open_breakers),
                "edges": [
                    f"{b['node']}:{b['edge']}:{b['state']}"
                    for b in open_breakers
                ],
            }

    def _queue_rollup(self, snaps) -> Dict[str, Any]:
        worst_depth, worst = 0.0, ""
        saturated = []
        for s in snaps:
            for k, v in s.get("counters", {}).items():
                if not (
                    k.startswith("messaging.queue.") and k.endswith(".depth")
                ):
                    continue
                q = f"{s['node']}:{k[len('messaging.queue.'):-len('.depth')]}"
                if v > worst_depth:
                    worst_depth, worst = v, q
                if v >= self.queue_depth_threshold:
                    saturated.append({"queue": q, "depth": v})
        return {
            "worst_depth": worst_depth,
            "worst_queue": worst,
            "saturated": saturated,
            "threshold": self.queue_depth_threshold,
        }

    def _queue_signal(self, snaps, firing) -> None:
        sat = self._queue_rollup(snaps)["saturated"]
        if sat:
            firing["queue_saturation"] = {
                "queues": [q["queue"] for q in sat],
                "threshold": self.queue_depth_threshold,
            }

    def _utilization_signal(self, snaps, firing) -> None:
        from openr_tpu.tracing.pipeline import parse_device_key

        skewed = []
        for s in snaps:
            utils = []
            for k, v in s.get("counters", {}).items():
                parsed = parse_device_key(k)
                if parsed is not None and parsed[1] == "utilization":
                    utils.append(v)
            if len(utils) < 2:
                continue
            spread = max(utils) - min(utils)
            if (
                spread >= self.utilization_spread_threshold
                and max(utils) >= self.utilization_spread_floor
            ):
                skewed.append(
                    {"node": s["node"], "spread": round(spread, 4)}
                )
        if skewed:
            firing["utilization_spread"] = {
                "nodes": skewed,
                "threshold": self.utilization_spread_threshold,
            }

    def _crash_signal(self, snaps, firing) -> None:
        for s in snaps:
            name = s.get("node", "")
            counters = s.get("counters", {})
            crashes = float(counters.get("watchdog.crashes", 0.0))
            st = self._gen_state.get(name)
            if st is None:
                continue
            if crashes < st.last_crashes:
                # counter went backwards: the node restarted and reset
                # its counters — the crashes already latched stay latched
                st.last_crashes = 0.0
            self._crashes_latched += max(crashes - st.last_crashes, 0.0)
            st.last_crashes = crashes
            # a supervisor restart replaces the node (and its counters)
            # faster than a sweep can see watchdog.crashes — the
            # incarnation stamp INCREASING is the restart the fleet
            # must remember (`node.start_ms`, clock-deterministic)
            start_ms = counters.get("node.start_ms")
            if start_ms is not None:
                if st.start_ms is not None and start_ms > st.start_ms:
                    # an incarnation the SUPERVISOR stamped as
                    # operator-requested (rolling upgrade) is expected:
                    # tracked, never paged.  Any other incarnation bump
                    # is an unexplained restart and latches.
                    expected = counters.get("node.restart_expected_ms")
                    if expected is not None and float(expected) == float(
                        start_ms
                    ):
                        self._expected_restarts_latched += 1.0
                    else:
                        self._restarts_latched += 1.0
                st.start_ms = float(start_ms)
        if self._crashes_latched > 0 or self._restarts_latched > 0:
            firing["node_crash"] = {
                "crashes_seen": self._crashes_latched,
                "restarts_seen": self._restarts_latched,
            }

    def _protection_signal(self, snaps, firing) -> None:
        """A fast-reroute patch that diverged from its confirming warm
        solve briefly installed a wrong route — cumulative counter, so
        the page stays active until the node restarts (deliberate: a
        mismatch means the mint envelope has a hole and a human must
        look)."""
        rows = []
        for s in snaps:
            n = float(
                s.get("counters", {}).get("protection.mismatches", 0.0)
            )
            if n > 0:
                rows.append({"node": s["node"], "mismatches": n})
        if rows:
            firing["protection_mismatch"] = {"nodes": rows}

    # -- query surface -----------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """The last sweep's rollup (empty before the first sweep)."""
        return dict(self._last_status)

    def active_alerts(self) -> List[Dict[str, Any]]:
        return self.sink.active_alerts()

    def alert_log(self) -> List[str]:
        return list(self.sink.log)

    def gauges(self) -> Dict[str, float]:
        """Monitor.add_counter_provider provider."""
        out = {
            "health.sweeps": float(self.num_sweeps),
            "health.crashes_seen": self._crashes_latched,
            "health.expected_restarts_seen": (
                self._expected_restarts_latched
            ),
        }
        out.update(self.sink.gauges())
        return out


class HealthMonitor(Actor):
    """The sweep driver: one fiber on the injected Clock calling
    ``aggregator.sweep()`` every ``interval_s``.  Kept separate from
    the aggregator so tests (and the ctrl refresh path) can sweep
    synchronously without an actor in the way."""

    def __init__(
        self,
        aggregator: FleetHealthAggregator,
        clock: Clock,
        counters: Optional[CounterMap] = None,
        interval_s: float = 15.0,
    ) -> None:
        super().__init__("health", clock, counters)
        self.aggregator = aggregator
        self._interval = interval_s

    def start(self) -> None:
        # the sweep is a pure sampler: run it after every same-instant
        # mutator so what it observes at T is schedule-independent
        self.clock.mark_observer("health.sweeps")
        self.spawn(self._sweep_fiber(), name="health.sweeps")

    async def _sweep_fiber(self) -> None:
        while True:
            await self.clock.sleep(self._interval)
            self.touch()
            self.aggregator.sweep()
